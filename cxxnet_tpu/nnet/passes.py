"""Graph-level optimizing passes over the NetConfig DAG.

NetConfig already parses configs into a layer DAG; this module treats
that DAG as an IR with Relay-style optimizing passes (PAPERS.md:
arXiv:1810.00952) run by the trainer before the Network is built -
`PassPipeline` of named `GraphPass`es over a shared pattern-rewrite
engine (docs/GRAPH_PASSES.md). Shipped passes:

- **space_to_depth** (graph stage): the input-conv space-to-depth
  rewrite, previously an auto heuristic buried inside `ops.conv2d`,
  re-expressed as a pattern rewrite: the pass evaluates the SAME
  predicate (`ops.conv.s2d_auto` - one definition, so the pass and
  the op cannot disagree) against the inferred node shapes and stamps
  an explicit `space_to_depth = 0|1` onto each conv's layer config.
  An explicit per-layer `space_to_depth` always wins.
- **autocast** (graph stage): the bf16/f32 mixed-precision policy as
  ONE pass instead of per-layer flags: under `dtype = bfloat16` it
  stamps a compute dtype per layer (`GraphModule.dtype_plan`,
  consumed by `Network.forward`) - matmul/conv-heavy layers run
  bf16, numerically fragile layers (batch_norm, lrn, the loss heads)
  stay f32. The existing flags become overrides: `dtype` sets the
  policy, a per-layer `layer_dtype = float32|bfloat16` pins a layer.
- **dead_layer_elim** (infer stage): prune every layer not on a path
  to the requested output node - the extract/finetune/serve subgraph.
  jax's jit DCEs the *lowered* module already (measured: the compiled
  HLO of an early-node infer is byte-identical with or without the
  dead tail), so the honest wins are the traced program (strictly
  fewer jaxpr equations), trace/lowering latency, and keeping the
  fold pass's pattern space small. Kept `share[...]` layers whose
  primary is pruned are promoted to primaries (their params arrive
  via the param map, so no dead ancestor is retained).
- **fold_conv_bn** (infer stage): fold a batch_norm following a conv
  or fullc into that layer's weights/bias so the donation-free
  `infer_step` executes a single fused matmul/conv with NO moment or
  variance computation. This repo's BN normalizes with *minibatch*
  statistics even at eval (reference quirk), so the fold freezes the
  statistics captured from ONE calibration batch (the trainer's
  first inference batch, or an explicit
  `trainer.calibrate_graph_passes(batch)`); `rsqrt(var + eps)` is
  precomputed on the host so the folded jaxpr carries no rsqrt
  either. The folded weights stay a LIVE function of the params
  argument (`W' = W * slope * rstd` inside the jit), so a
  checkpoint load or set_weight is picked up without re-folding;
  only the frozen statistics are calibration-time constants.
  Semantics note (docs/GRAPH_PASSES.md "when folding loses"):
  frozen stats make inference batch-composition-INDEPENDENT - for
  serving that is a correctness win (a request's answer no longer
  depends on what else was coalesced into its bucket); parity with
  the unfolded path is exact (~ULP contraction change) when the
  calibration batch IS the inference batch and approximate
  otherwise.

Passes never touch the training graph structure or the checkpoint
format: graph-stage passes only stamp layer configs / dtype
annotations (NetConfig.to_dict is structure-only), and infer-stage
passes run on a clone consumed solely by the inference executables.

On top, the TVM-style tuning cache (arXiv:1802.04799) lives in
`nnet/tuning.py` and `tools/autotune.py`.
"""

from __future__ import annotations

import difflib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from cxxnet_tpu.nnet.net_config import NetConfig

# layer types whose math is one big contraction - the autocast
# policy's bf16 set is "everything except the fragile ones", this set
# only documents the headline beneficiaries
_F32_SENSITIVE_TYPES = frozenset((
    "batch_norm", "lrn", "softmax", "l2_loss", "multi_logistic"))

# fold pattern: the producing layer types a batch_norm folds into
_FOLDABLE_TYPES = frozenset(("conv", "fullc"))


# ---------------------------------------------------------------------------
# the IR the passes transform
# ---------------------------------------------------------------------------
@dataclass
class FoldSite:
    """One folded conv/fullc + batch_norm pair: the live-params keys
    of both layers plus the frozen per-channel calibration statistics
    (mean of the BN input, rsqrt(var + eps))."""

    conv_key: str
    bn_key: str
    mean: np.ndarray
    rstd: np.ndarray


@dataclass
class GraphModule:
    """A NetConfig DAG in flight through the pass pipeline.

    `param_keys[i]` is the LIVE params-pytree key layer i's weights
    come from (None for param-less or shared layers) - structural
    passes keep it aligned so `make_param_fn` can rebuild the
    transformed graph's params from the live train params no matter
    how indices shifted."""

    cfg: NetConfig
    batch_size: int
    compute_dtype: Any = None
    param_keys: List[Optional[str]] = field(default_factory=list)
    folds: List[FoldSite] = field(default_factory=list)
    dtype_plan: Dict[int, Any] = field(default_factory=dict)
    log: List[str] = field(default_factory=list)

    @classmethod
    def from_net_config(cls, cfg: NetConfig, batch_size: int,
                        compute_dtype: Any = None) -> "GraphModule":
        from cxxnet_tpu.nnet.network import param_key
        keys: List[Optional[str]] = []
        for idx, info in enumerate(cfg.layers):
            keys.append(None if info.is_shared
                        else param_key(cfg, idx))
        return cls(cfg=cfg, batch_size=batch_size,
                   compute_dtype=compute_dtype, param_keys=keys)

    # -- structural edits -------------------------------------------------
    def remove_layers(self, indices: Sequence[int]) -> None:
        """Drop layers by index, remapping share back-references and
        keeping layercfg/param_keys/dtype_plan aligned."""
        drop = set(indices)
        if not drop:
            return
        cfg = self.cfg
        remap: Dict[int, int] = {}
        for old in range(len(cfg.layers)):
            if old not in drop:
                remap[old] = len(remap)
        for old in drop:
            info = cfg.layers[old]
            if any(li.primary_layer_index == old
                   for i, li in enumerate(cfg.layers)
                   if i not in drop and li.is_shared):
                raise ValueError(
                    f"cannot remove layer {old} "
                    f"({info.type_name}): a kept share[...] layer "
                    "references it as primary")
        cfg.layers = [li for i, li in enumerate(cfg.layers)
                      if i not in drop]
        cfg.layercfg = [c for i, c in enumerate(cfg.layercfg)
                        if i not in drop]
        self.param_keys = [k for i, k in enumerate(self.param_keys)
                           if i not in drop]
        self.dtype_plan = {remap[i]: d for i, d in
                           self.dtype_plan.items() if i in remap}
        for li in cfg.layers:
            if li.is_shared:
                li.primary_layer_index = remap[li.primary_layer_index]
        cfg.layer_name_map = {
            li.name: i for i, li in enumerate(cfg.layers)
            if li.name and not li.is_shared}

    def param_map(self) -> Dict[str, str]:
        """Transformed-graph param key -> live-params key."""
        from cxxnet_tpu.nnet.network import param_key
        out: Dict[str, str] = {}
        for idx, info in enumerate(self.cfg.layers):
            if info.is_shared or self.param_keys[idx] is None:
                continue
            out[param_key(self.cfg, idx)] = self.param_keys[idx]
        return out


@dataclass
class PassContext:
    """Per-run inputs the passes read (never mutate)."""

    #: requested output node for infer-stage passes (None = train
    #: graph, where only graph-stage passes apply)
    target_node: Optional[int] = None
    #: bn live-params key -> (mean, rstd) calibration stats; None =
    #: not calibrated yet (fold defers)
    fold_stats: Optional[Dict[str, Tuple[np.ndarray, np.ndarray]]] = None


# ---------------------------------------------------------------------------
# pattern-rewrite engine: DAG queries shared by every pass
# ---------------------------------------------------------------------------
def node_consumers(cfg: NetConfig) -> Dict[int, List[int]]:
    """node index -> layer indices reading it (declaration order)."""
    cons: Dict[int, List[int]] = {}
    for idx, info in enumerate(cfg.layers):
        for j in info.nindex_in:
            cons.setdefault(j, []).append(idx)
    return cons


def share_primaries(cfg: NetConfig) -> set:
    """Layer indices that are the primary of some share[...] layer."""
    return {li.primary_layer_index for li in cfg.layers if li.is_shared}


def find_fold_sites(cfg: NetConfig) -> List[Tuple[int, int]]:
    """(producer_idx, bn_idx) pairs matching the fold pattern: a
    non-shared conv/fullc whose single output node feeds EXACTLY one
    batch_norm (self-loop BN allowed - later readers then see the
    post-BN value, which the folded layer reproduces). Weight-shared
    layers are excluded on both sides: folding a shared weight would
    specialize it per site."""
    sites: List[Tuple[int, int]] = []
    primaries = share_primaries(cfg)
    cons = node_consumers(cfg)
    for j, bn in enumerate(cfg.layers):
        if (bn.type_name != "batch_norm" or bn.is_shared
                or j in primaries):
            continue
        if len(bn.nindex_in) != 1 or len(bn.nindex_out) != 1:
            continue
        a = bn.nindex_in[0]
        writers = [i for i, li in enumerate(cfg.layers)
                   if a in li.nindex_out and i != j]
        if len(writers) != 1:
            continue
        i = writers[0]
        conv = cfg.layers[i]
        if (i > j or conv.type_name not in _FOLDABLE_TYPES
                or conv.is_shared or i in primaries):
            continue
        if len(conv.nindex_out) != 1 or conv.nindex_out[0] != a:
            continue
        readers = [c for c in cons.get(a, ()) if c != j]
        if bn.nindex_out[0] == a:
            # self-loop BN overwrites a: only a reader BETWEEN the
            # conv and the bn would see the raw conv output
            if any(i < c < j for c in readers):
                continue
        elif readers:
            continue
        sites.append((i, j))
    return sites


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------
class GraphPass:
    """One named transform over a GraphModule. `stage` declares when
    it runs: "graph" passes apply to the train+eval network at build
    time and must preserve values and checkpoint structure; "infer"
    passes apply per requested output node to the clone the inference
    executables are built from."""

    name: str = ""
    stage: str = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        raise NotImplementedError


PASS_REGISTRY: Dict[str, Type[GraphPass]] = {}

# canonical application order (infer passes prune first so the fold
# never sees - or folds - a dead subgraph)
_CANONICAL_ORDER = ("space_to_depth", "autocast",
                    "dead_layer_elim", "fold_conv_bn")


def register_pass(cls: Type[GraphPass]) -> Type[GraphPass]:
    assert cls.name, "pass class must define a name"
    PASS_REGISTRY[cls.name] = cls
    return cls


def resolve_pass_name(name: str) -> str:
    """Validate a pass name with did-you-mean (the `serve_max_batchh`
    precedent applied to pass names: a typo'd pass must cost an error
    with a suggestion, never a silently-unoptimized run)."""
    if name in PASS_REGISTRY:
        return name
    hint = difflib.get_close_matches(name, PASS_REGISTRY.keys(), n=1,
                                     cutoff=0.6)
    msg = f"unknown graph pass '{name}'"
    if hint:
        msg += f" (did you mean '{hint[0]}'?)"
    raise ValueError(
        msg + f"; available passes: {', '.join(sorted(PASS_REGISTRY))}")


@register_pass
class SpaceToDepthPass(GraphPass):
    """Stamp the space-to-depth input-conv rewrite decision onto the
    DAG (module docstring). Value-identical to the in-op auto
    heuristic by construction: both evaluate `ops.conv.s2d_auto`."""

    name = "space_to_depth"
    stage = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        from cxxnet_tpu.ops.conv import s2d_auto

        def unstamped(idx, info):
            return (info.type_name == "conv" and not info.is_shared
                    and not any(k == "space_to_depth"
                                for k, _ in (gm.cfg.defcfg
                                             + gm.cfg.layercfg[idx])))

        if not any(unstamped(i, li)
                   for i, li in enumerate(gm.cfg.layers)):
            # nothing to stamp: skip the shape-inference Network
            # build entirely (the common MLP/no-conv case)
            return gm
        from cxxnet_tpu.nnet.network import Network
        net = Network(gm.cfg, gm.batch_size)
        for idx, info in enumerate(gm.cfg.layers):
            if not unstamped(idx, info):
                continue
            lay = net.layer_objs[idx]
            in_ch = net.node_shapes[info.nindex_in[0]][1]
            on = s2d_auto(in_ch, lay.param.stride,
                          lay.param.kernel_height,
                          lay.param.kernel_width, lay.param.num_group)
            gm.cfg.layercfg[idx].append(
                ("space_to_depth", "1" if on else "0"))
            gm.log.append(
                f"space_to_depth: conv[{idx}] in_ch={in_ch} "
                f"stride={lay.param.stride} -> {int(on)}")
        return gm


@register_pass
class AutocastPass(GraphPass):
    """Stamp a compute dtype per layer (module docstring). A no-op
    under f32 compute; under bf16 the fragile layer types stay f32
    and `layer_dtype = float32|bfloat16` pins individual layers."""

    name = "autocast"
    stage = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        import jax.numpy as jnp
        if gm.compute_dtype is None or gm.compute_dtype == jnp.float32:
            gm.log.append("autocast: f32 compute, nothing to stamp")
            return gm
        parse = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}
        for idx, info in enumerate(gm.cfg.layers):
            src = (info.primary_layer_index if info.is_shared else idx)
            ltype = gm.cfg.layers[src].type_name
            override = ""
            for k, v in gm.cfg.defcfg + gm.cfg.layercfg[src]:
                if k == "layer_dtype":
                    override = v
            if override:
                if override not in parse:
                    raise ValueError(
                        "layer_dtype must be float32 or bfloat16, "
                        f"got {override!r}")
                d = parse[override]
            elif ltype in _F32_SENSITIVE_TYPES:
                d = jnp.float32
            else:
                d = gm.compute_dtype
            gm.dtype_plan[idx] = d
            gm.log.append(f"autocast: layer[{idx}] {ltype} -> "
                          f"{jnp.dtype(d).name}")
        return gm


@register_pass
class DeadLayerElimPass(GraphPass):
    """Prune layers not on a path to the requested output node
    (module docstring)."""

    name = "dead_layer_elim"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        if ctx.target_node is None:
            return gm
        cfg = gm.cfg
        needed = {ctx.target_node}
        keep: set = set()
        for idx in reversed(range(len(cfg.layers))):
            info = cfg.layers[idx]
            if any(o in needed for o in info.nindex_out):
                keep.add(idx)
                needed.update(info.nindex_in)
        if ctx.target_node >= cfg.num_nodes:
            raise ValueError(
                f"dead_layer_elim: unknown target node "
                f"{ctx.target_node}")
        # kept share layers whose primary died: promote to primary -
        # the weights arrive through the param map, so the dead
        # ancestor chain need not be retained for them
        for idx in sorted(keep):
            info = cfg.layers[idx]
            if not info.is_shared:
                continue
            prim = info.primary_layer_index
            if prim in keep:
                continue
            primary = cfg.layers[prim]
            info.type_name = primary.type_name
            info.primary_layer_index = -1
            info.name = ""
            cfg.layercfg[idx] = list(cfg.layercfg[prim])
            gm.param_keys[idx] = gm.param_keys[prim]
            gm.log.append(
                f"dead_layer_elim: promoted share[{idx}] to primary "
                f"(its primary {prim} is dead)")
        dropped = [i for i in range(len(cfg.layers)) if i not in keep]
        if dropped:
            gm.log.append(
                f"dead_layer_elim: pruned {len(dropped)}/"
                f"{len(cfg.layers)} layers not reaching node "
                f"{ctx.target_node}")
        gm.remove_layers(dropped)
        return gm


@register_pass
class FoldConvBNPass(GraphPass):
    """Fold conv/fullc + batch_norm chains using frozen calibration
    statistics (module docstring). Defers (logs, no rewrite) until
    `ctx.fold_stats` exists; skips any site whose raw pre-BN value is
    the requested output."""

    name = "fold_conv_bn"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        sites = find_fold_sites(gm.cfg)
        if not sites:
            return gm
        if ctx.fold_stats is None:
            gm.log.append(
                f"fold_conv_bn: {len(sites)} site(s) deferred - no "
                "calibration stats yet")
            return gm
        drop: List[int] = []
        for i, j in sites:
            conv, bn = gm.cfg.layers[i], gm.cfg.layers[j]
            bn_key, conv_key = gm.param_keys[j], gm.param_keys[i]
            stats = ctx.fold_stats.get(bn_key)
            if stats is None:
                gm.log.append(
                    f"fold_conv_bn: no stats for {bn_key}, skipped")
                continue
            if (bn.nindex_out[0] != bn.nindex_in[0]
                    and bn.nindex_in[0] == ctx.target_node):
                # the caller asked for the RAW conv output
                gm.log.append(
                    f"fold_conv_bn: target node is {conv_key}'s raw "
                    "output, site skipped")
                continue
            conv.nindex_out = list(bn.nindex_out)
            gm.folds.append(FoldSite(conv_key=conv_key, bn_key=bn_key,
                                     mean=stats[0], rstd=stats[1]))
            drop.append(j)
            gm.log.append(
                f"fold_conv_bn: folded {bn_key} into {conv_key}")
        gm.remove_layers(drop)
        return gm


# ---------------------------------------------------------------------------
# params of a transformed graph, from the live train params
# ---------------------------------------------------------------------------
def make_param_fn(gm: GraphModule):
    """jax-traceable function: live train params -> the transformed
    graph's params. Key remaps are free; fold sites compute
    `W' = W * (slope * rstd)` and `b' = (b - mean) * k + beta` from
    the LIVE weights (the folded weights track checkpoint loads and
    set_weight), with only mean/rstd frozen at calibration - and
    rstd precomputed, so no rsqrt (let alone a moment reduction)
    appears in the folded jaxpr."""
    import jax.numpy as jnp
    pairs = list(gm.param_map().items())
    fold_by_key = {s.conv_key: s for s in gm.folds}

    def param_fn(params):
        out = {}
        for new_key, live_key in pairs:
            if live_key not in params:
                continue
            site = fold_by_key.get(live_key)
            if site is None:
                out[new_key] = params[live_key]
                continue
            conv_p, bn_p = params[live_key], params[site.bn_key]
            k = bn_p["slope"] * jnp.asarray(site.rstd)
            w = conv_p["wmat"]
            kw = k.reshape((-1,) + (1,) * (w.ndim - 1))
            bias = conv_p.get("bias", jnp.zeros_like(k))
            out[new_key] = {
                "wmat": w * kw.astype(w.dtype),
                "bias": (bias - jnp.asarray(site.mean)) * k
                        + bn_p["bias"],
            }
        return out

    return param_fn


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------
class PassPipeline:
    """An ordered set of GraphPasses (canonical order, module
    docstring). Built from the `graph_passes = a,b,...` config key
    plus the per-pass `pass_<name> = 0|1` toggles; unknown names get
    did-you-mean errors."""

    def __init__(self, passes: Sequence[GraphPass]):
        order = {n: i for i, n in enumerate(_CANONICAL_ORDER)}
        self.passes = sorted(passes,
                             key=lambda p: order.get(p.name, 99))

    @classmethod
    def from_config(cls, spec: str,
                    toggles: Optional[Dict[str, int]] = None,
                    ) -> "PassPipeline":
        spec = (spec or "").strip()
        if spec in ("0", "none", "off"):
            spec = ""
        if spec == "all":
            # every REGISTERED pass - not the canonical-order tuple,
            # which only sorts: a pass added via @register_pass must
            # not be silently excluded from `graph_passes = all`
            enabled = set(PASS_REGISTRY)
        else:
            enabled = {resolve_pass_name(t.strip())
                       for t in spec.split(",") if t.strip()}
        for name, on in (toggles or {}).items():
            resolve_pass_name(name)
            if on:
                enabled.add(name)
            else:
                enabled.discard(name)
        return cls([PASS_REGISTRY[n]() for n in enabled])

    @property
    def graph_passes(self) -> List[GraphPass]:
        return [p for p in self.passes if p.stage == "graph"]

    @property
    def infer_passes(self) -> List[GraphPass]:
        return [p for p in self.passes if p.stage == "infer"]

    def has(self, name: str) -> bool:
        return any(p.name == name for p in self.passes)

    def run_graph(self, gm: GraphModule,
                  ctx: Optional[PassContext] = None) -> GraphModule:
        ctx = ctx or PassContext()
        for p in self.graph_passes:
            gm = p.run(gm, ctx)
        return gm

    def run_infer(self, gm: GraphModule,
                  ctx: PassContext) -> GraphModule:
        for p in self.infer_passes:
            gm = p.run(gm, ctx)
        return gm

    def names(self) -> List[str]:
        return [p.name for p in self.passes]
