"""Model checkpoint format.

Role parity with the reference model file (SURVEY.md Appendix B:
[int net_type][NetConfig][epoch][model blob]), re-designed as
[magic][json header][raw little-endian arrays]:

- header carries net_type, the NetConfig structure dict, epoch counter,
  and an ordered manifest of arrays (pytree path, dtype, shape);
- the reference does NOT checkpoint optimizer state (momentum resets on
  resume - sgd_updater-inl.hpp:33-37); we keep that default but support
  `save_optimizer=1` which appends updater state arrays, an explicit
  improvement the format records in the header.
"""

from __future__ import annotations

import json
import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"CXTPU001"


def _flatten(tree: Any, prefix: str = "") -> List[Tuple[str, np.ndarray]]:
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flatten(tree[k], f"{prefix}/{k}" if prefix else k))
    else:
        out.append((prefix, np.asarray(tree)))
    return out


def _unflatten(items: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in items.items():
        keys = path.split("/")
        d = root
        for k in keys[:-1]:
            d = d.setdefault(k, {})
        d[keys[-1]] = arr
    return root


def save_model(fo: BinaryIO, net_type: int, net_structure: dict, epoch: int,
               params: dict, opt_state: Optional[dict] = None) -> None:
    flat_params = _flatten(params)
    flat_opt = _flatten(opt_state) if opt_state is not None else []
    header = {
        "net_type": net_type,
        "net": net_structure,
        "epoch": int(epoch),
        "params": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_params
        ],
        "opt_state": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_opt
        ],
    }
    hbytes = json.dumps(header).encode("utf-8")
    fo.write(MAGIC)
    fo.write(struct.pack("<q", len(hbytes)))
    fo.write(hbytes)
    for _, a in flat_params + flat_opt:
        fo.write(np.ascontiguousarray(a).tobytes())


def load_model(fi: BinaryIO) -> dict:
    """Returns {net_type, net, epoch, params, opt_state or None}."""
    magic = fi.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError("invalid model file (bad magic)")
    (hlen,) = struct.unpack("<q", fi.read(8))
    header = json.loads(fi.read(hlen).decode("utf-8"))

    def read_arrays(manifest):
        items = {}
        for ent in manifest:
            n = int(np.prod(ent["shape"])) if ent["shape"] else 1
            dtype = np.dtype(ent["dtype"])
            buf = fi.read(n * dtype.itemsize)
            items[ent["path"]] = np.frombuffer(
                buf, dtype=dtype).reshape(ent["shape"]).copy()
        return items

    params = _unflatten(read_arrays(header["params"]))
    opt_state = (_unflatten(read_arrays(header["opt_state"]))
                 if header["opt_state"] else None)
    return {
        "net_type": header["net_type"],
        "net": header["net"],
        "epoch": header["epoch"],
        "params": params,
        "opt_state": opt_state,
    }
