"""Model checkpoint format.

Role parity with the reference model file (SURVEY.md Appendix B:
[int net_type][NetConfig][epoch][model blob]), re-designed as
[magic][json header][raw little-endian arrays]:

- header carries net_type, the NetConfig structure dict, epoch counter,
  and an ordered manifest of arrays (pytree path, dtype, shape);
- the reference does NOT checkpoint optimizer state (momentum resets on
  resume - sgd_updater-inl.hpp:33-37); we keep that default but support
  `save_optimizer=1` which appends updater state arrays, an explicit
  improvement the format records in the header.
- pytree paths join nested dict keys with a separator recorded in the
  header ("/" normally; an ASCII unit separator when a layer name
  itself contains "/"), so arbitrary config-given layer names
  round-trip.
- an integrity TRAILER follows the arrays: [b"CXCRC001"][u64 payload
  bytes][u32 crc32-of-payload]. load_model validates it (a flipped or
  missing byte anywhere fails loudly instead of resuming from garbage);
  pre-trailer files still load. docs/FAULT_TOLERANCE.md has the spec.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.utils import fault

MAGIC = b"CXTPU001"
TRAILER_MAGIC = b"CXCRC001"
TRAILER_LEN = len(TRAILER_MAGIC) + 8 + 4
_ALT_SEP = "\x1f"  # used when a key contains "/"
_MAX_HEADER = 1 << 30


class _CrcWriter:
    """Pass-through writer accumulating crc32 + byte count."""

    def __init__(self, fo: BinaryIO):
        self.fo = fo
        self.crc = 0
        self.nbytes = 0

    def write(self, buf: bytes) -> int:
        self.crc = zlib.crc32(buf, self.crc)
        self.nbytes += len(buf)
        return self.fo.write(buf)


class _CrcReader:
    """Pass-through reader accumulating crc32 + byte count."""

    def __init__(self, fi: BinaryIO):
        self.fi = fi
        self.crc = 0
        self.nbytes = 0

    def read(self, n: int) -> bytes:
        buf = self.fi.read(n)
        self.crc = zlib.crc32(buf, self.crc)
        self.nbytes += len(buf)
        return buf


def _flatten(tree: Any, sep: str,
             prefix: str = "") -> List[Tuple[str, np.ndarray]]:
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flatten(tree[k], sep,
                                f"{prefix}{sep}{k}" if prefix else k))
    else:
        out.append((prefix, np.asarray(tree)))
    return out


def _keys(tree: Any):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield k
            yield from _keys(v)


def _pick_sep(*trees) -> str:
    for tree in trees:
        if tree is None:
            continue
        for k in _keys(tree):
            if "/" in str(k):
                return _ALT_SEP
    return "/"


def _unflatten(items: Dict[str, np.ndarray], sep: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in items.items():
        keys = path.split(sep)
        d = root
        for k in keys[:-1]:
            d = d.setdefault(k, {})
        d[keys[-1]] = arr
    return root


def save_model(fo: BinaryIO, net_type: int, net_structure: dict, epoch: int,
               params: dict, opt_state: Optional[dict] = None) -> None:
    t0 = time.perf_counter()
    sep = _pick_sep(params, opt_state)
    flat_params = _flatten(params, sep)
    flat_opt = _flatten(opt_state, sep) if opt_state is not None else []
    header = {
        "net_type": net_type,
        "net": net_structure,
        "epoch": int(epoch),
        "sep": sep,
        "params": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_params
        ],
        "opt_state": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_opt
        ],
    }
    hbytes = json.dumps(header).encode("utf-8")
    cw = _CrcWriter(fo)
    cw.write(MAGIC)
    cw.write(struct.pack("<q", len(hbytes)))
    cw.write(hbytes)
    arrays = flat_params + flat_opt
    midpoint = len(arrays) // 2
    for i, (_, a) in enumerate(arrays):
        buf = np.ascontiguousarray(a).tobytes()
        if i == midpoint:
            # `save_model` fault point, deliberately MID-payload so an
            # injected kill/crash models preemption during the write
            # (tests prove the atomic-save protocol leaves no
            # truncated final file). corrupt: emit half of this array
            # and stop - structurally truncated, crc-trailer-less -
            # the shape a non-atomic writer would have left on disk.
            if fault.fault_point("save_model") == "corrupt":
                cw.write(buf[:max(1, len(buf) // 2)])
                return
        cw.write(buf)
    if not arrays and fault.fault_point("save_model") == "corrupt":
        return  # header-only blob, still trailer-less -> invalid
    fo.write(TRAILER_MAGIC)
    fo.write(struct.pack("<Q", cw.nbytes))
    fo.write(struct.pack("<I", cw.crc))
    # serialization-only accounting (the fsync/replace cost of the
    # atomic protocol is timed by the task layer's checkpoint.save)
    telemetry.observe("checkpoint.write_s", time.perf_counter() - t0)
    telemetry.inc("checkpoint.bytes_written", cw.nbytes + TRAILER_LEN)


def _read_exact(fi: BinaryIO, n: int, what: str) -> bytes:
    buf = fi.read(n)
    if len(buf) != n:
        raise ValueError(
            f"invalid model file: truncated while reading {what} "
            f"(wanted {n} bytes, got {len(buf)})")
    return buf


def load_model(fi: BinaryIO) -> dict:
    """Returns {net_type, net, epoch, params, opt_state or None}.

    Validates the crc32 trailer when present; raises ValueError on any
    truncation / corruption instead of returning garbage weights."""
    t0 = time.perf_counter()
    cr = _CrcReader(fi)
    magic = cr.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError("invalid model file (bad magic)")
    (hlen,) = struct.unpack("<q", _read_exact(cr, 8, "header length"))
    if hlen <= 0 or hlen > _MAX_HEADER:
        raise ValueError(
            f"invalid model file: implausible header length {hlen}")
    try:
        header = json.loads(_read_exact(cr, hlen, "header").decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError("invalid model file: corrupt header") from e
    sep = header.get("sep", "/")  # pre-sep files used "/"

    def read_arrays(manifest):
        items = {}
        for ent in manifest:
            n = int(np.prod(ent["shape"])) if ent["shape"] else 1
            try:
                dtype = np.dtype(ent["dtype"])
            except TypeError as e:
                raise ValueError(
                    f"invalid model file: unknown dtype {ent['dtype']!r} "
                    f"for {ent['path']!r}") from e
            buf = _read_exact(cr, n * dtype.itemsize,
                              f"array {ent['path']!r}")
            items[ent["path"]] = np.frombuffer(
                buf, dtype=dtype).reshape(ent["shape"]).copy()
        return items

    params = _unflatten(read_arrays(header["params"]), sep)
    opt_state = (_unflatten(read_arrays(header["opt_state"]), sep)
                 if header["opt_state"] else None)
    _check_trailer(fi, cr)
    telemetry.observe("checkpoint.read_s", time.perf_counter() - t0)
    telemetry.inc("checkpoint.bytes_read", cr.nbytes)
    return {
        "net_type": header["net_type"],
        "net": header["net"],
        "epoch": header["epoch"],
        "params": params,
        "opt_state": opt_state,
    }


def _check_trailer(fi: BinaryIO, cr: _CrcReader) -> None:
    """Validate the integrity trailer, if any, after the arrays.

    - no bytes follow: pre-trailer file, accepted unvalidated;
    - a (possibly truncated) trailer follows: length + crc must match;
    - anything else: not ours - rewound and ignored (a wrapping stream
      may carry unrelated framing after the model blob)."""
    payload_bytes, payload_crc = cr.nbytes, cr.crc
    tail = fi.read(TRAILER_LEN)
    if not tail:
        return
    if not tail.startswith(TRAILER_MAGIC):
        if TRAILER_MAGIC.startswith(tail[:len(TRAILER_MAGIC)]):
            raise ValueError(
                "invalid model file: truncated integrity trailer")
        try:
            fi.seek(-len(tail), 1)
        except (OSError, ValueError):
            pass
        return
    if len(tail) < TRAILER_LEN:
        raise ValueError("invalid model file: truncated integrity trailer")
    (want_bytes,) = struct.unpack(
        "<Q", tail[len(TRAILER_MAGIC):len(TRAILER_MAGIC) + 8])
    (want_crc,) = struct.unpack("<I", tail[len(TRAILER_MAGIC) + 8:])
    if want_bytes != payload_bytes:
        raise ValueError(
            f"invalid model file: payload length mismatch (trailer says "
            f"{want_bytes} bytes, read {payload_bytes})")
    if want_crc != payload_crc:
        raise ValueError(
            f"invalid model file: crc32 mismatch (trailer {want_crc:#010x}"
            f" != computed {payload_crc:#010x}) - corrupt checkpoint")


def validate_file(path: str) -> Optional[str]:
    """Cheap validity probe for an on-disk checkpoint: returns None when
    the file is a complete, uncorrupted model, else a one-line reason.

    Files with the integrity trailer are validated by streaming crc32
    (no array materialization); trailer-less native files fall back to
    a full parse; non-native (legacy cxxnet-binary) files cannot be
    cheaply validated and are assumed valid unless empty. Used by the
    resume path to walk backward past corrupt/truncated checkpoints."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fi:
            head = fi.read(len(MAGIC))
            if len(head) < len(MAGIC):
                return f"file too short ({size} bytes)"
            if head != MAGIC:
                return None  # legacy/foreign format: assume valid
            if size >= len(MAGIC) + TRAILER_LEN:
                fi.seek(size - TRAILER_LEN)
                tail = fi.read(TRAILER_LEN)
                if tail.startswith(TRAILER_MAGIC):
                    (want_bytes,) = struct.unpack(
                        "<Q", tail[len(TRAILER_MAGIC):
                                   len(TRAILER_MAGIC) + 8])
                    (want_crc,) = struct.unpack(
                        "<I", tail[len(TRAILER_MAGIC) + 8:])
                    if want_bytes != size - TRAILER_LEN:
                        return (f"payload length mismatch (trailer says "
                                f"{want_bytes}, file has "
                                f"{size - TRAILER_LEN})")
                    fi.seek(0)
                    crc, left = 0, want_bytes
                    while left > 0:
                        buf = fi.read(min(1 << 20, left))
                        if not buf:
                            return "file shrank while validating"
                        crc = zlib.crc32(buf, crc)
                        left -= len(buf)
                    if crc != want_crc:
                        return (f"crc32 mismatch ({crc:#010x} != trailer "
                                f"{want_crc:#010x})")
                    return None
            # no trailer at EOF (pre-trailer file): structural check
            # from the header alone - the arrays are raw fixed-size
            # bytes, so the header-promised payload length is the full
            # validation a full parse could do, without materializing
            # the arrays (resume would load them a second time anyway)
            fi.seek(len(MAGIC))
            (hlen,) = struct.unpack("<q", _read_exact(fi, 8,
                                                      "header length"))
            if hlen <= 0 or hlen > _MAX_HEADER:
                return f"implausible header length {hlen}"
            header = json.loads(
                _read_exact(fi, hlen, "header").decode("utf-8"))
            need = 0
            for ent in header["params"] + (header["opt_state"] or []):
                n = 1
                for d in ent["shape"]:
                    n *= d
                need += n * np.dtype(ent["dtype"]).itemsize
            payload = len(MAGIC) + 8 + hlen + need
            if size < payload:
                return (f"truncated: file has {size} bytes, header "
                        f"promises {payload}")
            if size > payload:
                # stray tail bytes: defer to the real parser's
                # trailer/framing rules (rare, so the full parse cost
                # is acceptable here)
                fi.seek(0)
                load_model(fi)
        return None
    except (OSError, TypeError, ValueError, KeyError, struct.error) as e:
        return str(e)


def publish_model(src_path: str, publish_path: str) -> None:
    """Publish a saved checkpoint to a serving-watched path
    (docs/SERVING.md "Hot-swap runbook"): a streaming atomic copy
    (tmp + fsync + os.replace), so a live Server's `swap_watch`
    poller only ever observes a complete file appear - never a
    half-written one. The `swap_torn_checkpoint` fault point
    ("corrupt") publishes a deliberately truncated, trailer-less copy
    instead, driving the swap-reject path in tests and the
    serve-http-smoke torn-checkpoint leg."""
    import json
    t0 = time.perf_counter()
    torn = fault.fault_point("swap_torn_checkpoint") == "corrupt"
    size = os.path.getsize(src_path)
    copied = 0
    # a torn publish keeps roughly half the payload and drops the
    # rest (incl. the crc trailer): the shape a non-atomic writer
    # killed mid-copy would have left behind
    budget = max(1, size // 2) if torn else size
    # provenance sidecar FIRST (then the model copy): the watcher
    # triggers on the model file's stat, so the published model is
    # never observable without its metadata - swap/canary events can
    # always name the source checkpoint they promoted or rolled back
    with fault.atomic_writer(publish_path + ".meta", "w") as fm:
        fm.write(json.dumps({
            "src": os.path.abspath(src_path),
            "bytes": budget,
            "torn": bool(torn),
        }, sort_keys=True))
    with open(src_path, "rb") as fi, \
            fault.atomic_writer(publish_path) as fo:
        while copied < budget:
            buf = fi.read(min(1 << 20, budget - copied))
            if not buf:
                break
            fo.write(buf)
            copied += len(buf)
    telemetry.event("checkpoint", op="publish", src=src_path,
                    path=publish_path, bytes=copied, torn=torn,
                    secs=round(time.perf_counter() - t0, 4))


def read_publish_meta(publish_path: str):
    """Provenance sidecar of a published checkpoint (written by
    publish_model next to the model file), or None when absent or
    unparseable - pre-sidecar publishes and hand-copied files stay
    swappable."""
    import json
    try:
        with open(publish_path + ".meta", "r") as fi:
            meta = json.load(fi)
        return meta if isinstance(meta, dict) else None
    except (OSError, ValueError):
        return None
