"""Model checkpoint format.

Role parity with the reference model file (SURVEY.md Appendix B:
[int net_type][NetConfig][epoch][model blob]), re-designed as
[magic][json header][raw little-endian arrays]:

- header carries net_type, the NetConfig structure dict, epoch counter,
  and an ordered manifest of arrays (pytree path, dtype, shape);
- the reference does NOT checkpoint optimizer state (momentum resets on
  resume - sgd_updater-inl.hpp:33-37); we keep that default but support
  `save_optimizer=1` which appends updater state arrays, an explicit
  improvement the format records in the header.
- pytree paths join nested dict keys with a separator recorded in the
  header ("/" normally; an ASCII unit separator when a layer name
  itself contains "/"), so arbitrary config-given layer names
  round-trip.
"""

from __future__ import annotations

import json
import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"CXTPU001"
_ALT_SEP = "\x1f"  # used when a key contains "/"
_MAX_HEADER = 1 << 30


def _flatten(tree: Any, sep: str,
             prefix: str = "") -> List[Tuple[str, np.ndarray]]:
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flatten(tree[k], sep,
                                f"{prefix}{sep}{k}" if prefix else k))
    else:
        out.append((prefix, np.asarray(tree)))
    return out


def _keys(tree: Any):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield k
            yield from _keys(v)


def _pick_sep(*trees) -> str:
    for tree in trees:
        if tree is None:
            continue
        for k in _keys(tree):
            if "/" in str(k):
                return _ALT_SEP
    return "/"


def _unflatten(items: Dict[str, np.ndarray], sep: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in items.items():
        keys = path.split(sep)
        d = root
        for k in keys[:-1]:
            d = d.setdefault(k, {})
        d[keys[-1]] = arr
    return root


def save_model(fo: BinaryIO, net_type: int, net_structure: dict, epoch: int,
               params: dict, opt_state: Optional[dict] = None) -> None:
    sep = _pick_sep(params, opt_state)
    flat_params = _flatten(params, sep)
    flat_opt = _flatten(opt_state, sep) if opt_state is not None else []
    header = {
        "net_type": net_type,
        "net": net_structure,
        "epoch": int(epoch),
        "sep": sep,
        "params": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_params
        ],
        "opt_state": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in flat_opt
        ],
    }
    hbytes = json.dumps(header).encode("utf-8")
    fo.write(MAGIC)
    fo.write(struct.pack("<q", len(hbytes)))
    fo.write(hbytes)
    for _, a in flat_params + flat_opt:
        fo.write(np.ascontiguousarray(a).tobytes())


def _read_exact(fi: BinaryIO, n: int, what: str) -> bytes:
    buf = fi.read(n)
    if len(buf) != n:
        raise ValueError(
            f"invalid model file: truncated while reading {what} "
            f"(wanted {n} bytes, got {len(buf)})")
    return buf


def load_model(fi: BinaryIO) -> dict:
    """Returns {net_type, net, epoch, params, opt_state or None}."""
    magic = fi.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError("invalid model file (bad magic)")
    (hlen,) = struct.unpack("<q", _read_exact(fi, 8, "header length"))
    if hlen <= 0 or hlen > _MAX_HEADER:
        raise ValueError(
            f"invalid model file: implausible header length {hlen}")
    try:
        header = json.loads(_read_exact(fi, hlen, "header").decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError("invalid model file: corrupt header") from e
    sep = header.get("sep", "/")  # pre-sep files used "/"

    def read_arrays(manifest):
        items = {}
        for ent in manifest:
            n = int(np.prod(ent["shape"])) if ent["shape"] else 1
            try:
                dtype = np.dtype(ent["dtype"])
            except TypeError as e:
                raise ValueError(
                    f"invalid model file: unknown dtype {ent['dtype']!r} "
                    f"for {ent['path']!r}") from e
            buf = _read_exact(fi, n * dtype.itemsize,
                              f"array {ent['path']!r}")
            items[ent["path"]] = np.frombuffer(
                buf, dtype=dtype).reshape(ent["shape"]).copy()
        return items

    params = _unflatten(read_arrays(header["params"]), sep)
    opt_state = (_unflatten(read_arrays(header["opt_state"]), sep)
                 if header["opt_state"] else None)
    return {
        "net_type": header["net_type"],
        "net": header["net"],
        "epoch": header["epoch"],
        "params": params,
        "opt_state": opt_state,
    }
