"""Reference-binary checkpoint compatibility (import AND export).

Byte-level implementation of the cxxnet model file so models move
between the reference binary and this framework in both directions.
Layouts transcribed from the reference source (not linked code):

    int32   net_type                      (cxxnet_main.cpp SaveModel)
    NetParam struct, 152 B:               (nnet_config.h:28-50)
        int32 num_nodes, int32 num_layers,
        uint32 input_shape[3] (c, y, x),
        int32 init_end, int32 extra_data_num, int32 reserved[31]
    [extra_shape: uint64 count + int32 x count  (if extra_data_num)]
    node_names x num_nodes: uint64 len + bytes  (io.h:70-76)
    per layer:                            (nnet_config.h:126-145)
        int32 type (enum below), int32 primary_layer_index,
        string name, vec<int32> nindex_in, vec<int32> nindex_out
    int64   epoch_counter
    string  model_blob (uint64 len + bytes), concatenating per
    non-shared weighted layer, in declaration order:
        fullc / conv / bias: LayerParam struct (328 B, param.h:15-80)
                             + tensors below
        fullc: wmat SaveBinary 2D (nhidden, nin); bias 1D (nhidden)
        conv:  wmat SaveBinary 3D (g, O/g, I/g*kh*kw) - the same
               memory order as our OIHW; bias 1D (O)
        batch_norm: slope 1D + bias 1D (no LayerParam)
        prelu: slope 1D (no LayerParam)
        (all other layers write nothing)
    SaveBinary = uint32 shape[dim] + packed float32 data
    (mshadow tensor_container.h/io.h format)

Everything is little-endian. The reference does not checkpoint
optimizer state; neither does this format (use the native format with
save_optimizer=1 for that).
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Dict, List, Tuple

import numpy as np

# layer.h:284-317 string <-> enum (names are OUR registry names)
LAYER_TYPE_TO_INT = {
    "shared": 0, "fullc": 1, "softmax": 2, "relu": 3, "sigmoid": 4,
    "tanh": 5, "softplus": 6, "flatten": 7, "dropout": 8, "conv": 10,
    "max_pooling": 11, "sum_pooling": 12, "avg_pooling": 13, "lrn": 15,
    "bias": 17, "concat": 18, "xelu": 19, "caffe": 20,
    "relu_max_pooling": 21, "maxout": 22, "split": 23, "insanity": 24,
    "insanity_max_pooling": 25, "l2_loss": 26, "multi_logistic": 27,
    "ch_concat": 28, "prelu": 29, "batch_norm": 30, "fixconn": 31,
}
_NET_PARAM = struct.Struct("<ii3Iii31i")   # 152 bytes
_LAYER_PARAM_HEAD = struct.Struct("<ififfiiiiiiiiiiiii")  # 18 fields
_LAYER_PARAM_SIZE = _LAYER_PARAM_HEAD.size + 64 * 4       # + reserved


def _w_string(fo: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    fo.write(struct.pack("<Q", len(b)))
    fo.write(b)


def _r_string(fi: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", fi.read(8))
    return fi.read(n).decode("utf-8")


def _w_ivec(fo: BinaryIO, v: List[int]) -> None:
    fo.write(struct.pack("<Q", len(v)))
    if v:
        fo.write(struct.pack(f"<{len(v)}i", *v))


def _r_ivec(fi: BinaryIO) -> List[int]:
    (n,) = struct.unpack("<Q", fi.read(8))
    if n == 0:
        return []
    return list(struct.unpack(f"<{n}i", fi.read(4 * n)))


def _w_tensor(fo: BinaryIO, arr: np.ndarray, shape: Tuple[int, ...]) -> None:
    arr = np.ascontiguousarray(arr, np.float32).reshape(shape)
    fo.write(struct.pack(f"<{len(shape)}I", *shape))
    fo.write(arr.tobytes())


def _r_tensor(fi: BinaryIO, ndim: int) -> np.ndarray:
    shape = struct.unpack(f"<{ndim}I", fi.read(4 * ndim))
    n = int(np.prod(shape))
    return np.frombuffer(fi.read(4 * n), np.float32).reshape(shape).copy()


def _w_layer_param(fo: BinaryIO, lp) -> None:
    fo.write(_LAYER_PARAM_HEAD.pack(
        lp.num_hidden, lp.init_sigma, lp.init_sparse, lp.init_uniform,
        lp.init_bias, lp.num_channel, lp.random_type, lp.num_group,
        lp.kernel_height, lp.kernel_width, lp.stride, lp.pad_y, lp.pad_x,
        lp.no_bias, 64 << 18, lp.silent, lp.num_input_channel,
        lp.num_input_node))
    fo.write(b"\0" * (64 * 4))


def _skip_layer_param(fi: BinaryIO) -> None:
    fi.read(_LAYER_PARAM_SIZE)


# ---------------------------------------------------------------------------
# per-layer blob writers/readers (reference SaveModel/LoadModel pairs)
# ---------------------------------------------------------------------------

def _blob_write(fo: BinaryIO, info, layer, p: Dict[str, np.ndarray]) -> None:
    t = info.type_name
    lp = layer.param
    if t == "fullc":
        _w_layer_param(fo, lp)
        w = np.asarray(p["wmat"], np.float32)
        _w_tensor(fo, w, w.shape)
        bias = np.asarray(p.get("bias",
                                np.zeros(w.shape[0], np.float32)))
        _w_tensor(fo, bias, bias.shape)
    elif t == "conv":
        _w_layer_param(fo, lp)
        w = np.asarray(p["wmat"], np.float32)  # OIHW
        o, ipg, kh, kw = w.shape
        g = lp.num_group
        _w_tensor(fo, w, (g, o // g, ipg * kh * kw))
        bias = np.asarray(p.get("bias", np.zeros(o, np.float32)))
        _w_tensor(fo, bias, bias.shape)
    elif t == "bias":
        _w_layer_param(fo, lp)
        b = np.asarray(p["bias"], np.float32)
        _w_tensor(fo, b, b.shape)
    elif t == "batch_norm":
        _w_tensor(fo, np.asarray(p["slope"]), p["slope"].shape)
        _w_tensor(fo, np.asarray(p["bias"]), p["bias"].shape)
    elif t == "prelu":
        _w_tensor(fo, np.asarray(p["slope"]), p["slope"].shape)
    elif p:
        # a param-bearing type with no reference encoding (e.g. the
        # torch plugin under the caffe code) must not round-trip to
        # random re-init silently
        raise ValueError(
            f"layer type {t} has trainable params but no reference "
            "blob encoding (save with the native format instead)")


def _blob_read(fi: BinaryIO, info,
               p: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Read one layer's weights (tensor headers carry the shapes);
    `p`, when non-empty, provides expected shapes to validate."""
    t = info.type_name
    out = {}
    if t == "fullc":
        _skip_layer_param(fi)
        out["wmat"] = _r_tensor(fi, 2)
        bias = _r_tensor(fi, 1)
        if not p or "bias" in p:
            out["bias"] = bias
    elif t == "conv":
        _skip_layer_param(fi)
        w3 = _r_tensor(fi, 3)  # (g, O/g, I/g*kh*kw)
        if p:
            o, ipg, kh, kw = p["wmat"].shape
            g = w3.shape[0]
            want = (g, o // g, ipg * kh * kw)
            if w3.shape != want:
                raise ValueError(
                    f"legacy model: {info.name or t}.wmat 3D shape "
                    f"{w3.shape} != expected {want}")
            out["wmat"] = w3.reshape(p["wmat"].shape)
        else:
            out["wmat"] = w3
        bias = _r_tensor(fi, 1)
        if not p or "bias" in p:
            out["bias"] = bias
    elif t == "bias":
        _skip_layer_param(fi)
        out["bias"] = _r_tensor(fi, 1)
    elif t == "batch_norm":
        out["slope"] = _r_tensor(fi, 1)
        out["bias"] = _r_tensor(fi, 1)
    elif t == "prelu":
        out["slope"] = _r_tensor(fi, 1)
    for k, v in out.items():
        if p and k in p and tuple(p[k].shape) != tuple(v.shape):
            raise ValueError(
                f"legacy model: {info.name or t}.{k} shape "
                f"{v.shape} != expected {tuple(p[k].shape)}")
    return out or p


# ---------------------------------------------------------------------------
# whole-file save/load
# ---------------------------------------------------------------------------

def save_legacy_model(fo: BinaryIO, net_cfg, net, params: dict,
                      epoch: int, net_type: int = 0) -> None:
    import io as _io
    fo.write(struct.pack("<i", net_type))
    fo.write(_NET_PARAM.pack(
        net_cfg.num_nodes, net_cfg.num_layers, *net_cfg.input_shape,
        1, net_cfg.extra_data_num, *([0] * 31)))
    if net_cfg.extra_data_num != 0:
        _w_ivec(fo, list(net_cfg.extra_shape))
    for name in net_cfg.node_names:
        _w_string(fo, name)
    for info in net_cfg.layers:
        if info.is_shared:
            tcode = 0
        elif info.type_name in LAYER_TYPE_TO_INT:
            tcode = LAYER_TYPE_TO_INT[info.type_name]
        else:
            raise ValueError(
                f"layer type {info.type_name} has no reference encoding "
                "(save with the native format instead)")
        fo.write(struct.pack("<ii", tcode, info.primary_layer_index))
        _w_string(fo, info.name)
        _w_ivec(fo, list(info.nindex_in))
        _w_ivec(fo, list(info.nindex_out))
    fo.write(struct.pack("<q", int(epoch)))
    blob = _io.BytesIO()
    from cxxnet_tpu.nnet.network import param_key
    for idx, info in enumerate(net_cfg.layers):
        if info.is_shared:
            continue
        lk = param_key(net_cfg, idx)
        _blob_write(blob, info, net.layer_objs[idx], params.get(lk, {}))
    b = blob.getvalue()
    fo.write(struct.pack("<Q", len(b)))
    fo.write(b)


def read_legacy_model(fi: BinaryIO) -> dict:
    """Parse a legacy file WITHOUT a configured net (finetune path):
    returns {net_type, epoch, params: {layer_name_or_index: {pn: arr}}}.
    Conv weights come back in the file's 3D (g, O/g, I/g*kh*kw) layout
    (same memory order as OIHW; callers reshape by element count)."""
    import io as _io
    from types import SimpleNamespace
    (net_type,) = struct.unpack("<i", fi.read(4))
    head = _NET_PARAM.unpack(fi.read(_NET_PARAM.size))
    num_nodes, num_layers = head[0], head[1]
    if head[6] != 0:
        _r_ivec(fi)
    for _ in range(num_nodes):
        _r_string(fi)
    recs = []
    for _ in range(num_layers):
        tcode, primary = struct.unpack("<ii", fi.read(8))
        name = _r_string(fi)
        _r_ivec(fi)
        _r_ivec(fi)
        recs.append((tcode, primary, name))
    (epoch,) = struct.unpack("<q", fi.read(8))
    (blob_len,) = struct.unpack("<Q", fi.read(8))
    blob = _io.BytesIO(fi.read(blob_len))
    int_to_type = {v: k for k, v in LAYER_TYPE_TO_INT.items()}
    params = {}
    for i, (tcode, primary, name) in enumerate(recs):
        if tcode == 0 and primary >= 0:
            continue  # shared layer: no own weights in the blob
        info = SimpleNamespace(type_name=int_to_type.get(tcode, ""),
                               name=name)
        p = _blob_read(blob, info, {})
        if p:
            params[name or f"layer_{i}"] = p
    return {"net_type": net_type, "epoch": int(epoch), "params": params}


def load_legacy_model(fi: BinaryIO, net_cfg, net, params: dict) -> dict:
    """Validate structure against the configured net (the reference's
    LoadNet consistency check) and return the params tree from the file.
    `params` supplies expected shapes (e.g. from init_params)."""
    import io as _io
    (net_type,) = struct.unpack("<i", fi.read(4))
    head = _NET_PARAM.unpack(fi.read(_NET_PARAM.size))
    num_nodes, num_layers = head[0], head[1]
    input_shape = head[2:5]
    extra_data_num = head[6]
    if num_nodes != net_cfg.num_nodes or num_layers != net_cfg.num_layers:
        raise ValueError(
            f"legacy model: {num_nodes} nodes/{num_layers} layers != "
            f"configured {net_cfg.num_nodes}/{net_cfg.num_layers}")
    if tuple(input_shape) != tuple(net_cfg.input_shape):
        raise ValueError("legacy model: input_shape mismatch")
    if extra_data_num != 0:
        _r_ivec(fi)
    for i in range(num_nodes):
        _r_string(fi)
    for i in range(num_layers):
        tcode, primary = struct.unpack("<ii", fi.read(8))
        name = _r_string(fi)
        nin = _r_ivec(fi)
        nout = _r_ivec(fi)
        info = net_cfg.layers[i]
        want = (0 if info.is_shared
                else LAYER_TYPE_TO_INT.get(info.type_name, -1))
        if (tcode != want or nin != list(info.nindex_in)
                or nout != list(info.nindex_out)):
            raise ValueError(
                f"legacy model: layer {i} structure mismatch "
                f"(file type {tcode} {name!r}, config "
                f"{info.type_name} {info.name!r})")
    (epoch,) = struct.unpack("<q", fi.read(8))
    (blob_len,) = struct.unpack("<Q", fi.read(8))
    blob = _io.BytesIO(fi.read(blob_len))
    from cxxnet_tpu.nnet.network import param_key
    out = {}
    for idx, info in enumerate(net_cfg.layers):
        if info.is_shared:
            continue
        lk = param_key(net_cfg, idx)
        if lk in params:
            out[lk] = _blob_read(blob, info, params[lk])
        else:
            _blob_read(blob, info, {})
    return {"net_type": net_type, "epoch": int(epoch), "params": out}
