"""NetTrainer: the INetTrainer product surface, TPU-native.

Role parity with CXXNetThreadTrainer (nnet_impl-inl.hpp:16-455) - the full
virtual API of nnet.h:18-92: SetParam / InitModel / SaveModel / LoadModel /
StartRound / Update / Evaluate / Predict / ExtractFeature / CopyModelFrom /
SetWeight / GetWeight - but the execution model is re-designed for TPU:

reference                               this trainer
---------                               ------------
per-GPU host thread + stream            one SPMD program over a Mesh
batch sliced into per-device chunks     batch dim sharded over 'data' axis
mshadow-ps push/pull + AsyncUpdater     XLA AllReduce inserted by GSPMD
updater objects mutating weights        pure per-tensor updater transforms
                                        folded into the same jitted step
AdjustBatchSize for short batches       pad-to-static + validity mask
update_period grad accumulation         carried accumulator + lax.cond

The entire train step (forward + backward + gradient all-reduce +
optimizer) compiles to ONE XLA executable; eval/predict use a second
forward-only executable.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet import checkpoint
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.nnet.network import Network, param_key
from cxxnet_tpu.parallel import distributed
from cxxnet_tpu.parallel.mesh import (
    MeshSpec, build_mesh, parse_device_spec, parse_mesh_spec)
from cxxnet_tpu.parallel.sharding import shardings_for
from cxxnet_tpu.updater import UpdaterParam, create_updater
from cxxnet_tpu.utils import fault
from cxxnet_tpu.utils.fault import DivergenceError
from cxxnet_tpu.utils.metric import MetricSet


class StagedBatch(NamedTuple):
    """A training batch whose device buffers are already staged under
    the jitted step's in_shardings (stage_batch). update() accepts it
    and skips ALL per-step host work (pad, cast, H2D) - the TPU-first
    analog of the reference's membuffer (iter_mem_buffer-inl.hpp: a
    RAM-resident HOST buffer): a dataset that fits HBM streams zero
    bytes per step, so e2e throughput equals the compute ceiling even
    over a slow host link."""
    data: Any
    extras: Tuple[Any, ...]
    labels: Dict[str, Any]
    mask: Any
    n_examples: int


class StagedChunk(NamedTuple):
    """K staged batches stacked along a leading microstep axis - the
    input of ONE fused dispatch (steps_per_dispatch=K): a single jitted
    lax.scan carries the train state through all K updates, so the
    host pays one dispatch + one readback per chunk instead of K
    (docs/PERFORMANCE.md). Built by stage_chunk from the exact
    per-batch staging pipeline, so the weight trajectory is bitwise
    identical to K streamed updates."""
    data: Any                      # (K, ...) under the chunked sharding
    extras: Tuple[Any, ...]        # each (K, ...)
    labels: Dict[str, Any]         # each (K, ...)
    mask: Any                      # (K, batch)
    n_examples: Tuple[int, ...]    # distinct instances per microstep

    @property
    def n_steps(self) -> int:
        return len(self.n_examples)


def _masked_absmax(x, mask):
    """Valid-row absmax of a tapped activation (f32) - the
    quantize_int8 act-scale arithmetic, shared by the single-batch
    and multi-batch calibration paths so their pinned agreement
    cannot drift: padding rows carry bias/activation garbage at
    depth, so the mask keeps them from widening the frozen range."""
    xf = x.astype(jnp.float32)
    m = jnp.broadcast_to(
        mask.astype(jnp.float32).reshape(
            (-1,) + (1,) * (xf.ndim - 1)), xf.shape)
    return jnp.max(jnp.abs(xf) * m)


def _bf16_cast(data: np.ndarray) -> np.ndarray:
    """f32 -> bf16 on the HOST, fast path via torch (~1.8x faster than
    ml_dtypes on this class of host, bitwise identical round-to-
    nearest-even - measured in round 4; an AlexNet b256 batch is ~40M
    elements, so this cast sits on the e2e critical path)."""
    import ml_dtypes
    try:
        import torch
        t = torch.from_numpy(np.ascontiguousarray(data))
        # AttributeError: torch.uint16 needs torch >= 2.3;
        # RuntimeError: torch built against numpy 1.x under numpy 2.x
        # ("Numpy is not available") - any such host must fall back,
        # not crash the staging path
        return (t.to(torch.bfloat16).view(torch.uint16).numpy()
                .view(ml_dtypes.bfloat16))
    except (ImportError, AttributeError, RuntimeError):
        return data.astype(ml_dtypes.bfloat16)


class NetTrainer:
    """Config-driven trainer for one network."""

    def __init__(self, dev: str = "", cfg: str = ""):
        self.cfg_pairs: List[Tuple[str, str]] = []
        self.net_cfg = NetConfig()
        self.net: Optional[Network] = None
        self.batch_size = 0
        self.update_period = 1
        self.eval_train = 1
        self.seed = 0
        self.silent = 0
        self.compute_dtype = jnp.float32
        self.metric = MetricSet()
        self.train_metric = MetricSet()
        # (node_name or "", node_id or -1) per metric - "" = final node
        self.eval_nodes: List[Tuple[str, int]] = []
        self.mesh_spec = MeshSpec()
        self.mesh: Optional[Mesh] = None
        self.epoch = 0       # update counter (reference epoch_counter)
        self.round = 0
        self._step_counter = 0
        self.state: Optional[Dict[str, Any]] = None
        self._loaded_params = None
        self._loaded_opt = None
        self.save_optimizer = 0
        # ZeRO weight-update sharding stage (docs/parallel.md,
        # arXiv:2004.13336): 0 = fully replicated update; 1 = optimizer
        # state sharded over 'data' (`shard_optimizer=1` stays as the
        # legacy alias); 2 = + gradients reduce-scattered and the
        # update run on each device's shard only, fresh weights
        # all-gathered once per step; 3 = + parameters sharded BETWEEN
        # steps, each weight all-gathered just in time for its layer
        # in the forward pass
        self.zero_stage = 0
        self._zero_src = ""   # config key that last set zero_stage
        self.stage_dtype = ""   # "" = follow compute_dtype
        self.device_augment = 0
        # augment spec, shared config keys with the host iterator
        # pipeline (the CLI feeds every conf pair to every component,
        # reference-style, so these arrive without extra wiring)
        self._daug_cfg: Dict[str, str] = {}
        self._augment_fn = None
        self.remat = 0
        # divergence guard (docs/FAULT_TOLERANCE.md): check_nan=1 adds
        # a jitted all-finite check over loss+params to the train step;
        # a non-finite step is dropped (params rolled back in-jit) and
        # max_bad_rounds CONSECUTIVE bad steps raise DivergenceError
        self.check_nan = 0
        self._check_nan_built = False
        self.max_bad_rounds = 3
        self.bad_rounds = 0        # total dropped steps (this process)
        self._bad_consec = 0
        self._skipped_steps = 0
        self.model_format = "native"
        # fused multi-step dispatch (docs/PERFORMANCE.md): K staged
        # batches scan through ONE jitted executable per chunk. 1 =
        # today's streamed/staged per-step dispatch, byte-for-byte.
        self.steps_per_dispatch = 1
        # eval loop in-flight bound: sync on the tiny metric rows every
        # N batches so at most N batches of input buffers pin HBM
        # (0 = never sync - the whole eval set may stage ahead)
        self.eval_inflight = 8
        # continuous-batching serving knobs (serve/server.py,
        # docs/SERVING.md): largest request bucket (0 = batch_size),
        # fill-or-timeout admission wait, and dispatcher replica count
        self.serve_max_batch = 0
        self.serve_max_wait_ms = 2.0
        self.serve_replicas = 1
        # serving production front (docs/SERVING.md "Serving over
        # HTTP"): serve_port arms the /predict HTTP request path on
        # the attached exposition listener (0 = off, in-process
        # submit only); serve_queue_limit is the hard admission bound
        # in rows (0 = unlimited - submits past it shed with 429 /
        # QueueFullError); serve_deadline_ms the default per-request
        # deadline (0 = none, expired requests drop before dispatch);
        # serve_shed_clear_ms the shed->healthy /healthz hysteresis
        self.serve_port = 0
        self.serve_queue_limit = 0
        self.serve_deadline_ms = 0.0
        self.serve_shed_clear_ms = 1000.0
        # zero-downtime checkpoint hot-swap (docs/SERVING.md "Hot-swap
        # runbook"): a live Server polls swap_watch every swap_poll_ms
        # and swaps weights from any newly published (atomic,
        # checksummed) checkpoint; "" = off
        self.swap_watch = ""
        self.swap_poll_ms = 200.0
        # canaried rollout (docs/SERVING.md "Canary runbook"): with
        # swap_canary_frac in (0, 1] a validated new checkpoint is
        # STAGED, not promoted - that fraction of requests (hashed by
        # trace id) serves the candidate params while a judge thread
        # scores it for swap_canary_window seconds (error/deadline
        # rates vs incumbent + shadow-pair divergence), then
        # auto-promotes or auto-rolls-back. 0 = off (PR-16 immediate
        # swap, byte-identical behavior)
        self.swap_canary_frac = 0.0
        self.swap_canary_window = 10.0
        # connection-level ingress hardening (docs/SERVING.md
        # "Connection limits & drain"; all 0 = off, the PR-16
        # listener): per-connection read deadline so a slow-loris
        # client cannot pin a listener thread, a hard cap on
        # concurrent connections (503 + Retry-After past it, own
        # `serve_conns` health source), and a max request-body size
        # (413 past it, rejected before the body is read)
        self.serve_conn_timeout_ms = 0.0
        self.serve_max_conns = 0
        self.serve_max_body_bytes = 0
        # explicit serving bucket ladder (serve_bucket_ladder = comma
        # ints; None = power-of-two default): Server(trainer) reads
        # it; a tuning-cache serve_ladder fills it as a default under
        # the explicit-keys-win rule (docs/GRAPH_PASSES.md)
        self.serve_ladder: Optional[List[int]] = None
        # graph-level optimizing passes over the NetConfig DAG
        # (nnet/passes.py, docs/GRAPH_PASSES.md): comma list of pass
        # names ("" = off, "all" = every registered pass) plus
        # per-pass `pass_<name> = 0|1` toggles. Graph-stage passes
        # (space_to_depth stamp, autocast plan) apply to the built
        # network; infer-stage passes (dead_layer_elim, fold_conv_bn)
        # apply only to the clone the inference executables compile
        # from - training trajectories and checkpoints are untouched
        self.graph_passes = ""
        self._pass_toggles: Dict[str, int] = {}
        self._pipeline = None
        self._graph_dtype_plan = None
        # fold_conv_bn calibration batches: 1 = the historic
        # single-batch freeze (bitwise-pinned); N > 1 averages moments
        # over N calibration batches (calibrate_graph_passes with a
        # batch sequence - main.py's pass_calibration_iter feeds it)
        self.pass_calibration_batches = 1
        # fold_conv_bn calibration state: bn param key -> (mean,
        # rstd) frozen at calibration; epoch keys the per-node infer
        # executable cache so a recalibration rebuilds cleanly
        self._fold_stats: Optional[Dict[str, Any]] = None
        # quantize_int8 calibration state: eligible conv/fullc param
        # key -> activation absmax from the same calibration sweep
        # (the per-tensor act scale is absmax/127; the per-channel
        # weight scales freeze later, per transformed infer graph -
        # _fill_quant_scales). Shares the fold epoch/eviction.
        self._quant_stats: Optional[Dict[str, float]] = None
        self._fold_epoch = 0
        self._infer_graph_cache: Dict[Any, Any] = {}
        # dispatch-site fingerprint cache (telemetry/flight.py): one
        # executable-registry registration per compiled program shape;
        # steady-state dispatches pay a dict hit
        self._flight_fps: Dict[Any, str] = {}
        # TVM-style tuning cache (nnet/tuning.py, tools/autotune.py):
        # tuned knob values are DEFAULTS - explicitly-set config keys
        # always win (tracked per key at set_param time)
        self.tuning_cache = ""
        self._explicit_tunables: set = set()
        self.profile = 0
        self.profile_dir = ""
        self.trace_round = 1
        self._epoch_base = 0
        self.profiler = None
        # telemetry_steps=0 opts OUT of per-step instrumentation while
        # keeping event logging: per-step timing costs a device sync +
        # loss readback per update (honest step times), which kills the
        # async-dispatch overlap - event-only production runs can keep
        # checkpoint/fault telemetry without paying it
        self.telemetry_steps = 1
        # per-step telemetry armed? captured at _build_net so the
        # per-step branch is one attribute check (and consistent with
        # what the compiled run actually instruments)
        self._tel_steps = False
        if dev:
            self.set_param("dev", dev)
        if cfg:
            from cxxnet_tpu.utils.config import parse_config_string
            for k, v in parse_config_string(cfg):
                self.set_param(k, v)

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        if name == "dev":
            self.mesh_spec.device_indices = parse_device_spec(val)
        if name == "mesh":
            self.mesh_spec.axes = parse_mesh_spec(val)
        if name == "batch_size":
            self.batch_size = int(val)
        if name == "update_period":
            self.update_period = int(val)
        if name == "eval_train":
            self.eval_train = int(val)
        if name == "seed":
            self.seed = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "save_optimizer":
            self.save_optimizer = int(val)
        if name == "zero_stage":
            self._set_zero_stage(name, int(val))
        if name == "shard_optimizer":
            # legacy alias: ZeRO-1, optimizer state only
            self._set_zero_stage(name, 1 if int(val) else 0)
        if name == "update_on_server" and int(val):
            # reference knob (nnet_ps_server.cpp): run the updater on
            # the PS instead of replicating it per worker. The TPU
            # analog is sharding the optimizer state (docs/parallel.md).
            # Enable-only: an explicit =0 (the reference default in
            # non-PS configs) must not clobber shard_optimizer=1.
            self._set_zero_stage(name, max(1, self.zero_stage))
        if name == "remat":
            self.remat = int(val)
        if name == "check_nan":
            self.check_nan = int(val)
        if name == "max_bad_rounds":
            self.max_bad_rounds = int(val)
        if name == "stage_dtype":
            if val not in ("", "float32", "bfloat16"):
                raise ValueError("stage_dtype must be float32 or bfloat16")
            self.stage_dtype = val
        if name == "device_augment":
            self.device_augment = int(val)
        if name in ("image_mean", "mean_value", "scale", "divideby",
                    "rand_crop", "rand_mirror", "mirror",
                    "crop_y_start", "crop_x_start",
                    "max_random_contrast", "max_random_illumination"):
            # crop/mirror/mean/scale spec for device_augment=1 (same
            # key names the host AugmentIterator consumes; ignored
            # unless device_augment is set). divideby is the
            # reciprocal-scale alias, like augment.py's handler.
            if name == "divideby":
                name, val = "scale", str(1.0 / float(val))
            self._daug_cfg[name] = val
        if name == "model_format":
            if val not in ("native", "cxxnet"):
                raise ValueError("model_format must be native or cxxnet")
            self.model_format = val
        if name == "steps_per_dispatch":
            if int(val) < 1:
                raise ValueError("steps_per_dispatch must be >= 1")
            self.steps_per_dispatch = int(val)
        if name == "eval_inflight":
            if int(val) < 0:
                raise ValueError("eval_inflight must be >= 0")
            self.eval_inflight = int(val)
        if name == "serve_max_batch":
            if int(val) < 0:
                raise ValueError("serve_max_batch must be >= 0")
            self.serve_max_batch = int(val)
        if name == "serve_max_wait_ms":
            if float(val) < 0:
                raise ValueError("serve_max_wait_ms must be >= 0")
            self.serve_max_wait_ms = float(val)
        if name == "serve_replicas":
            if int(val) < 1:
                raise ValueError("serve_replicas must be >= 1")
            self.serve_replicas = int(val)
        if name == "serve_port":
            if int(val) < 0 or int(val) > 65535:
                raise ValueError("serve_port must be in [0, 65535]")
            self.serve_port = int(val)
        if name == "serve_queue_limit":
            if int(val) < 0:
                raise ValueError("serve_queue_limit must be >= 0")
            self.serve_queue_limit = int(val)
        if name == "serve_deadline_ms":
            if float(val) < 0:
                raise ValueError("serve_deadline_ms must be >= 0")
            self.serve_deadline_ms = float(val)
        if name == "serve_shed_clear_ms":
            if float(val) < 0:
                raise ValueError("serve_shed_clear_ms must be >= 0")
            self.serve_shed_clear_ms = float(val)
        if name == "swap_watch":
            self.swap_watch = val
        if name == "swap_poll_ms":
            if float(val) <= 0:
                raise ValueError("swap_poll_ms must be > 0")
            self.swap_poll_ms = float(val)
        if name == "swap_canary_frac":
            if not 0.0 <= float(val) <= 1.0:
                raise ValueError("swap_canary_frac must be in [0, 1]")
            self.swap_canary_frac = float(val)
        if name == "swap_canary_window":
            if float(val) <= 0:
                raise ValueError("swap_canary_window must be > 0")
            self.swap_canary_window = float(val)
        if name == "serve_conn_timeout_ms":
            if float(val) < 0:
                raise ValueError("serve_conn_timeout_ms must be >= 0")
            self.serve_conn_timeout_ms = float(val)
        if name == "serve_max_conns":
            if int(val) < 0:
                raise ValueError("serve_max_conns must be >= 0")
            self.serve_max_conns = int(val)
        if name == "serve_max_body_bytes":
            if int(val) < 0:
                raise ValueError("serve_max_body_bytes must be >= 0")
            self.serve_max_body_bytes = int(val)
        if name == "serve_bucket_ladder":
            rungs = [int(t) for t in val.split(",") if t.strip()]
            if (not rungs or any(r < 1 for r in rungs)
                    or sorted(set(rungs)) != rungs):
                raise ValueError(
                    "serve_bucket_ladder must be a strictly "
                    f"increasing comma list of positive ints, got "
                    f"{val!r}")
            self.serve_ladder = rungs
        if name == "graph_passes":
            self.graph_passes = val
        if name == "pass_calibration_batches":
            if int(val) < 1:
                raise ValueError(
                    "pass_calibration_batches must be >= 1")
            self.pass_calibration_batches = int(val)
        if (name.startswith("pass_")
                and name not in ("pass_calibration_batches",
                                 "pass_calibration_iter")):
            # per-pass toggles layered over graph_passes (membership
            # add/remove): prefix-form so a new @register_pass needs
            # no handler edit here; the name is validated against the
            # pass registry at _build_net with did-you-mean.
            # pass_calibration_* are calibration knobs, not toggles
            # (pass_calibration_iter is consumed by main.LearnTask)
            self._pass_toggles[name[len("pass_"):]] = int(val)
        if name == "tuning_cache":
            self.tuning_cache = val
        if name in ("steps_per_dispatch", "serve_max_batch",
                    "stage_dtype", "serve_bucket_ladder"):
            # explicit config keys beat tuning-cache defaults
            self._explicit_tunables.add(name)
        if name == "profile":
            self.profile = int(val)
        if name == "profile_dir":
            self.profile_dir = val
            self.profile = max(self.profile, 1)
        if name == "trace_round":
            # which profiled round profile_dir traces (1-based; round 1
            # is compile-dominated, steady state wants >= 2)
            self.trace_round = int(val)
        if name == "telemetry_steps":
            self.telemetry_steps = int(val)
        if name == "dtype":
            self.compute_dtype = {"float32": jnp.float32,
                                  "bfloat16": jnp.bfloat16}[val]
        if name == "compile_cache" and val:
            # persistent XLA compilation cache: the first AlexNet-sized
            # TPU compile costs 20-40 s; with this set, re-runs (resume,
            # pred, eval-only) hit the on-disk cache instead. No
            # reference analog (CUDA kernels are precompiled; XLA's
            # compile-at-trace model creates the need). NOTE: the cache
            # is PROCESS-GLOBAL jax state (one cache per process, last
            # writer wins) - not per-trainer.
            from cxxnet_tpu.utils.platform import \
                set_compilation_cache_dir
            set_compilation_cache_dir(val)
        if name.startswith("metric"):
            import re
            m = re.match(r"^metric\[([^,\]]+),([^\]]+)\]$", name)
            if m:
                self.metric.add_metric(val, m.group(1))
                self.train_metric.add_metric(val, m.group(1))
                self.eval_nodes.append((m.group(2), 0))
            elif name == "metric":
                self.metric.add_metric(val, "label")
                self.train_metric.add_metric(val, "label")
                self.eval_nodes.append(("", -1))
        self.cfg_pairs.append((name, val))

    def _set_zero_stage(self, key: str, stage: int) -> None:
        """zero_stage with alias handling: `shard_optimizer` /
        `update_on_server` are legacy spellings of stage <= 1.
        Last-writer-wins holds only WITHIN one key - an alias arriving
        after an explicit `zero_stage = 2|3` must not silently
        downgrade the run to ZeRO-1; it warns and is ignored."""
        if not 0 <= stage <= 3:
            raise ValueError("zero_stage must be 0, 1, 2 or 3")
        if key != "zero_stage" and self._zero_src == "zero_stage":
            if stage != self.zero_stage:
                telemetry.stderr(
                    f"warning: {key} (a zero_stage={stage} alias) "
                    f"conflicts with the explicit zero_stage="
                    f"{self.zero_stage}; keeping zero_stage="
                    f"{self.zero_stage}\n",
                    event_kind="config", type="zero_stage_conflict",
                    key=key, requested=stage, kept=self.zero_stage)
            # agreeing alias: the explicit setting stays authoritative
            return
        self.zero_stage = stage
        self._zero_src = key

    @property
    def shard_optimizer(self) -> int:
        """Legacy view of the ZeRO knob: any stage shards the
        optimizer state (readers predate zero_stage)."""
        return int(self.zero_stage >= 1)

    # ------------------------------------------------------------------
    # initialization
    # ------------------------------------------------------------------
    def init_model(self) -> None:
        if (self.stage_dtype == "bfloat16"
                and self.compute_dtype == jnp.float32):
            # would silently stage f32 anyway (_host_input): reject the
            # no-op combination instead of hiding a misconfiguration
            raise ValueError(
                "stage_dtype=bfloat16 requires dtype=bfloat16 "
                "(f32 compute always stages f32)")
        # param_server=dist -> join the multi-controller job before any
        # device is touched (replaces InitParamServer,
        # nnet_impl-inl.hpp:376-390)
        distributed.init_from_config(self.cfg_pairs)
        self.net_cfg.configure(self.cfg_pairs)
        self._build_net()
        key = jax.random.PRNGKey(self.seed)
        params = self.net.init_params(key)
        self._init_state(params)
        self.epoch = 0
        self._epoch_base = 0
        self._step_counter = 0
        self._skipped_steps = 0
        self._bad_consec = 0

    def _build_net(self) -> None:
        if self.batch_size <= 0:
            raise ValueError("batch_size must be set")
        self._apply_tuning_cache()
        # graph-pass pipeline (nnet/passes.py): graph-stage passes
        # stamp the live NetConfig (layer configs / dtype plan only -
        # structure, and with it the checkpoint format, is untouched);
        # infer-stage passes run lazily per requested node in
        # _build_infer_graph. An empty graph_passes config builds an
        # empty pipeline and every path below is byte-identical to
        # the pass-less trainer.
        from cxxnet_tpu.nnet.passes import (
            GraphModule, PassPipeline)
        self._pipeline = PassPipeline.from_config(self.graph_passes,
                                                  self._pass_toggles)
        self._graph_dtype_plan = None
        self._fold_stats = None
        self._quant_stats = None
        self._fold_epoch = 0
        self._infer_graph_cache = {}
        # fold/quant sites depend only on the graph structure: matched
        # ONCE here, not per inference batch (passes_need_calibration
        # sits on the predict hot path)
        from cxxnet_tpu.nnet.passes import (
            find_fold_sites, find_quant_sites)
        self._fold_sites = (find_fold_sites(self.net_cfg)
                            if self._pipeline.has("fold_conv_bn")
                            else [])
        self._quant_sites = (find_quant_sites(self.net_cfg)
                             if self._pipeline.has("quantize_int8")
                             else [])
        if self._pipeline.graph_passes:
            gm = GraphModule.from_net_config(
                self.net_cfg, self.batch_size, self.compute_dtype)
            gm = self._pipeline.run_graph(gm)
            self._graph_dtype_plan = gm.dtype_plan or None
            if not self.silent and gm.log:
                for line in gm.log:
                    telemetry.stdout(f"graph_passes: {line}")
        self.net = Network(self.net_cfg, self.batch_size)
        self.net.dtype_plan = self._graph_dtype_plan
        if not self.silent:
            for i, s in enumerate(self.net.node_shapes):
                telemetry.stdout(
                    f"node[{self.net_cfg.node_names[i]}].shape: "
                    f"{s[0]},{s[1]},{s[2]},{s[3]}")
        self.mesh = build_mesh(self.mesh_spec, self.batch_size)
        self._local_rows = self._compute_local_rows()
        # tensor-parallel parameter shardings over the 'model' mesh axis
        # (all-replicated on a pure-data mesh - parallel/sharding.py)
        self._pshard = shardings_for(self.mesh, self.net)
        self._resolve_eval_nodes()
        self._build_updaters()
        self._compile()
        # telemetry reuses the profiler's per-round accumulator for its
        # round records even when profile=0 (summaries print only under
        # profile=1, so the profile-less stderr stays untouched)
        self._tel_steps = (bool(self.telemetry_steps)
                           and telemetry.get().enabled)
        if (self.profile or self._tel_steps) and self.profiler is None:
            from cxxnet_tpu.utils.profiler import StepProfiler
            self.profiler = StepProfiler(self.profile_dir,
                                         self.trace_round)

    def _resolve_eval_nodes(self) -> None:
        resolved = []
        for name, _ in self.eval_nodes:
            if name == "":
                resolved.append(("", self.net_cfg.num_nodes - 1))
            else:
                resolved.append((name, self.net.node_index(name)))
        self.eval_nodes = resolved

    def _build_updaters(self) -> None:
        """One Updater per weight tensor, configured with defcfg +
        layercfg[i] under its tag (neural_net-inl.hpp:177-204)."""
        self.updaters: Dict[str, Dict[str, Any]] = {}
        utype = self.net_cfg.updater_type
        for idx, info in enumerate(self.net_cfg.layers):
            if info.is_shared:
                continue
            tags = self.net.layer_objs[idx].param_tags()
            if not tags:
                continue
            key = param_key(self.net_cfg, idx)
            self.updaters[key] = {}
            for pname, tag in tags.items():
                up = UpdaterParam(tag)
                kwargs = {}
                for k, v in (self.net_cfg.defcfg
                             + self.net_cfg.layercfg[idx]):
                    up.set_param(k, v)
                    if utype == "adam" and k == "beta1":
                        kwargs["decay1"] = float(v)
                    if utype == "adam" and k == "beta2":
                        kwargs["decay2"] = float(v)
                self.updaters[key][pname] = create_updater(utype, up,
                                                           **kwargs)

    def _retire_calibration_state(self) -> None:
        """Weights changed (set_weight / copy_model_from / checkpoint
        reload): any frozen fold statistics or quant scales describe
        the OLD activations/weight ranges - drop them AND retire the
        executables compiled against them (bumping the epoch +
        evicting, same as a recalibration), so an infer_rows/Server
        built afterwards can never silently dispatch an executable
        frozen with the previous model's constants. Folded weights
        and the int8 values themselves are live functions of the
        params argument; only the baked mean/rstd and act/weight
        scales go stale - the next inference recalibrates them."""
        if (self._fold_stats is not None
                or self._quant_stats is not None):
            self._fold_stats = None
            self._quant_stats = None
            self._fold_epoch += 1
            self._evict_stale_infer_caches()

    def _init_state(self, params) -> None:
        self._retire_calibration_state()
        ustate = {
            lk: {pn: up.init_state(params[lk][pn])
                 for pn, up in d.items() if pn in params.get(lk, {})}
            for lk, d in self.updaters.items()}
        accum = jax.tree.map(jnp.zeros_like, params)
        state = {
            "params": params,
            "ustate": ustate,
            "accum": accum,
            "count": jnp.zeros((), jnp.int32),
            "epoch": jnp.asarray(self.epoch, jnp.int32),
            # on-device train-metric accumulator: one (sum, comp,
            # count) row per configured metric; `comp` is the Kahan
            # compensation term so a long round's f32 sum doesn't
            # drift (the eval path avoids this with per-batch host f64
            # reduction; the train path cannot read back per step)
            "tmetric": jnp.zeros((len(self.train_metric), 3), jnp.float32),
        }
        if self._loaded_opt is not None:
            state["ustate"] = jax.tree.map(
                lambda a: jnp.asarray(a), self._loaded_opt)
            self._loaded_opt = None
        # prefix pytree: one sharding per weight covers its updater-state
        # dict too; same tree drives the jitted steps' in/out_shardings
        if jax.process_count() == 1:
            self.state = jax.device_put(state, self._state_shardings)
        else:
            # multi-controller: every process holds the full value of
            # each state leaf; put_global_full materializes only the
            # locally-owned shards (handles sharded optimizer state)
            full = self._expand_prefix(self._state_shardings, state)
            self.state = jax.tree.map(distributed.put_global_full, state,
                                      full)

    @staticmethod
    def _expand_prefix(prefix, tree):
        """Expand a sharding prefix pytree to a full per-leaf tree."""
        return jax.tree.map(
            lambda p, sub: jax.tree.map(lambda _: p, sub),
            prefix, tree,
            is_leaf=lambda x: isinstance(x, NamedSharding))

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------
    @property
    def _replicated(self):
        return NamedSharding(self.mesh, P())

    @property
    def _batch_sharded(self):
        # a mesh without a 'data' axis (e.g. pure pipeline parallelism,
        # mesh=pipe:4) replicates the batch
        d = "data" if "data" in self.mesh.axis_names else None
        return NamedSharding(self.mesh, P(d) if d else P())

    @property
    def _data_sharded(self):
        """Input-tensor sharding: batch over 'data' and, for sequence
        models on a mesh with a 'seq' axis, the sequence (y) dim over
        'seq' (parallel/ring.py). Labels/mask stay batch-only."""
        d = "data" if "data" in self.mesh.axis_names else None
        nseq = self.mesh.shape.get("seq", 1)
        if nseq > 1 and self.net_cfg.input_shape[1] % nseq == 0:
            return NamedSharding(self.mesh, P(d, None, "seq", None))
        return self._batch_sharded

    def _label_fields(self, label: np.ndarray) -> Dict[str, np.ndarray]:
        fields = {}
        for fname, idx in self.net_cfg.label_name_map.items():
            a, b = self.net_cfg.label_range[idx]
            fields[fname] = label[:, a:b]
        return fields

    def _apply_tuning_cache(self) -> None:
        """Apply tuned knob defaults from `tuning_cache =` (nnet/
        tuning.py): only knobs the config never set explicitly, and
        only values applicable to this trainer (an inapplicable
        tuned value is skipped, never an error - a shared cache file
        must not break a valid config). Schema-v2 caches additionally
        carry a PER-LAYER plan (s2d per conv, layer_dtype feeding the
        autocast pass) stamped onto the layer configs here - a key
        the config already names for that layer (or globally in
        defcfg) always wins - and a serve bucket ladder picked up
        unless `serve_bucket_ladder =` was set."""
        if not self.tuning_cache:
            return
        from cxxnet_tpu.nnet import tuning
        entry = tuning.platform_entry(self.tuning_cache)
        knobs = {k: str(v) for k, v in entry.get("knobs", {}).items()}
        explicit = self._explicit_tunables
        applied = {}
        # tuning.int_knob is THE shared apply rule (explicit keys
        # win, malformed values skip) - main.LearnTask consumes the
        # same cache through the same helper
        v = tuning.int_knob(knobs, "steps_per_dispatch", explicit, 1)
        if v is not None:
            self.steps_per_dispatch = applied["steps_per_dispatch"] = v
        v = tuning.int_knob(knobs, "serve_max_batch", explicit, 0)
        if v is not None:
            self.serve_max_batch = applied["serve_max_batch"] = v
        if ("stage_dtype" in knobs
                and "stage_dtype" not in explicit):
            val = knobs["stage_dtype"]
            if (val in ("", "float32", "bfloat16")
                    and not (val == "bfloat16"
                             and self.compute_dtype
                             == jnp.float32)):
                self.stage_dtype = applied["stage_dtype"] = val
        plan_applied = self._apply_layer_plan(entry.get("layers") or {})
        if plan_applied:
            applied["layers"] = plan_applied
        ladder = entry.get("serve_ladder")
        if (ladder and self.serve_ladder is None
                and "serve_bucket_ladder" not in explicit):
            try:
                rungs = sorted({int(b) for b in ladder if int(b) >= 1})
            except (TypeError, ValueError):
                rungs = []
            if rungs:
                self.serve_ladder = rungs
                applied["serve_ladder"] = rungs
        if applied:
            telemetry.event("tuning", op="apply",
                            cache=self.tuning_cache, **applied)

    def _apply_layer_plan(self, plan: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp a v2 cache's per-layer plan onto the layer configs
        (the per-layer analog of the scalar knob pickup): skip
        unknown layers, inapplicable knobs (s2d on a non-conv),
        malformed values, and any key the config names for that
        layer or globally - explicit keys always win. Stamps go into
        net_cfg.layercfg, which NetConfig.configure rebuilds from
        the user's pairs on every (re)configure, so they never
        accumulate or masquerade as explicit keys."""
        applied: Dict[str, Any] = {}
        valid = {"space_to_depth": ("0", "1", "auto"),
                 "layer_dtype": ("float32", "bfloat16"),
                 "layer_quant": ("int8", "float")}
        for lname, kv in plan.items():
            idx = self.net_cfg.layer_name_map.get(lname)
            if idx is None or not isinstance(kv, dict):
                continue
            info = self.net_cfg.layers[idx]
            for k, v in kv.items():
                v = str(v)
                if k not in valid or v not in valid[k]:
                    continue
                if k == "space_to_depth" and info.type_name != "conv":
                    continue
                if (k == "layer_quant"
                        and info.type_name not in ("conv", "fullc")):
                    continue  # only layers with an int8 kernel route
                if any(kk == k for kk, _ in
                       (self.net_cfg.defcfg
                        + self.net_cfg.layercfg[idx])):
                    continue  # explicitly configured: the user wins
                self.net_cfg.layercfg[idx].append((k, v))
                applied.setdefault(lname, {})[k] = v
        return applied

    def _cast(self, tree):
        if (self.compute_dtype == jnp.float32
                or self._graph_dtype_plan is not None):
            # an autocast dtype plan owns the casts per layer
            # (Network.forward); a wholesale bf16 pre-cast here would
            # round the f32-stamped layers' inputs before they ever
            # ran
            return tree
        return jax.tree.map(
            lambda a: a.astype(self.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def _host_input(self, data: np.ndarray) -> np.ndarray:
        """Input image batch as staged to device.

        Under dtype=bfloat16 the default stages bf16: the cast happens
        on the HOST, halving the H2D transfer (the step's _cast then
        no-ops; labels/mask stay f32). `stage_dtype = float32` flips
        the trade: stage f32 (2x bytes) and let the step's in-jit
        _cast do it on DEVICE, fused into the first conv - wins when
        the host CPU, not the link, is the staging bottleneck (an
        AlexNet b256 host cast is ~40M elements, tens of ms
        single-threaded; bench.py measures both as e2e variants)."""
        if self.device_augment and data.dtype == np.uint8:
            # raw pixels stage as uint8: 1/4 the f32 H2D bytes and
            # ZERO host arithmetic; the in-step augment casts on device
            return data
        if (self.compute_dtype == jnp.float32
                or self.stage_dtype == "float32"
                or (self.device_augment and self.stage_dtype != "bfloat16")):
            # device_augment defaults to f32 staging (integer pixel
            # values; no host cast) - stage_dtype=bfloat16 opts into
            # the halved transfer at host-cast cost (lossless for
            # integer-valued pixels <= 256). copy=False: an
            # already-f32 batch must not pay a 150 MB memcpy
            return data.astype(np.float32, copy=False)
        return _bf16_cast(data)

    def _compile(self) -> None:
        net = self.net
        # rebuilt executables get re-registered on first dispatch (the
        # registry is idempotent per fingerprint; shapes key the cache)
        self._flight_fps = {}
        # ZeRO effective stage for THIS mesh (docs/parallel.md): stages
        # >= 2 need a real 'data' axis to cut over; a single-device or
        # data-less mesh compiles the replicated stage-0 program (the
        # same degradation rule zero1_shardings applies to stage 1)
        dsize = self.mesh.shape.get("data", 1)
        zrun = self.zero_stage if dsize > 1 else min(self.zero_stage, 1)
        if zrun >= 2:
            extra_axes = [a for a in self.mesh.axis_names
                          if a not in ("data", "model")
                          and self.mesh.shape[a] > 1]
            if extra_axes:
                raise ValueError(
                    f"zero_stage={self.zero_stage} composes with "
                    f"'data'/'model' mesh axes only; axes {extra_axes} "
                    "drive layers that shard_map over the full mesh "
                    "(ring/ulysses attention, pipelined stacks, moe), "
                    "which cannot nest inside the manual-'data' ZeRO "
                    "region - use zero_stage<=1 on seq/pipe/expert "
                    "meshes")
            for lk, d in self.updaters.items():
                for pn, up in d.items():
                    if not getattr(up, "zero_shardable", False):
                        raise ValueError(
                            f"updater '{up.kind or type(up).__name__}' "
                            f"({lk}.{pn}) declares zero_shardable="
                            "False (its math reduces over the full "
                            "tensor, so a per-shard update computes "
                            "different results); use zero_stage<=1")
            for idx, _info in enumerate(self.net_cfg.layers):
                lay = self.net.layer_objs[idx]
                if (getattr(lay, "type_name", "") == "batch_norm"
                        and getattr(lay, "global_stats", 0)):
                    raise ValueError(
                        "batch_norm global_stats=1 (sync-BN) needs "
                        "global-batch statistics, but zero_stage>=2 "
                        "runs the forward per data shard (per-shard "
                        "stats, the reference's per-GPU semantics); "
                        "use zero_stage<=1 with sync-BN")
        self._zero_run = zrun
        eval_node_ids = sorted({nid for _, nid in self.eval_nodes})
        scale = 1.0 / (self.batch_size * self.update_period)
        update_period = self.update_period
        updaters = self.updaters
        # train metrics accumulate on device inside the step (the
        # reference computes them from the same forward pass,
        # nnet_impl-inl.hpp:174-180; a per-step host readback here would
        # serialize the device - metric_jit.py)
        from cxxnet_tpu.utils import metric_jit
        metric_specs = self.train_metric.specs
        metric_fns = [metric_jit.create_step_fn(name)
                      for name, _ in metric_specs]
        eval_train = bool(self.eval_train and metric_specs)
        # captured at build time: the jitted step's return arity (2- vs
        # 3-tuple) is baked into the compiled function, so update()
        # must branch on what was BUILT, not on a check_nan later
        # toggled through set_param
        check_nan = self._check_nan_built = bool(self.check_nan)

        def metric_rows(outs, labels, mask, rng, base):
            """Stacked (n_metrics, 2) device rows of (sum, count); the
            single definition both the train and eval steps fold in."""
            rows = []
            for i, ((_, field), fn, (_, nid)) in enumerate(
                    zip(metric_specs, metric_fns, self.eval_nodes)):
                pred = outs[nid].reshape(outs[nid].shape[0], -1)
                s, c = fn(pred, labels[field], mask,
                          jax.random.fold_in(rng, base + i))
                rows.append(jnp.stack([s, c]))
            return jnp.stack(rows)

        from cxxnet_tpu.layers.base import active_step
        from cxxnet_tpu.parallel.mesh import active_mesh

        daug = None
        if self.device_augment:
            from cxxnet_tpu.ops.augment_jit import make_device_augment
            dc = self._daug_cfg
            mean_loader = None
            if dc.get("image_mean"):
                def mean_loader(path=dc["image_mean"]):
                    # lazy: called at TRACE time (first update), after
                    # the iterator's init had its chance to create the
                    # mean file on a fresh dataset
                    if not os.path.exists(path):
                        raise FileNotFoundError(
                            f"device_augment: mean image '{path}' not "
                            "found; run the data pipeline once (the "
                            "iterator creates it) or point image_mean "
                            "at an existing mean file")
                    from cxxnet_tpu.io.augment import load_mean_image
                    return load_mean_image(path)
            mean_values = None
            if dc.get("mean_value"):
                b_, g_, r_ = (float(t)
                              for t in dc["mean_value"].split(","))
                mean_values = (b_, g_, r_)
            daug = make_device_augment(
                tuple(self.net_cfg.input_shape),
                mean_loader=mean_loader, mean_values=mean_values,
                scale=float(dc.get("scale", "1.0")),
                rand_crop=int(dc.get("rand_crop", "0")),
                rand_mirror=int(dc.get("rand_mirror", "0")),
                mirror=int(dc.get("mirror", "0")),
                crop_y_start=int(dc.get("crop_y_start", "-1")),
                crop_x_start=int(dc.get("crop_x_start", "-1")),
                max_random_contrast=float(
                    dc.get("max_random_contrast", "0")),
                max_random_illumination=float(
                    dc.get("max_random_illumination", "0")))
        self._augment_fn = daug

        # zero_stage>=2 traces the TRAIN forward inside a manual-'data'
        # shard_map region (per-device values): the mesh-keyed op
        # routes (per-shard batch_norm, fullc_gather, Pallas device
        # routes) must decline there - their plain per-device fallback
        # IS the right semantics inside the region (batch_norm's local
        # stats are bitwise the stats its shard_map route computes) -
        # so the region binds no active mesh. Eval keeps self.mesh.
        fwd_mesh = None if zrun >= 2 else self.mesh

        def loss_fn(params, data, extras, labels, mask, rng, step):
            cparams = self._cast(params)
            if daug is not None:
                data = daug(data, jax.random.fold_in(rng, 0xA6), True)
            inputs = {0: self._cast(data)}
            for i, e in enumerate(extras):
                inputs[1 + i] = self._cast(e)
            with active_mesh(fwd_mesh), active_step(step):
                values, loss = net.forward(
                    cparams, inputs, train=True, rng=rng,
                    labels=labels, mask=mask)
            outs = {nid: values[nid].astype(jnp.float32)
                    for nid in eval_node_ids}
            return loss.astype(jnp.float32) * scale, outs

        if self.remat:
            # remat=1: recompute forward activations in the backward
            # pass instead of keeping them in HBM - trades FLOPs for
            # memory, the standard lever for big batches / deep nets on
            # TPU (the reference's analog is temp_col_max chunking,
            # convolution_layer-inl.hpp:189-204, which bounds im2col
            # scratch the same way)
            loss_fn = jax.checkpoint(loss_fn)

        # ZeRO-2/3 sharding trees (parallel/sharding.py): the per-weight
        # 'data' cut shared by optimizer state, gradients/accumulator
        # and (stage 3) the parameters themselves
        zdims = zshard = scatter_specs = gather_specs = None
        zshapes = None
        if zrun >= 2:
            from cxxnet_tpu.parallel.sharding import (
                zero2_shardings, zero_partition_dims, zero_region_specs)
            # one abstract init trace shared by every zero helper (it
            # scales with the model, and ZeRO targets big models)
            zshapes = jax.eval_shape(net.init_params,
                                     jax.random.PRNGKey(0))
            zdims = zero_partition_dims(self.mesh, self.net,
                                        self._pshard, zshapes)
            zshard = zero2_shardings(self.mesh, self.net, self._pshard,
                                     zshapes, zdims)
            scatter_specs, gather_specs = zero_region_specs(
                self.mesh, self.net, self._pshard, zshapes, zdims)

        grad_inner = jax.value_and_grad(loss_fn, has_aux=True)
        grad_and_loss = grad_inner
        if zrun >= 2:
            # The cross-replica weight-update sharding recipe
            # (arXiv:2004.13336) needs the gradients in UNREDUCED
            # per-device form - GSPMD only exposes them post-allreduce -
            # so the fwd/bwd runs manual over 'data' (shard_map; every
            # other mesh axis stays auto, i.e. the tensor-parallel
            # 'model' placement keeps riding GSPMD) and ends in an
            # explicit psum_scatter: the literal reduce-scatter the
            # jaxpr audit asserts on. Everything after (accumulate,
            # updater, counters, guard) stays plain GSPMD on the
            # zero-sharded global values.
            from cxxnet_tpu.parallel.sharding import shard_map_manual

            def _scatter(grads):
                # reduce-scatter eligible weights onto their zero cut;
                # ineligible ones psum (replicated update, stage-0
                # semantics for that tensor)
                return {
                    lk: {pn: (lax.psum(g, "data")
                              if zdims[lk][pn] is None else
                              lax.psum_scatter(
                                  g, "data",
                                  scatter_dimension=zdims[lk][pn],
                                  tiled=True))
                         for pn, g in d.items()}
                    for lk, d in grads.items()}

            def zero_region(params, data, extras, labels, mask, rng,
                            step):
                # per-device RNG stream: random layers (dropout, device
                # augment) must not draw the same local pattern on
                # every data shard
                rng = jax.random.fold_in(rng, lax.axis_index("data"))
                (loss, outs), grads = grad_inner(
                    params, data, extras, labels, mask, rng, step)
                return (lax.psum(loss, "data"), outs), _scatter(grads)

            dspec = P("data")
            # params enter replicated-over-'data' (P()): under stage 3
            # they LIVE on their zero cut between steps, so GSPMD
            # inserts one all-gather per weight at the region boundary
            # - the just-in-time gather, one op per layer's weight,
            # placed by the scheduler (a manual 'data' in_spec on a
            # tensor that also rides the auto 'model' axis trips an
            # XLA manual-subgroup partitioner check in this jax)
            param_in = gather_specs
            grad_and_loss = shard_map_manual(
                zero_region, self.mesh, ("data",),
                in_specs=(param_in, dspec,
                          (dspec,) * self.net_cfg.extra_data_num,
                          {f: dspec
                           for f in self.net_cfg.label_name_map},
                          dspec, P(), P()),
                out_specs=((P(), {nid: dspec
                                  for nid in eval_node_ids}),
                           scatter_specs))

        def train_step(state, data, extras, labels, mask, rng):
            # per-forward training-step counter (updates so far) for
            # step-dependent layers (insanity anneal)
            step = state["epoch"] * update_period + state["count"]
            (loss, outs), grads = grad_and_loss(
                state["params"], data, extras, labels, mask, rng, step)
            if update_period == 1:
                # state["accum"] is invariantly all-zero between
                # updates; adding it would stream the whole gradient-
                # sized zero tree through HBM every step for nothing
                accum = grads
            else:
                accum = jax.tree.map(jnp.add, state["accum"], grads)
            count = state["count"] + 1
            do_update = count >= update_period

            def apply_updates(args):
                params, ustate, accum = args
                new_params = jax.tree.map(lambda x: x, params)
                new_ustate = jax.tree.map(lambda x: x, ustate)
                for lk, d in updaters.items():
                    for pn, up in d.items():
                        if lk not in params or pn not in params[lk]:
                            continue
                        w = params[lk][pn]
                        if zrun == 2 and zdims[lk][pn] is not None:
                            # slice the replicated weight down to this
                            # device's zero shard (no comm - a local
                            # dynamic-slice): the updater then runs at
                            # 1/N FLOPs on shard-shaped state/grad, and
                            # the params out_sharding all-gathers the
                            # fresh weights once per update. Stage 3
                            # skips the slice - params arrive sharded.
                            w = lax.with_sharding_constraint(
                                w, zshard[lk][pn])
                        st, w = up.apply(ustate[lk][pn], w,
                                         accum[lk][pn], state["epoch"])
                        new_params[lk][pn] = w
                        new_ustate[lk][pn] = st
                # graftlint: disable=GL007 the zero tree inherits accum's zero-stage sharding via donation/out_shardings
                zero = jax.tree.map(jnp.zeros_like, accum)
                return new_params, new_ustate, zero

            if update_period == 1:
                # do_update is tautologically true every step; a
                # lax.cond here is not just dead weight - the
                # conditional boundary blocks XLA from fusing the
                # optimizer into the backward fusions (measured ~6% of
                # AlexNet b256 device step time as a standalone
                # %conditional in the round-4 on-chip profile)
                params, ustate, accum = apply_updates(
                    (state["params"], state["ustate"], accum))
            else:
                params, ustate, accum = lax.cond(
                    do_update, apply_updates, lambda a: a,
                    (state["params"], state["ustate"], accum))
            tmetric = state["tmetric"]
            if eval_train:
                rows = metric_rows(outs, labels, mask, rng, 1000)
                # Kahan-compensated sum in column 0; plain count in 2
                s, comp, cnt = (tmetric[:, 0], tmetric[:, 1],
                                tmetric[:, 2])
                y = rows[:, 0] - comp
                t = s + y
                tmetric = jnp.stack(
                    [t, (t - s) - y, cnt + rows[:, 1]], axis=1)
            new_state = {
                "params": params,
                "ustate": ustate,
                "accum": accum,
                "count": jnp.where(do_update, 0, count),
                "epoch": state["epoch"] + do_update.astype(jnp.int32),
                "tmetric": tmetric,
            }
            if not check_nan:
                return new_state, loss
            # divergence guard, fully in-jit: all-finite over loss,
            # updated params, and (update_period>1) the gradient
            # accumulator - a micro-step whose grads go NaN with a
            # finite loss leaves params untouched, so checking params
            # alone would commit the NaN into accum and make every
            # retry of that update non-finite. update_period==1 skips
            # accum: it is invariantly zero post-update and NaN grads
            # reach params in the same step. A non-finite step selects
            # the ENTIRE old state (params, updater state, grad accum,
            # counters, train metrics) - a select, not a host copy
            check_tree = {"params": new_state["params"]}
            if update_period > 1:
                check_tree["accum"] = new_state["accum"]
            finite = jax.tree.reduce(
                lambda acc, leaf: jnp.logical_and(
                    acc, jnp.all(jnp.isfinite(leaf))),
                check_tree, jnp.isfinite(loss))
            new_state = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_state, state)
            return new_state, loss, finite

        def eval_step(params, data, extras):
            cparams = self._cast(params)
            if daug is not None:
                # deterministic eval augment (center crop, no mirror/
                # jitter); the key is never consumed on this path
                data = daug(data, jax.random.PRNGKey(0), False)
            inputs = {0: self._cast(data)}
            for i, e in enumerate(extras):
                inputs[1 + i] = self._cast(e)
            with active_mesh(self.mesh):
                values, _ = net.forward(cparams, inputs, train=False)
            return {nid: values[nid].astype(jnp.float32)
                    for nid in range(net.cfg.num_nodes)
                    if values[nid] is not None}

        def eval_metric_step(params, data, extras, labels, mask, rng):
            """Forward + per-batch metric rows fully on device: the eval
            loop keeps the tiny (n_metrics, 2) results and sums them on
            the host in float64 after the dataset - no per-batch
            readback of node outputs (nnet_impl-inl.hpp:224-245 does
            that on the host every batch) and no cross-batch f32
            accumulation drift."""
            outs = eval_step(params, data, extras)
            return metric_rows(outs, labels, mask, rng, 2000)

        rep, shd = self._replicated, self._batch_sharded
        dshd = self._data_sharded
        # ustate prefix tree: one sharding per weight, prefixing the inner
        # updater-state dict ({m} / {m1,m2}); mirrors _init_state's filter
        ushard = self._pshard
        if zrun >= 1:
            # ZeRO-1 / update_on_server analog: optimizer state sharded
            # over 'data' (parallel/sharding.py:zero1_shardings)
            from cxxnet_tpu.parallel.sharding import zero1_shardings
            ushard = zero1_shardings(self.mesh, self.net, self._pshard,
                                     zshapes, zdims)
        ustate_prefix = {
            lk: {pn: ushard[lk][pn] for pn in d
                 if pn in ushard.get(lk, {})}
            for lk, d in self.updaters.items()}
        self._ustate_shard = ustate_prefix
        # stage 3 keeps the PARAMETERS on their zero cut between steps;
        # stage 2 additionally stores the update_period>1 accumulator
        # sharded (each microstep reduce-scatters into it)
        pstore = self._pshard
        if zrun == 3:
            from cxxnet_tpu.parallel.sharding import zero3_shardings
            pstore = zero3_shardings(self.mesh, self.net, self._pshard,
                                     zshapes, zdims)
        self._params_store_shard = pstore
        state_shardings = {
            "params": pstore, "ustate": ustate_prefix,
            "accum": zshard if zrun >= 2 else self._pshard,
            "count": rep, "epoch": rep, "tmetric": rep,
        }
        self._state_shardings = state_shardings
        label_shardings = {
            f: shd for f in self.net_cfg.label_name_map}
        eshd = (shd,) * self.net_cfg.extra_data_num
        self._train_step = jax.jit(
            train_step,
            in_shardings=(state_shardings, dshd, eshd, label_shardings,
                          shd, rep),
            out_shardings=((state_shardings, rep, rep) if check_nan
                           else (state_shardings, rep)),
            donate_argnums=(0,))

        # fused multi-step dispatch (steps_per_dispatch=K): ONE jitted
        # lax.scan carries state through K full train steps. The scan
        # body IS train_step - same math, same metric folds, same
        # in-jit guard rollback - with the per-step RNG folded ON
        # DEVICE from the identical (seed, step_counter) stream, so
        # the trajectory is bitwise K streamed updates. Per-microstep
        # (loss, finite) vectors come back so the divergence guard and
        # loss gauge keep exact per-step semantics with one host
        # readback per chunk. Chunk length K is read from the stacked
        # leading axis (a short final chunk just retraces).
        def _chunked(s: NamedSharding) -> NamedSharding:
            return NamedSharding(self.mesh, P(None, *s.spec))

        cshd, cdshd = _chunked(shd), _chunked(dshd)
        ceshd = (cshd,) * self.net_cfg.extra_data_num
        clabel_shardings = {f: cshd for f in self.net_cfg.label_name_map}
        self._chunk_stack_shardings = (cdshd, ceshd, clabel_shardings,
                                       cshd)

        def train_chunk(state, data, extras, labels, mask, step_idx,
                        base_rng):
            def body(st, xs):
                d, ex, lb, mk, idx = xs
                rng = jax.random.fold_in(base_rng, idx)
                if check_nan:
                    st, loss, finite = train_step(st, d, ex, lb, mk,
                                                  rng)
                else:
                    st, loss = train_step(st, d, ex, lb, mk, rng)
                    finite = jnp.bool_(True)
                return st, (loss, finite)

            # unroll=True: ONE flat XLA program with the K microstep
            # bodies inlined - the whole point (hand the compiler the
            # full dataflow region so it can schedule across step
            # boundaries), and the condition for the bitwise guarantee:
            # a rolled while-loop body compiled the fc backward with
            # ~1-ULP different contractions than the standalone step
            # (measured on jax-cpu), while the inlined bodies compile
            # identically. Cost: compile time grows with K, and each
            # distinct chunk length (e.g. the short round-end chunk)
            # retraces once - keep K modest (docs/PERFORMANCE.md).
            state, (losses, finites) = lax.scan(
                body, state, (data, extras, labels, mask, step_idx),
                unroll=True)
            return state, losses, finites

        self._train_chunk = jax.jit(
            train_chunk,
            in_shardings=(state_shardings, cdshd, ceshd,
                          clabel_shardings, cshd, rep, rep),
            out_shardings=(state_shardings, rep, rep),
            donate_argnums=(0,))
        # device-side stacker: K staged batches -> one chunk. Pure
        # data movement after the per-batch staging pipeline, which is
        # the structural trajectory-equality argument (stage_chunk).
        self._stack_chunk = jax.jit(
            lambda *bs: jax.tree.map(lambda *ls: jnp.stack(ls), *bs),
            out_shardings=self._chunk_stack_shardings)
        # eval consumes params at their BETWEEN-STEPS layout: under
        # zero_stage=3 they arrive sharded and GSPMD inserts the
        # gathers where the forward needs full tensors
        self._eval_step = jax.jit(
            eval_step, in_shardings=(pstore, dshd, eshd),
            out_shardings=shd)

        # dedicated inference executable (docs/SERVING.md): donation-
        # free, dropout-free, and - unlike eval_step, which returns
        # EVERY node's value - computes only the requested node, so
        # XLA dead-code-eliminates the rest and the host reads back
        # one output tensor per batch instead of the whole node set
        # (the wrapper predict path used to fetch every intermediate).
        # Batch-size POLYMORPHIC: the first dim is whatever the caller
        # stages, and jit caches one executable per distinct shape -
        # the serving layer's per-bucket executables are exactly this
        # cache (serve/server.py counts it to prove zero steady-state
        # recompiles). One jit per requested node, built lazily;
        # predict/extract/serve all share the cache.
        def infer_step(node, params, data, extras):
            outs = eval_step(params, data, extras)
            return outs[node]

        infer_jits: Dict[Any, Any] = {}
        pass_infer = bool(self._pipeline.infer_passes
                          if self._pipeline is not None else False)

        def infer_graph_step(node, net2, pfn, params, data, extras):
            """Inference forward over the pass-transformed graph
            (nnet/passes.py): params remapped/folded in-jit by pfn
            (pruned weights are unused arguments jit drops), then the
            same eval semantics as eval_step - deterministic augment,
            train=False forward, f32 readout of the requested node."""
            gp = self._cast(pfn(params))
            if daug is not None:
                data = daug(data, jax.random.PRNGKey(0), False)
            inputs = {0: self._cast(data)}
            for i, e in enumerate(extras):
                inputs[1 + i] = self._cast(e)
            with active_mesh(self.mesh):
                values, _ = net2.forward(gp, inputs, train=False)
            return values[node].astype(jnp.float32)

        def infer_fn(node: int):
            import functools
            if not pass_infer:
                fn = infer_jits.get(node)
                if fn is None:
                    fn = jax.jit(
                        functools.partial(infer_step, node),
                        in_shardings=(pstore, dshd, eshd),
                        out_shardings=shd)
                    infer_jits[node] = fn
                return fn
            # pass-transformed inference: one executable per
            # (node, fold calibration epoch) - a recalibration
            # rebuilds; existing callables (e.g. a running Server's)
            # keep working on their frozen stats
            key = (node, self._fold_epoch)
            fn = infer_jits.get(key)
            if fn is None:
                net2, pfn, _gm = self._build_infer_graph(node)
                fn = jax.jit(
                    functools.partial(infer_graph_step, node, net2,
                                      pfn),
                    in_shardings=(pstore, dshd, eshd),
                    out_shardings=shd)
                infer_jits[key] = fn
            return fn

        self._infer_fn = infer_fn
        # exposed so a recalibration can evict the previous epoch's
        # compiled executables (_calibrate_staged)
        self._infer_jits = infer_jits
        self._eval_metric_step = None
        if metric_specs:
            self._eval_metric_step = jax.jit(
                eval_metric_step,
                in_shardings=(pstore, dshd, eshd, label_shardings,
                              shd, rep),
                out_shardings=rep)

    # ------------------------------------------------------------------
    # dispatch introspection (telemetry/flight.py)
    # ------------------------------------------------------------------
    def _register_executable(self, site: str, key, kind: str,
                             name: str, shape, arg_bytes: int,
                             donated: int) -> str:
        """First sight of one compiled program shape at a jit-cache
        site: fingerprint it and register it with the executable
        registry (the `/executables` plane + flight-recorder entries
        name executables by this fingerprint). Callers cache the
        result in _flight_fps so the steady state pays one dict hit."""
        from cxxnet_tpu.telemetry.flight import fingerprint
        fp = fingerprint(site, *key)
        telemetry.get().executables.register(
            fp, name=name, kind=kind, shape=str(tuple(shape)),
            arg_bytes=int(arg_bytes), device=jax.default_backend(),
            donated=donated)
        self._flight_fps[key] = fp
        return fp

    @contextlib.contextmanager
    def _flight_record(self, site: str, key, kind: str, name: str,
                       shape, nbytes: int, donated: int = 0,
                       bucket: Optional[int] = None, fields=None):
        """One dispatch under flight-recorder + executable-registry
        accounting (the single definition every trainer dispatch site
        wraps itself in): register the program shape on first sight,
        open a ring entry when armed, close it WITH the error if the
        block raises (a failed dispatch must not read as a hung one -
        only one that never returns stays in-flight), and count the
        dispatch on success."""
        tel = telemetry.get()
        fp = self._flight_fps.get(key)
        if fp is None:
            fp = self._register_executable(
                site, key, kind=kind, name=name, shape=shape,
                arg_bytes=nbytes, donated=donated)
        fl = (tel.flight.start(
                  kind, fp=fp,
                  bucket=shape[0] if bucket is None else bucket,
                  nbytes=int(nbytes), fields=fields)
              if tel.flight.enabled else None)
        try:
            yield
        except BaseException as e:
            tel.flight.fail(fl, f"{type(e).__name__}: {e}")
            raise
        tel.flight.finish(fl)
        tel.executables.count_dispatch(fp)

    # ------------------------------------------------------------------
    # training api
    # ------------------------------------------------------------------
    def start_round(self, round_counter: int) -> None:
        self.round = round_counter
        if self.profiler is not None:
            # close out + report the previous round's profile, then arm
            # the next (the trace_round-th profiled round also dumps
            # the trace). The stderr summary stays profile=1-only; a
            # telemetry-only profiler feeds round records silently.
            if self.profile and self.profiler.step_s:
                telemetry.stderr(self.profiler.summary() + "\n")
            self.profiler.round_end()
            self.profiler.round_start()

    def finish_round_profile(self) -> None:
        """Close the round's trace right after the update loop so the
        dump scopes to TRAINING steps only, not the eval passes or the
        checkpoint save that follow in the round (round_end is
        idempotent; start_round still prints the summary)."""
        if self.profiler is not None:
            self.profiler.round_end()

    def profile_summary(self) -> str:
        """Summary line for the round in progress ('' when profiling is
        off or no steps ran); closes any open trace either way. A
        telemetry-only profiler (profile=0) reports nothing here - the
        stderr surface under profile=0 is pinned byte-identical."""
        if self.profiler is None:
            return ""
        self.profiler.round_end()
        if not self.profile or not self.profiler.step_s:
            return ""
        return self.profiler.summary()

    def round_stats(self) -> Optional[Dict[str, float]]:
        """Step/data timing stats of the round in progress (None when
        nothing is instrumented or no steps ran) - the payload of the
        telemetry `round` event/metrics record (main.py emits them)."""
        if self.profiler is None:
            return None
        return self.profiler.stats()

    def _compute_local_rows(self) -> Tuple[int, int]:
        """(rows this process feeds, their global start row) under the
        batch sharding - batch/nproc on a pure-data mesh, but the FULL
        batch when the batch dim is replicated across processes (e.g. a
        cross-host 'seq' mesh, where hosts split the sequence dim
        instead - parallel/ring.py). Mesh-invariant after _build_net,
        so computed once there (this sits on the per-step hot path)."""
        if jax.process_count() == 1:
            return self.batch_size, 0
        shd = self._batch_sharded
        imap = shd.devices_indices_map((self.batch_size,))
        spans = {imap[d][0].indices(self.batch_size)[:2]
                 for d in shd.addressable_devices}
        total = sum(stop - start for start, stop in spans)
        lo = min(start for start, _ in spans)
        hi = max(stop for _, stop in spans)
        if total != hi - lo:
            # put_global_rows slices the host batch as ONE contiguous
            # range; a mesh/device ordering that fragments a process's
            # row ownership would silently feed wrong rows - fail loudly
            raise RuntimeError(
                f"process-local batch rows are not contiguous: spans="
                f"{sorted(spans)} over batch {self.batch_size} (mesh "
                f"device order fragments row ownership; reorder the "
                f"mesh axes or devices so each process owns one range)")
        return total, lo

    @property
    def _local_batch(self) -> int:
        return self._local_rows[0]

    @property
    def _local_row_start(self) -> int:
        return self._local_rows[1]

    def _put_data(self, data: np.ndarray) -> jax.Array:
        """Stage the input tensor under _data_sharded; correct even
        when the 'seq' axis spans processes (put_global_rows)."""
        gshape = (self.batch_size,) + data.shape[1:]
        return distributed.put_global_rows(
            self._host_input(data), self._data_sharded, gshape,
            self._local_row_start)

    def _pad_batch(self, batch: DataBatch, train: bool = False):
        """Pad a short batch up to the local batch (static shapes).

        Sparse CSR batches (data.h:96-181) densify to the net input
        shape first - the jitted step consumes static dense tensors.

        `train`: every DELIVERED row is valid. num_batch_padd marks
        round_batch wrap-fill rows, which are REAL instances consumed
        early from the next epoch - the reference trains them and trims
        them only from eval/pred (nnet_impl-inl.hpp:239); masking them
        in training would mean they are never trained at all (the
        iterator deliberately does not re-serve them). Eval paths keep
        the trimming mask.

        Returns (data, label, mask, extras) where extras are the padded
        extra-data arrays feeding input nodes 1..k (network.py)."""
        b = batch.batch_size
        if batch.is_sparse():
            c, y, x = self.net_cfg.input_shape
            batch = DataBatch(
                data=batch.to_dense(c * y * x).reshape(b, c, y, x),
                label=batch.label, inst_index=batch.inst_index,
                num_batch_padd=batch.num_batch_padd,
                extra_data=batch.extra_data)
        n_extra = self.net_cfg.extra_data_num
        extras = list(batch.extra_data[:n_extra])
        if len(extras) < n_extra:
            raise ValueError(
                f"net declares extra_data_num={n_extra} but the batch "
                f"carries {len(extras)} extra arrays (use attachtxt or "
                "fill DataBatch.extra_data)")
        valid = np.ones(b, np.float32) if train else batch.valid_mask()
        if b == self._local_batch:
            return batch.data, batch.label, valid, tuple(
                np.asarray(e, np.float32) for e in extras)
        if b > self._local_batch:
            raise ValueError("batch larger than configured batch_size")
        pad = self._local_batch - b

        def padrows(a):
            a = np.asarray(a)
            return np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)

        mask = np.concatenate([valid, np.zeros(pad, np.float32)])
        return (padrows(batch.data), padrows(batch.label), mask,
                tuple(padrows(e).astype(np.float32) for e in extras))

    def stage_batch(self, batch: DataBatch) -> StagedBatch:
        """Stage a batch's device buffers ONCE for repeated update()
        calls (see StagedBatch). The staging runs the exact per-step
        pipeline (pad, host cast, put under the step's in_shardings),
        so a staged update is trajectory-identical to a streamed one."""
        if fault.fault_point("stage_batch") == "corrupt":
            # NaN-poison the batch (fault injection): models a decode /
            # DMA error feeding garbage into the step - the divergence
            # guard must drop the step, not ship NaN into the weights
            bad = np.full(np.shape(batch.data), np.nan, np.float32)
            batch = DataBatch(
                data=bad, label=batch.label,
                inst_index=batch.inst_index,
                num_batch_padd=batch.num_batch_padd,
                extra_data=batch.extra_data)
        data, label, mask, extras = self._pad_batch(batch, train=True)
        labels = self._label_fields(label.astype(np.float32))
        shd = self._batch_sharded
        return StagedBatch(
            data=self._put_data(data),
            extras=tuple(distributed.put_global(e, shd)
                         for e in extras),
            labels={k: distributed.put_global(v, shd)
                    for k, v in labels.items()},
            mask=distributed.put_global(mask.astype(np.float32), shd),
            n_examples=batch.batch_size - batch.num_batch_padd)

    def stage_chunk(self, batches: Sequence) -> StagedChunk:
        """Stack K batches into one fused-dispatch chunk (StagedChunk).
        Each unstaged batch runs the EXACT per-batch staging pipeline
        (stage_batch), then a jitted device-side stack prepends the
        microstep axis - pure data movement, so a fused chunk is
        trajectory-identical to streaming its batches one by one.
        Accepts DataBatch and StagedBatch mixed; K is len(batches)
        (a short final chunk at round end is fine - the scan reads
        its length from the stacked axis)."""
        if not batches:
            raise ValueError("stage_chunk needs at least one batch")
        staged = [b if isinstance(b, StagedBatch) else
                  self.stage_batch(b) for b in batches]
        data, extras, labels, mask = self._stack_chunk(
            *((s.data, s.extras, s.labels, s.mask) for s in staged))
        return StagedChunk(
            data=data, extras=extras, labels=labels, mask=mask,
            n_examples=tuple(s.n_examples for s in staged))

    def prefetch(self, data_iter, depth: int = 1, chunk: int = 1):
        """Wrap a DataIter so batch k+1 is staged (pad + cast + H2D)
        on a worker thread while step k runs - the reference's
        ThreadBuffer idea applied at the host->device edge
        (io/prefetch.py). update() consumes the staged values with
        zero per-step host work; trajectory-identical to streaming.

        chunk=K assembles fused-dispatch chunks (stage_chunk) on the
        worker instead of single batches - the staging half of
        steps_per_dispatch=K. HBM budget: K*(depth+1) batches resident
        (docs/PERFORMANCE.md)."""
        from cxxnet_tpu.io.prefetch import StagedPrefetcher
        return StagedPrefetcher(self.stage_batch, data_iter, depth,
                                chunk=chunk, chunk_fn=self.stage_chunk)

    # graftlint: hot-path
    def update(self, batch) -> None:
        """One training mini-batch (CXXNetThreadTrainer::Update).
        Accepts a DataBatch (streamed: per-step pad/cast/H2D), a
        StagedBatch (device-resident: zero per-step host work), or a
        StagedChunk (fused: K microsteps in one dispatch)."""
        if isinstance(batch, StagedChunk):
            return self.update_chunk(batch)
        track = bool(self.profile) or self._tel_steps
        t0 = time.perf_counter() if track else 0.0
        if not isinstance(batch, StagedBatch):
            # the streamed path IS one stage_batch call - structural
            # guarantee of the staged/streamed trajectory equivalence.
            # Staging also validates; a rejected batch must raise
            # BEFORE the step counter moves, or a caller that catches
            # the error would silently shift the whole RNG stream
            batch = self.stage_batch(batch)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.seed + 100), self._step_counter)
        self._step_counter += 1
        gdata, gextras = batch.data, batch.extras
        glabels, gmask = batch.labels, batch.mask
        n_examples = batch.n_examples
        data_s = 0.0
        if track:
            # host-side prep (padding, casting, H2D staging) vs device
            # step, reported separately by StepProfiler.summary
            t1 = time.perf_counter()
            data_s = t1 - t0
            if self.profiler is not None:
                self.profiler.add_data(data_s)
            t0 = t1
        # collective-scope fault point (docs/FAULT_TOLERANCE.md
        # "Elastic pod"): the dispatched step carries the pod-wide
        # gradient AllReduce, so kill_rank/hang_rank/delay_collective
        # armed here murder or wedge ONE worker at a deterministic
        # step - every rank hits this point in the same order under
        # SPMD, so @N names the same step on every worker
        fault.fault_point("collective")
        # the step is dispatched asynchronously and train metrics
        # accumulate on device - nothing here blocks on the result, so
        # host-side input prep for batch k+1 overlaps compute of batch
        # k. The _flight_record wrapper spans the dispatch + guard
        # readback (the sync a hung backend wedges in) so a stall dump
        # names this exact executable.
        ok = None
        with self._flight_record(
                "train_step", ("train_step", tuple(gdata.shape)),
                kind="train", name=f"train_step@b{gdata.shape[0]}",
                shape=gdata.shape, nbytes=gdata.nbytes, donated=1):
            if self._check_nan_built:
                # divergence guard: the per-step finite flag must be
                # read back (a device sync - the cost of check_nan=1;
                # staging prefetch still overlaps on its worker thread)
                self.state, loss, finite = self._train_step(
                    self.state, gdata, gextras, glabels, gmask, rng)
                # graftlint: disable=GL002 the guard's documented sync: the finite flag must be read back before the next step commits
                ok = bool(np.asarray(distributed.fetch_local(finite)))
            else:
                self.state, loss = self._train_step(
                    self.state, gdata, gextras, glabels, gmask, rng)
        if ok is not None:
            self._guard_step(ok, self._step_counter - 1)
        # host mirror of the device epoch counter (one update per
        # update_period steps) - avoids forcing a device sync per step;
        # guard-dropped steps never advanced the device counters
        self.epoch = self._epoch_base + (
            (self._step_counter - self._skipped_steps)
            // self.update_period)
        # progress beacon for the hang watchdog / absence alert rules
        # (docs/OBSERVABILITY.md): one dict store, no device sync -
        # the step DISPATCHED; a hung backend blocks above, in the
        # step call or the guard readback, and the beacon goes stale
        telemetry.beacon("train.step")
        if track:
            # per-step timing forces a device sync (same cost profile=1
            # always paid; staging prefetch still overlaps on its
            # worker thread) - the price of honest step times
            # graftlint: disable=GL002 honest per-step timing requires the sync - profile/telemetry_steps opt-in only
            jax.block_until_ready(self.state["epoch"])
            step_s = time.perf_counter() - t0
            if self.profiler is not None:
                # distinct-instance count: wrap/pad rows in
                # num_batch_padd would inflate images/sec
                self.profiler.add_step(step_s, n_examples)
            if self._tel_steps:
                tel = telemetry.get()
                step_idx = self._step_counter - 1
                # graftlint: disable=GL002 loss gauge readback, gated by telemetry_steps=1
                loss_val = float(np.asarray(
                    distributed.fetch_local(loss)))
                tel.observe("train.data_s", data_s)
                tel.observe("train.step_s", step_s)
                tel.inc("train.images", n_examples)
                tel.set_gauge("train.loss", loss_val)
                tel.event("span", name="train.data", secs=data_s,
                          round=self.round, step=step_idx)
                tel.event("span", name="train.step", secs=step_s,
                          round=self.round, step=step_idx,
                          loss=loss_val, examples=n_examples)

    # graftlint: hot-path
    def update_chunk(self, chunk) -> None:
        """K training microsteps in ONE dispatch (steps_per_dispatch):
        a jitted lax.scan over a StagedChunk - accepts a sequence of
        DataBatch/StagedBatch too (staged + stacked here). One host
        readback per chunk serves the divergence guard, loss gauge and
        per-step accounting for all K microsteps. Trajectory-bitwise-
        identical to K update() calls; the one semantic difference is
        that a DivergenceError can surface up to K-1 microsteps after
        the fatal one (the chunk has already run on device), with the
        in-jit rollback semantics unchanged."""
        track = bool(self.profile) or self._tel_steps
        t0 = time.perf_counter() if track else 0.0
        if not isinstance(chunk, StagedChunk):
            # staging validates; a rejected batch must raise BEFORE
            # the step counter moves (same contract as update())
            chunk = self.stage_chunk(chunk)
        k = chunk.n_steps
        base_rng = jax.random.PRNGKey(self.seed + 100)
        first_step = self._step_counter
        step_idx = distributed.put_global(
            np.arange(first_step, first_step + k, dtype=np.int32),
            self._replicated)
        self._step_counter += k
        data_s = 0.0
        if track:
            t1 = time.perf_counter()
            data_s = t1 - t0
            if self.profiler is not None:
                self.profiler.add_data(data_s)
            t0 = t1
        # same collective-scope fault point as the streamed path: one
        # hit per DISPATCH (K microsteps), still rank-deterministic
        fault.fault_point("collective")
        # flight-recorder entry: one per K-step chunk dispatch, same
        # contract as update()'s (in-flight across the guard readback)
        fin = None
        with self._flight_record(
                "train_chunk",
                ("train_chunk", k, tuple(chunk.data.shape)),
                kind="train",
                name=f"train_chunk@K{k}b{chunk.data.shape[1]}",
                shape=chunk.data.shape, nbytes=chunk.data.nbytes,
                donated=1, bucket=chunk.data.shape[1],
                fields={"steps": k}):
            self.state, losses, finites = self._train_chunk(
                self.state, chunk.data, chunk.extras, chunk.labels,
                chunk.mask, step_idx, base_rng)
            if self._check_nan_built:
                # ONE readback per chunk (vs one per step streamed) -
                # the whole point of the fused dispatch; the guard then
                # walks the per-microstep flags in order, so drop
                # counts and consecutive-failure accounting match
                # streaming exactly
                # graftlint: disable=GL002 ONE guard readback per K-step chunk - the fused dispatch's whole point
                fin = np.asarray(distributed.fetch_local(finites))
        if fin is not None:
            for i in range(k):
                self._guard_step(bool(fin[i]), first_step + i)
        self.epoch = self._epoch_base + (
            (self._step_counter - self._skipped_steps)
            // self.update_period)
        # K dispatched microsteps of progress (same beacon the
        # streamed path marks - the watchdog is dispatch-mode-blind)
        telemetry.beacon("train.step", k)
        if track:
            # graftlint: disable=GL002 honest per-chunk timing requires the sync - profile/telemetry_steps opt-in only
            jax.block_until_ready(self.state["epoch"])
            chunk_s = time.perf_counter() - t0
            n_examples = sum(chunk.n_examples)
            if self.profiler is not None:
                self.profiler.add_chunk(chunk_s, k, n_examples)
            if self._tel_steps:
                tel = telemetry.get()
                # graftlint: disable=GL002 per-chunk loss readback, gated by telemetry_steps=1
                loss_v = np.asarray(distributed.fetch_local(losses),
                                    np.float64)
                per_s = chunk_s / k
                for _ in range(k):
                    # per-step amortized cost: keeps the registry's
                    # windowed p50/p99 on a per-STEP scale, comparable
                    # across steps_per_dispatch settings (data_s too -
                    # a non-prefetched chunk stages all K batches here,
                    # and a per-chunk sample would read as a Kx staging
                    # regression next to a K=1 run)
                    tel.observe("train.step_s", per_s)
                    tel.observe("train.data_s", data_s / k)
                tel.inc("train.images", n_examples)
                tel.set_gauge("train.loss", float(loss_v[-1]))
                tel.event("span", name="train.data", secs=data_s,
                          round=self.round, step=first_step)
                tel.event("span", name="train.chunk", secs=chunk_s,
                          round=self.round, step=first_step, steps=k,
                          loss=[float(v) for v in loss_v],
                          examples=n_examples)

    def _guard_step(self, ok: bool, step_idx: int) -> None:
        """Host half of the divergence guard: count dropped steps and
        abort after max_bad_rounds CONSECUTIVE non-finite steps (the
        jitted step already rolled the state back)."""
        if ok:
            self._bad_consec = 0
            return
        self._bad_consec += 1
        self.bad_rounds += 1
        self._skipped_steps += 1
        telemetry.inc("fault.nan_rollback")
        telemetry.stderr(
            f"divergence guard: non-finite loss/params at update "
            f"{step_idx}; batch dropped, params rolled "
            f"back ({self._bad_consec}/{self.max_bad_rounds} "
            f"consecutive)\n",
            event_kind="fault", type="nan_rollback",
            step=step_idx, consecutive=self._bad_consec,
            max_bad_rounds=self.max_bad_rounds)
        if self._bad_consec >= self.max_bad_rounds:
            raise DivergenceError(
                f"training diverged: {self._bad_consec} consecutive "
                f"non-finite update rounds (loss or params hit NaN/Inf "
                f"every round); lower eta or inspect the data pipeline "
                f"- params remain at the last finite state")

    def update_all(self, data_iter, eval_iters=None,
                   eval_names=None) -> str:
        """Convenience: one full pass (round) over a data iterator,
        then evaluate each of eval_iters (named by eval_names,
        default eval/eval2/...) - the reference's per-round loop body
        (cxxnet_main.cpp:367-405). Returns the concatenated
        reference-format metric string ('' when no eval iters)."""
        data_iter.before_first()
        while data_iter.next():
            self.update(data_iter.value())
        parts = []
        for i, it in enumerate(eval_iters or ()):
            name = (eval_names[i] if eval_names and i < len(eval_names)
                    else ("eval" if i == 0 else f"eval{i + 1}"))
            parts.append(self.evaluate(it, name))
        return "".join(parts)

    # ------------------------------------------------------------------
    # evaluation / inference api
    # ------------------------------------------------------------------
    def _forward_nodes(self, batch: DataBatch) -> Dict[int, np.ndarray]:
        data, _, mask, extras = self._pad_batch(batch)
        gdata = self._put_data(data)
        shd = self._batch_sharded
        gextras = tuple(distributed.put_global(e, shd) for e in extras)
        with self._flight_record(
                "eval_step", ("eval_step", tuple(gdata.shape)),
                kind="eval", name=f"eval_step@b{gdata.shape[0]}",
                shape=gdata.shape, nbytes=gdata.nbytes):
            outs = self._eval_step(self.state["params"], gdata,
                                   gextras)
            valid = int(mask.sum())
            got = {nid: distributed.fetch_local(v)[:valid]
                   for nid, v in outs.items()}
        return got

    def _infer_node(self, batch: DataBatch, node: int) -> np.ndarray:
        """One node's output rows for a batch via the dedicated
        inference executable (_compile's infer_fn): pad to the static
        batch, stage, run, read back ONLY the requested node, trim the
        padding rows. The predict/extract path - evaluate's metric-less
        fallback keeps _forward_nodes (it needs several nodes from one
        forward)."""
        data, _, mask, extras = self._pad_batch(batch)
        gdata = self._put_data(data)
        shd = self._batch_sharded
        gextras = tuple(distributed.put_global(e, shd) for e in extras)
        if self.passes_need_calibration():
            # fold_conv_bn freezes its statistics from the FIRST
            # inference batch (docs/GRAPH_PASSES.md) - staged through
            # this very pipeline, so on a single-shard mesh a
            # single-batch predict is contraction-ULP-identical to
            # the unfolded path (data-sharded meshes: per-shard vs
            # global stats, warned at calibration)
            self._calibrate_staged(
                gdata, gextras,
                distributed.put_global(np.asarray(mask, np.float32),
                                       shd))
        with self._flight_record(
                "infer",
                ("infer", node, self._fold_epoch, tuple(gdata.shape)),
                kind="infer", name=f"infer:n{node}@b{gdata.shape[0]}",
                shape=gdata.shape, nbytes=gdata.nbytes):
            out = self._infer_fn(node)(self.state["params"], gdata,
                                       gextras)
            valid = int(mask.sum())
            got = distributed.fetch_local(out)[:valid]
        return got

    def stage_infer_rows(self, data: np.ndarray, extras: Sequence = ()):
        """Stage an ARBITRARY-row-count inference input under the infer
        executable's in_shardings (the serving layer's bucket staging,
        serve/server.py). Single-process serving only - the multi-
        controller batch-row split of _put_data does not apply; the
        row count must divide over the mesh's data axis (the Server's
        bucket rule guarantees that)."""
        if jax.process_count() > 1:
            raise RuntimeError(
                "stage_infer_rows is single-process (serving a "
                "multi-controller mesh is not supported)")
        gdata = jax.device_put(self._host_input(np.ascontiguousarray(data)),
                               self._data_sharded)
        shd = self._batch_sharded
        gextras = tuple(
            jax.device_put(np.ascontiguousarray(e, dtype=np.float32), shd)
            for e in extras)
        return gdata, gextras

    def infer_rows(self, gdata, gextras=(), node: int = -1) -> jax.Array:
        """Dispatch the inference executable on staged rows (the device
        half of the serving hot path; stage_infer_rows is the host
        half). node=-1 = the final node. Returns the device array -
        the caller decides when to read back."""
        if node < 0:
            node = self.net_cfg.num_nodes - 1
        return self._infer_fn(node)(self.state["params"], gdata,
                                    tuple(gextras))

    # ------------------------------------------------------------------
    # graph passes: infer-graph construction + fold calibration
    # ------------------------------------------------------------------
    def _build_infer_graph(self, node: int):
        """(Network, param_fn, GraphModule) for the pass-transformed
        inference graph of one output node (nnet/passes.py): the
        infer-stage pipeline over a CLONE of the net config - prune
        to the target's ancestors, then fold conv+bn sites whose
        calibration stats exist. Cached per (node, fold epoch)."""
        from cxxnet_tpu.nnet.passes import (
            GraphModule, PassContext, make_param_fn)
        key = (node, self._fold_epoch)
        hit = self._infer_graph_cache.get(key)
        if hit is not None:
            return hit
        gm = GraphModule.from_net_config(
            self.net_cfg.clone(), self.batch_size, self.compute_dtype)
        gm.dtype_plan = dict(self._graph_dtype_plan or {})
        gm = self._pipeline.run_infer(
            gm, PassContext(target_node=node,
                            fold_stats=self._fold_stats,
                            quant_stats=self._quant_stats))
        self._fill_quant_scales(gm)
        net2 = Network(gm.cfg, self.batch_size)
        net2.dtype_plan = gm.dtype_plan or None
        out = (net2, make_param_fn(gm), gm)
        self._infer_graph_cache[key] = out
        return out

    def _fill_quant_scales(self, gm) -> None:
        """Freeze each QuantSite's per-channel weight scale from the
        TRANSFORMED float weights (nnet/passes.py QuantSite): evaluate
        the float view of the staged param transforms once (eager -
        a few weight-sized ops) and absmax per output channel on the
        host, so a folded or merged weight is scaled at its COMPOSED
        values. The scale is the frozen constant make_param_fn's in-jit
        quantize stage divides by; the int8 values themselves stay live
        functions of the params argument."""
        sites = [s for s in gm.quants if s.wscale is None]
        if not sites:
            return
        from cxxnet_tpu.nnet.passes import make_param_fn
        from cxxnet_tpu.ops.int8 import per_channel_scale
        fl = make_param_fn(gm, quantize=False)(self.state["params"])
        by_live = {live: new for new, live in gm.param_map().items()}
        for site in sites:
            entry = fl.get(by_live.get(site.key))
            if entry is None or "wmat" not in entry:
                continue  # pruned between matching and build: float
            # fetch_local, not device_get: params may be sharded
            # across processes (zero_stage=3 / tensor parallelism),
            # like every other host read-back in this file
            site.wscale = per_channel_scale(np.asarray(
                distributed.fetch_local(entry["wmat"]), np.float32))

    def _needs_fold_stats(self) -> bool:
        return (self._fold_stats is None
                and bool(getattr(self, "_fold_sites", ())))

    def _needs_quant_stats(self) -> bool:
        return (self._quant_stats is None
                and bool(getattr(self, "_quant_sites", ())))

    def passes_need_calibration(self) -> bool:
        """True when a calibrating pass (fold_conv_bn's frozen moments,
        quantize_int8's activation ranges) is configured with at least
        one matched site whose statistics are missing - the
        predict/extract paths then calibrate on their first batch;
        serving without calibration runs the un-rewritten graph (the
        Server warns - docs/GRAPH_PASSES.md)."""
        if self._pipeline is None:
            return False
        return self._needs_fold_stats() or self._needs_quant_stats()

    def calibrate_graph_passes(self, batch) -> bool:
        """Capture the fold_conv_bn statistics from one calibration
        DataBatch (staged through the exact inference pipeline, so on
        a single-shard mesh a later inference of the SAME batch
        reproduces the unfolded values to contraction-order ULP; on a
        mesh whose data axis is > 1 the unfolded BN normalizes
        per shard while calibration captures GLOBAL stats - see
        _calibrate_staged). A SEQUENCE of batches instead averages
        the frozen moments over all of them (multi-batch
        calibration, `pass_calibration_batches` - less sensitive to
        one unlucky batch; the single-batch path stays
        bitwise-unchanged). Returns True when stats were
        (re)captured, False when nothing needed calibration."""
        if isinstance(batch, (list, tuple)):
            if len(batch) == 1:
                # one-element sequence rides the pinned single-batch
                # arithmetic (bitwise default)
                return self.calibrate_graph_passes(batch[0])
            return self._calibrate_batches(list(batch))
        if not self.passes_need_calibration():
            return False
        data, _, mask, extras = self._pad_batch(batch)
        gdata = self._put_data(data)
        shd = self._batch_sharded
        gextras = tuple(distributed.put_global(e, shd)
                        for e in extras)
        return self._calibrate_staged(
            gdata, gextras,
            distributed.put_global(np.asarray(mask, np.float32), shd))

    def _calibrate_batches(self, batches: List) -> bool:
        """Multi-batch fold calibration: ONE jitted moments forward
        (mean, var per fold site - the same tap + f32 arithmetic as
        _calibrate_staged) run per calibration batch, the per-batch
        moments pooled on the host (valid-row-weighted mean of means;
        var from the pooled second moment), rstd = 1/sqrt(var + eps)
        precomputed so the folded jaxpr still carries no rsqrt.
        Padding rows (a round_batch=0 iterator zero-fills its tail
        batch) are masked out of both the per-batch moments and the
        pooling weights."""
        if not batches:
            raise ValueError("calibration needs at least one batch")
        if not self.passes_need_calibration():
            return False
        from cxxnet_tpu.parallel.mesh import active_mesh
        sites = self._fold_sites if self._needs_fold_stats() else []
        qsites = (self._quant_sites if self._needs_quant_stats()
                  else [])
        if sites and self.mesh.shape.get("data", 1) > 1:
            # same documented caveat as _calibrate_staged: global
            # frozen stats vs the unfolded BN's per-shard stats
            telemetry.stderr(
                "graph_passes: fold_conv_bn calibrating GLOBAL batch "
                "statistics on a data-sharded mesh; the unfolded BN "
                "uses per-shard stats, so folded outputs are not "
                "ULP-comparable to unfolded ones here "
                "(docs/GRAPH_PASSES.md)\n",
                event_kind="graph_passes", op="calibrate_sharded",
                data_axis=self.mesh.shape.get("data", 1))
        net = self.net
        daug = self._augment_fn
        eps_by_key = {param_key(self.net_cfg, j):
                      net.layer_objs[j].eps for _i, j in sites}

        def moments_fn(params, data, extras, mask):
            cparams = self._cast(params)
            if daug is not None:
                data = daug(data, jax.random.PRNGKey(0), False)
            inputs = {0: self._cast(data)}
            for i, e in enumerate(extras):
                inputs[1 + i] = self._cast(e)
            taps: Dict[int, Any] = {j: None for _i, j in sites}
            taps.update({q: None for q in qsites})
            with active_mesh(self.mesh):
                net.forward(cparams, inputs, train=False, taps=taps)
            out = {}
            for _i, j in sites:
                lay = net.layer_objs[j]
                xf = taps[j].astype(jnp.float32)
                axes, _slices = lay._axes(taps[j].shape)
                # moments over REAL rows only: a round_batch=0
                # iterator zero-pads its tail batch, and all-zero
                # rows would drag the pooled frozen stats toward 0
                # (the pinned single-batch path keeps them - there
                # the calibration batch IS the inference batch)
                m = jnp.broadcast_to(
                    mask.astype(jnp.float32).reshape(
                        (-1,) + (1,) * (xf.ndim - 1)), xf.shape)
                denom = jnp.sum(m, axis=axes, keepdims=True)
                mean = jnp.sum(xf * m, axis=axes,
                               keepdims=True) / denom
                var = jnp.sum(m * (xf - mean) ** 2, axis=axes,
                              keepdims=True) / denom
                out[param_key(self.net_cfg, j)] = (mean.reshape(-1),
                                                   var.reshape(-1))
            qout = {param_key(self.net_cfg, q):
                    _masked_absmax(taps[q], mask) for q in qsites}
            return out, qout

        jfn = jax.jit(
            moments_fn,
            in_shardings=(self._params_store_shard,
                          self._data_sharded,
                          (self._batch_sharded,)
                          * self.net_cfg.extra_data_num,
                          self._batch_sharded),
            out_shardings=self._replicated)
        per_batch: List[Dict[str, Any]] = []
        q_batch: List[Dict[str, float]] = []
        weights: List[float] = []
        for b in batches:
            data, _, mask, extras = self._pad_batch(b)
            gdata = self._put_data(data)
            shd = self._batch_sharded
            gextras = tuple(distributed.put_global(e, shd)
                            for e in extras)
            gmask = distributed.put_global(
                np.asarray(mask, np.float32), shd)
            res, qres = jfn(self.state["params"], gdata, gextras,
                            gmask)
            per_batch.append({
                k: (np.asarray(distributed.fetch_local(m)),
                    np.asarray(distributed.fetch_local(v)))
                for k, (m, v) in res.items()})
            q_batch.append({
                k: float(np.asarray(distributed.fetch_local(v)))
                for k, v in qres.items()})
            weights.append(float(np.asarray(mask).sum()))
        w = np.asarray(weights, np.float64)
        w = w / w.sum()
        stats: Dict[str, Any] = {}
        for key in (per_batch[0] if per_batch else {}):
            means = np.stack([pb[key][0] for pb in per_batch])
            variances = np.stack([pb[key][1] for pb in per_batch])
            # pooled moments over the union of REAL rows: each batch
            # weighted by its valid-row count, var from the pooled
            # second moment E[x^2] - E[x]^2 with E[x^2]_i = var_i
            # + mean_i^2
            mean = (means * w[:, None]).sum(axis=0)
            var = ((variances + means ** 2)
                   * w[:, None]).sum(axis=0) - mean ** 2
            rstd = 1.0 / np.sqrt(np.maximum(var, 0.0)
                                 + eps_by_key[key])
            stats[key] = (mean.astype(np.float32),
                          rstd.astype(np.float32))
        if sites:
            self._fold_stats = stats
        if qsites:
            # ranges pool by MAX across batches - an absmax is an
            # absmax over the union of rows, no weighting involved
            self._quant_stats = {
                k: max(qb[k] for qb in q_batch) for k in q_batch[0]}
        self._fold_epoch += 1
        self._evict_stale_infer_caches()
        telemetry.event("graph_passes", op="calibrate",
                        sites=sorted(stats),
                        quant_sites=sorted(self._quant_stats or {}),
                        batches=len(batches))
        return True

    def _calibrate_staged(self, gdata, gextras, gmask) -> bool:
        """Fold calibration on already-staged device rows: ONE jitted
        forward over the UNFOLDED graph computing each fold site's BN
        input moments with BatchNormLayer._normalize's arithmetic
        (f32 stats, same axes, rsqrt(var + eps)) - the frozen
        (mean, rstd) the folded weights are built from. One-time
        executable; steady-state inference never recompiles it.

        `gmask` (staged valid-row mask) guards ONLY the quant absmax:
        a round_batch=0 iterator zero-fills its tail batch, and the
        padding rows' garbage activations at depth must not widen the
        frozen activation range (the `_calibrate_batches` arithmetic).
        The fold moments deliberately stay UNmasked here - on the
        pinned single-batch path the calibration batch IS the
        inference batch, padding included, and the unfolded BN
        normalizes over all of it.

        Sharding caveat (docs/GRAPH_PASSES.md "when folding loses"):
        the stats here are GLOBAL over the calibration batch, while
        the unfolded BN on a mesh with data-axis size > 1 normalizes
        each shard with its OWN stats - so the ULP-level fold parity
        holds on single-shard meshes only; on a sharded data mesh
        folding deliberately replaces per-shard batch statistics
        with the frozen global ones (warned below - for serving that
        is the batch-composition-independence feature, for accuracy
        work it is a semantics change to opt into knowingly)."""
        if not self.passes_need_calibration():
            return False
        from cxxnet_tpu.parallel.mesh import active_mesh
        sites = self._fold_sites if self._needs_fold_stats() else []
        qsites = (self._quant_sites if self._needs_quant_stats()
                  else [])
        net = self.net
        daug = self._augment_fn
        if sites and self.mesh.shape.get("data", 1) > 1:
            telemetry.stderr(
                "graph_passes: fold_conv_bn calibrating GLOBAL batch "
                "statistics on a data-sharded mesh; the unfolded BN "
                "uses per-shard stats, so folded outputs are not "
                "ULP-comparable to unfolded ones here "
                "(docs/GRAPH_PASSES.md)\n",
                event_kind="graph_passes", op="calibrate_sharded",
                data_axis=self.mesh.shape.get("data", 1))

        def stats_fn(params, data, extras, mask):
            cparams = self._cast(params)
            if daug is not None:
                data = daug(data, jax.random.PRNGKey(0), False)
            inputs = {0: self._cast(data)}
            for i, e in enumerate(extras):
                inputs[1 + i] = self._cast(e)
            # tap each fold site's BN INPUT as the layer receives it:
            # a `layer[+0] = batch_norm` self-loop overwrites its
            # node, so reading values[node] after the forward would
            # capture POST-normalization moments (~(beta, 1/slope))
            # and fold silently wrong weights. Quant sites tap the
            # same way: each eligible conv/fullc's INPUT activation,
            # whose absmax becomes the frozen per-tensor act scale.
            taps: Dict[int, Any] = {j: None for _i, j in sites}
            taps.update({q: None for q in qsites})
            with active_mesh(self.mesh):
                net.forward(cparams, inputs, train=False, taps=taps)
            out = {}
            for _i, j in sites:
                lay = net.layer_objs[j]
                x = taps[j]
                xf = x.astype(jnp.float32)
                axes, _slices = lay._axes(x.shape)
                mean = jnp.mean(xf, axis=axes, keepdims=True)
                var = jnp.mean((xf - mean) ** 2, axis=axes,
                               keepdims=True)
                rstd = lax.rsqrt(var + lay.eps)
                out[param_key(self.net_cfg, j)] = (mean.reshape(-1),
                                                   rstd.reshape(-1))
            qout = {param_key(self.net_cfg, q):
                    _masked_absmax(taps[q], mask) for q in qsites}
            return out, qout

        jfn = jax.jit(
            stats_fn,
            in_shardings=(self._params_store_shard,
                          self._data_sharded,
                          (self._batch_sharded,)
                          * self.net_cfg.extra_data_num,
                          self._batch_sharded),
            out_shardings=self._replicated)
        res, qres = jfn(self.state["params"], gdata, gextras, gmask)
        if sites:
            self._fold_stats = {
                k: (np.asarray(distributed.fetch_local(m)),
                    np.asarray(distributed.fetch_local(r)))
                for k, (m, r) in res.items()}
        if qsites:
            self._quant_stats = {
                k: float(np.asarray(distributed.fetch_local(v)))
                for k, v in qres.items()}
        self._fold_epoch += 1
        self._evict_stale_infer_caches()
        telemetry.event("graph_passes", op="calibrate",
                        sites=sorted(self._fold_stats or {}),
                        quant_sites=sorted(self._quant_stats or {}))
        return True

    def _evict_stale_infer_caches(self) -> None:
        """Drop transformed graphs + compiled executables of every
        fold epoch but the current one: nothing re-reads them through
        _infer_fn (a running Server pinned its own fn reference and
        keeps it) - without eviction a copy_model_from/predict reload
        loop would leak one compiled executable + Network clone per
        recalibration, and a stale-stats executable could be
        re-dispatched after a params reload."""
        epoch = self._fold_epoch
        self._infer_graph_cache = {
            k: v for k, v in self._infer_graph_cache.items()
            if k[1] == epoch}
        jits = getattr(self, "_infer_jits", None)
        if jits is not None:
            for k in [k for k in jits
                      if isinstance(k, tuple) and k[1] != epoch]:
                del jits[k]

    # graftlint: hot-path
    def evaluate(self, data_iter, data_name: str) -> str:
        """Run eval metrics over an iterator; returns the reference-format
        string `\\tname-metric:value...` (nnet_impl-inl.hpp:224-245).

        Metrics accumulate on device (one readback per dataset); the
        host MetricSet path remains for metric-less trainers."""
        from cxxnet_tpu.utils import metric_jit
        specs = self.metric.specs
        if self._eval_metric_step is not None:
            shd = self._batch_sharded
            per_batch = []  # tiny (n_metrics, 2) device arrays
            data_iter.before_first()
            step = 0
            while data_iter.next():
                batch = data_iter.value()
                data, label, mask, extras = self._pad_batch(batch)
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(self.seed + 200), step)
                step += 1
                labels = self._label_fields(label.astype(np.float32))
                gdata = self._put_data(data)
                with self._flight_record(
                        "eval_metric",
                        ("eval_metric", tuple(gdata.shape)),
                        kind="eval",
                        name=f"eval_metric@b{gdata.shape[0]}",
                        shape=gdata.shape, nbytes=gdata.nbytes):
                    per_batch.append(self._eval_metric_step(
                        self.state["params"],
                        gdata,
                        tuple(distributed.put_global(e, shd)
                              for e in extras),
                        {k: distributed.put_global(v, shd)
                         for k, v in labels.items()},
                        distributed.put_global(
                            mask.astype(np.float32), shd),
                        rng))
                # eval progress beacon: round-boundary evals can
                # dwarf watchdog_secs without being a hang
                telemetry.beacon("eval.step")
                if self.eval_inflight and step % self.eval_inflight == 0:
                    # bound in-flight work: without a periodic sync the
                    # host loop stages the whole dataset's input
                    # buffers ahead of the device (HBM blow-up on large
                    # eval sets); syncing on the tiny metric rows keeps
                    # <= eval_inflight batches of inputs pinned. The
                    # knob trades HBM headroom for sync stalls
                    # (docs/PERFORMANCE.md); 0 = never sync
                    # graftlint: disable=GL002 eval_inflight HBM bound: sync every N batches by design
                    jax.block_until_ready(per_batch[-1])
            # host-side float64 reduction across batches (the host
            # MetricSet path accumulated in f64; per-batch f32 sums are
            # exact at batch scale, the cross-batch sum is not)
            vals = np.zeros((len(specs), 2), np.float64)
            for r in per_batch:
                # graftlint: disable=GL002 one tiny-row readback per eval batch, after the dataset dispatched
                vals += np.asarray(distributed.fetch_local(r),
                                   np.float64)
            return metric_jit.format_metrics(data_name, specs, vals)
        self.metric.clear()
        data_iter.before_first()
        while data_iter.next():
            batch = data_iter.value()
            nodes = self._forward_nodes(batch)
            nvalid = batch.batch_size - batch.num_batch_padd
            labels = self._label_fields(
                batch.label.astype(np.float32)[:nvalid])
            preds = []
            for _, nid in self.eval_nodes:
                p = nodes[nid][:nvalid]
                preds.append(p.reshape(p.shape[0], -1))
            self.metric.add_eval(preds, labels)
            telemetry.beacon("eval.step")
        return self.metric.print(data_name)

    def eval_train_metric(self) -> str:
        from cxxnet_tpu.utils import metric_jit
        specs = self.train_metric.specs
        if specs and self.state is not None:
            acc = distributed.fetch_local(self.state["tmetric"])
            # resolve the Kahan pair: true sum ~= sum - comp
            vals = np.stack([acc[:, 0] - acc[:, 1], acc[:, 2]], axis=1)
            out = metric_jit.format_metrics("train", specs, vals)
            self.clear_train_metric()
            return out
        out = self.train_metric.print("train")
        self.train_metric.clear()
        return out

    def clear_train_metric(self) -> None:
        """Zero the on-device train-metric accumulator."""
        self.train_metric.clear()
        if self.state is not None and "tmetric" in self.state:
            n = len(self.train_metric)
            self.state["tmetric"] = distributed.put_global(
                np.zeros((n, 3), np.float32), self._replicated)

    def predict(self, batch: DataBatch) -> np.ndarray:
        """Prediction = argmax of the final node (or raw scalar);
        nnet_impl-inl.hpp:186-199 TransformPred. Runs the dedicated
        inference executable (single-node readback, docs/SERVING.md)."""
        out = self._infer_node(batch, self.net_cfg.num_nodes - 1)
        flat = out.reshape(out.shape[0], -1)
        if flat.shape[1] == 1:
            return flat[:, 0]
        return np.argmax(flat, axis=1).astype(np.float32)

    def predict_dist(self, batch: DataBatch) -> np.ndarray:
        """Full output distribution of the final node."""
        out = self._infer_node(batch, self.net_cfg.num_nodes - 1)
        return out.reshape(out.shape[0], -1)

    def extract_feature(self, batch: DataBatch,
                        node_name: str) -> np.ndarray:
        """Copy out any node by name or `top[-k]`
        (nnet_impl-inl.hpp:200-223)."""
        nid = self.net.node_index(node_name)
        return self._infer_node(batch, nid)

    # ------------------------------------------------------------------
    # checkpoint api
    # ------------------------------------------------------------------
    def _full_params(self):
        """Host params at FULL (stage-0) shapes: zero_stage=3 stores
        shards between steps, so gather first (one all-gather per
        weight) - checkpoints stay byte-compatible with stage 0 and
        resume works across differing zero_stage."""
        params = self.state["params"]
        if getattr(self, "_zero_run", 0) == 3:
            params = jax.jit(lambda t: t,
                             out_shardings=self._pshard)(params)
        return jax.tree.map(distributed.fetch_local, params)

    def save_model(self, fo) -> None:
        params = self._full_params()
        if self.model_format == "cxxnet":
            # reference-binary export (nnet/legacy_format.py)
            from cxxnet_tpu.nnet import legacy_format
            legacy_format.save_legacy_model(fo, self.net_cfg, self.net,
                                            params, self.epoch)
            return
        opt = None
        if self.save_optimizer:
            opt = self.state["ustate"]
            if getattr(self, "_zero_run", 0) >= 1:
                # re-replicate ZeRO-sharded state (one all-gather) so the
                # host readback sees full tensors on every process
                opt = jax.jit(lambda t: t,
                              out_shardings=self._replicated)(opt)
            opt = jax.tree.map(distributed.fetch_local, opt)
        checkpoint.save_model(fo, 0, self.net_cfg.to_dict(), self.epoch,
                              params, opt)

    def load_model(self, fi) -> None:
        # sniff the format: native files start with the CXTPU magic,
        # reference-binary files with a little int32 net_type
        head = fi.read(len(checkpoint.MAGIC))
        fi.seek(-len(head), 1)
        if head != checkpoint.MAGIC:
            self._load_legacy(fi)
            return
        blob = checkpoint.load_model(fi)
        self.net_cfg = NetConfig.from_dict(blob["net"])
        self.net_cfg.configure(self.cfg_pairs)
        self.epoch = blob["epoch"]
        self._epoch_base = self.epoch
        self._step_counter = 0
        self._skipped_steps = 0
        self._bad_consec = 0
        self._loaded_opt = blob["opt_state"]
        self._build_net()
        params = jax.tree.map(jnp.asarray, blob["params"])
        self._init_state(params)
        self.state["epoch"] = distributed.put_global(
            np.asarray(self.epoch, np.int32), self._replicated)

    def _load_legacy(self, fi) -> None:
        """Load a reference-binary model. Like the reference, the
        netconfig must come from the config file; the file supplies
        structure (validated for equality) + weights."""
        from cxxnet_tpu.nnet import legacy_format
        self.net_cfg = NetConfig()
        self.net_cfg.configure(self.cfg_pairs)
        self._build_net()
        # shapes only - no throwaway device init
        expected = jax.eval_shape(self.net.init_params,
                                  jax.random.PRNGKey(self.seed))
        blob = legacy_format.load_legacy_model(fi, self.net_cfg,
                                               self.net, expected)
        self.epoch = blob["epoch"]
        self._epoch_base = self.epoch
        self._step_counter = 0
        self._skipped_steps = 0
        self._bad_consec = 0
        params = jax.tree.map(jnp.asarray, blob["params"])
        self._init_state(params)
        self.state["epoch"] = distributed.put_global(
            np.asarray(self.epoch, np.int32), self._replicated)

    def copy_model_from(self, fi) -> None:
        """Finetune: copy params of layers whose names match
        (nnet_impl-inl.hpp:101-134). Must be called after init_model."""
        if self.state is None:
            raise RuntimeError("copy_model_from requires init_model first")
        head = fi.read(len(checkpoint.MAGIC))
        fi.seek(-len(head), 1)
        if head == checkpoint.MAGIC:
            blob = checkpoint.load_model(fi)
        else:
            from cxxnet_tpu.nnet import legacy_format
            blob = legacy_format.read_legacy_model(fi)
        params = self._full_params()
        copied = []
        for lk, d in blob["params"].items():
            if lk.startswith("layer_"):
                continue  # unnamed layers are not matched
            if lk in params:
                for pn, arr in d.items():
                    if pn not in params[lk]:
                        continue
                    want = params[lk][pn].shape
                    if arr.shape != want and arr.size == params[
                            lk][pn].size:
                        # legacy conv wmat arrives in the file's 3D
                        # layout - same memory order as our OIHW
                        arr = arr.reshape(want)
                    if arr.shape == want:
                        params[lk][pn] = arr
                copied.append(lk)
        if not self.silent:
            telemetry.stdout(f"finetune: copied layers {copied}")
        self._init_state(jax.tree.map(jnp.asarray, params))

    # ------------------------------------------------------------------
    # weight access api (visitor semantics)
    # ------------------------------------------------------------------
    def get_weight(self, layer_name: str,
                   tag: str) -> Tuple[np.ndarray, Tuple[int, ...]]:
        """Returns (2-D flattened weight, original shape); GetWeightVisitor
        flattening = (shape[0], prod(rest)) (visitor.h:26-100)."""
        lk = self._weight_key(layer_name, tag)
        leaf = self.state["params"][lk[0]][lk[1]]
        if getattr(self, "_zero_run", 0) == 3:
            # gather this weight's zero shards (visitors see full 2-D)
            leaf = jax.jit(
                lambda t: t,
                out_shardings=self._pshard[lk[0]][lk[1]])(leaf)
        arr = distributed.fetch_local(leaf)
        return arr.reshape(arr.shape[0], -1), arr.shape

    def set_weight(self, weight: np.ndarray, layer_name: str,
                   tag: str) -> None:
        lk = self._weight_key(layer_name, tag)
        cur = self.state["params"][lk[0]][lk[1]]
        arr = np.asarray(weight, dtype=np.float32).reshape(cur.shape)
        params = self.state["params"]
        # full global host value -> put_global_full (put_global would
        # misread it as a pre-cut local shard when the param is sharded
        # across processes, e.g. tensor parallelism over hosts); lands
        # on the between-steps layout (the zero cut under zero_stage=3)
        params[lk[0]][lk[1]] = distributed.put_global_full(
            arr, self._params_store_shard[lk[0]][lk[1]])
        self.state["params"] = params
        self._retire_calibration_state()

    def check_weights(self) -> List[str]:
        """test_on_server analog (async_updater-inl.hpp:144-153): verify
        replicated params are identical on every device/process."""
        return distributed.check_replicated(self.state["params"])

    def _weight_key(self, layer_name: str, tag: str) -> Tuple[str, str]:
        idx = self.net_cfg.get_layer_index(layer_name)
        tags = self.net.layer_objs[idx].param_tags()
        for pname, t in tags.items():
            if t == tag or pname == tag:
                return param_key(self.net_cfg, idx), pname
        raise KeyError(f"layer {layer_name} has no weight tagged {tag}")


def create_net(net_type: int = 0, dev: str = "", cfg: str = "") -> NetTrainer:
    """CreateNet factory parity (nnet.h:99-100; net_type is ignored by the
    reference too - nnet_impl-inl.hpp:457-460)."""
    return NetTrainer(dev, cfg)
