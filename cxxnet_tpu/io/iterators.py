"""IIterator base protocol (src/io/data.h:18-38)."""

from __future__ import annotations

from typing import Generic, TypeVar

T = TypeVar("T")


class DataIter(Generic[T]):
    """SetParam / Init / BeforeFirst / Next / Value protocol."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self) -> T:
        raise NotImplementedError

    # iteration sugar
    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()
