"""IIterator base protocol (src/io/data.h:18-38)."""

from __future__ import annotations

from typing import Generic, TypeVar

T = TypeVar("T")


class DataIter(Generic[T]):
    """SetParam / Init / BeforeFirst / Next / Value protocol."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self) -> T:
        raise NotImplementedError

    # iteration sugar
    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()


def shard_quota(n: int, num_worker: int, rank: int):
    """Equalized per-worker shard accounting shared by the base
    iterators (reference discipline iter_thread_imbin-inl.hpp:189-220,
    tightened for sync SPMD): every worker must serve EXACTLY
    floor(n/num_worker) instances - unequal per-worker batch counts
    would desynchronize the per-batch collectives. A dataset smaller
    than the worker count cannot satisfy that and fails loudly.

    Returns (quota, rank). Callers either slice `rows[rank::nw][:quota]`
    or filter ordinals `ord % nw == rank` counting served up to quota.
    """
    if num_worker <= 1:
        return n, 0
    if n < num_worker:
        raise ValueError(
            f"dataset of {n} instances cannot shard over "
            f"{num_worker} workers (fewer instances than workers)")
    return n // num_worker, rank
