"""IIterator base protocol (src/io/data.h:18-38)."""

from __future__ import annotations

from typing import Generic, TypeVar

T = TypeVar("T")


class DataIter(Generic[T]):
    """SetParam / Init / BeforeFirst / Next / Value protocol."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self) -> T:
        raise NotImplementedError

    # iteration sugar
    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()


class RetryIterator(DataIter):
    """Transparent wrapper adding transient-IO-error retry around
    next()/before_first() (utils/fault.retry): a network-mount hiccup
    on a shared dataset costs a backoff, not the training run.

    Config keys (forwarded to the wrapped chain as well):
    - ``io_retry``: attempts per call (default 3; 1 disables retry)
    - ``io_retry_backoff``: initial backoff seconds (default 0.05)

    Only OSError (and subclasses - includes the injected-fault
    InjectedIOError) is considered transient; anything else propagates
    immediately. NOTE a retried next() re-invokes the underlying chain,
    which may skip the batch the failed call was assembling - the
    contract is at-most-once delivery per instance, matching the
    reference's tolerance for dropped tail batches.

    The ``io.next`` / ``io.before_first`` fault points fire INSIDE the
    retried call, so injected ``ioerror`` faults are absorbed exactly
    like real transient errors."""

    def __init__(self, inner: "DataIter"):
        self.inner = inner
        self.attempts = 3
        self.backoff = 0.05
        self._next = None
        self._bf = None

    def set_param(self, name: str, val: str) -> None:
        if name == "io_retry":
            self.attempts = max(1, int(val))
            self._next = self._bf = None
        elif name == "io_retry_backoff":
            self.backoff = float(val)
            self._next = self._bf = None
        self.inner.set_param(name, val)

    def init(self) -> None:
        self.inner.init()

    def _build(self) -> None:
        from cxxnet_tpu import telemetry
        from cxxnet_tpu.utils.fault import (
            default_on_retry, fault_point, retry)

        def notify(fn, attempt, total, exc, sleep_s):
            # io-scoped retry count alongside the global fault.retry
            # counter/event the shared notifier keeps (same stderr text)
            telemetry.inc("io.retry")
            default_on_retry(fn, attempt, total, exc, sleep_s)

        deco = retry(attempts=self.attempts, backoff=self.backoff,
                     retry_on=(OSError,), on_retry=notify)

        def raw_next():
            fault_point("io.next")
            return self.inner.next()

        def raw_before_first():
            fault_point("io.before_first")
            self.inner.before_first()

        self._next = deco(raw_next)
        self._bf = deco(raw_before_first)

    def before_first(self) -> None:
        if self._bf is None:
            self._build()
        self._bf()

    def next(self) -> bool:
        if self._next is None:
            self._build()
        return self._next()

    def value(self):
        return self.inner.value()

    def __getattr__(self, name):
        # transparent delegation for chain-specific surface (close,
        # labels, handles) so wrapping is invisible to callers
        return getattr(self.inner, name)


def shard_quota(n: int, num_worker: int, rank: int):
    """Equalized per-worker shard accounting shared by the base
    iterators (reference discipline iter_thread_imbin-inl.hpp:189-220,
    tightened for sync SPMD): every worker must serve EXACTLY
    floor(n/num_worker) instances - unequal per-worker batch counts
    would desynchronize the per-batch collectives. A dataset smaller
    than the worker count cannot satisfy that and fails loudly.

    Returns (quota, rank). Callers either slice `rows[rank::nw][:quota]`
    or filter ordinals `ord % nw == rank` counting served up to quota.
    """
    if num_worker <= 1:
        return n, 0
    if n < num_worker:
        raise ValueError(
            f"dataset of {n} instances cannot shard over "
            f"{num_worker} workers (fewer instances than workers)")
    return n // num_worker, rank
