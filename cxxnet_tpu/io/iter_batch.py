"""Instance->batch collation and batch-level prefetch.

- BatchAdaptIterator (iter_batch_proc-inl.hpp:16-133): collates DataInst
  into DataBatch; `round_batch=1` wraps to the start to fill the final
  short batch, recording num_batch_padd (and returning False on the next
  round until before_first); round_batch=0 zero-pads instead.
- ThreadBufferIterator (iter_batch_proc-inl.hpp:136-224): double-buffers
  whole batches on a background thread (the ThreadBuffer role).
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataBatch, DataInst
from cxxnet_tpu.io.iterators import DataIter
from cxxnet_tpu.io.thread_util import (
    ErrorBox, drain_and_join, stoppable_put)


class BatchAdaptIterator(DataIter):
    def __init__(self, base: DataIter):
        self.base = base
        self.batch_size = 0
        self.label_width = 1
        self.round_batch = 0
        self.num_overflow = 0
        self.test_skipread = 0
        self.silent = 0
        self._head = 1

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "batch_size":
            self.batch_size = int(val)
        if name == "label_width":
            self.label_width = int(val)
        if name == "round_batch":
            self.round_batch = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "test_skipread":
            self.test_skipread = int(val)

    def init(self) -> None:
        self.base.init()

    def before_first(self) -> None:
        if self.round_batch == 0 or self.num_overflow == 0:
            self.base.before_first()
        else:
            self.num_overflow = 0
        self._head = 1

    def _collect(self, insts) -> DataBatch:
        # uint8 instances (device_augment raw passthrough) stay uint8:
        # the 1/4-size H2D staging is the point of that mode
        data = np.stack([d.data for d in insts])
        if data.dtype != np.uint8:
            data = data.astype(np.float32, copy=False)
        label = np.zeros((len(insts), self.label_width), dtype=np.float32)
        for i, d in enumerate(insts):
            w = min(self.label_width, len(d.label))
            label[i, :w] = d.label[:w]
        inst_index = np.asarray([d.index for d in insts], dtype=np.uint32)
        extra = []
        if insts[0].extra_data:
            for k in range(len(insts[0].extra_data)):
                extra.append(np.stack([d.extra_data[k] for d in insts]))
        return DataBatch(data=data, label=label, inst_index=inst_index,
                         extra_data=extra)

    def next(self) -> bool:
        # test_skipread: serve the same batch forever after the first read
        if self.test_skipread and not self._head:
            return True
        self._head = 0
        if self.num_overflow:
            return False
        insts = []
        while self.base.next():
            insts.append(self.base.value())
            if len(insts) >= self.batch_size:
                self._out = self._collect(insts)
                return True
        if not insts:
            return False
        top = len(insts)
        if self.round_batch:
            self.base.before_first()
            self.num_overflow = 0
            while len(insts) < self.batch_size:
                if not self.base.next():
                    raise ValueError(
                        "number of inputs must exceed batch size")
                insts.append(self.base.value())
                self.num_overflow += 1
            self._out = self._collect(insts)
            self._out.num_batch_padd = self.num_overflow
        else:
            # zero-pad the short tail
            pad = self.batch_size - top
            template = insts[0]
            for _ in range(pad):
                insts.append(DataInst(
                    index=0,
                    data=np.zeros_like(template.data),
                    label=np.zeros_like(template.label),
                    extra_data=[np.zeros_like(e)
                                for e in template.extra_data]))
            self._out = self._collect(insts)
            self._out.num_batch_padd = pad
        return True

    def value(self) -> DataBatch:
        return self._out


class ThreadBufferIterator(DataIter):
    """Prefetches batches from `base` on a daemon thread."""

    def __init__(self, base: DataIter):
        self.base = base
        self.buffer_size = 2
        self.silent = 0
        self._thread: Optional[threading.Thread] = None
        self._q: Optional[queue.Queue] = None
        self._stop = threading.Event()

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "buffer_size":
            self.buffer_size = int(val)
        if name == "silent":
            self.silent = int(val)

    def init(self) -> None:
        self.base.init()
        if not self.silent:
            telemetry.stdout(
                f"ThreadBufferIterator: buffer_size={self.buffer_size}")

    def _producer(self, q: "queue.Queue", stop: threading.Event) -> None:
        try:
            self.base.before_first()
            while not stop.is_set() and self.base.next():
                if not stoppable_put(q, stop, self.base.value()):
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised in next()
            # a producer failure must surface in the consumer, not
            # masquerade as a clean end-of-data (lock-guarded handoff:
            # the write is published before the sentinel put below)
            self._err.put(e)
        finally:
            stoppable_put(q, stop, None)

    def before_first(self) -> None:
        self._shutdown()
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self.buffer_size)
        self._err = ErrorBox()
        self._done = False
        self._thread = threading.Thread(
            target=self._producer, args=(self._q, self._stop), daemon=True)
        self._thread.start()

    def _shutdown(self) -> None:
        if self._thread is not None:
            drain_and_join(self._q, self._thread, self._stop)
            self._thread = None

    def next(self) -> bool:
        if self._q is None:
            self.before_first()
        if self._done:
            # reference ThreadBuffer keeps returning false after EOF;
            # blocking on the dead producer's empty queue would hang
            return False
        item = self._q.get()
        if item is None:
            self._done = True
            exc = self._err.take()
            if exc is not None:
                raise RuntimeError(
                    "ThreadBufferIterator: producer thread failed") \
                    from exc
            return False
        self._out = item
        return True

    def value(self) -> DataBatch:
        return self._out
