"""ctypes binding for the native io pipeline (native/cxxnet_io.cc).

The native library implements the reference's two-stage decode pipeline
(iter_thread_imbin_x-inl.hpp:18-397) in C++: a page-reader thread streams
64MiB BinaryPages, a worker pool decodes JPEG/PNG blobs off the GIL, and
records are handed back strictly in stream order. Python keeps the .lst
parsing, label join, shuffle, augmentation, and batching.

The library is searched at cxxnet_tpu/lib/libcxxnet_io.so (built by
`make -C native`) or $CXXNET_TPU_NATIVE; when g++ is available and the
library is missing it is built on demand. `native_available()` gates all
use; every consumer falls back to the pure-Python decoder.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_LIB_NAME = "libcxxnet_io.so"
_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


class CxioRecord(ctypes.Structure):
    _fields_ = [("data", ctypes.POINTER(ctypes.c_ubyte)),
                ("h", ctypes.c_int),
                ("w", ctypes.c_int),
                ("c", ctypes.c_int)]


def _lib_path() -> str:
    env = os.environ.get("CXXNET_TPU_NATIVE")
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "lib", _LIB_NAME)


def _try_build(path: str) -> bool:
    """Build the library from native/ if the source tree is present."""
    global _build_attempted
    if _build_attempted:
        return os.path.exists(path)
    _build_attempted = True
    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")
    if not os.path.exists(os.path.join(native_dir, "Makefile")):
        return False
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    return os.path.exists(path)


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _lib_path()
        if not os.path.exists(path) and not _try_build(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.cxio_open.restype = ctypes.c_void_p
        lib.cxio_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int]
        lib.cxio_before_first.argtypes = [ctypes.c_void_p]
        lib.cxio_next.restype = ctypes.c_int
        lib.cxio_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(CxioRecord)]
        lib.cxio_last_error.restype = ctypes.c_char_p
        lib.cxio_last_error.argtypes = [ctypes.c_void_p]
        lib.cxio_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeBinReader:
    """Ordered record stream over one or more .bin files.

    out_mode 1 (default): (c,h,w) float32 CHW, converted on the native
    worker threads - the host-augmentation layout. out_mode 2: (c,h,w)
    uint8 CHW - device-side augmentation staging (device_augment=1),
    1/4 the f32 bytes end-to-end."""

    def __init__(self, bin_paths: List[str], n_threads: int = 4,
                 max_inflight: int = 64, out_mode: int = 1):
        lib = _load()
        if lib is None:
            raise RuntimeError("native io library unavailable")
        self._lib = lib
        self._mode = out_mode
        arr = (ctypes.c_char_p * len(bin_paths))(
            *[p.encode() for p in bin_paths])
        self._h = lib.cxio_open(arr, len(bin_paths), n_threads,
                                max_inflight, out_mode)
        self._rec = CxioRecord()

    def before_first(self) -> None:
        self._lib.cxio_before_first(self._h)

    def next(self) -> Optional[np.ndarray]:
        """Next decoded image as (c,h,w) CHW (f32 or u8 per out_mode),
        or the raw blob decoded via PIL when the native decoders could
        not handle it. None at end of stream (raises on stream error)."""
        if not self._lib.cxio_next(self._h, ctypes.byref(self._rec)):
            err = self._lib.cxio_last_error(self._h)
            if err:
                raise IOError(err.decode())
            return None
        r = self._rec
        if r.c == 0:  # undecodable natively; PIL fallback on the raw blob
            from cxxnet_tpu.io.iter_img import decode_image
            blob = ctypes.string_at(r.data, r.w)
            img = decode_image(blob)  # uint8 CHW
            return img if self._mode == 2 else img.astype(np.float32)
        n = r.h * r.w * r.c
        if self._mode == 2:
            u8 = ctypes.cast(r.data, ctypes.POINTER(ctypes.c_uint8))
            return np.ctypeslib.as_array(u8, shape=(n,)).reshape(
                r.c, r.h, r.w).copy()
        # the record already is CHW float32 (converted on the native
        # worker threads); one memcpy to own the buffer
        fptr = ctypes.cast(r.data, ctypes.POINTER(ctypes.c_float))
        return np.ctypeslib.as_array(fptr, shape=(n,)).reshape(
            r.c, r.h, r.w).copy()

    def close(self) -> None:
        if self._h:
            self._lib.cxio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
