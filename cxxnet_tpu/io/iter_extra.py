"""DenseBufferIterator (`membuffer`) and AttachTxtIterator (`attachtxt`).

- membuffer (iter_mem_buffer-inl.hpp:16-77): caches the first max_nbatch
  batches in RAM and serves only those from then on.
- attachtxt (iter_attach_txt-inl.hpp:15-101): joins per-instance side
  features from a text table into batch.extra_data by inst_index.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.io.iterators import DataIter


class DenseBufferIterator(DataIter):
    def __init__(self, base: DataIter):
        self.base = base
        self.max_nbatch = 0
        self.silent = 0
        self._cache: List[DataBatch] = []
        self._filled = False
        self._pos = 0

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "max_nbatch":
            self.max_nbatch = int(val)
        if name == "silent":
            self.silent = int(val)

    def init(self) -> None:
        self.base.init()
        if self.max_nbatch <= 0:
            raise ValueError("membuffer requires max_nbatch > 0")

    def before_first(self) -> None:
        self._pos = 0
        if not self._filled:
            # restarting mid-fill: refill from scratch to avoid duplicates
            self._cache = []
            self.base.before_first()

    def next(self) -> bool:
        if not self._filled:
            if (len(self._cache) < self.max_nbatch and self.base.next()):
                b = self.base.value()
                self._cache.append(DataBatch(
                    data=b.data.copy(), label=b.label.copy(),
                    inst_index=None if b.inst_index is None
                    else b.inst_index.copy(),
                    num_batch_padd=b.num_batch_padd,
                    extra_data=[e.copy() for e in b.extra_data]))
                self._out = self._cache[-1]
                self._pos = len(self._cache)
                return True
            self._filled = True
        if self._pos < len(self._cache):
            self._out = self._cache[self._pos]
            self._pos += 1
            return True
        return False

    def value(self) -> DataBatch:
        return self._out


class AttachTxtIterator(DataIter):
    """Joins a text table `index feat...` into batch.extra_data."""

    def __init__(self, base: DataIter):
        self.base = base
        self.filename = ""
        self.silent = 0
        self._table: Dict[int, np.ndarray] = {}
        self._width = 0

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "filename":
            self.filename = val
        if name == "silent":
            self.silent = int(val)

    def init(self) -> None:
        self.base.init()
        with open(self.filename, "r", encoding="utf-8") as f:
            for line in f:
                toks = line.split()
                if not toks:
                    continue
                idx = int(float(toks[0]))
                feats = np.asarray([float(t) for t in toks[1:]],
                                   dtype=np.float32)
                self._table[idx] = feats
                self._width = max(self._width, len(feats))
        if not self.silent:
            telemetry.stdout(
                f"AttachTxtIterator: {len(self._table)} rows of width "
                f"{self._width}")

    def before_first(self) -> None:
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        b = self.base.value()
        extra = np.zeros((b.batch_size, 1, 1, self._width),
                         dtype=np.float32)
        for i, idx in enumerate(b.inst_index):
            row = self._table.get(int(idx))
            if row is not None:
                extra[i, 0, 0, :len(row)] = row
        self._out = DataBatch(
            data=b.data, label=b.label, inst_index=b.inst_index,
            num_batch_padd=b.num_batch_padd,
            extra_data=b.extra_data + [extra])
        return True

    def value(self) -> DataBatch:
        return self._out
