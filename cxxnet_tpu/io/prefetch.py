"""Double-buffered host->device staging (the ThreadBuffer at the H2D edge).

The reference hides disk/decode latency behind compute with a generic
two-semaphore double buffer (utils/thread_buffer.h:22-202) and a
batch-level ThreadBufferIterator (iter_batch_proc-inl.hpp:136-224).
On TPU the analogous stall is not the disk but the HOST->DEVICE edge:
the per-step pad + cast + device_put of batch k+1 serializes after the
(asynchronously dispatched) step k unless it runs on its own thread.

StagedPrefetcher wraps any DataIter and runs the trainer's FULL
staging pipeline (trainer.stage_batch: pad, host cast, device_put
under the step's in_shardings) on a worker thread, `depth` batches
ahead. value() yields StagedBatch objects, which trainer.update()
consumes with zero per-step host work - so staging of batch k+1
overlaps both the host dispatch and the device compute of batch k.
Trajectory-identical to streaming the DataBatches directly (staging is
the same code either way; RNG folds on the step counter, not on wall
time).
"""

from __future__ import annotations

import queue
import sys
import threading
import time

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.thread_util import drain_and_join

_END = object()


class StagedPrefetcher:
    """DataIter-protocol wrapper: before_first()/next()/value(), where
    value() returns the staged (device-resident) batch. stage_fn is
    typically trainer.stage_batch; source is any DataIter yielding
    DataBatches. Up to depth+1 staged batches are resident at once
    (depth queued plus the one the worker holds while the queue is
    full), each pinning its device buffers in HBM until consumed -
    budget HBM headroom for depth+1, not depth.

    Fused dispatch (steps_per_dispatch=K, docs/PERFORMANCE.md):
    chunk=K makes the worker assemble K staged batches into one
    StagedChunk via chunk_fn (trainer.stage_chunk) per queue item -
    the last item of a pass may be a SHORT chunk (the round-boundary
    flush). HBM budget then scales to K*(depth+1) batches resident."""

    def __init__(self, stage_fn, source, depth: int = 1,
                 chunk: int = 1, chunk_fn=None):
        self.stage_fn = stage_fn
        self.source = source
        self.depth = max(1, int(depth))
        self.chunk = max(1, int(chunk))
        if self.chunk > 1 and chunk_fn is None:
            raise ValueError("chunk > 1 requires chunk_fn")
        self.chunk_fn = chunk_fn
        self._q = None
        self._thread = None
        self._stop = threading.Event()
        self._cur = None
        self._exhausted = False
        self._closed = False
        self._pending_error = None
        # telemetry armed? cached per pass (before_first) - the
        # disabled next() path must cost one attribute check, not a
        # singleton lookup per batch
        self._tel = False

    # -- DataIter protocol -------------------------------------------------
    def before_first(self) -> None:
        self._shutdown()
        # restarting the pass abandons any undelivered worker error
        # (the rewind re-reads the same data; a persistent fault will
        # re-raise on this pass)
        self._pending_error = None
        self.source.before_first()
        self._tel = telemetry.enabled()
        self._q = queue.Queue(maxsize=self.depth)
        self._stop.clear()
        self._exhausted = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="staged-prefetch", daemon=True)
        self._thread.start()

    # graftlint: hot-path (per-batch consumer path: no host syncs here)
    def next(self) -> bool:
        if self._closed:
            # close() is terminal for the current pass: a stray next()
            # from a consumer's cleanup path must not silently rewind
            # the source and resurrect a worker nothing will close
            return False
        if self._q is None:
            self.before_first()
        if self._exhausted:
            # the worker put ONE _END and exited; a blocking get here
            # would hang forever
            return False
        t0 = time.perf_counter() if self._tel else 0.0
        stalled = False
        try:
            # common path: the worker is ahead and the queue is
            # non-empty - ONE non-blocking get, zero timeout wakeups
            # (the old 0.2 s get-loop woke 5x/sec for the whole stall
            # on data-bound runs)
            item = self._q.get_nowait()
        except queue.Empty:
            # the staging worker is behind the consumer: block on the
            # queue. The first get keeps the historic 0.2 s bar so the
            # io.prefetch.stalls metric retains its meaning (a wait
            # the consumer actually felt, not an instantaneously-empty
            # queue); later gets stretch to 2 s - the timeout then
            # exists ONLY as the dead-worker sweep (a healthy worker
            # always delivers a batch, _END, or its exception)
            timeout = 0.2
            while True:
                try:
                    item = self._q.get(timeout=timeout)
                    break
                except queue.Empty:
                    stalled = True
                    timeout = 2.0
                    if (self._thread is not None
                            and self._thread.is_alive()):
                        continue
                    # worker died without delivering a batch, _END, or
                    # an exception (e.g. killed interpreter-side): one
                    # last race-free sweep, then fail instead of
                    # hanging forever
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        self._exhausted = True
                        raise RuntimeError(
                            "staged-prefetch worker died without "
                            "delivering a batch or an error; the data "
                            "pipeline is gone (see stderr for the "
                            "worker's traceback)")
        if item is _END:
            self._exhausted = True
            return False
        if isinstance(item, BaseException):
            # the worker exits after putting its exception; a caller
            # that catches it and calls next() again must get False,
            # not a hang on a dead producer's queue
            self._exhausted = True
            telemetry.inc("io.prefetch.worker_errors")
            raise item
        self._cur = item
        if self._tel:
            telemetry.inc("io.prefetch.batches")
            telemetry.set_gauge("io.prefetch.depth", self._q.qsize())
            wait = time.perf_counter() - t0
            telemetry.observe("io.prefetch.wait_s", wait)
            if stalled:
                telemetry.inc("io.prefetch.stalls")
        return True

    def value(self):
        return self._cur

    def close(self) -> None:
        """Stop the worker and drop queued staged batches. REQUIRED
        when abandoning a pass mid-stream (consumer error): the worker
        otherwise spins in _put holding staged batches - pinned device
        memory - alive for the life of the process (the running
        thread's self-reference also defeats GC). Terminal for the
        pass: next() returns False until before_first() reopens.
        Idempotent.

        A worker exception still queued (the consumer stopped before
        next() could deliver it) is raised here rather than swallowed -
        unless close() is itself running from an exception handler, in
        which case the in-flight error wins and the worker's is noted
        on stderr."""
        self._shutdown()
        self._closed = True
        err, self._pending_error = self._pending_error, None
        if err is not None:
            if sys.exc_info()[1] is None:
                raise err
            telemetry.stderr(
                f"staged-prefetch: worker error superseded by the "
                f"consumer's: {type(err).__name__}: {err}\n",
                event_kind="io", type="prefetch_worker_error_superseded",
                error=f"{type(err).__name__}: {err}")

    # -- worker ------------------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded put that stays responsive to _shutdown (a plain
        blocking put would deadlock against a consumer that stopped
        consuming)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            pending = []
            while not self._stop.is_set() and self.source.next():
                staged = self.stage_fn(self.source.value())
                if self.chunk <= 1:
                    if not self._put(staged):
                        return
                    continue
                pending.append(staged)
                if len(pending) >= self.chunk:
                    # release the per-batch staged singles BEFORE the
                    # (possibly long) blocking put: holding them
                    # through a full-queue wait would pin K extra
                    # batches of HBM beyond the documented
                    # K*(depth+1) budget
                    item = self.chunk_fn(pending)
                    pending = []
                    if not self._put(item):
                        return
            if pending and not self._stop.is_set():
                # round-boundary flush: the pass ended mid-chunk; a
                # SHORT chunk ships the tail so every delivered batch
                # trains this round (dropping it would silently starve
                # the trailing batches of every epoch)
                item = self.chunk_fn(pending)
                pending = []
                if not self._put(item):
                    return
            self._put(_END)
        except BaseException as e:  # noqa: BLE001 - re-raised in next()
            self._put(e)

    def _shutdown(self) -> None:
        if self._thread is None:
            return
        # bounded drain-while-join (thread_util discipline shared with
        # the rest of io/): a worker stuck outside q.put fails loudly
        # after the timeout instead of hanging the trainer; drained
        # worker exceptions are kept, not discarded
        def keep_error(item):
            if (isinstance(item, BaseException)
                    and self._pending_error is None):
                self._pending_error = item

        drain_and_join(self._q, self._thread, self._stop,
                       on_item=keep_error)
        self._q = None
        self._thread = None
