"""Per-instance augmentation.

AugmentIterator parity (src/io/iter_augment_proc-inl.hpp:21-246):
random/fixed crop to input_shape, random mirror, scale / divideby,
mean-image subtraction (with first-run mean computation + caching) or
per-channel mean_value, random contrast/illumination. Affine warps
(rotation / shear / aspect-ratio / random scale composed into one warp)
follow ImageAugmenter (src/io/image_augmenter-inl.hpp:13-204), implemented
with scipy.ndimage instead of cv::warpAffine.

Channel convention: images are loaded RGB; `mean_value = b,g,r` keeps the
reference's (BGR) config order and is applied to the matching channels.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataInst
from cxxnet_tpu.io.iterators import DataIter


class ImageAugmenter:
    """Affine warp + crop (image_augmenter-inl.hpp)."""

    def __init__(self) -> None:
        self.shape = None  # (c, y, x)
        self.rand_crop = 0
        self.max_rotate_angle = 0.0
        self.max_aspect_ratio = 0.0
        self.max_shear_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate = -1.0
        self.max_random_scale = 1.0
        self.min_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.fill_value = 255
        self.rotate_list: List[int] = []

    def set_param(self, name: str, val: str) -> None:
        if name == "input_shape":
            self.shape = tuple(int(t) for t in val.split(","))
        if name == "rand_crop":
            self.rand_crop = int(val)
        if name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        if name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        if name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        if name == "min_crop_size":
            self.min_crop_size = int(val)
        if name == "max_crop_size":
            self.max_crop_size = int(val)
        if name == "min_random_scale":
            self.min_random_scale = float(val)
        if name == "max_random_scale":
            self.max_random_scale = float(val)
        if name == "min_img_size":
            self.min_img_size = float(val)
        if name == "max_img_size":
            self.max_img_size = float(val)
        if name == "fill_value":
            self.fill_value = int(val)
        if name == "rotate":
            self.rotate = int(val)
        if name == "rotate_list":
            self.rotate_list = [int(t) for t in val.split(",")]

    def need_process(self) -> bool:
        if (self.max_rotate_angle > 0 or self.max_shear_ratio > 0
                or self.rotate > 0 or self.rotate_list):
            return True
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            return True
        return False

    def process(self, data: np.ndarray,
                rng: np.random.RandomState) -> np.ndarray:
        """data: (c, h, w) float; returns (c, h', w')."""
        if not self.need_process():
            return data
        from scipy import ndimage

        c, rows, cols = data.shape
        s = rng.uniform(-self.max_shear_ratio, self.max_shear_ratio)
        if self.max_rotate_angle > 0:
            angle = rng.randint(0, int(self.max_rotate_angle * 2) + 1) \
                - self.max_rotate_angle
        else:
            angle = 0
        if self.rotate > 0:
            angle = self.rotate
        if self.rotate_list:
            angle = self.rotate_list[rng.randint(0, len(self.rotate_list))]
        a = np.cos(angle / 180.0 * np.pi)
        b = np.sin(angle / 180.0 * np.pi)
        scale = rng.uniform(self.min_random_scale, self.max_random_scale)
        ratio = rng.uniform(-self.max_aspect_ratio,
                            self.max_aspect_ratio) + 1.0
        hs = 2 * scale / (1 + ratio)
        ws = ratio * hs
        new_w = int(max(self.min_img_size,
                        min(self.max_img_size, scale * cols)))
        new_h = int(max(self.min_img_size,
                        min(self.max_img_size, scale * rows)))
        # forward map (x', y') = M @ (x, y) + t  (image_augmenter:86-95)
        m00 = hs * a - s * b * ws
        m01 = hs * b + s * a * ws
        m10 = -b * ws
        m11 = a * ws
        t0 = (new_w - (m00 * cols + m01 * rows)) / 2
        t1 = (new_h - (m10 * cols + m11 * rows)) / 2
        # scipy wants the inverse map from output coords to input coords
        fwd = np.array([[m00, m01, t0], [m10, m11, t1], [0, 0, 1]],
                       dtype=np.float64)
        inv = np.linalg.inv(fwd)
        # affine_transform matrix is in (row, col) order
        mat = np.array([[inv[1, 1], inv[1, 0]], [inv[0, 1], inv[0, 0]]])
        off = np.array([inv[1, 2], inv[0, 2]])
        out = np.empty((c, new_h, new_w), dtype=data.dtype)
        for ch in range(c):
            out[ch] = ndimage.affine_transform(
                data[ch], mat, offset=off, output_shape=(new_h, new_w),
                order=1, mode="constant", cval=self.fill_value)

        # optional random crop-size crop + resize back to >= input shape
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            cs = rng.randint(self.min_crop_size, self.max_crop_size + 1)
            cs = min(cs, out.shape[1], out.shape[2])
            yy = rng.randint(0, out.shape[1] - cs + 1)
            xx = rng.randint(0, out.shape[2] - cs + 1)
            crop = out[:, yy:yy + cs, xx:xx + cs]
            ty, tx = self.shape[1], self.shape[2]
            zy, zx = ty / crop.shape[1], tx / crop.shape[2]
            out = np.stack([
                ndimage.zoom(crop[ch], (zy, zx), order=1)
                for ch in range(c)])
        return out


def load_mean_image(path: str) -> np.ndarray:
    """Load a mean image, auto-detecting the format.

    Reference files are mshadow Tensor<cpu,3>::LoadBinary payloads
    (iter_augment_proc-inl.hpp:84): uint32 shape[3] = (c, y, x) followed
    by packed little-endian float32 data - the same SaveBinary layout the
    checkpoint weights use (nnet/legacy_format.py). Files written by
    earlier rounds of this repo are .npy; sniffed by the numpy magic.
    """
    with open(path, "rb") as fi:
        head = fi.read(6)
        fi.seek(0)
        if head == b"\x93NUMPY":
            return np.load(fi)
        shape = np.frombuffer(fi.read(12), "<u4")
        n = int(shape.prod())
        data = np.frombuffer(fi.read(4 * n), "<f4")
        if data.size != n:
            raise ValueError(
                f"{path}: truncated mean image (expected {n} floats)")
        return data.reshape(tuple(int(s) for s in shape)).copy()


def save_mean_image(path: str, mean: np.ndarray) -> None:
    """Write the reference SaveBinary layout
    (iter_augment_proc-inl.hpp:193) so reference binaries can consume
    the file."""
    if mean.ndim != 3:
        raise ValueError("mean image must be (c, y, x)")
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "wb") as fo:
        fo.write(np.asarray(mean.shape, "<u4").tobytes())
        fo.write(np.ascontiguousarray(mean, "<f4").tobytes())


class AugmentIterator(DataIter):
    """Crop/mirror/scale/mean pipeline over a DataInst iterator."""

    K_RAND_MAGIC = 0

    def __init__(self, base: DataIter):
        self.base = base
        self.rand_crop = 0
        self.rand_mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.scale = 1.0
        self.silent = 0
        self.name_meanimg = ""
        self.mean_b = self.mean_g = self.mean_r = 0.0
        self.mirror = 0
        self.max_random_illumination = 0.0
        self.max_random_contrast = 0.0
        self.shape = None  # (c, y, x)
        self.device_augment = 0
        self.aug = ImageAugmenter()
        self.rng = np.random.RandomState(self.K_RAND_MAGIC)
        self.meanimg: Optional[np.ndarray] = None

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "input_shape":
            self.shape = tuple(int(t) for t in val.split(","))
        if name == "seed_data":
            self.rng = np.random.RandomState(self.K_RAND_MAGIC + int(val))
        if name == "rand_crop":
            self.rand_crop = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "divideby":
            self.scale = 1.0 / float(val)
        if name == "scale":
            self.scale = float(val)
        if name == "image_mean":
            self.name_meanimg = val
        if name == "crop_y_start":
            self.crop_y_start = int(val)
        if name == "crop_x_start":
            self.crop_x_start = int(val)
        if name == "rand_mirror":
            self.rand_mirror = int(val)
        if name == "mirror":
            self.mirror = int(val)
        if name == "max_random_contrast":
            self.max_random_contrast = float(val)
        if name == "max_random_illumination":
            self.max_random_illumination = float(val)
        if name == "mean_value":
            self.mean_b, self.mean_g, self.mean_r = (
                float(t) for t in val.split(","))
        if name == "device_augment":
            self.device_augment = int(val)
        self.aug.set_param(name, val)

    def init(self) -> None:
        self.base.init()
        if self.name_meanimg:
            if os.path.exists(self.name_meanimg):
                if not self.silent:
                    telemetry.stdout(
                        f"loading mean image from {self.name_meanimg}")
                self.meanimg = load_mean_image(self.name_meanimg)
            else:
                self._create_mean_img()

    def before_first(self) -> None:
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        self._set_data(self.base.value())
        return True

    def value(self) -> DataInst:
        return self._out

    # ------------------------------------------------------------------
    def _set_data(self, d: DataInst) -> None:
        if self.device_augment:
            # passthrough: stage the RAW decoded image; crop / mirror /
            # mean / scale run inside the jitted step
            # (ops/augment_jit.py). Affine warps cannot be deferred -
            # they run scipy on the host.
            if self.aug.need_process():
                raise ValueError(
                    "device_augment=1 cannot defer affine augmenters "
                    "(rotate/shear/aspect/random-scale run on the "
                    "host); disable them or device_augment")
            self._out = DataInst(index=d.index,
                                 data=np.ascontiguousarray(d.data),
                                 label=d.label, extra_data=d.extra_data)
            return
        data = self.aug.process(d.data, self.rng)
        c, ty, tx = self.shape

        if ty == 1:  # flat input: scale only
            img = data.astype(np.float32) * self.scale
            self._out = DataInst(index=d.index, data=img, label=d.label,
                                 extra_data=d.extra_data)
            return

        if data.shape[1] < ty or data.shape[2] < tx:
            raise ValueError(
                "data size must not be smaller than the net input size")
        yy_max = data.shape[1] - ty
        xx_max = data.shape[2] - tx
        if self.rand_crop and (yy_max or xx_max):
            yy = self.rng.randint(0, yy_max + 1)
            xx = self.rng.randint(0, xx_max + 1)
        else:
            yy, xx = yy_max // 2, xx_max // 2
        if data.shape[1] != ty and self.crop_y_start != -1:
            yy = self.crop_y_start
        if data.shape[2] != tx and self.crop_x_start != -1:
            xx = self.crop_x_start

        contrast = (self.rng.uniform() * self.max_random_contrast * 2
                    - self.max_random_contrast + 1)
        illumination = (self.rng.uniform() * self.max_random_illumination * 2
                        - self.max_random_illumination)
        do_mirror = ((self.rand_mirror and self.rng.uniform() < 0.5)
                     or self.mirror == 1)

        x = data.astype(np.float32)
        if self.mean_r > 0.0 or self.mean_g > 0.0 or self.mean_b > 0.0:
            # RGB layout; config order is b,g,r (see module docstring)
            x = x.copy()
            if x.shape[0] == 3:
                x[2] -= self.mean_b
                x[1] -= self.mean_g
                x[0] -= self.mean_r
            x = x * contrast + illumination
            img = x[:, yy:yy + ty, xx:xx + tx]
        elif self.meanimg is None:
            img = x[:, yy:yy + ty, xx:xx + tx]
        else:
            if x.shape == self.meanimg.shape:
                x = (x - self.meanimg) * contrast + illumination
                img = x[:, yy:yy + ty, xx:xx + tx]
            else:
                img = ((x[:, yy:yy + ty, xx:xx + tx] - self.meanimg)
                       * contrast + illumination)
        if do_mirror:
            img = img[:, :, ::-1]
        img = img * self.scale
        self._out = DataInst(index=d.index,
                             data=np.ascontiguousarray(img),
                             label=d.label, extra_data=d.extra_data)

    def _create_mean_img(self) -> None:
        if not self.silent:
            telemetry.stdout(
                f"cannot find {self.name_meanimg}: creating mean image, "
                "this will take some time...")
        # accumulate the *processed* instances exactly like CreateMeanImg
        # (meanimg is None here so _set_data performs no subtraction)
        self.base.before_first()
        acc = None
        cnt = 0
        while self.next():
            x = self._out.data.astype(np.float64)
            if acc is None:
                acc = np.zeros_like(x)
            acc += x
            cnt += 1
        mean = (acc / max(cnt, 1)).astype(np.float32)
        save_mean_image(self.name_meanimg, mean)
        self.meanimg = mean
        self.base.before_first()
