"""MNIST idx-format iterator (src/io/iter_mnist-inl.hpp:14-156).

Reads the gzipped idx files, normalizes to [0,1) by 1/256, optionally
shuffles, serves full batches only (the final partial batch is dropped,
exactly like the reference Next() :63-71). input_flat=1 yields matrix
nodes (b,1,1,784); input_flat=0 yields images (b,1,28,28).
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.io.iterators import DataIter


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        _, count, rows, cols = struct.unpack(">iiii", f.read(16))
        buf = f.read(count * rows * cols)
    return np.frombuffer(buf, dtype=np.uint8).reshape(count, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        _, count = struct.unpack(">ii", f.read(8))
        buf = f.read(count)
    return np.frombuffer(buf, dtype=np.uint8)


class MNISTIterator(DataIter):
    def __init__(self) -> None:
        self.mode = 1  # input_flat
        self.inst_offset = 0
        self.silent = 0
        self.shuffle = 0
        self.batch_size = 0
        self.path_img = ""
        self.path_label = ""
        self.seed = 0
        self.loc = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "silent":
            self.silent = int(val)
        if name == "batch_size":
            self.batch_size = int(val)
        if name == "input_flat":
            self.mode = int(val)
        if name == "shuffle":
            self.shuffle = int(val)
        if name == "index_offset":
            self.inst_offset = int(val)
        if name == "path_img":
            self.path_img = val
        if name == "path_label":
            self.path_label = val
        if name == "seed_data":
            self.seed = int(val)
        if name == "dist_num_worker":
            self.dist_num_worker = int(val)
        if name == "dist_worker_rank":
            self.dist_worker_rank = int(val)

    def init(self) -> None:
        img = _read_idx_images(self.path_img).astype(np.float32) / 256.0
        labels = _read_idx_labels(self.path_label).astype(np.float32)
        inst = np.arange(len(labels), dtype=np.uint32) + self.inst_offset
        nw = getattr(self, "dist_num_worker", 1)
        if nw > 1:
            from cxxnet_tpu.io.iterators import shard_quota
            quota, r = shard_quota(len(labels), nw,
                                   getattr(self, "dist_worker_rank", 0))
            img, labels, inst = (img[r::nw][:quota], labels[r::nw][:quota],
                                 inst[r::nw][:quota])
        if self.shuffle:
            rng = np.random.RandomState(self.seed)
            order = rng.permutation(len(labels))
            img, labels, inst = img[order], labels[order], inst[order]
        if self.mode == 1:
            self.data = img.reshape(len(labels), 1, 1, -1)
        else:
            self.data = img[:, None, :, :]
        self.labels = labels.reshape(-1, 1)
        self.inst = inst
        self.loc = 0
        if not self.silent:
            s = (self.batch_size,) + self.data.shape[1:]
            telemetry.stdout(f"MNISTIterator: load {len(labels)} images, "
                             f"shuffle={self.shuffle}, shape={s}")

    def before_first(self) -> None:
        self.loc = 0

    def next(self) -> bool:
        if self.loc + self.batch_size <= self.data.shape[0]:
            s = slice(self.loc, self.loc + self.batch_size)
            self._out = DataBatch(data=self.data[s], label=self.labels[s],
                                  inst_index=self.inst[s])
            self.loc += self.batch_size
            return True
        return False

    def value(self) -> DataBatch:
        return self._out
