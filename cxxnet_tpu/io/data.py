"""DataBatch / DataInst: the host-side batch containers.

Parity with src/io/data.h:41-181: a batch carries CPU tensors
data (b,c,h,w) and label (b,label_width), the instance indices, the count
of padding rows in a final short batch (num_batch_padd), and optional
extra-data tensors. All arrays are numpy (host); the trainer moves them
to device inside the jitted step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


@dataclass
class DataInst:
    """Single instance (data.h:41-56)."""
    index: int
    data: np.ndarray            # (c, h, w)
    label: np.ndarray           # (label_width,)
    extra_data: List[np.ndarray] = field(default_factory=list)


@dataclass
class DataBatch:
    """Batch of instances (data.h:79-181)."""
    data: np.ndarray                       # (b, c, h, w) float32
    label: np.ndarray                      # (b, label_width) float32
    inst_index: Optional[np.ndarray] = None  # (b,) uint32
    num_batch_padd: int = 0
    extra_data: List[np.ndarray] = field(default_factory=list)

    @property
    def batch_size(self) -> int:
        return int(self.data.shape[0])

    def valid_mask(self) -> np.ndarray:
        """(b,) float mask zeroing the trailing padding rows."""
        b = self.batch_size
        mask = np.ones(b, dtype=np.float32)
        if self.num_batch_padd:
            mask[b - self.num_batch_padd:] = 0.0
        return mask
