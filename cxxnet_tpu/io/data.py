"""DataBatch / DataInst: the host-side batch containers.

Parity with src/io/data.h:41-181: a batch carries CPU tensors
data (b,c,h,w) and label (b,label_width), the instance indices, the count
of padding rows in a final short batch (num_batch_padd), and optional
extra-data tensors. A batch may instead carry a sparse CSR view
(data.h:96-181: sparse_row_ptr + (findex, fvalue) entries); numpy-style,
the Entry array-of-structs is split into parallel index/value arrays.
All arrays are numpy (host); the trainer moves them to device inside the
jitted step - sparse batches densify first (TPU compute wants static
dense shapes; `to_dense`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


@dataclass
class DataInst:
    """Single instance (data.h:41-56)."""
    index: int
    data: np.ndarray            # (c, h, w)
    label: np.ndarray           # (label_width,)
    extra_data: List[np.ndarray] = field(default_factory=list)


@dataclass
class SparseInst:
    """One row of a sparse batch (data.h:51-72)."""
    index: int
    label: np.ndarray            # (label_width,)
    findex: np.ndarray           # (nnz,) uint32 feature indices
    fvalue: np.ndarray           # (nnz,) float32 feature values

    @property
    def length(self) -> int:
        return int(self.findex.shape[0])


@dataclass
class DataBatch:
    """Batch of instances (data.h:79-181)."""
    data: Optional[np.ndarray] = None      # (b, c, h, w) float32
    label: np.ndarray = None               # (b, label_width) float32
    inst_index: Optional[np.ndarray] = None  # (b,) uint32
    num_batch_padd: int = 0
    extra_data: List[np.ndarray] = field(default_factory=list)
    # sparse CSR view (data.h:96-100): row_ptr[b+1]; parallel
    # entry arrays instead of the reference's Entry struct array
    sparse_row_ptr: Optional[np.ndarray] = None   # (b+1,) int64
    sparse_findex: Optional[np.ndarray] = None    # (nnz,) uint32
    sparse_fvalue: Optional[np.ndarray] = None    # (nnz,) float32

    @property
    def batch_size(self) -> int:
        if self.data is not None:
            return int(self.data.shape[0])
        return int(self.sparse_row_ptr.shape[0]) - 1

    def is_sparse(self) -> bool:
        """data.h:166-168."""
        return self.sparse_row_ptr is not None

    def get_row_sparse(self, rid: int) -> SparseInst:
        """rid'th row of the sparse view (data.h:169-180)."""
        if not self.is_sparse():
            raise ValueError("GetRowSparse on a dense batch")
        a, b = int(self.sparse_row_ptr[rid]), int(
            self.sparse_row_ptr[rid + 1])
        return SparseInst(
            index=int(self.inst_index[rid])
            if self.inst_index is not None else 0,
            label=self.label[rid],
            findex=self.sparse_findex[a:b],
            fvalue=self.sparse_fvalue[a:b])

    def to_dense(self, num_features: int) -> np.ndarray:
        """Densify the CSR view to (b, 1, 1, num_features) float32 - the
        shape the (static-shape, MXU-friendly) jitted step consumes.
        Out-of-range feature indices are dropped, matching a fixed
        input_shape contract."""
        if not self.is_sparse():
            raise ValueError("to_dense on a dense batch")
        b = self.batch_size
        out = np.zeros((b, num_features), np.float32)
        ptr = self.sparse_row_ptr
        rows = np.repeat(np.arange(b), np.diff(ptr))
        cols = self.sparse_findex.astype(np.int64)
        keep = cols < num_features
        out[rows[keep], cols[keep]] = self.sparse_fvalue[keep]
        return out.reshape(b, 1, 1, num_features)

    def valid_mask(self) -> np.ndarray:
        """(b,) float mask zeroing the trailing padding rows."""
        b = self.batch_size
        mask = np.ones(b, dtype=np.float32)
        if self.num_batch_padd:
            mask[b - self.num_batch_padd:] = 0.0
        return mask
