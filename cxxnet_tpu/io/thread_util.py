"""Shared helpers for the queue-backed producer threads in io.

The role of utils/thread_buffer.h (thread_buffer.h:22-202) — a bounded
producer/consumer handoff with a shutdown protocol that can't deadlock:
the producer only ever blocks in a stop-aware put, and the consumer side
drains the queue while joining so a pending put always unblocks.
"""

from __future__ import annotations

import queue
import threading
import time


class ErrorBox:
    """Single-slot cross-thread exception handoff: the producer
    ``put``s its failure, the consumer ``take``s it after the queue's
    sentinel arrives. The box is what makes the publication explicit -
    a bare ``self._exc = e`` on the worker is exactly the unlocked
    shared-state write the GL012 lint rule exists to catch (the queue
    sentinel *usually* orders it, but nothing says so in the code).
    First error wins; ``take`` clears the slot."""

    __slots__ = ("_lock", "_exc")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._exc = None

    def put(self, exc: BaseException) -> None:
        with self._lock:
            if self._exc is None:
                self._exc = exc

    def take(self):
        """Return-and-clear the stored exception (None if clean)."""
        with self._lock:
            exc, self._exc = self._exc, None
            return exc


def stoppable_put(q: "queue.Queue", stop: threading.Event, item) -> bool:
    """Bounded put that aborts when `stop` is set. Returns False if
    aborted (the producer should exit)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def drain_and_join(q: "queue.Queue", thread: threading.Thread,
                   stop: threading.Event, timeout: float = 30.0,
                   on_item=None) -> None:
    """Stop a producer: set the flag, drain so a pending put unblocks,
    join with a bounded total wait.

    `on_item` sees every drained queue item - so a shutdown can notice
    an undelivered worker EXCEPTION instead of silently discarding it
    (io/prefetch.py surfaces those from close()).

    Raises RuntimeError if the producer is still alive after `timeout`
    (stuck outside q.put, e.g. a stalled read): restarting on top of a
    live producer would race it on the shared underlying iterator, so a
    stuck pipeline must fail loudly instead."""
    stop.set()
    deadline = time.monotonic() + timeout

    def drain():
        try:
            while True:
                item = q.get_nowait()
                if on_item is not None:
                    on_item(item)
        except queue.Empty:
            pass

    while thread.is_alive() and time.monotonic() < deadline:
        drain()
        thread.join(timeout=0.1)
    if thread.is_alive():
        raise RuntimeError(
            f"io producer thread failed to stop within {timeout}s "
            "(stalled read?); cannot safely restart the pipeline")
    # the producer may have completed a final put between the last
    # drain and its exit - sweep once more so nothing lingers
    drain()
