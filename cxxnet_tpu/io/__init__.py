"""Data pipeline: composable iterators driven by config blocks.

Factory parity with src/io/data.cpp:23-74: `iter = <name>` lines build the
chain (base instance iterators are wrapped in augment + batch adapters);
params following an `iter =` line are applied to the whole current chain.
"""

from __future__ import annotations

from typing import List, Tuple

from cxxnet_tpu.io.data import DataBatch, DataInst
from cxxnet_tpu.io.iterators import DataIter, RetryIterator


def create_iterator(cfg: List[Tuple[str, str]]) -> DataIter:
    from cxxnet_tpu.io.augment import AugmentIterator
    from cxxnet_tpu.io.iter_batch import (BatchAdaptIterator,
                                          ThreadBufferIterator)
    from cxxnet_tpu.io.iter_extra import (AttachTxtIterator,
                                          DenseBufferIterator)
    from cxxnet_tpu.io.iter_img import ImageBinIterator, ImageIterator
    from cxxnet_tpu.io.iter_mnist import MNISTIterator

    it: DataIter = None
    for name, val in cfg:
        if name == "iter":
            if val == "mnist":
                assert it is None, "mnist cannot chain over other iterators"
                it = MNISTIterator()
            elif val in ("imgbin", "imgbinx"):
                assert it is None, "imgbin cannot chain over other iterators"
                it = BatchAdaptIterator(
                    AugmentIterator(ImageBinIterator()))
            elif val == "img":
                assert it is None, "img cannot chain over other iterators"
                it = BatchAdaptIterator(AugmentIterator(ImageIterator()))
            elif val == "threadbuffer":
                assert it is not None, "must specify input of threadbuffer"
                # the retry must sit UNDER the producer thread: a read
                # error inside the producer surfaces to the consumer as
                # RuntimeError (iter_batch.py next()) with the producer
                # already dead, where no outer retry can help
                it = ThreadBufferIterator(RetryIterator(it))
            elif val == "membuffer":
                assert it is not None, "must specify input of membuffer"
                it = DenseBufferIterator(it)
            elif val == "attachtxt":
                assert it is not None, "must specify input of attachtxt"
                it = AttachTxtIterator(it)
            elif val == "end":
                break
            else:
                raise ValueError(f"unknown iterator type {val}")
        elif it is not None:
            it.set_param(name, val)
    assert it is not None, "must specify iterator by iter=itername"
    # transient-IO-error retry around the whole chain (iterators.py:
    # RetryIterator; io_retry / io_retry_backoff config keys). A
    # threadbuffer top already carries the retry inside its producer,
    # and retrying a dead producer from outside cannot help - skip the
    # redundant outer wrapper there. Replay the retry keys from the
    # block so they reach the wrapper (set_param forwards down the
    # chain) even though it is created after the block params applied.
    if not isinstance(it, ThreadBufferIterator):
        it = RetryIterator(it)
    for name, val in cfg:
        if name in ("io_retry", "io_retry_backoff"):
            it.set_param(name, val)
        elif name == "iter" and val == "end":
            break
    return it


__all__ = ["DataBatch", "DataInst", "DataIter", "RetryIterator",
           "create_iterator"]
