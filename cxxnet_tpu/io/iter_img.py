"""Image-list iterators.

- ImageIterator (`img`): .lst file + loose image files
  (src/io/iter_img-inl.hpp:16-137).
- ImageBinIterator (`imgbin`/`imgbinx`): .lst + packed BinaryPage .bin
  with background page prefetch (src/io/iter_thread_imbin-inl.hpp and
  iter_thread_imbin_x-inl.hpp roles merged: page-level prefetch thread +
  in-memory JPEG decode, instance-level shuffle, multi-bin template
  support, per-worker sharding for distributed runs).

.lst line format: `index \\t label... \\t filename`.
Images decode to RGB (c,h,w) float arrays in [0,255].
"""

from __future__ import annotations

import io as _io
import queue
import threading
from typing import List, Optional, Tuple

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataInst
from cxxnet_tpu.io.iterators import DataIter
from cxxnet_tpu.io.thread_util import (
    ErrorBox, drain_and_join, stoppable_put)
from cxxnet_tpu.utils.binary_page import iter_page_blobs


def decode_image(blob: bytes) -> np.ndarray:
    """JPEG/PNG bytes -> (c, h, w) uint8 RGB in [0,255].

    uint8 is both reference-faithful (cv::Mat u8 end to end) and what
    device_augment staging wants (1/4 the f32 H2D bytes); the host
    augmentation path casts to f32 per instance exactly where the
    reference does (augment.py _set_data)."""
    from PIL import Image
    img = Image.open(_io.BytesIO(blob))
    img = img.convert("RGB")
    arr = np.asarray(img)  # (h, w, 3) uint8
    return np.ascontiguousarray(arr.transpose(2, 0, 1))


def load_image_file(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        return decode_image(f.read())


def parse_list_file(path: str) -> List[Tuple[int, List[float], str]]:
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip("\n\r")
            if not line:
                continue
            parts = line.split("\t")
            idx = int(float(parts[0]))
            labels = [float(t) for t in parts[1:-1]]
            out.append((idx, labels, parts[-1]))
    return out


class ImageIterator(DataIter):
    """`img`: loose image files listed in a .lst."""

    K_RAND_MAGIC = 111

    def __init__(self) -> None:
        self.path_imglist = ""
        self.path_root = ""
        self.shuffle = 0
        self.silent = 0
        self.label_width = 1
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.rng = np.random.RandomState(self.K_RAND_MAGIC)
        self.order: List[int] = []
        self.loc = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "image_list":
            self.path_imglist = val
        if name == "image_root":
            self.path_root = val
        if name == "shuffle":
            self.shuffle = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "label_width":
            self.label_width = int(val)
        if name == "seed_data":
            self.rng = np.random.RandomState(self.K_RAND_MAGIC + int(val))
        if name == "dist_num_worker":
            self.dist_num_worker = int(val)
        if name == "dist_worker_rank":
            self.dist_worker_rank = int(val)

    def init(self) -> None:
        from cxxnet_tpu.io.iterators import shard_quota
        entries = parse_list_file(self.path_imglist)
        nw = self.dist_num_worker
        if nw > 1:
            quota, rank = shard_quota(len(entries), nw,
                                      self.dist_worker_rank)
            entries = entries[rank::nw][:quota]
        self.entries = entries
        self.order = list(range(len(self.entries)))
        if not self.silent:
            telemetry.stdout(f"ImageIterator: {self.path_imglist}, "
                             f"{len(self.entries)} images")
        self.before_first()

    def before_first(self) -> None:
        if self.shuffle:
            self.rng.shuffle(self.order)
        self.loc = 0

    def next(self) -> bool:
        if self.loc >= len(self.order):
            return False
        idx, labels, fname = self.entries[self.order[self.loc]]
        self.loc += 1
        data = load_image_file(self.path_root + fname)
        label = np.asarray(labels[:self.label_width], dtype=np.float32)
        self._out = DataInst(index=idx, data=data, label=label)
        return True

    def value(self) -> DataInst:
        return self._out


class _PageReader(threading.Thread):
    """Background thread streaming page blob-lists from .bin files."""

    def __init__(self, paths: List[str], out_q: "queue.Queue",
                 stop: threading.Event):
        super().__init__(daemon=True)
        self.paths = paths
        self.out_q = out_q
        self.stop_event = stop
        self.err = ErrorBox()

    def _put(self, item) -> bool:
        return stoppable_put(self.out_q, self.stop_event, item)

    def run(self) -> None:
        try:
            for path in self.paths:
                with open(path, "rb") as f:
                    for blobs in iter_page_blobs(f):
                        if not self._put(blobs):
                            return
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            # lock-guarded handoff, published before the sentinel put
            self.err.put(e)
        finally:
            self._put(None)  # sentinel


class ImageBinIterator(DataIter):
    """`imgbin` / `imgbinx`: .lst + BinaryPage-packed image blobs.

    The reference's two iterators differ in pipelining depth; here one
    implementation covers both config names: a prefetch thread loads 64MiB
    pages ahead of decode (ThreadBuffer role), instances optionally
    shuffle inside a page (imgbinx shuffle_), and `image_conf_prefix` /
    `image_conf_ids` template multi-file datasets with round-robin
    sharding across distributed workers
    (iter_thread_imbin-inl.hpp:189-220).
    """

    K_RAND_MAGIC = 222

    def __init__(self) -> None:
        self.path_imglist = ""
        self.path_imgbin: List[str] = []
        self.conf_prefix = ""
        self.conf_ids = ""
        self.shuffle = 0
        self.silent = 0
        self.label_width = 1
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.rng = np.random.RandomState(self.K_RAND_MAGIC)
        # native decode pipeline: -1 auto (use when built), 0 off, 1 force
        self.use_native = -1
        self.decode_threads = 4
        self.shuffle_buffer = 1024
        self.device_augment = 0
        self._native = None
        self._native_mode = False
        self._pool = None  # Python-path decode ThreadPoolExecutor

    def set_param(self, name: str, val: str) -> None:
        if name == "image_list":
            self.path_imglist = val
        if name == "image_bin":
            self.path_imgbin = [val]
        if name == "image_conf_prefix":
            self.conf_prefix = val
        if name == "image_conf_ids":
            self.conf_ids = val
        if name == "shuffle":
            self.shuffle = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "label_width":
            self.label_width = int(val)
        if name == "dist_num_worker":
            self.dist_num_worker = int(val)
        if name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        if name == "seed_data":
            self.rng = np.random.RandomState(self.K_RAND_MAGIC + int(val))
        if name == "use_native":
            self.use_native = int(val)
        if name == "decode_threads":
            self.decode_threads = int(val)
        if name == "shuffle_buffer":
            self.shuffle_buffer = int(val)
        if name == "device_augment":
            # raw uint8 staging for the in-step augment path: the
            # native pipeline converts to CHW uint8 instead of CHW f32
            self.device_augment = int(val)

    def _expand_templates(self) -> Tuple[List[str], List[str]]:
        """image_conf_prefix with %d + image_conf_ids `a-b` -> shard lists
        round-robin over workers (reference :189-220)."""
        if not self.conf_prefix:
            return [self.path_imglist], list(self.path_imgbin)
        a, b = (int(t) for t in self.conf_ids.split("-"))
        ids = [i for i in range(a, b + 1)]
        mine = [i for k, i in enumerate(ids)
                if k % self.dist_num_worker == self.dist_worker_rank]
        lists = [(self.conf_prefix % i) + ".lst" for i in mine]
        bins = [(self.conf_prefix % i) + ".bin" for i in mine]
        return lists, bins

    def init(self) -> None:
        from cxxnet_tpu.io.native import native_available
        lists, bins = self._expand_templates()
        self.entries = []
        for lst in lists:
            self.entries.extend(parse_list_file(lst))
        self.bins = bins
        if self.use_native == 1 and not native_available():
            raise RuntimeError(
                "use_native=1 but libcxxnet_io.so is not available "
                "(run `make -C native`)")
        if self.shuffle and self.shuffle_buffer < 1:
            raise ValueError("shuffle=1 requires shuffle_buffer >= 1")
        self._native_mode = (self.use_native != 0 and native_available())
        # without conf_prefix file-sharding, multi-worker runs shard at
        # the INSTANCE level (ordinal % nw == rank, quota-trimmed so
        # every worker serves the same count - unequal batch counts
        # would desynchronize the per-batch SPMD collectives); with
        # conf_prefix, files are round-robin sharded above instead
        self._shard_nw = (self.dist_num_worker
                          if (self.dist_num_worker > 1
                              and not self.conf_prefix) else 1)
        self._shard_quota = 0
        if self._shard_nw > 1:
            from cxxnet_tpu.io.iterators import shard_quota
            self._shard_quota, _ = shard_quota(
                len(self.entries), self._shard_nw, self.dist_worker_rank)
        if not self.silent:
            mode = "native" if self._native_mode else "python"
            telemetry.stdout(
                f"ImageBinIterator: {len(self.entries)} images from "
                f"{len(bins)} bins ({mode} decode)")
        self.before_first()

    def before_first(self) -> None:
        self._served = 0
        if self._native_mode:
            from cxxnet_tpu.io.native import NativeBinReader
            if self._native is None:
                self._native = NativeBinReader(
                    self.bins, n_threads=self.decode_threads,
                    out_mode=2 if self.device_augment else 1)
            self._native.before_first()
            self._nseq = 0
            self._nbuf: List[DataInst] = []
            return
        self._shutdown_reader()
        self._stop = threading.Event()
        self._q: "queue.Queue" = queue.Queue(maxsize=4)
        self._reader = _PageReader(self.bins, self._q, self._stop)
        self._reader.start()
        if self._pool is None and self.decode_threads > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.decode_threads,
                thread_name_prefix="cxn-decode")
        self._page_objs: List[bytes] = []
        self._page_order: List[int] = []
        self._page_pos = 0
        self._entry_pos = 0
        self._futures = {}
        self._submit_pos = 0

    def _shutdown_reader(self) -> None:
        reader = getattr(self, "_reader", None)
        if reader is None or not reader.is_alive():
            return
        drain_and_join(self._q, reader, self._stop)
        self._reader = None

    def _next_page(self) -> bool:
        blobs = self._q.get()
        if blobs is None:
            exc = self._reader.err.take()
            if exc is not None:
                raise RuntimeError(
                    "imgbin page reader failed") from exc
            return False
        self._page_objs = blobs
        self._page_order = list(range(len(self._page_objs)))
        if self.shuffle:
            self.rng.shuffle(self._page_order)
        self._page_pos = 0
        self._submit_pos = 0
        self._futures = {}
        self._fill_decode_window()
        return True

    def _fill_decode_window(self) -> None:
        """Second pipeline stage of the Python path: keep a bounded
        window of blobs decoding on the pool (PIL releases the GIL
        during decompression) while the consumer drains earlier ones -
        the decode-pool role iter_thread_imbin's pipeline plays, without
        densifying a whole 64MiB page at once."""
        if self._pool is None:
            return
        ahead = max(8, 2 * self.decode_threads)
        while (self._submit_pos < len(self._page_order)
               and self._submit_pos - self._page_pos < ahead):
            j = self._page_order[self._submit_pos]
            ent_idx = self._entry_pos + j
            if (self._shard_nw <= 1
                    or ent_idx % self._shard_nw == self.dist_worker_rank):
                # non-owned instances are skipped by next(); don't burn
                # the decode pool on them
                self._futures[self._submit_pos] = self._pool.submit(
                    decode_image, self._page_objs[j])
            self._submit_pos += 1

    def _pull_native(self) -> Optional[DataInst]:
        while True:
            if self._shard_nw > 1 and self._served >= self._shard_quota:
                return None
            data = self._native.next()
            if data is None:
                return None
            ordinal = self._nseq
            self._nseq += 1
            if self._shard_nw > 1:
                if ordinal % self._shard_nw != self.dist_worker_rank:
                    continue
                self._served += 1
            idx, labels, _ = self.entries[ordinal]
            label = np.asarray(labels[:self.label_width],
                               dtype=np.float32)
            return DataInst(index=idx, data=data, label=label)

    def _next_native(self) -> bool:
        """Native stream is strictly ordered; shuffle uses a bounded
        reservoir (the analog of the Python path's within-page shuffle).
        The reservoir is additionally capped to ~64MiB of decoded floats
        (the page-shuffle window size) so large images don't pin GBs."""
        if self.shuffle:
            if not self._nbuf:
                inst = self._pull_native()
                if inst is not None:
                    self._nbuf.append(inst)
            if self._nbuf:
                per_img = max(1, self._nbuf[0].data.nbytes)
                cap = min(self.shuffle_buffer,
                          max(16, (64 << 20) // per_img))
                while len(self._nbuf) < cap:
                    inst = self._pull_native()
                    if inst is None:
                        break
                    self._nbuf.append(inst)
            if not self._nbuf:
                return False
            j = int(self.rng.randint(len(self._nbuf)))
            self._nbuf[j], self._nbuf[-1] = self._nbuf[-1], self._nbuf[j]
            self._out = self._nbuf.pop()
            return True
        inst = self._pull_native()
        if inst is None:
            return False
        self._out = inst
        return True

    def next(self) -> bool:
        if self._native_mode:
            return self._next_native()
        while True:
            while self._page_pos >= len(self._page_objs):
                if not self._next_page():
                    return False
            k = self._page_pos
            ent_idx = self._entry_pos + self._page_order[k]
            self._page_pos += 1
            owned = True
            if self._shard_nw > 1:
                if self._served >= self._shard_quota:
                    return False
                owned = (ent_idx % self._shard_nw
                         == self.dist_worker_rank)
            if owned and k in self._futures:
                data = self._futures.pop(k).result()
            elif owned:
                data = decode_image(self._page_objs[self._page_order[k]])
            else:
                self._futures.pop(k, None)
            self._fill_decode_window()
            if self._page_pos >= len(self._page_objs):
                self._entry_pos += len(self._page_objs)
            if not owned:
                continue
            if self._shard_nw > 1:
                self._served += 1
            idx, labels, _ = self.entries[ent_idx]
            label = np.asarray(labels[:self.label_width],
                               dtype=np.float32)
            self._out = DataInst(index=idx, data=data, label=label)
            return True

    def value(self) -> DataInst:
        return self._out
