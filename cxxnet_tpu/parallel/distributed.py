"""Multi-host distributed runtime: the mshadow-ps "dist" replacement.

The reference scales across machines with an async parameter server
(mshadow-ps over ps-lite/ZMQ: bin/cxxnet.ps + nnet_ps_server.cpp,
SURVEY.md par.2.7). The TPU-native equivalent is multi-controller SPMD:
every host runs the SAME program under its own JAX process, the global
device mesh spans all hosts, and gradient reduction is a synchronous XLA
AllReduce over ICI/DCN inside the compiled step - no server processes,
no push/pull, no worker/server distinction.

Config surface parity:
    param_server = dist          -> multi-controller mode
    dist_coordinator = host:port -> coordinator (env CXN_COORDINATOR)
    dist_num_worker = N          -> process count (env CXN_NUM_WORKER)
    dist_worker_rank = i         -> this process   (env CXN_WORKER_RANK)
and the data side reuses the reference's per-worker shard keys on the
iterators (dist_num_worker/dist_worker_rank - iter_img.py, mirroring
iter_thread_imbin-inl.hpp:189-220).

`check_replicated` is the test_on_server/CheckWeight_ analog
(async_updater-inl.hpp:144-153): verify that what should be identical
on every device/process actually is.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

from cxxnet_tpu.utils.config import ConfigError
from cxxnet_tpu.utils.fault import retry


_initialized = False

# bounded init retry defaults (overridable per call / via the
# dist_init_* config keys): a peer that is still binding its
# coordinator port, or a control-plane record written a beat late,
# costs a backoff, not the pod - but the wait is CAPPED, because an
# address that is simply wrong must become a clear error, not an
# infinite connect loop
INIT_ATTEMPTS = 5
INIT_BACKOFF = 0.5
INIT_DEADLINE = 120.0


def _enable_cpu_collectives() -> None:
    """Select the gloo TCP collectives for multi-process CPU jobs.

    jax's CPU client is built with NO cross-process collective
    implementation by default - a multi-controller job on the cpu
    platform compiles fine and then dies at the first AllReduce with
    "Multiprocess computations aren't implemented on the CPU backend".
    The implementation is chosen when the backend client is CREATED,
    so the flag must be set here (before jax.distributed.initialize;
    the client does not exist yet or initialize itself would fail).
    Scoped to cpu platforms: TPU pods keep their native ICI
    collectives and never see this flag."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if not platforms:
        try:
            platforms = jax.config.jax_platforms or ""
        except AttributeError:  # very old/new jax: leave the default
            return
    if "cpu" in str(platforms).lower():
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            # flag renamed/absent on this jax: the job either works
            # without it or fails with the explicit runtime error
            pass


def init_distributed(coordinator: Optional[str] = None,
                     num_workers: Optional[int] = None,
                     rank: Optional[int] = None,
                     attempts: int = INIT_ATTEMPTS,
                     backoff: float = INIT_BACKOFF,
                     deadline: float = INIT_DEADLINE) -> None:
    """Join the multi-controller job (idempotent).

    Arguments fall back to CXN_COORDINATOR / CXN_NUM_WORKER /
    CXN_WORKER_RANK env vars (the launcher sets them). Single-worker
    jobs are a no-op, like the reference's local parameter server.

    The gloo/distributed handshake is retried with exponential backoff
    + jitter (the PR 1 ``retry`` decorator): a slow-starting peer used
    to be an immediate crash. Total wait is capped by ``deadline``
    seconds; exhaustion raises ``ConfigError`` naming the coordinator.
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("CXN_COORDINATOR", "")
    num_workers = num_workers if num_workers is not None else int(
        os.environ.get("CXN_NUM_WORKER", "1"))
    rank = rank if rank is not None else int(
        os.environ.get("CXN_WORKER_RANK", "0"))
    if num_workers <= 1:
        return
    if not coordinator:
        raise ValueError(
            "param_server=dist needs dist_coordinator (or "
            "CXN_COORDINATOR) when dist_num_worker > 1")
    _enable_cpu_collectives()

    # RuntimeError is what jax.distributed surfaces for a refused /
    # unreachable coordinator; OSError covers raw socket failures.
    # ValueError (bad arguments) propagates immediately - retrying a
    # typo'd rank cannot help.
    @retry(attempts=max(1, attempts), backoff=backoff,
           jitter=backoff / 2, retry_on=(RuntimeError, OSError),
           deadline=deadline)
    def _connect():
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_workers,
                                   process_id=rank)

    try:
        _connect()
    except (RuntimeError, OSError) as e:
        raise ConfigError(
            f"param_server=dist: could not join the job at "
            f"{coordinator} as rank {rank}/{num_workers} after "
            f"{attempts} attempts (deadline {deadline:g}s): {e}"
        ) from e
    _initialized = True


def init_from_config(pairs: List[Tuple[str, str]]) -> None:
    """Pull the dist_* keys out of a config pair list and initialize."""
    cfg: Dict[str, str] = {}
    for k, v in pairs:
        cfg[k] = v
    if cfg.get("param_server", "local") != "dist":
        return
    init_distributed(
        coordinator=cfg.get("dist_coordinator"),
        num_workers=int(cfg["dist_num_worker"])
        if "dist_num_worker" in cfg else None,
        rank=int(cfg["dist_worker_rank"])
        if "dist_worker_rank" in cfg else None,
        attempts=int(cfg.get("dist_init_retries", INIT_ATTEMPTS)),
        backoff=float(cfg.get("dist_init_backoff", INIT_BACKOFF)),
        deadline=float(cfg.get("dist_init_deadline", INIT_DEADLINE)))


def read_membership(coord_dir: str, attempts: int = INIT_ATTEMPTS,
                    backoff: float = INIT_BACKOFF,
                    deadline: float = INIT_DEADLINE) -> Dict[str, Any]:
    """The pod membership record (``generation.json`` - written by the
    elastic supervisor before each launch, parallel/coordinator.py),
    read with the same bounded retry discipline as the gloo init: the
    record may lag the worker by a beat at generation start, and on a
    network filesystem a read can transiently fail - but a coord_dir
    that never produces a record must become a clear ConfigError, not
    a silent hang or a crash on the first ENOENT."""
    path = os.path.join(coord_dir, "generation.json")

    @retry(attempts=max(1, attempts), backoff=backoff,
           jitter=backoff / 2, retry_on=(OSError,), deadline=deadline)
    def _read() -> Dict[str, Any]:
        with open(path, "r", encoding="utf-8") as f:
            try:
                rec = json.load(f)
            except ValueError as e:
                # torn read on close-to-open-consistency filesystems:
                # transient, retry-absorbable like the OSError path
                raise OSError(f"unparseable membership record: {e}")
        if not isinstance(rec, dict) or "members" not in rec:
            raise OSError(f"membership record missing 'members': {rec}")
        return rec

    try:
        return _read()
    except OSError as e:
        raise ConfigError(
            f"elastic: cannot read pod membership record {path} "
            f"after {attempts} attempts (deadline {deadline:g}s): {e}"
        ) from e


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


# ---------------------------------------------------------------------------
# global-array construction / host readback (multi-process safe)
# ---------------------------------------------------------------------------

def put_global(arr: np.ndarray, sharding) -> jax.Array:
    """Host array -> global jax.Array under a BATCH-DIM-ONLY sharding
    (labels, masks, replicated scalars).

    Single process: plain device_put. Multi-process: `arr` is this
    process's local batch rows (or the full identical value for
    replicated leaves); make_array_from_process_local_data assembles
    the global view. Input tensors whose NON-batch dims may shard
    across processes (the 'seq' mesh axis) go through put_global_rows
    instead - trainer._put_data.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


def put_global_rows(arr: np.ndarray, sharding, global_shape,
                    row_start: int) -> jax.Array:
    """Host value covering THIS process's batch rows (dim 0 starting at
    `row_start` of the global batch) and the FULL extent of every other
    dim -> global array under any sharding.

    Unlike put_global, correct when NON-batch dims shard across
    processes (e.g. a cross-host 'seq' mesh axis - parallel/ring.py):
    each device's callback slices its seq portion out of the full-seq
    host rows instead of treating the host array as one pre-cut shard.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    global_shape = tuple(global_shape)

    def cb(idx):
        if not idx:  # 0-d leaf (scalar state, via put_global_full)
            return arr
        r0, r1, _ = idx[0].indices(global_shape[0])
        return arr[(slice(r0 - row_start, r1 - row_start),)
                   + tuple(idx[1:])]

    return jax.make_array_from_callback(global_shape, sharding, cb)


def put_global_full(arr: np.ndarray, sharding) -> jax.Array:
    """FULL (global-shaped) host value -> global array under any
    sharding (e.g. ZeRO-1 optimizer state split over devices owned by
    several processes): the row_start=0 full-coverage special case of
    put_global_rows."""
    arr = np.asarray(arr)
    return put_global_rows(arr, sharding, arr.shape, 0)


def fetch_local(arr: jax.Array) -> np.ndarray:
    """Global array -> this process's host view.

    Fully-addressable arrays round-trip exactly. For multi-process
    batch-sharded outputs the result is the concatenation of this
    process's shards (rows of the local batch); replicated outputs
    return the full value.
    """
    if arr.is_fully_addressable:
        return np.asarray(arr)
    if arr.sharding.is_fully_replicated:
        return np.asarray(arr.addressable_data(0))
    shards = sorted(arr.addressable_shards, key=lambda s: s.index)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


# ---------------------------------------------------------------------------
# consistency checking (test_on_server analog)
# ---------------------------------------------------------------------------

def check_replicated(tree: Any, name: str = "params") -> List[str]:
    """Verify replicated leaves are bit-identical on every local device
    (and, across processes, that checksums agree). Returns a list of
    human-readable mismatch descriptions; [] = consistent."""
    bad: List[str] = []
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    sums = []
    for path, leaf in leaves:
        if not isinstance(leaf, jax.Array):
            continue
        if not leaf.sharding.is_fully_replicated:
            continue  # sharded-by-design leaves have nothing to compare
        shards = leaf.addressable_shards
        base = np.asarray(shards[0].data)
        for s in shards[1:]:
            if not np.array_equal(base, np.asarray(s.data),
                                  equal_nan=True):
                bad.append(
                    f"{name}{jax.tree_util.keystr(path)}: device "
                    f"{s.device} diverges from {shards[0].device}")
                break
        sums.append(float(np.float64(np.abs(base).sum())))
    if jax.process_count() > 1 and sums:
        # gather every device's view of the checksums through one XLA
        # all-gather over the global device list (same collective setup
        # the train step itself uses)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mine = np.asarray(sums, np.float32)
        devs = jax.devices()
        mesh = Mesh(np.asarray(devs), ("dev",))
        local = np.tile(mine[None, :], (len(jax.local_devices()), 1))
        g = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("dev")), local,
            (len(devs), mine.size))
        rep = jax.jit(lambda x: x,
                      out_shardings=NamedSharding(mesh, P()))(g)
        allv = np.asarray(rep.addressable_data(0))
        for d in range(allv.shape[0]):
            if not np.allclose(allv[d], mine, rtol=1e-6):
                bad.append(
                    f"{name}: device {devs[d]} checksums diverge from "
                    f"process {jax.process_index()}")
    return bad
