"""Per-parameter sharding rules: tensor parallelism over the 'model' axis.

The reference has no tensor parallelism (SURVEY.md par.2.7 - every device
holds a full replica); this module is the TPU-native extension that makes
`mesh = data:8,model:4` meaningful. The design follows the GSPMD recipe:
annotate *parameter* shardings only, and let XLA propagate activation
shardings and insert the collectives (all-gather on the fullc output
feature dim, reduce-scatter/all-reduce on contractions) over ICI.

Rules (each layer declares which dim of each param rides 'model' via
`Layer.model_shard_dims()`):
- fullc wmat (nhidden, nin): shard nhidden (Megatron column-parallel);
  bias (nhidden,) likewise. The following layer's contraction makes XLA
  all-gather or keep the sharding, whichever its cost model prefers.
- conv wmat OIHW: shard O (out channels); bias likewise. Channel-wise
  params downstream of a sharded conv (prelu slope, batch-norm
  slope/bias) shard the same dim so no resharding is needed.
- Any param whose shard dim is not divisible by the model-axis size is
  replicated (falling back is always legal - GSPMD handles mixtures).
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cxxnet_tpu.nnet.network import Network, param_key

MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"
DATA_AXIS = "data"


def param_pspecs(net: Network, shapes=None) -> Dict[str, Dict[str, P]]:
    """PartitionSpec per parameter; P() (replicated) unless the layer
    declares a model- and/or expert-shard dim. A param may ride both
    axes on different dims (none of the shipped layers do, but the
    combination is legal GSPMD)."""
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    specs: Dict[str, Dict[str, P]] = {}
    for idx, info in enumerate(net.cfg.layers):
        if info.is_shared:
            continue
        lk = param_key(net.cfg, idx)
        if lk not in shapes:
            continue
        layer = net.layer_objs[idx]
        by_axis = ((MODEL_AXIS, layer.model_shard_dims()),
                   (EXPERT_AXIS, layer.expert_shard_dims()),
                   (PIPE_AXIS, layer.pipe_shard_dims()))
        specs[lk] = {}
        for pn, sd in shapes[lk].items():
            spec = [None] * len(sd.shape)
            for axis, dims in by_axis:
                d = dims.get(pn)
                if d is not None and spec[d] is None:
                    spec[d] = axis
            specs[lk][pn] = P(*spec) if any(spec) else P()
    return specs


def zero1_eligible_dim(spec, shape, dsize):
    """Index of the first still-unsharded dim divisible by the
    data-axis size - the dim zero1_shardings additionally shards over
    'data' - or None when the weight keeps its parameter sharding.
    THE eligibility rule; the multichip dryrun asserts against it."""
    full = list(spec) + [None] * (len(shape) - len(spec))
    for i, (ax, dim) in enumerate(zip(full, shape)):
        if ax is None and dim % dsize == 0:
            return i
    return None


def zero1_shardings(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]]
) -> Dict[str, Dict[str, NamedSharding]]:
    """ZeRO-1-style optimizer-state shardings: the update_on_server
    analog (nnet_ps_server.cpp:20-170 moves the updater to the server so
    workers don't replicate its state; here the state is sharded over
    the 'data' axis and GSPMD partitions the update math + all-gathers
    the fresh weights).

    Starting from each weight's parameter sharding, the first
    still-unsharded dim divisible by the data-axis size additionally
    rides 'data'. Weights with no such dim keep the parameter sharding
    (replication over data is always legal).
    """
    dsize = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        DATA_AXIS, 1)
    shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pshard.items():
        out[lk] = {}
        for pn, ns in d.items():
            shape = shapes[lk][pn].shape
            if dsize <= 1:
                out[lk][pn] = ns
                continue
            i = zero1_eligible_dim(ns.spec, shape, dsize)
            if i is None:
                out[lk][pn] = ns
                continue
            spec = list(ns.spec) + [None] * (len(shape) - len(ns.spec))
            spec[i] = DATA_AXIS
            out[lk][pn] = NamedSharding(mesh, P(*spec))
    return out


def shardings_for(mesh: Mesh,
                  net: Network) -> Dict[str, Dict[str, NamedSharding]]:
    """NamedSharding tree parallel to the params pytree (two levels).

    Each declared axis ('model', 'expert') is dropped back to
    replication independently when it is absent from the mesh, has size
    1, or the sharded dim does not divide its size.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    pspecs = param_pspecs(net, shapes)
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pspecs.items():
        out[lk] = {}
        for pn, spec in d.items():
            kept = []
            for i, ax in enumerate(tuple(spec)):
                n = sizes.get(ax, 1) if ax is not None else 1
                ok = (ax is not None and n > 1
                      and shapes[lk][pn].shape[i] % n == 0)
                kept.append(ax if ok else None)
            out[lk][pn] = NamedSharding(
                mesh, P(*kept) if any(kept) else P())
    return out
