"""Per-parameter sharding rules: tensor parallelism over the 'model' axis.

The reference has no tensor parallelism (SURVEY.md par.2.7 - every device
holds a full replica); this module is the TPU-native extension that makes
`mesh = data:8,model:4` meaningful. The design follows the GSPMD recipe:
annotate *parameter* shardings only, and let XLA propagate activation
shardings and insert the collectives (all-gather on the fullc output
feature dim, reduce-scatter/all-reduce on contractions) over ICI.

Rules (each layer declares which dim of each param rides 'model' via
`Layer.model_shard_dims()`):
- fullc wmat (nhidden, nin): shard nhidden (Megatron column-parallel);
  bias (nhidden,) likewise. The following layer's contraction makes XLA
  all-gather or keep the sharding, whichever its cost model prefers.
- conv wmat OIHW: shard O (out channels); bias likewise. Channel-wise
  params downstream of a sharded conv (prelu slope, batch-norm
  slope/bias) shard the same dim so no resharding is needed.
- Any param whose shard dim is not divisible by the model-axis size is
  replicated (falling back is always legal - GSPMD handles mixtures).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cxxnet_tpu.nnet.network import Network, param_key

MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"
DATA_AXIS = "data"


def shard_map_manual(fn, mesh: Mesh, manual_axes, in_specs, out_specs):
    """shard_map across the old/new jax API split: manual over
    `manual_axes`, every OTHER mesh axis left to GSPMD (auto), value
    replication unchecked (the zero region's in/out specs assert the
    layouts the trainer compiles against; a varying-axes check would
    reject the deliberately-unreduced gradients). New API
    (jax.shard_map: axis_names/check_vma) first, the 0.4.x
    experimental spelling (auto/check_rep) as fallback."""
    manual = set(manual_axes)
    try:
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=manual,
                             check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm
        auto = frozenset(a for a in mesh.axis_names
                         if a not in manual)
        return _sm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False, auto=auto)


def param_pspecs(net: Network, shapes=None) -> Dict[str, Dict[str, P]]:
    """PartitionSpec per parameter; P() (replicated) unless the layer
    declares a model- and/or expert-shard dim. A param may ride both
    axes on different dims (none of the shipped layers do, but the
    combination is legal GSPMD)."""
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    specs: Dict[str, Dict[str, P]] = {}
    for idx, info in enumerate(net.cfg.layers):
        if info.is_shared:
            continue
        lk = param_key(net.cfg, idx)
        if lk not in shapes:
            continue
        layer = net.layer_objs[idx]
        by_axis = ((MODEL_AXIS, layer.model_shard_dims()),
                   (EXPERT_AXIS, layer.expert_shard_dims()),
                   (PIPE_AXIS, layer.pipe_shard_dims()))
        specs[lk] = {}
        for pn, sd in shapes[lk].items():
            spec = [None] * len(sd.shape)
            for axis, dims in by_axis:
                d = dims.get(pn)
                if d is not None and spec[d] is None:
                    spec[d] = axis
            specs[lk][pn] = P(*spec) if any(spec) else P()
    return specs


def zero1_eligible_dim(spec, shape, dsize):
    """Index of the first still-unsharded dim divisible by the
    data-axis size - the dim zero1_shardings additionally shards over
    'data' - or None when the weight keeps its parameter sharding.
    THE eligibility rule; the multichip dryrun asserts against it."""
    full = list(spec) + [None] * (len(shape) - len(spec))
    for i, (ax, dim) in enumerate(zip(full, shape)):
        if ax is None and dim % dsize == 0:
            return i
    return None


def zero_partition_dims(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None,
) -> Dict[str, Dict[str, Optional[int]]]:
    """zero1_eligible_dim per parameter: the dim each ZeRO stage cuts
    over 'data' (None = ineligible, the weight stays at its parameter
    sharding). One tree drives all three stages so optimizer state
    (stage 1), gradients/accumulator (stage 2) and parameters between
    steps (stage 3) always agree on the cut. `shapes` (an init_params
    eval_shape tree) may be passed to avoid re-tracing - the abstract
    init trace scales with the model, and ZeRO targets big models."""
    dsize = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        DATA_AXIS, 1)
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    out: Dict[str, Dict[str, Optional[int]]] = {}
    for lk, d in pshard.items():
        out[lk] = {}
        for pn, ns in d.items():
            if dsize <= 1:
                out[lk][pn] = None
                continue
            out[lk][pn] = zero1_eligible_dim(
                ns.spec, shapes[lk][pn].shape, dsize)
    return out


def _zero_shard_tree(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None, dims=None,
) -> Dict[str, Dict[str, NamedSharding]]:
    """Parameter shardings with the eligible dim additionally riding
    'data' (ineligible weights keep their parameter sharding)."""
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    if dims is None:
        dims = zero_partition_dims(mesh, net, pshard, shapes)
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pshard.items():
        out[lk] = {}
        for pn, ns in d.items():
            i = dims[lk][pn]
            if i is None:
                out[lk][pn] = ns
                continue
            shape = shapes[lk][pn].shape
            spec = list(ns.spec) + [None] * (len(shape) - len(ns.spec))
            spec[i] = DATA_AXIS
            out[lk][pn] = NamedSharding(mesh, P(*spec))
    return out


def zero1_shardings(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None, dims=None,
) -> Dict[str, Dict[str, NamedSharding]]:
    """ZeRO-1-style optimizer-state shardings: the update_on_server
    analog (nnet_ps_server.cpp:20-170 moves the updater to the server so
    workers don't replicate its state; here the state is sharded over
    the 'data' axis and GSPMD partitions the update math + all-gathers
    the fresh weights).

    Starting from each weight's parameter sharding, the first
    still-unsharded dim divisible by the data-axis size additionally
    rides 'data'. Weights with no such dim keep the parameter sharding
    (replication over data is always legal).
    """
    return _zero_shard_tree(mesh, net, pshard, shapes, dims)


def zero2_shardings(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None, dims=None,
) -> Dict[str, Dict[str, NamedSharding]]:
    """ZeRO-2 gradient/accumulator shardings (arXiv:2004.13336 the rest
    of the way): the same per-weight cut as the stage-1 optimizer state,
    so the reduce-scattered gradient lands exactly on the shard its
    updater state lives on and the update math needs no resharding. The
    trainer stores the update_period>1 accumulator in this layout too
    (peak gradient HBM / data-axis size between microsteps)."""
    return _zero_shard_tree(mesh, net, pshard, shapes, dims)


def zero3_shardings(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None, dims=None,
) -> Dict[str, Dict[str, NamedSharding]]:
    """ZeRO-3 parameter shardings BETWEEN steps: same cut again, now
    applied to the weights themselves - each device keeps only its
    shard and the forward all-gathers a weight just in time for its
    layer (trainer's zero region). Checkpoints still store full
    tensors (gather-on-save / reshard-on-load, nnet/checkpoint.py)."""
    return _zero_shard_tree(mesh, net, pshard, shapes, dims)


def zero_region_specs(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]],
        shapes=None, dims=None,
) -> Tuple[Dict[str, Dict[str, P]], Dict[str, Dict[str, P]]]:
    """(scatter_specs, gather_specs) for the trainer's manual-'data'
    fwd/bwd region (shard_map with every other mesh axis auto): per
    weight, the PartitionSpec naming ONLY the 'data' placement of its
    zero cut. scatter_specs describe the psum_scatter'd gradient
    outputs (and the stage-3 parameter inputs); gather_specs are P()
    everywhere - the full-weight view the per-layer all_gather
    restores (auto axes must not be named in manual specs, so the
    tensor-parallel 'model' placement rides along via GSPMD)."""
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    if dims is None:
        dims = zero_partition_dims(mesh, net, pshard, shapes)
    scatter: Dict[str, Dict[str, P]] = {}
    gather: Dict[str, Dict[str, P]] = {}
    for lk, d in dims.items():
        scatter[lk], gather[lk] = {}, {}
        for pn, i in d.items():
            gather[lk][pn] = P()
            if i is None:
                scatter[lk][pn] = P()
                continue
            spec = [None] * len(shapes[lk][pn].shape)
            spec[i] = DATA_AXIS
            scatter[lk][pn] = P(*spec)
    return scatter, gather


def shardings_for(mesh: Mesh,
                  net: Network) -> Dict[str, Dict[str, NamedSharding]]:
    """NamedSharding tree parallel to the params pytree (two levels).

    Each declared axis ('model', 'expert') is dropped back to
    replication independently when it is absent from the mesh, has size
    1, or the sharded dim does not divide its size.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    pspecs = param_pspecs(net, shapes)
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pspecs.items():
        out[lk] = {}
        for pn, spec in d.items():
            kept = []
            for i, ax in enumerate(tuple(spec)):
                n = sizes.get(ax, 1) if ax is not None else 1
                ok = (ax is not None and n > 1
                      and shapes[lk][pn].shape[i] % n == 0)
                kept.append(ax if ok else None)
            out[lk][pn] = NamedSharding(
                mesh, P(*kept) if any(kept) else P())
    return out
