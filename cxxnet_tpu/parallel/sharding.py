"""Per-parameter sharding rules: tensor parallelism over the 'model' axis.

The reference has no tensor parallelism (SURVEY.md par.2.7 - every device
holds a full replica); this module is the TPU-native extension that makes
`mesh = data:8,model:4` meaningful. The design follows the GSPMD recipe:
annotate *parameter* shardings only, and let XLA propagate activation
shardings and insert the collectives (all-gather on the fullc output
feature dim, reduce-scatter/all-reduce on contractions) over ICI.

Rules (each layer declares which dim of each param rides 'model' via
`Layer.model_shard_dims()`):
- fullc wmat (nhidden, nin): shard nhidden (Megatron column-parallel);
  bias (nhidden,) likewise. The following layer's contraction makes XLA
  all-gather or keep the sharding, whichever its cost model prefers.
- conv wmat OIHW: shard O (out channels); bias likewise. Channel-wise
  params downstream of a sharded conv (prelu slope, batch-norm
  slope/bias) shard the same dim so no resharding is needed.
- Any param whose shard dim is not divisible by the model-axis size is
  replicated (falling back is always legal - GSPMD handles mixtures).
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cxxnet_tpu.nnet.network import Network, param_key

MODEL_AXIS = "model"
DATA_AXIS = "data"


def param_pspecs(net: Network, shapes=None) -> Dict[str, Dict[str, P]]:
    """PartitionSpec per parameter; P() (replicated) unless the layer
    declares a model-shard dim and the dim divides the axis size."""
    if shapes is None:
        shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    specs: Dict[str, Dict[str, P]] = {}
    for idx, info in enumerate(net.cfg.layers):
        if info.is_shared:
            continue
        lk = param_key(net.cfg, idx)
        if lk not in shapes:
            continue
        dims = net.layer_objs[idx].model_shard_dims()
        specs[lk] = {}
        for pn, sd in shapes[lk].items():
            d = dims.get(pn)
            if d is None:
                specs[lk][pn] = P()
            else:
                spec = [None] * len(sd.shape)
                spec[d] = MODEL_AXIS
                specs[lk][pn] = P(*spec)
    return specs


def zero1_shardings(
        mesh: Mesh, net: Network,
        pshard: Dict[str, Dict[str, NamedSharding]]
) -> Dict[str, Dict[str, NamedSharding]]:
    """ZeRO-1-style optimizer-state shardings: the update_on_server
    analog (nnet_ps_server.cpp:20-170 moves the updater to the server so
    workers don't replicate its state; here the state is sharded over
    the 'data' axis and GSPMD partitions the update math + all-gathers
    the fresh weights).

    Starting from each weight's parameter sharding, the first
    still-unsharded dim divisible by the data-axis size additionally
    rides 'data'. Weights with no such dim keep the parameter sharding
    (replication over data is always legal).
    """
    dsize = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        DATA_AXIS, 1)
    shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pshard.items():
        out[lk] = {}
        for pn, ns in d.items():
            shape = shapes[lk][pn].shape
            if dsize <= 1:
                out[lk][pn] = ns
                continue
            spec = list(ns.spec) + [None] * (len(shape) - len(ns.spec))
            for i, ax in enumerate(spec):
                if ax is None and shape[i] % dsize == 0:
                    spec[i] = DATA_AXIS
                    break
            else:
                out[lk][pn] = ns
                continue
            out[lk][pn] = NamedSharding(mesh, P(*spec))
    return out


def shardings_for(mesh: Mesh,
                  net: Network) -> Dict[str, Dict[str, NamedSharding]]:
    """NamedSharding tree parallel to the params pytree (two levels).

    Falls back to replication when 'model' is absent from the mesh or the
    sharded dim does not divide the axis size.
    """
    have_model = MODEL_AXIS in mesh.axis_names
    msize = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        MODEL_AXIS, 1)
    shapes = jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    pspecs = param_pspecs(net, shapes)
    out: Dict[str, Dict[str, NamedSharding]] = {}
    for lk, d in pspecs.items():
        out[lk] = {}
        for pn, spec in d.items():
            if (not have_model or msize == 1 or spec == P()):
                out[lk][pn] = NamedSharding(mesh, P())
                continue
            dim = next(i for i, a in enumerate(spec) if a == MODEL_AXIS)
            if shapes[lk][pn].shape[dim] % msize != 0:
                out[lk][pn] = NamedSharding(mesh, P())
            else:
                out[lk][pn] = NamedSharding(mesh, spec)
    return out
