"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference scales by data parallelism only (mshadow-ps over the batch
dim - SURVEY.md par.2.7); long-context models need the SEQUENCE dim
sharded because activation memory grows with S and attention FLOPs with
S^2. This module adds the two standard TPU-native schemes over a 'seq'
mesh axis:

ring_attention    K/V blocks rotate around the ring with lax.ppermute
                  while each device's resident Q block accumulates
                  online-softmax partials (ops/attention.py). Peak
                  activation memory per device is O(S/n); each of the n
                  steps overlaps its ppermute with the partial-attention
                  compute (XLA's latency-hiding scheduler on ICI).
ulysses_attention lax.all_to_all reshards [B, H, S/n, D] -> [B, H/n, S, D]
                  so each device runs FULL-sequence attention for H/n
                  heads, then reshards back. Two all-to-alls of the
                  activation size per call; requires heads % n == 0.

Both are shard_map'd over the full mesh: batch rides 'data', heads ride
'model' (when present and divisible), sequence rides 'seq'. Gradients
flow through shard_map/ppermute/all_to_all transposes, so the same code
path serves training - no separate backward.

Choosing: ring has no head-count constraint and its comm (2 x S/n x D
per step, n steps) rides neighbor ICI links; Ulysses moves the same
total bytes in 2 all-to-alls but needs n <= heads. docs/parallel.md
"Sequence parallelism" quantifies both.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from cxxnet_tpu.ops.attention import (
    attention_partial, blockwise_attention, empty_partial,
    finalize_partial, merge_partials)

SEQ_AXIS = "seq"


def seq_axis_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh.shape.get(SEQ_AXIS, 1)


def _bhsd_spec(mesh: Mesh, heads: int) -> P:
    """[B, H, S, D] partition spec over every mesh axis present: batch on
    'data', heads on 'model' (only when divisible - replication across
    'model' is the legal fallback), seq on 'seq'."""
    names = mesh.axis_names
    data = "data" if "data" in names else None
    model = None
    if "model" in names and heads % mesh.shape["model"] == 0:
        model = "model"
    return P(data, model, SEQ_AXIS, None)


def ring_eligible(mesh: Optional[Mesh], seq_len: int) -> bool:
    """A real 'seq' axis whose size divides the sequence length."""
    n = seq_axis_size(mesh)
    return n > 1 and seq_len % n == 0


@partial(jax.jit, static_argnames=("mesh", "causal", "scale"))
def _ring_jit(q, k, v, mesh, causal, scale):
    spec = _bhsd_spec(mesh, q.shape[1])
    n = mesh.shape[SEQ_AXIS]

    def local_fn(q, k, v):
        idx = lax.axis_index(SEQ_AXIS)
        s_local = q.shape[2]
        # rotate kv to the next rank each step: after t steps this
        # device holds the block that started on rank (idx - t) mod n
        perm = [(j, (j + 1) % n) for j in range(n)]

        def partial_at(part, k_cur, v_cur, t):
            blk = (idx - t) % n

            def compute(part):
                p = attention_partial(q, k_cur, v_cur, scale=scale,
                                      causal=causal,
                                      q_offset=idx * s_local,
                                      kv_offset=blk * s_local)
                return merge_partials(part, p)

            if not causal:
                return compute(part)
            # causal: a K/V block from a strictly-later rank is entirely
            # in this Q block's masked future - skip its partial (the
            # naive schedule burns ~2x the needed FLOPs; the rotation
            # still happens, so correctness is carry-identical)
            return lax.cond(blk > idx, lambda p: p, compute, part)

        def step(carry, t):
            k_cur, v_cur, part = carry
            part = partial_at(part, k_cur, v_cur, t)
            k_nxt = lax.ppermute(k_cur, SEQ_AXIS, perm)
            v_nxt = lax.ppermute(v_cur, SEQ_AXIS, perm)
            return (k_nxt, v_nxt, part), None

        # the empty partial is built from constants; mark it as varying
        # over exactly the axes the inputs vary on (the in_specs' axes -
        # NOT every mesh axis: an unmentioned axis, e.g. 'expert', must
        # stay replicated or the out_specs vma check rejects the body)
        part0 = empty_partial(q)
        axes = tuple(a for a in spec if a is not None)
        if hasattr(lax, "pcast"):
            part0 = jax.tree.map(
                lambda x: lax.pcast(x, axes, to="varying"), part0)
        elif hasattr(lax, "pvary"):
            part0 = jax.tree.map(lambda x: lax.pvary(x, axes), part0)
        # n-1 rotate-and-accumulate steps, then the final block WITHOUT
        # the rotation (its K/V would only feed the discarded carry -
        # one whole ring pass of wasted ICI traffic per call otherwise)
        (k_l, v_l, part), _ = lax.scan(step, (k, v, part0),
                                       jnp.arange(n - 1))
        acc, _, l = partial_at(part, k_l, v_l, n - 1)
        return finalize_partial(acc, l, q.dtype)

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over the mesh's 'seq' axis; [B, H, S, D] global
    arrays in, semantics == ops.attention.naive_attention."""
    return _ring_jit(q, k, v, mesh, causal, scale)


@partial(jax.jit, static_argnames=("mesh", "causal", "scale", "kv_block"))
def _ulysses_jit(q, k, v, mesh, causal, scale, kv_block):
    nseq = mesh.shape[SEQ_AXIS]
    spec = _bhsd_spec(mesh, q.shape[1])
    # heads per model-shard must split across the seq axis too
    local_heads = q.shape[1] // (mesh.shape["model"]
                                 if spec[1] == "model" else 1)
    if local_heads % nseq != 0:
        raise ValueError(
            f"ulysses needs heads per shard ({local_heads}) divisible by "
            f"the seq axis ({nseq}); use ring_attention instead")

    def local_fn(q, k, v):
        # [B, H, S/n, D] -> [B, H/n, S, D]: trade the head dim for the
        # full sequence on every device
        a2a = partial(lax.all_to_all, axis_name=SEQ_AXIS, split_axis=1,
                      concat_axis=2, tiled=True)
        qg, kg, vg = a2a(q), a2a(k), a2a(v)
        o = blockwise_attention(qg, kg, vg, causal=causal, scale=scale,
                                kv_block=kv_block)
        return lax.all_to_all(o, axis_name=SEQ_AXIS, split_axis=2,
                              concat_axis=1, tiled=True)

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                      scale: Optional[float] = None, kv_block: int = 512):
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism; [B, H, S,
    D] global arrays in, semantics == naive_attention. Requires the
    per-model-shard head count to be divisible by the 'seq' axis size."""
    return _ulysses_jit(q, k, v, mesh, causal, scale, kv_block)


SEQ_SCHEMES = ("ring", "ulysses", "none")


def seq_parallel_attention(q, k, v, mesh, scheme: str, *,
                           causal: bool = False, kv_block: int = 512):
    """Shared sp dispatch for the attention-bearing layers
    (layers/attention.py, layers/transformer_stack.py): ring or Ulysses
    over an eligible 'seq' mesh, or None for the caller's per-device
    fallback (scheme == 'none', no mesh, or ineligible seq length)."""
    if scheme == "none" or mesh is None or not ring_eligible(
            mesh, q.shape[2]):
        return None
    if scheme == "ulysses":
        return ulysses_attention(q, k, v, mesh, causal=causal,
                                 kv_block=kv_block)
    return ring_attention(q, k, v, mesh, causal=causal)
