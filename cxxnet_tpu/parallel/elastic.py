"""Elastic pod supervisor: preemption recovery and mesh reshape.

    python -m cxxnet_tpu.parallel.elastic train.conf elastic_nproc=3

jax's multi-controller runtime fixes the process set at
``jax.distributed.initialize``: a member cannot join or leave a live
gloo job, so "elastic" training is built from **generations** - the
coordinated-checkpoint recipe of arXiv:1605.08695 §4.3 and the elastic
recipe of arXiv:2004.13336. Each generation is one fixed-membership
pod launched by this supervisor (every worker runs the ordinary
``python -m cxxnet_tpu.main`` CLI with ``elastic=1``); inside a
generation the coordinator (parallel/coordinator.py) barriers every
round boundary and the elected leader publishes ONE checkpoint. When a
member is lost the supervisor ends the generation and starts the next
one from the published checkpoint:

- **detection** - redundant signals, any one convicts: (1) the worker
  process exits (preemption: exit 117 from the ``kill``/``kill_rank``
  injectors, or any crash); (2) a surviving worker's barrier times out
  and it exits RESHAPE_EXIT_CODE after writing a conviction record;
  (3) the worker's own absence alert (telemetry/alerts.py: no
  ``train.step`` beacon progress) fires and its alert_cmd hook writes
  a conviction record - the wedged-but-alive case a process poll can
  never see; (4) the supervisor's cross-worker aggregation
  (tools/agg.py) returns a STALE ``restart`` verdict for the member's
  metrics stream (its telemetry heartbeat died).
- **decision** - a lost member with restart budget left
  (``elastic_respawn``) stays in the member set: the restarted process
  re-reads the membership record, replays the published checkpoint via
  the ordinary ``continue=1`` walkback, and rejoins the mesh at the
  next barrier. A member out of budget is dropped: the pod **reshapes**
  to N-1 hosts.
- **rollback** - nothing bespoke: the published checkpoint IS the
  rollback point (at most one round of progress is lost, the same
  walk-back-one-good-state semantics as the divergence guard), and the
  next generation's ``continue=1`` resume re-trains from it with the
  new mesh.

The supervisor is deliberately jax-free: it never imports the backend,
so it can outlive any number of wedged generations.

See docs/FAULT_TOLERANCE.md "Elastic pod" for the protocol and the
CI ``elastic-smoke`` job for the end-to-end proof.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from cxxnet_tpu.parallel.coordinator import ControlPlane
from cxxnet_tpu.utils.fault import KILL_EXIT_CODE, RESHAPE_EXIT_CODE


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def classify_lost(members: List[int],
                  exit_codes: Dict[int, Optional[int]],
                  convictions: Dict[int, Dict]) -> List[int]:
    """Which members a finished generation charges a restart to.

    ROOT CAUSES only: preemption (KILL_EXIT_CODE) and convicted
    members (barrier timeout, self-conviction, supervisor STALE
    verdict - a conviction may name a member the exit poll never saw
    die: wedged, then SIGKILLed by teardown). Every OTHER nonzero
    exit in a generation that has a culprit is collateral: jax's
    coordination service terminates every task when one dies
    ("Terminating process because ... another task died") and
    teardown SIGTERMs survivors blocked in collectives - those
    members rejoin the next generation at no budget cost. With no
    preemption and no conviction, any crash is the member's own
    (e.g. a bad config kills everyone; the generation cap bounds the
    retry loop)."""
    culprits = [m for m in members
                if exit_codes.get(m) == KILL_EXIT_CODE]
    culprits += [m for m in convictions
                 if m not in culprits and exit_codes.get(m) != 0]
    if not culprits:
        culprits = [m for m in members
                    if exit_codes.get(m)
                    not in (0, RESHAPE_EXIT_CODE, None)]
    return sorted(culprits)


class GenerationResult:
    """Outcome of one pod generation."""

    def __init__(self) -> None:
        self.done = False           # every member exited 0
        self.lost: List[int] = []   # members to respawn or drop
        self.exit_codes: Dict[int, Optional[int]] = {}
        self.convictions: Dict[int, Dict] = {}


class ElasticPod:
    """Generation loop driver. Config keys (the same ``k = v`` surface
    as every other component - the schema gate registers them from
    this handler):

    - ``elastic_nproc``        pod size N (default 2)
    - ``elastic_respawn``      per-member restart budget before the
                               member is dropped and the pod reshapes
                               to N-1 (default 1; 0 = always reshape)
    - ``elastic_max_generations`` hard cap on relaunches (default 8)
    - ``elastic_grace_secs``   SIGTERM->SIGKILL teardown grace (5)
    - ``elastic_poll_secs``    supervisor poll period (0.2)
    - ``elastic_absence_secs`` worker-side absence alert on the
                               train.step beacon; fires the
                               self-conviction hook (default 60;
                               0 disables the alert wiring)
    - ``elastic_stale_secs``   supervisor-side agg STALE conviction
                               threshold over the members' metrics
                               streams (default 60; 0 disables)
    - ``elastic_fault``        CXXNET_FAULT spec exported to
                               GENERATION 0 ONLY (deterministic e2e
                               murder - a spec that recurred in every
                               generation would kill the pod forever)
    """

    def __init__(self, conf: str, overrides: Optional[List[str]] = None):
        self.conf = conf
        self.overrides = list(overrides or [])
        self.nproc = 2
        self.respawn = 1
        self.max_generations = 8
        self.grace_secs = 5.0
        self.poll_secs = 0.2
        self.absence_secs = 60.0
        self.stale_secs = 60.0
        self.fault_spec = ""
        self.model_dir = "models"
        self.coord_dir = ""
        self.num_round = 10
        self._pairs: List[Tuple[str, str]] = []
        from cxxnet_tpu.utils.config import (parse_config_file,
                                             parse_config_string)
        for k, v in parse_config_file(conf):
            self.set_param(k, v)
        for arg in self.overrides:
            if "=" in arg:
                k, v = arg.split("=", 1)
                for kk, vv in parse_config_string(
                        f"{k.strip()} = {v.strip()}"):
                    self.set_param(kk, vv)
        self.coord_dir = self.coord_dir or os.path.join(
            self.model_dir, "coord")
        self.plane = ControlPlane(self.coord_dir)

    def set_param(self, name: str, val: str) -> None:
        if name == "elastic_nproc":
            self.nproc = int(val)
        if name == "elastic_respawn":
            self.respawn = int(val)
        if name == "elastic_max_generations":
            self.max_generations = int(val)
        if name == "elastic_grace_secs":
            self.grace_secs = float(val)
        if name == "elastic_poll_secs":
            self.poll_secs = float(val)
        if name == "elastic_absence_secs":
            self.absence_secs = float(val)
        if name == "elastic_stale_secs":
            self.stale_secs = float(val)
        if name == "elastic_fault":
            self.fault_spec = val
        if name == "model_dir":
            self.model_dir = val
        if name == "coord_dir":
            self.coord_dir = val
        if name == "num_round":
            self.num_round = int(val)
        self._pairs.append((name, val))

    # -- helpers -----------------------------------------------------------
    def _log(self, kind: str, **fields) -> None:
        self.plane.log_event("supervisor", kind, **fields)

    def _have_checkpoint(self) -> bool:
        import re
        try:
            names = os.listdir(self.model_dir)
        except OSError:
            return False
        return any(re.fullmatch(r"\d{4,}\.model", n) for n in names)

    def _member_metrics(self, member: int) -> str:
        return os.path.join(self.coord_dir, f"metrics.m{member}.jsonl")

    def _alert_rules_path(self) -> str:
        return os.path.join(self.coord_dir, "alerts.json")

    def _write_alert_rules(self) -> None:
        import json
        rules = [{
            "type": "absence", "name": "elastic_train_step_absent",
            "beacon": "train.step", "for_secs": self.absence_secs,
            "startup_grace_secs": max(self.absence_secs, 120.0),
        }]
        from cxxnet_tpu.utils.fault import atomic_writer
        with atomic_writer(self._alert_rules_path(), "w") as fo:
            json.dump(rules, fo)

    def _worker_argv(self, member: int, generation: int,
                     members: List[int]) -> List[str]:
        argv = [sys.executable, "-m", "cxxnet_tpu.main", self.conf]
        argv += self.overrides
        argv += [
            "elastic=1",
            f"coord_dir={self.coord_dir}",
            # per-member telemetry stream: the supervisor's agg
            # verdict + the CI artifacts read these; a SHARED
            # metrics_file would interleave processes
            f"metrics_file={self._member_metrics(member)}",
            "heartbeat_secs=1.0",
        ]
        if len(members) > 1:
            argv.append("param_server=dist")
        if generation > 0 or self._have_checkpoint():
            # roll back to the published checkpoint: the ordinary
            # validated continue=1 walkback IS the rollback path
            argv.append("continue=1")
        if self.absence_secs > 0:
            # the worker convicts ITSELF when its train.step beacon
            # stalls: the alert thread outlives a wedged main thread
            argv += [
                f"alert_rules={self._alert_rules_path()}",
                "alert_cmd=" + (
                    f"{sys.executable} -m cxxnet_tpu.parallel.elastic "
                    f"--self-convict {self.coord_dir} {member}"),
            ]
        return argv

    def _spawn(self, generation: int,
               members: List[int]) -> Dict[int, subprocess.Popen]:
        port = _free_port()
        if self.absence_secs > 0:
            self._write_alert_rules()
        procs: Dict[int, subprocess.Popen] = {}
        for rank, member in enumerate(sorted(members)):
            env = dict(os.environ)
            env["CXN_COORDINATOR"] = f"127.0.0.1:{port}"
            env["CXN_NUM_WORKER"] = str(len(members))
            env["CXN_WORKER_RANK"] = str(rank)
            env["CXN_MEMBER_ID"] = str(member)
            if self.fault_spec:
                if generation == 0:
                    env["CXXNET_FAULT"] = self.fault_spec
                else:
                    env.pop("CXXNET_FAULT", None)
            log_path = os.path.join(
                self.coord_dir, f"worker.m{member}.g{generation}.log")
            logf = open(log_path, "w")
            try:
                procs[member] = subprocess.Popen(
                    self._worker_argv(member, generation, members),
                    env=env, stdout=logf, stderr=subprocess.STDOUT)
            finally:
                logf.close()  # the child owns the fd now
        return procs

    def _teardown(self, procs: Dict[int, subprocess.Popen]) -> None:
        """End a generation: survivors are likely blocked inside a
        collective whose peer is gone - SIGTERM them, escalate to
        SIGKILL after the grace."""
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.grace_secs
        for p in procs.values():
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=self.grace_secs)
            except subprocess.TimeoutExpired:
                pass

    def _stale_members(self, agg, procs) -> List[int]:
        """Map the aggregator's STALE restart verdicts (host/pid keys)
        back to members via the workers' pids."""
        if agg is None:
            return []
        agg.poll()
        pid_to_member = {p.pid: m for m, p in procs.items()}
        out = []
        for rec in agg.verdict().get("restart", []):
            if rec.get("reason") != "stale":
                continue
            key = str(rec.get("host", ""))
            try:
                pid = int(key.rsplit("/", 1)[1])
            except (IndexError, ValueError):
                continue
            m = pid_to_member.get(pid)
            if m is not None:
                out.append(m)
        return out

    # -- one generation ----------------------------------------------------
    def run_generation(self, generation: int,
                       members: List[int]) -> GenerationResult:
        members = sorted(members)
        self.plane.write_generation(generation, members)
        # conviction records are per-generation evidence: stale ones
        # from the previous teardown must not instantly re-convict
        for m in members:
            try:
                os.remove(self.plane.conviction_path(m))
            except OSError:
                pass
        self._log("generation_start", generation=generation,
                  members=members)
        procs = self._spawn(generation, members)
        agg = None
        if self.stale_secs > 0:
            from cxxnet_tpu.tools.agg import Aggregator, make_source
            agg = Aggregator(
                [make_source(self._member_metrics(m)) for m in members],
                stale_secs=self.stale_secs)
        res = GenerationResult()
        live = dict(procs)
        lost: List[int] = []
        while live and not lost:
            time.sleep(self.poll_secs)
            for m, p in list(live.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del live[m]
                res.exit_codes[m] = rc
                if rc == 0:
                    self._log("member_done", member=m,
                              generation=generation)
                elif rc == RESHAPE_EXIT_CODE:
                    # a survivor convicting a peer is itself healthy;
                    # the convicted member shows up in the records
                    self._log("member_reshape_exit", member=m,
                              generation=generation)
                else:
                    cause = ("preempted" if rc == KILL_EXIT_CODE
                             else "crashed")
                    self._log("member_lost", member=m, exit=rc,
                              cause=cause, generation=generation)
                    lost.append(m)
            if lost:
                break
            convicted = self.plane.convictions(members)
            fresh = [m for m in convicted
                     if m in live or m not in res.exit_codes]
            for m in fresh:
                self._log("member_convicted", member=m,
                          generation=generation,
                          reason=convicted[m].get("reason"),
                          by=convicted[m].get("by"))
            lost.extend(m for m in fresh if m not in lost)
            for m in self._stale_members(agg, procs):
                if m not in lost and m in live:
                    # record the verdict as a conviction so the
                    # post-teardown classification charges it
                    self.plane.write_conviction(
                        m, -1, "stale-metrics")
                    self._log("member_stale", member=m,
                              generation=generation)
                    lost.append(m)
        self._teardown(procs)
        for m, p in procs.items():
            res.exit_codes.setdefault(m, p.poll())
        res.convictions = self.plane.convictions(members)
        res.lost = classify_lost(members, res.exit_codes,
                                 res.convictions)
        res.done = (not lost and res.exit_codes
                    and all(rc == 0 for rc in res.exit_codes.values()))
        self._log("generation_end", generation=generation,
                  done=res.done, lost=res.lost,
                  exit_codes={str(k): v
                              for k, v in res.exit_codes.items()})
        return res

    # -- the pod -----------------------------------------------------------
    def run(self) -> int:
        os.makedirs(self.coord_dir, exist_ok=True)
        members = list(range(self.nproc))
        restarts = {m: 0 for m in members}
        self._log("pod_start", nproc=self.nproc,
                  respawn=self.respawn, conf=self.conf)
        for generation in range(self.max_generations):
            res = self.run_generation(generation, members)
            if res.done:
                manifest = self.plane.read_manifest()
                self._log("pod_done", generation=generation,
                          members=members, manifest=manifest)
                return 0
            if not res.lost:
                # ended without a culprit (every member crashed, or
                # teardown raced completion): retry the same set -
                # the generation cap bounds a crash loop
                self._log("pod_retry", generation=generation)
                continue
            next_members = []
            for m in members:
                if m not in res.lost:
                    next_members.append(m)
                elif restarts[m] < self.respawn:
                    # preemption recovery: the member rejoins - its
                    # restarted process replays the published
                    # checkpoint and meets the pod at the next barrier
                    restarts[m] += 1
                    next_members.append(m)
                    self._log("member_respawn", member=m,
                              restarts=restarts[m])
                else:
                    # out of budget: reshape the pod to N-1 around it
                    self._log("member_dropped", member=m)
            if not next_members:
                self._log("pod_failed", reason="no members left")
                return 1
            members = next_members
        self._log("pod_failed", reason="max generations exceeded",
                  max_generations=self.max_generations)
        return 1


def _self_convict(coord_dir: str, member: int) -> int:
    """alert_cmd hook target: record this worker's own absence alert
    as a conviction (state comes from the ALERT_* env the alert engine
    sets; only a FIRING absence convicts - the resolve hook run is a
    no-op)."""
    if os.environ.get("ALERT_STATE") != "firing":
        return 0
    plane = ControlPlane(coord_dir)
    plane.write_conviction(
        member, member,
        f"absence-alert:{os.environ.get('ALERT_NAME', '?')}")
    plane.log_event(f"m{member}", "self_convict",
                    alert=os.environ.get("ALERT_NAME"),
                    message=os.environ.get("ALERT_MESSAGE"))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        sys.stdout.write(__doc__ + "\n")
        return 1
    if argv[0] == "--self-convict":
        return _self_convict(argv[1], int(argv[2]))
    return ElasticPod(argv[0], argv[1:]).run()


if __name__ == "__main__":
    sys.exit(main())
