"""Parallelism: device mesh construction and sharding rules.

The reference's multi-device story (one host thread + CUDA stream + full
model replica per GPU, gradients synced through mshadow-ps - SURVEY.md
par.2.7) maps to a single SPMD program over a `jax.sharding.Mesh`: the batch
dim is sharded over the 'data' axis, params are replicated (or sharded over
'model' for tensor parallelism), and XLA inserts the AllReduce over ICI
that replaces the entire push/pull parameter server.
"""

from cxxnet_tpu.parallel.mesh import (
    MeshSpec, build_mesh, parse_device_spec)

__all__ = ["MeshSpec", "build_mesh", "parse_device_spec"]
