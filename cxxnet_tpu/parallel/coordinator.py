"""Coordinated checkpoint barriers, leases, and leader election.

The multi-controller port inherited the reference's fixed-worker-set
assumption (mshadow-ps: one lost peer stalls the job, SURVEY §0.7):
every process used to checkpoint independently - N writers racing on
the same ``%04d.model`` - and a preempted host wedged the pod until
the hang watchdog dumped stacks for a human. This module is the
coordination layer that replaces those per-process heroics
(TensorFlow's coordinated-checkpoint fault tolerance, arXiv:1605.08695
§4.3): at every round boundary the pod reaches a **barrier**, a
deterministic **leader** (lowest live member over the control plane)
publishes ONE atomic checkpoint with a pod-wide epoch stamp, and a
member that never arrives is **convicted** so the elastic supervisor
(parallel/elastic.py) can roll back one round, rebuild the mesh
without it, and continue.

The control plane is a shared directory (``coord_dir``), not a gloo
collective: the training collectives die with their slowest member -
exactly the failure being coordinated around - so membership must ride
a channel that survives a dead peer. Records are tiny JSON files
written through the PR 1 ``atomic_writer`` (a reader sees a complete
record or the previous one, never a torn write); on a pod this is the
same shared filesystem the checkpoints already use.

Records under ``coord_dir``:

- ``lease.<member>.json``  - liveness lease, renewed by a heartbeat
  thread every ``lease_secs / 3``; a lease older than ``lease_secs``
  is stale and its member counts as dead (vs wedged: alive lease,
  absent from the barrier).
- ``generation.json``      - the membership record: which members form
  pod generation g (written by the supervisor before each launch).
- ``barrier/g<G>.r<R>.m<M>.json`` - member M arrived at round R's
  barrier in generation G.
- ``published.json``       - the publish manifest: the ONE checkpoint
  the pod agrees on (path, sha256, round, generation, monotonically
  increasing pod epoch, writer member).
- ``events.<name>.jsonl``  - per-process append-only event log
  (arrivals, elections, publishes, convictions): the coordinator
  beacons the CI elastic-smoke job archives.

See docs/FAULT_TOLERANCE.md "Elastic pod" for the protocol spec and
what is deliberately NOT survivable.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from cxxnet_tpu.utils.fault import atomic_writer, fault_point

# exit code for "I convicted an absent peer at a barrier": the elastic
# supervisor reshapes instead of treating the exit as a crash
# (re-exported by utils.fault as RESHAPE_EXIT_CODE)
LEASE_SECS = 10.0
BARRIER_SECS = 30.0


class PodReshapeRequired(RuntimeError):
    """A barrier timed out with members missing: the pod must be
    rebuilt without (or with a restarted copy of) the absentees. The
    worker exits with RESHAPE_EXIT_CODE; the supervisor rolls back to
    the published checkpoint and relaunches."""

    def __init__(self, round_no: int, missing: List[int],
                 dead: List[int]):
        self.round_no = round_no
        self.missing = list(missing)    # never arrived
        self.dead = list(dead)          # ... and their lease is stale
        wedged = [m for m in missing if m not in dead]
        parts = []
        if dead:
            parts.append(f"dead (stale lease): {dead}")
        if wedged:
            parts.append(f"wedged (live lease, absent): {wedged}")
        super().__init__(
            f"checkpoint barrier for round {round_no} timed out; "
            + "; ".join(parts))


@dataclass
class BarrierResult:
    """One completed checkpoint barrier."""

    round_no: int
    generation: int
    members: List[int]      # who arrived (== the generation members)
    leader: int             # lowest live member - the one publisher
    is_leader: bool
    epoch: int              # pod epoch the NEXT publish will stamp


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ControlPlane:
    """The shared-directory record store. All methods are process-safe
    (atomic replace writes, whole-file reads); instances are cheap and
    carry no daemon state - the heartbeat lives in Coordinator."""

    def __init__(self, root: str,
                 clock: Callable[[], float] = time.time):
        self.root = root
        # wall clock by default: lease timestamps are compared ACROSS
        # processes (possibly across hosts), which a per-process
        # monotonic clock cannot do; injectable for fake-clock tests
        self.clock = clock
        os.makedirs(os.path.join(root, "barrier"), exist_ok=True)

    # -- raw records -------------------------------------------------------
    def _write_json(self, path: str, rec: Dict, fsync: bool) -> None:
        with atomic_writer(path, "w", fsync=fsync) as fo:
            json.dump(rec, fo)

    @staticmethod
    def read_json(path: str) -> Optional[Dict]:
        """One record, or None when absent. Torn/garbage content is
        impossible locally (atomic replace) but treated as absent
        anyway - NFS-style close-to-open races must not crash the
        reader, the next poll sees the complete record."""
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except OSError:
            return None
        except ValueError:
            return None

    # -- leases ------------------------------------------------------------
    def lease_path(self, member: int) -> str:
        return os.path.join(self.root, f"lease.{member}.json")

    def write_lease(self, member: int, generation: int,
                    pid: Optional[int] = None) -> None:
        # leases renew ~3x per lease_secs: skip the fsync (a lost
        # lease write costs one stale-by-a-beat read, not correctness)
        self._write_json(self.lease_path(member), {
            "member": member, "generation": generation,
            "pid": os.getpid() if pid is None else pid,
            "ts": self.clock()}, fsync=False)

    def lease_fresh(self, member: int, lease_secs: float,
                    now: Optional[float] = None) -> bool:
        rec = self.read_json(self.lease_path(member))
        if rec is None:
            return False
        now = self.clock() if now is None else now
        return now - float(rec.get("ts", 0.0)) <= lease_secs

    def live_members(self, members: List[int], lease_secs: float,
                     now: Optional[float] = None) -> List[int]:
        now = self.clock() if now is None else now
        return [m for m in members
                if self.lease_fresh(m, lease_secs, now)]

    # -- membership (generation) record ------------------------------------
    def generation_path(self) -> str:
        return os.path.join(self.root, "generation.json")

    def write_generation(self, generation: int,
                         members: List[int]) -> None:
        self._write_json(self.generation_path(), {
            "generation": generation,
            "members": sorted(members),
            "ts": self.clock()}, fsync=True)

    def read_generation(self) -> Optional[Dict]:
        return self.read_json(self.generation_path())

    # -- barrier arrivals ---------------------------------------------------
    def _barrier_path(self, generation: int, round_no: int,
                      member: int) -> str:
        return os.path.join(
            self.root, "barrier",
            f"g{generation}.r{round_no}.m{member}.json")

    def write_arrival(self, generation: int, round_no: int,
                      member: int) -> None:
        self._write_json(
            self._barrier_path(generation, round_no, member),
            {"member": member, "round": round_no,
             "generation": generation, "ts": self.clock()},
            fsync=False)

    def arrivals(self, generation: int, round_no: int,
                 members: List[int]) -> List[int]:
        return [m for m in members
                if self.read_json(
                    self._barrier_path(generation, round_no, m))
                is not None]

    # -- publish manifest ---------------------------------------------------
    def manifest_path(self) -> str:
        return os.path.join(self.root, "published.json")

    def read_manifest(self) -> Optional[Dict]:
        return self.read_json(self.manifest_path())

    def write_manifest(self, rec: Dict) -> None:
        self._write_json(self.manifest_path(), rec, fsync=True)

    # -- conviction records (absence-alert hook + barrier verdicts) ---------
    def conviction_path(self, member: int) -> str:
        return os.path.join(self.root, f"convict.{member}.json")

    def write_conviction(self, member: int, by: int,
                         reason: str) -> None:
        self._write_json(self.conviction_path(member), {
            "member": member, "by": by, "reason": reason,
            "ts": self.clock()}, fsync=False)

    def convictions(self, members: List[int]) -> Dict[int, Dict]:
        out: Dict[int, Dict] = {}
        for m in members:
            rec = self.read_json(self.conviction_path(m))
            if rec is not None:
                out[m] = rec
        return out

    # -- event log (coordinator beacons) ------------------------------------
    def log_event(self, who: str, kind: str, **fields) -> None:
        rec = {"ts": self.clock(), "who": who, "kind": kind}
        rec.update(fields)
        path = os.path.join(self.root, f"events.{who}.jsonl")
        # single writer per file: O_APPEND keeps lines whole without
        # the atomic-replace dance (and readers tail incrementally)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")


class Coordinator:
    """One worker process's half of the barrier protocol. Owns the
    lease heartbeat thread; ``barrier()`` is called from the training
    thread at every round boundary."""

    def __init__(self, plane: ControlPlane, member: int,
                 members: List[int], generation: int = 0,
                 barrier_secs: float = BARRIER_SECS,
                 lease_secs: float = LEASE_SECS,
                 poll_secs: float = 0.05):
        self.plane = plane
        self.member = member
        self.members = sorted(members)
        self.generation = generation
        self.barrier_secs = barrier_secs
        self.lease_secs = lease_secs
        self.poll_secs = poll_secs
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # heartbeat-thread/trainer-thread shared state, moves only
        # under the lock (docs/STATIC_ANALYSIS.md GL016)
        # guarded-by: self._lock
        self._renewals = 0
        # guarded-by: self._lock
        self._last_renew = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Write the first lease synchronously (the pod must see this
        member live before any barrier), then start the renewal
        thread."""
        self.plane.write_lease(self.member, self.generation)
        self.plane.log_event(
            f"m{self.member}", "join", member=self.member,
            generation=self.generation, members=self.members)
        from cxxnet_tpu import telemetry
        telemetry.set_gauge("coord.generation", float(self.generation))
        telemetry.set_gauge("coord.member", float(self.member))
        t = threading.Thread(target=self._heartbeat,
                             name=f"coord-lease-m{self.member}",
                             daemon=True)
        self._thread = t
        t.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Coordinator":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _heartbeat(self) -> None:
        """Lease renewal loop. Note what this does NOT prove: a wedged
        main thread keeps its lease fresh (the thread is alive), which
        is exactly why conviction distinguishes dead (stale lease)
        from wedged (fresh lease, absent from the barrier) - and why
        the absence alert on the train.step beacon, not the lease, is
        the wedged-worker detector (docs/OBSERVABILITY.md)."""
        period = max(self.lease_secs / 3.0, 0.01)
        while not self._stop.wait(period):
            self.plane.write_lease(self.member, self.generation)
            with self._lock:
                self._renewals += 1
                self._last_renew = self.plane.clock()

    @property
    def renewals(self) -> int:
        with self._lock:
            return self._renewals

    # -- election ----------------------------------------------------------
    def live_members(self, now: Optional[float] = None) -> List[int]:
        live = self.plane.live_members(self.members, self.lease_secs,
                                       now)
        if self.member not in live:
            # self-evidently live (the lease file may lag a beat)
            live = sorted(live + [self.member])
        return live

    def leader(self, now: Optional[float] = None) -> int:
        """Deterministic lease-based election: the lowest member with
        a fresh lease. Within a generation every completed barrier
        contains ALL generation members, so the elected leader is
        stable; it changes exactly when a reshape drops the old one."""
        return min(self.live_members(now))

    def is_leader(self, now: Optional[float] = None) -> bool:
        return self.leader(now) == self.member

    # -- the barrier -------------------------------------------------------
    def barrier(self, round_no: int) -> BarrierResult:
        """Arrive at round ``round_no``'s checkpoint barrier and wait
        for every generation member. Completion elects the publisher:
        leader = lowest member of the arrival set (a pure function of
        the set - every process computes the same one). A member still
        missing after ``barrier_secs`` is convicted - dead when its
        lease is stale, wedged when the lease is fresh - and
        PodReshapeRequired is raised; the caller exits with
        RESHAPE_EXIT_CODE and the supervisor rebuilds the pod."""
        fault_point("barrier")
        plane = self.plane
        plane.write_arrival(self.generation, round_no, self.member)
        plane.log_event(f"m{self.member}", "arrive", round=round_no,
                        generation=self.generation)
        from cxxnet_tpu import telemetry
        telemetry.beacon("coord.barrier")
        telemetry.inc("coord.barriers")
        deadline = plane.clock() + self.barrier_secs
        while True:
            arrived = plane.arrivals(self.generation, round_no,
                                     self.members)
            if len(arrived) == len(self.members):
                break
            now = plane.clock()
            if now > deadline:
                missing = [m for m in self.members
                           if m not in arrived]
                dead = [m for m in missing
                        if not plane.lease_fresh(
                            m, self.lease_secs, now)]
                for m in missing:
                    reason = "dead" if m in dead else "wedged"
                    plane.write_conviction(m, self.member, reason)
                plane.log_event(
                    f"m{self.member}", "convict", round=round_no,
                    missing=missing, dead=dead)
                telemetry.inc("coord.convictions", len(missing))
                raise PodReshapeRequired(round_no, missing, dead)
            self._stop.wait(self.poll_secs)
        leader = min(arrived)
        manifest = plane.read_manifest()
        epoch = (int(manifest["epoch"]) + 1) if manifest else 1
        res = BarrierResult(
            round_no=round_no, generation=self.generation,
            members=sorted(arrived), leader=leader,
            is_leader=(leader == self.member), epoch=epoch)
        plane.log_event(
            f"m{self.member}", "barrier", round=round_no,
            generation=self.generation, leader=leader,
            is_leader=res.is_leader, epoch=epoch)
        telemetry.set_gauge("coord.leader", float(leader))
        telemetry.set_gauge("coord.is_leader", float(res.is_leader))
        return res

    # -- publishing --------------------------------------------------------
    def publish(self, result: BarrierResult, round_no: int,
                path: str, sha256: str, nbytes: int) -> Dict:
        """Record the checkpoint the pod agrees on. Leader-only by
        protocol; asserted here so a caller bug becomes a loud failure
        instead of a silent return to N-independent-writers races."""
        if not result.is_leader:
            raise RuntimeError(
                f"member {self.member} tried to publish round "
                f"{round_no} but the leader is {result.leader}")
        rec = {
            "epoch": result.epoch, "round": round_no,
            "generation": self.generation, "path": path,
            "sha256": sha256, "bytes": nbytes,
            "writer": self.member, "ts": self.plane.clock(),
        }
        self.plane.write_manifest(rec)
        self.plane.log_event(
            f"m{self.member}", "publish", round=round_no,
            epoch=result.epoch, path=path, sha256=sha256)
        from cxxnet_tpu import telemetry
        telemetry.inc("coord.publishes")
        telemetry.set_gauge("coord.epoch", float(result.epoch))
        return rec
