"""Device-spec parsing and Mesh construction.

Config surface parity (nnet_impl-inl.hpp:32-51): `dev = gpu:0-3`,
`dev = cpu:0,2`, `dev = tpu:0-63`. The device *kind* is advisory - the
process uses whatever platform JAX exposes (TPU under the tunnel, CPU with
a forced host platform in tests); the index list picks devices by position.

Extension over the reference: `mesh = data:8,model:4` declares a 2-D mesh
for combined data/tensor parallelism. Without it, all selected devices form
a 1-D 'data' mesh (pure data parallelism - the reference's only mode).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh


_ACTIVE_MESH: List[Optional[Mesh]] = [None]


class active_mesh:
    """Context manager binding 'the mesh this forward runs over' so ops
    deep in the layer stack (e.g. the Pallas LRN shard_map route,
    ops/pallas_lrn.py) can partition themselves without the mesh being
    threaded through every Layer.apply signature. The trainer enters it
    around net.forward inside the traced step, so the binding is active
    exactly while that trainer's trace runs (re-entrant per trainer)."""

    def __init__(self, mesh: Optional[Mesh]):
        self.mesh = mesh

    def __enter__(self):
        _ACTIVE_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _ACTIVE_MESH.pop()
        return False


def get_active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH[-1]


def data_axis_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh.shape.get("data", 1)


def batch_shardable(mesh: Optional[Mesh], batch: int) -> bool:
    """Shared eligibility for shard_map-over-'data' op routes (Pallas
    LRN, per-shard batch_norm): a real data axis whose size divides the
    batch dim."""
    n = data_axis_size(mesh)
    return n > 1 and batch % n == 0


@dataclass
class MeshSpec:
    device_indices: Optional[List[int]] = None  # None = single device
    axes: List[Tuple[str, int]] = field(default_factory=list)

    @property
    def num_devices(self) -> int:
        if self.axes:
            n = 1
            for _, k in self.axes:
                n *= k
            return n
        return len(self.device_indices) if self.device_indices else 1


def parse_device_spec(val: str) -> Optional[List[int]]:
    """`cpu` / `tpu` -> None (single default device);
    `tpu:0-3` -> [0,1,2,3]; `tpu:0,2` -> [0,2]."""
    if ":" not in val:
        return None
    spec = val.split(":", 1)[1]
    if "-" in spec:
        a, b = spec.split("-")
        return list(range(int(a), int(b) + 1))
    return [int(t) for t in spec.split(",")]


def parse_mesh_spec(val: str) -> List[Tuple[str, int]]:
    """`data:8` or `data:8,model:4` -> [(axis, size), ...]."""
    axes = []
    for part in val.split(","):
        name, size = part.split(":")
        axes.append((name.strip(), int(size)))
    return axes


def build_mesh(spec: MeshSpec, batch_size: int,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the mesh, pruning the data axis to divide batch_size.

    The reference prunes its device list when the batch is too small
    (nnet_impl-inl.hpp:141-150); here the constraint is divisibility:
    the data axis is shrunk to the largest size that divides batch_size.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec.axes:
        if spec.device_indices is not None:
            # `dev = tpu:4-7` + `mesh = ...` composes: the mesh is laid
            # out over the SELECTED devices, not silently over the
            # first N of the full list
            if max(spec.device_indices) >= len(devices):
                raise ValueError(
                    f"device spec requests index "
                    f"{max(spec.device_indices)} but only "
                    f"{len(devices)} devices are available")
            devices = [devices[i] for i in spec.device_indices]
        names = [a for a, _ in spec.axes]
        sizes = [k for _, k in spec.axes]
    else:
        idx = spec.device_indices
        if idx is None:
            # single-controller default: one device. Multi-controller
            # (param_server=dist): every process must own part of the
            # mesh, so default to data-parallel over ALL global devices.
            if jax.process_count() == 1:
                devices = devices[:1]
        else:
            if max(idx) >= len(devices):
                raise ValueError(
                    f"device spec requests index {max(idx)} but only "
                    f"{len(devices)} devices are available")
            devices = [devices[i] for i in idx]
        names = ["data"]
        sizes = [len(devices)]

    # prune the data axis to divide the batch (single-controller only:
    # under multi-controller SPMD, dropping devices would orphan some
    # processes' chips, so an indivisible batch is an error instead)
    if "data" in names:
        di = names.index("data")
        if jax.process_count() > 1:
            if batch_size % sizes[di] != 0:
                raise ValueError(
                    f"batch_size {batch_size} must be divisible by the "
                    f"data axis ({sizes[di]}) in multi-controller mode")
        else:
            while batch_size % sizes[di] != 0:
                sizes[di] -= 1

    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(
            f"mesh of {n} devices requested, {len(devices)} available")
    dev_array = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev_array, tuple(names))
