"""cxxnet_tpu: a TPU-native, config-driven neural-network trainer.

A ground-up JAX/XLA re-design of the capability surface of cxxnet (the
pre-MXNet dmlc CNN trainer, surveyed in /root/repo/SURVEY.md): a single
`key = value` config file declares data iterators, a layer DAG, updater
settings and a task (train / pred / extract / finetune); the framework
compiles the whole training step (forward + backward + gradient
all-reduce + optimizer update) into one XLA program and runs it over a
`jax.sharding.Mesh` of TPU chips.

Architectural mapping from the reference (file:line cites refer to the
reference tree, see SURVEY.md):

- mshadow expression templates      -> jax.numpy / lax ops, XLA fusion
- hand-written Backprop methods     -> jax.grad through the functional net
- in-place node gradient storage    -> pure functional node values
- NeuralNetThread-per-GPU + PS      -> single SPMD program over a Mesh,
  (nnet/neural_net-inl.hpp:304)        gradients reduced by XLA AllReduce
- mshadow-ps push/pull (updater.h)  -> compiler-inserted collectives over ICI
- AdjustBatchSize dynamic batches   -> pad-to-static + masked loss/metrics
"""

__version__ = "0.1.0"

__all__ = ["NetConfig", "NetTrainer", "create_net", "__version__"]


def __getattr__(name):
    # Lazy imports keep `import cxxnet_tpu.utils` free of the jax import cost.
    if name == "NetConfig":
        from cxxnet_tpu.nnet.net_config import NetConfig
        return NetConfig
    if name in ("NetTrainer", "create_net"):
        from cxxnet_tpu.nnet import trainer
        return getattr(trainer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
