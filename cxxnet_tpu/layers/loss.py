"""Loss layers.

In the reference these are self-loop layers that transform activations in
the forward pass and overwrite them with gradients in the backward pass
(loss_layer_base-inl.hpp:31-104). Functionally, each loss layer provides:

- forward_transform(x): what Predict/Evaluate see (softmax probs, sigmoid);
- per_example_loss(x, label): a scalar per instance whose gradient w.r.t.
  the raw input x equals the reference's hand-written gradient:
    softmax:        d/dx CE          = softmax(x) - onehot(label)
    l2_loss:        d/dx 0.5||x-y||^2 = x - y
    multi_logistic: d/dx BCEwithlogits = sigmoid(x) - y

The reference scales the gradient by grad_scale/(batch_size*update_period)
(loss_layer_base-inl.hpp:60-63); the trainer applies the same scale to the
summed loss, so the resulting parameter gradients are identical.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from cxxnet_tpu.layers.base import Layer, Shape, register_layer


class LossLayer(Layer):
    """Base loss layer (self-loop)."""

    is_loss = True

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.target = "label"
        self.grad_scale = 1.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "target":
            self.target = val
        if name == "grad_scale":
            self.grad_scale = float(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        b = x.shape[0]
        m = x.reshape(b, -1)
        return [self.forward_transform(m).reshape(x.shape)]

    # --- loss interface ---------------------------------------------------
    def forward_transform(self, x: jax.Array) -> jax.Array:
        return x

    def per_example_loss(self, x: jax.Array, label: jax.Array) -> jax.Array:
        """x: (n, k) raw pre-transform activations; label: (n, label_width).
        Returns (n,) per-example losses."""
        raise NotImplementedError


@register_layer
class SoftmaxLayer(LossLayer):
    """softmax + cross entropy (loss/softmax_layer-inl.hpp:12-33)."""

    type_name = "softmax"

    def forward_transform(self, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(x, axis=-1)

    def per_example_loss(self, x: jax.Array, label: jax.Array) -> jax.Array:
        if label.shape[1] != 1:
            # reference assert (softmax expects one class-id column)
            raise ValueError(
                f"softmax: label width must be 1, got {label.shape[1]} "
                "(use label_vec to slice the class column)")
        lbl = label[:, 0].astype(jnp.int32)
        logz = jax.nn.logsumexp(x, axis=-1)
        picked = jnp.take_along_axis(x, lbl[:, None], axis=1)[:, 0]
        return logz - picked


@register_layer
class L2LossLayer(LossLayer):
    """l2_loss (loss/l2_loss_layer-inl.hpp): identity forward,
    grad = pred - label."""

    type_name = "l2_loss"

    def per_example_loss(self, x: jax.Array, label: jax.Array) -> jax.Array:
        if label.shape[1] != x.shape[1]:
            # reference assert (l2_loss: label width == pred width);
            # silent broadcasting would train a wrong model
            raise ValueError(
                f"l2_loss: label width {label.shape[1]} != prediction "
                f"width {x.shape[1]} (set label_width / label_vec)")
        diff = x - label
        return 0.5 * jnp.sum(diff * diff, axis=-1)


@register_layer
class MultiLogisticLayer(LossLayer):
    """multi_logistic (loss/multi_logistic_layer-inl.hpp): sigmoid forward,
    grad = sigmoid(x) - label."""

    type_name = "multi_logistic"

    def forward_transform(self, x: jax.Array) -> jax.Array:
        return jax.nn.sigmoid(x)

    def per_example_loss(self, x: jax.Array, label: jax.Array) -> jax.Array:
        if label.shape[1] != x.shape[1]:
            # reference assert (multi_logistic: one target per output)
            raise ValueError(
                f"multi_logistic: label width {label.shape[1]} != "
                f"prediction width {x.shape[1]} (set label_width / "
                "label_vec)")
        # sum_j [softplus(x) - y*x]  (stable BCE-with-logits)
        return jnp.sum(jax.nn.softplus(x) - label * x, axis=-1)
