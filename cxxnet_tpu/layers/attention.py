"""Sequence layers: attention, layernorm, pos_embed.

Pure TPU-native extension surface - the reference has no sequence models
(SURVEY.md: cxxnet predates attention; CNN/MLP only), but this framework
treats long-context as first-class, so the config language gains a
minimal transformer vocabulary over "sequence nodes" of shape
(batch, 1, seq, embed) - the NCHW matrix convention (layer.h:33-54)
extended with a real y dim as sequence.

attention  multi-head self-attention. Params: qkv projection `wmat`
           (3*embed, embed) and output projection `wproj` (embed, embed),
           optional `bias` (3*embed,). Tensor parallelism shards wmat
           rows / wproj columns over 'model' (Megatron-style); sequence
           parallelism routes the core through ring or Ulysses attention
           (parallel/ring.py) whenever the active mesh has a 'seq' axis -
           `seq_parallel = ring|ulysses|none` overrides the default
           (ring). `causal = 1` masks the future; `nhead` sets heads.
layernorm  per-position normalization over the embed dim with learnable
           slope/bias - the sequence-model norm (batch_norm's per-batch
           statistics break under variable batch composition).
pos_embed  learned additive positional embedding (seq, embed).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from cxxnet_tpu.layers.base import Layer, Params, Shape, register_layer
from cxxnet_tpu.ops import attention as ops_attn


def layer_norm(x, slope, bias, eps):
    """Normalize the last dim in f32; shared by the layernorm layer and
    transformer_stack's in-block norms."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * slope + bias).astype(x.dtype)


def qkv_heads(xs, wqkv, bqkv, nhead):
    """(b, s, e) x (3e, e) [+ (3e,)] -> q, k, v as (b, h, s, e/h).

    Weights are cast to the ACTIVATION dtype (the trainer pre-casts
    params to the compute dtype anyway - trainer._cast - so in-product
    this is a no-op; direct mixed-dtype callers get the bf16 MXU path
    rather than a silent f32 promotion, same convention as moe /
    transformer_stack)."""
    b, s, e = xs.shape
    qkv = jnp.einsum("bse,fe->bsf", xs, wqkv.astype(xs.dtype))
    if bqkv is not None:
        qkv = qkv + bqkv.astype(xs.dtype)[None, None, :]
    qkv = qkv.reshape(b, s, 3, nhead, e // nhead)
    return tuple(jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))


def heads_proj(o, wproj):
    """(b, h, s, d) heads -> (b, s, e) through the output projection."""
    b, h, s, d = o.shape
    o = jnp.moveaxis(o, 1, 2).reshape(b, s, h * d)
    return jnp.einsum("bsf,ef->bse", o, wproj.astype(o.dtype))


@register_layer
class AttentionLayer(Layer):
    """Multi-head self-attention on (b, 1, s, e) sequence nodes."""

    type_name = "attention"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.nhead = 1
        self.causal = 0
        self.seq_parallel = "ring"
        self.kv_block = 512

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "nhead":
            self.nhead = int(val)
        if name == "causal":
            self.causal = int(val)
        if name == "seq_parallel":
            from cxxnet_tpu.parallel.ring import SEQ_SCHEMES
            if val not in SEQ_SCHEMES:
                raise ValueError(
                    "seq_parallel must be ring, ulysses or none")
            self.seq_parallel = val
        if name == "kv_block":
            self.kv_block = int(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, s, e = in_shapes[0]
        if c != 1:
            raise ValueError(
                "AttentionLayer: input must be a sequence node "
                f"(b,1,seq,embed); got channel={c}")
        if e % self.nhead != 0:
            raise ValueError(
                f"AttentionLayer: embed {e} not divisible by "
                f"nhead {self.nhead}")
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        e = in_shapes[0][3]
        k1, k2 = jax.random.split(key)
        wmat = self.param.rand_init_weight(k1, (3 * e, e),
                                           in_num=e, out_num=3 * e)
        wproj = self.param.rand_init_weight(k2, (e, e),
                                            in_num=e, out_num=e)
        params = {"wmat": wmat, "wproj": wproj}
        if self.param.no_bias == 0:
            params["bias"] = jnp.full((3 * e,), self.param.init_bias,
                                      dtype=jnp.float32)
        return params

    def param_tags(self) -> Dict[str, str]:
        return {"wmat": "wmat", "wproj": "wmat", "bias": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        # qkv rows are per-head blocks (column parallel); the output
        # projection contracts the head dim, so its COLUMNS shard
        # (row parallel) and XLA closes with one all-reduce
        return {"wmat": 0, "bias": 0, "wproj": 1}

    def _core(self, q, k, v):
        """Route the attention core by the active mesh (same pattern as
        the Pallas LRN route, ops/nn.py): ring/ulysses under a 'seq'
        axis; otherwise the fused Pallas flash kernel on TPU, blockwise
        XLA elsewhere."""
        from cxxnet_tpu.ops import pallas_attention as PA
        from cxxnet_tpu.parallel.mesh import get_active_mesh
        from cxxnet_tpu.parallel.ring import seq_parallel_attention
        mesh = get_active_mesh()
        causal = bool(self.causal)
        sp = seq_parallel_attention(q, k, v, mesh, self.seq_parallel,
                                    causal=causal,
                                    kv_block=self.kv_block)
        if sp is not None:
            return sp
        if mesh is not None and mesh.devices.size > 1 \
                and PA.use_flash_sharded(q, mesh):
            return PA.flash_attention_sharded(q, k, v, mesh, causal)
        if PA.use_flash(q):
            return PA.flash_attention(q, k, v, causal, None,
                                      PA._FORCE_INTERPRET)
        return ops_attn.blockwise_attention(q, k, v, causal=causal,
                                            kv_block=self.kv_block)

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        b, _, s, e = x.shape
        q, k, v = qkv_heads(x.reshape(b, s, e), params["wmat"],
                            params.get("bias"), self.nhead)
        out = heads_proj(self._core(q, k, v), params["wproj"])
        return [out.reshape(b, 1, s, e)]


@register_layer
class AttentionNaiveLayer(AttentionLayer):
    """attention_naive: the attention layer with the full-matrix naive
    core - the trusted slave for the pairtest harness
    (`pairtest-attention-attention_naive`), mirroring how conv_im2col
    backs the MXU conv (layers/pairtest.py)."""

    type_name = "attention_naive"

    def _core(self, q, k, v):
        return ops_attn.naive_attention(q, k, v,
                                        causal=bool(self.causal))


@register_layer
class SeqFullcLayer(Layer):
    """seq_fullc: position-wise fully-connected on (b, 1, s, e) sequence
    nodes -> (b, 1, s, nhidden); the transformer FFN building block.
    Kept separate from fullc so the reference layer's matrix-node
    requirement (fullc_layer-inl.hpp) still errors on misshaped nets."""

    type_name = "seq_fullc"

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, s, e = in_shapes[0]
        if c != 1:
            raise ValueError("seq_fullc: input must be a sequence node")
        if self.param.num_hidden <= 0:
            raise ValueError("seq_fullc: must set nhidden correctly")
        self.param.num_input_node = e
        return [(b, 1, s, self.param.num_hidden)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        e = in_shapes[0][3]
        nh = self.param.num_hidden
        params = {"wmat": self.param.rand_init_weight(
            key, (nh, e), in_num=e, out_num=nh)}
        if self.param.no_bias == 0:
            params["bias"] = jnp.full((nh,), self.param.init_bias,
                                      dtype=jnp.float32)
        return params

    def param_tags(self) -> Dict[str, str]:
        return {"wmat": "wmat", "bias": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        return {"wmat": 0, "bias": 0}

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        b, _, s, e = x.shape
        out = jnp.einsum("bse,fe->bsf", x.reshape(b, s, e),
                         params["wmat"])
        if "bias" in params:
            out = out + params["bias"][None, None, :]
        return [out.reshape(b, 1, s, -1)]


@register_layer
class LayerNormLayer(Layer):
    """Per-position layer normalization over the last (embed) dim."""

    type_name = "layernorm"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.eps = 1e-5
        self.init_slope = 1.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "eps":
            self.eps = float(val)
        if name == "init_slope":
            self.init_slope = float(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        e = in_shapes[0][3]
        return {"slope": jnp.full((e,), self.init_slope, jnp.float32),
                "bias": jnp.full((e,), self.param.init_bias, jnp.float32)}

    def param_tags(self) -> Dict[str, str]:
        # same visitor tags as batch_norm: slope under wmat, bias under
        # bias (bn_layer-inl.hpp ApplyVisitor convention)
        return {"slope": "wmat", "bias": "bias"}

    def apply(self, params, inputs, *, train, rng=None):
        return [layer_norm(inputs[0], params["slope"], params["bias"],
                           self.eps)]


@register_layer
class PosEmbedLayer(Layer):
    """Learned additive positional embedding on (b, 1, s, e) nodes."""

    type_name = "pos_embed"

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        _, _, s, e = in_shapes[0]
        wmat = self.param.rand_init_weight(key, (s, e), in_num=e,
                                           out_num=e)
        return {"wmat": wmat}

    def param_tags(self) -> Dict[str, str]:
        return {"wmat": "wmat"}

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        return [x + params["wmat"][None, None, :, :].astype(x.dtype)]
