"""transformer_stack: L identical transformer blocks + pipeline
parallelism over a 'pipe' mesh axis.

Pure TPU-native extension (the reference predates sequence models).
A stack of L pre-norm blocks

    x = x + Wproj . attn(layernorm(x))
    x = x + FFN(layernorm(x))            FFN = W2 . relu(W1 . _)

with every block's params stacked on a leading L dim, which buys two
things the per-layer config DAG cannot express:

- without a 'pipe' mesh axis: ONE lax.scan over the L stacked blocks -
  a single compiled block body instead of L inlined copies (compile
  time O(1) in depth; jax.checkpoint-friendly).
- with `mesh = ...,pipe:P` (L % P == 0): GPipe pipeline parallelism as
  one shard_map program. Device p holds only its L/P stage params
  (pipe_shard_dims -> HBM scales 1/P); the per-data-shard batch splits
  into M microbatches (config `microbatch`; an explicit value that
  does not divide the per-shard batch is an error, and the default
  picks the largest divisor <= P) that flow
  through the stages via lax.ppermute, M + P - 1 schedule ticks with
  the standard GPipe bubble (P-1)/(M+P-1). Autodiff through the
  schedule IS the reverse pipeline (ppermute transposes to the
  opposite rotation), so the same code trains.

The attention core inside the stack: ring attention when the mesh
has an eligible 'seq' axis and no pipeline route (scan-over-layers +
sequence parallelism compose), otherwise the XLA blockwise kernel
(ops/attention.py) - per-device and shard_map-safe inside the
pipelined schedule.

Config keys: nlayer, nhead, nhidden (FFN hidden), causal, microbatch,
kv_block, eps, seq_parallel (ring | ulysses | none - the non-pipelined
route's attention-core scheme, shared with the attention layer).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from cxxnet_tpu.layers.attention import (
    heads_proj, layer_norm, qkv_heads)
from cxxnet_tpu.layers.base import Layer, Params, Shape, register_layer
from cxxnet_tpu.ops.attention import blockwise_attention

PIPE_AXIS = "pipe"


@register_layer
class TransformerStackLayer(Layer):
    """L stacked pre-norm transformer blocks on (b, 1, s, e) nodes."""

    type_name = "transformer_stack"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.nlayer = 1
        self.nhead = 1
        self.causal = 0
        self.microbatch = 0     # 0 = pipe-axis size
        self.kv_block = 512
        self.eps = 1e-5
        self.seq_parallel = "ring"

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "nlayer":
            self.nlayer = int(val)
        if name == "nhead":
            self.nhead = int(val)
        if name == "causal":
            self.causal = int(val)
        if name == "microbatch":
            self.microbatch = int(val)
        if name == "kv_block":
            self.kv_block = int(val)
        if name == "eps":
            self.eps = float(val)
        if name == "seq_parallel":
            from cxxnet_tpu.parallel.ring import SEQ_SCHEMES
            if val not in SEQ_SCHEMES:
                raise ValueError(
                    "seq_parallel must be ring, ulysses or none")
            self.seq_parallel = val

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, s, e = in_shapes[0]
        if c != 1:
            raise ValueError(
                "transformer_stack: input must be a sequence node")
        if self.nlayer < 1:
            raise ValueError("transformer_stack: must set nlayer >= 1")
        if self.param.num_hidden <= 0:
            raise ValueError(
                "transformer_stack: must set nhidden correctly")
        if e % self.nhead != 0:
            raise ValueError(
                f"transformer_stack: embed {e} not divisible by "
                f"nhead {self.nhead}")
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        e = in_shapes[0][3]
        h, L = self.param.num_hidden, self.nlayer
        ks = jax.random.split(key, 4)
        rw = self.param.rand_init_weight
        return {
            "ln1_s": jnp.ones((L, e), jnp.float32),
            "ln1_b": jnp.zeros((L, e), jnp.float32),
            "wqkv": rw(ks[0], (L, 3 * e, e), in_num=e, out_num=3 * e),
            "bqkv": jnp.zeros((L, 3 * e), jnp.float32),
            "wproj": rw(ks[1], (L, e, e), in_num=e, out_num=e),
            "ln2_s": jnp.ones((L, e), jnp.float32),
            "ln2_b": jnp.zeros((L, e), jnp.float32),
            "w1": rw(ks[2], (L, h, e), in_num=e, out_num=h),
            "b1": jnp.zeros((L, h), jnp.float32),
            "w2": rw(ks[3], (L, e, h), in_num=h, out_num=e),
            "b2": jnp.zeros((L, e), jnp.float32),
        }

    def param_tags(self) -> Dict[str, str]:
        return {"wqkv": "wmat", "wproj": "wmat", "w1": "wmat",
                "w2": "wmat", "ln1_s": "wmat", "ln2_s": "wmat",
                "bqkv": "bias", "b1": "bias", "b2": "bias",
                "ln1_b": "bias", "ln2_b": "bias"}

    def pipe_shard_dims(self) -> Dict[str, int]:
        # every stacked param's leading (layer) dim rides 'pipe'
        return {pn: 0 for pn in ("ln1_s", "ln1_b", "wqkv", "bqkv",
                                 "wproj", "ln2_s", "ln2_b", "w1", "b1",
                                 "w2", "b2")}

    # ------------------------------------------------------------------
    def _block(self, bp, x, seq_mesh=None):
        """One block; bp leaves have NO leading layer dim; x (b, s, e).
        Norm + QKV plumbing shared with the single-layer family
        (layers/attention.py helpers). With `seq_mesh`, the attention
        core runs the configured sequence-parallel scheme over its
        'seq' axis (parallel/ring.py) instead of letting GSPMD
        all-gather the seq-sharded K/V."""
        from cxxnet_tpu.parallel.ring import seq_parallel_attention
        h = layer_norm(x, bp["ln1_s"], bp["ln1_b"], self.eps)
        q, k, v = qkv_heads(h, bp["wqkv"], bp["bqkv"], self.nhead)
        o = None
        if seq_mesh is not None:
            o = seq_parallel_attention(q, k, v, seq_mesh,
                                       self.seq_parallel,
                                       causal=bool(self.causal),
                                       kv_block=self.kv_block)
        if o is None:
            o = blockwise_attention(q, k, v, causal=bool(self.causal),
                                    kv_block=self.kv_block)
        x = x + heads_proj(o, bp["wproj"])
        h2 = layer_norm(x, bp["ln2_s"], bp["ln2_b"], self.eps)
        f = jnp.einsum("bse,he->bsh", h2, bp["w1"].astype(x.dtype))
        f = jnp.maximum(f + bp["b1"].astype(x.dtype)[None, None], 0.0)
        f = jnp.einsum("bsh,eh->bse", f, bp["w2"].astype(x.dtype))
        return x + f + bp["b2"].astype(x.dtype)[None, None]

    def _scan_blocks(self, params, x, seq_mesh=None):
        """Sequential route: scan over the stacked layer dim."""
        def step(c, bp):
            return self._block(bp, c, seq_mesh), None
        out, _ = lax.scan(step, x, params)
        return out

    # ------------------------------------------------------------------
    def _pipe_route(self, mesh) -> int:
        """Pipeline-parallel eligibility: returns P (the pipe-axis size)
        or 0 for the sequential route."""
        if mesh is None:
            return 0
        P = mesh.shape.get(PIPE_AXIS, 1)
        if P <= 1 or self.nlayer % P != 0:
            return 0
        return P

    def _pipelined(self, params, x, mesh, P):
        """GPipe schedule as one shard_map program; x (b, s, e) global."""
        names = mesh.axis_names
        data = "data" if "data" in names else None
        dsize = mesh.shape.get("data", 1) if data else 1
        b = x.shape[0]
        b_local = b // dsize
        if self.microbatch:
            # an explicit microbatch that cannot divide the per-shard
            # batch must fail loudly, not silently de-pipeline
            if b % dsize != 0 or b_local % self.microbatch != 0:
                raise ValueError(
                    f"transformer_stack: microbatch={self.microbatch} "
                    f"does not divide the per-data-shard batch "
                    f"{b_local} (batch {b} over data:{dsize})")
            M = self.microbatch
        else:
            if b % dsize != 0 or b_local == 0:
                # degenerate direct-layer use (the trainer's mesh
                # builder enforces batch divisibility): sequential route
                return self._scan_blocks(params, x)
            # default: as close to P microbatches as divides the
            # per-shard batch (M=1 still pipelines - full bubble, but
            # stage params stay sharded 1/P)
            M = next(m for m in range(min(P, b_local), 0, -1)
                     if b_local % m == 0)
        xspec = jax.sharding.PartitionSpec(data, None, None)
        pspec = jax.tree.map(
            lambda _: jax.sharding.PartitionSpec(PIPE_AXIS), params)
        vary = tuple(a for a in (data, PIPE_AXIS) if a)

        def local_fn(bp, xl):
            # bp leaves: (L/P, ...) local stage params; xl (b_l, s, e)
            stage = lax.axis_index(PIPE_AXIS)
            bl, s, e = xl.shape
            mb = bl // M
            xs = xl.reshape(M, mb, s, e)
            perm = [(i, (i + 1) % P) for i in range(P)]

            def stage_apply(c):
                out, _ = lax.scan(
                    lambda cc, p: (self._block(p, cc), None), c, bp)
                return out

            def tick(carry, t):
                recv, ys = carry
                inject = lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                cur = jnp.where(stage == 0, inject, recv)
                y = stage_apply(cur)
                recv_n = lax.ppermute(y, PIPE_AXIS, perm)
                oidx = t - (P - 1)
                take = jnp.logical_and(stage == P - 1, oidx >= 0)
                upd = jnp.where(take, y, 0.0)
                ys = lax.dynamic_update_index_in_dim(
                    ys, lax.dynamic_index_in_dim(
                        ys, jnp.clip(oidx, 0, M - 1), 0,
                        keepdims=False) + upd,
                    jnp.clip(oidx, 0, M - 1), 0)
                return (recv_n, ys), None

            init = (jnp.zeros((mb, s, e), xl.dtype),
                    jnp.zeros((M, mb, s, e), xl.dtype))
            if hasattr(lax, "pcast"):
                init = jax.tree.map(
                    lambda a: lax.pcast(a, vary, to="varying"), init)
            elif hasattr(lax, "pvary"):  # pre-pcast jax tier
                init = jax.tree.map(lambda a: lax.pvary(a, vary), init)
            (_, ys), _ = lax.scan(tick, init, jnp.arange(M + P - 1))
            # only the last stage wrote ys; broadcast it around the ring
            ys = lax.psum(ys, PIPE_AXIS)
            return ys.reshape(bl, s, e)

        return jax.shard_map(
            local_fn, mesh=mesh, in_specs=(pspec, xspec),
            out_specs=xspec)(params, x)

    def apply(self, params, inputs, *, train, rng=None):
        from cxxnet_tpu.parallel.mesh import get_active_mesh
        x = inputs[0]
        b, _, s, e = x.shape
        xs = x.reshape(b, s, e)
        mesh = get_active_mesh()
        P = self._pipe_route(mesh)
        if P:
            # pipelined: the stages themselves are the sharded dim; the
            # attention core stays per-device blockwise (a nested 'seq'
            # shard_map inside the pipe schedule is out of scope)
            out = self._pipelined(params, xs, mesh, P)
        else:
            out = self._scan_blocks(params, xs, mesh)
        return [out.reshape(b, 1, s, e)]
