"""All non-loss layer implementations.

Each class documents the reference file it mirrors behaviorally. Backward
passes are autodiff; where the reference computes activation grads from the
*output* values (op.h sigmoid_grad etc.) the analytic result is identical.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cxxnet_tpu import ops
from cxxnet_tpu.layers.base import (
    Layer, Params, Shape, is_mat, register_layer)


# ---------------------------------------------------------------------------
# fully connected
# ---------------------------------------------------------------------------

def _fullc_gather_matmul(x, w, mesh):
    """`x @ w.T` whose WGRAD rides activation gathering instead of a
    gradient AllReduce - the TPU-native `fullc_gather = 1` (the
    reference pushes the b x (nin+nout) activations to the parameter
    server and recomputes dw after the gather instead of pushing the
    nin x nout dense gradient - async_updater-inl.hpp:67-92,190-199,
    fullc_layer-inl.hpp:120-122).

    Here the same byte trade maps onto the data mesh axis: the normal
    SPMD wgrad psum moves ~2*nin*nout gradient bytes per step; this
    path all-gathers x and the output grad over 'data'
    (b*(nin+nout) bytes) and computes the FULL dw on every device -
    replicated by construction, so GSPMD inserts no psum for it. The
    win condition is the reference's: batch*(nin+nout) < nin*nout
    (big FC layers, e.g. AlexNet fc6: 3.4M vs 37.7M gathered f32
    elements at b256). Compute cost: the wgrad matmul runs on the
    full batch on every device (n_data x duplicated FLOPs) - the
    same recompute trade the reference's worker makes."""
    from jax.sharding import PartitionSpec as P

    @jax.custom_vjp
    def mm(x, w):
        return x @ w.T

    def fwd(x, w):
        return x @ w.T, (x, w)

    def bwd(res, g):
        x, w = res

        def dw_fn(gl, xl):
            gg = jax.lax.all_gather(gl, "data", axis=0, tiled=True)
            xg = jax.lax.all_gather(xl, "data", axis=0, tiled=True)
            return gg.T @ xg

        dw = jax.shard_map(
            dw_fn, mesh=mesh,
            in_specs=(P("data", None), P("data", None)),
            out_specs=P(None, None),
            # outputs are bitwise identical on every device after the
            # gathers; nothing for the varying-axes checker to verify
            check_vma=False)(g, x)
        return g @ w, dw

    mm.defvjp(fwd, bwd)
    return mm(x, w)


@register_layer
class FullConnectLayer(Layer):
    """fullc (src/layer/fullc_layer-inl.hpp:14-146).

    out = in . W^T + bias; W shape (nhidden, num_input_node).
    """

    type_name = "fullc"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.fullc_gather = 0
        self.fused_act = ""
        self.flatten_input = 0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "fullc_gather":
            self.fullc_gather = int(val)
        if name == "fused_act":
            # activation stamped by the fuse_activation graph pass
            # (nnet/passes.py): applied inline after the bias add so
            # the fused node replaces the separate activation layer
            if val not in ("", "relu"):
                raise ValueError(
                    f"fused_act must be '' or relu, got {val!r}")
            self.fused_act = val
        if name == "flatten_input":
            # stamped by the elim_reshape graph pass (nnet/passes.py):
            # accept a 4-D input node and consume it flattened - the
            # apply reshapes to (b, -1) anyway, so the eliminated
            # flatten layer's semantics move in here bitwise
            self.flatten_input = int(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        (b, c, h, w) = in_shapes[0]
        if not is_mat(in_shapes[0]) and not self.flatten_input:
            raise ValueError("FullcLayer: input needs to be a matrix")
        if self.param.num_hidden <= 0:
            raise ValueError("FullcLayer: must set nhidden correctly")
        self.param.num_input_node = c * h * w
        return [(b, 1, 1, self.param.num_hidden)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        nin = in_shapes[0][1] * in_shapes[0][2] * in_shapes[0][3]
        nhidden = self.param.num_hidden
        wmat = self.param.rand_init_weight(
            key, (nhidden, nin), in_num=nin, out_num=nhidden)
        params = {"wmat": wmat}
        if self.param.no_bias == 0:
            params["bias"] = jnp.full((nhidden,), self.param.init_bias,
                                      dtype=jnp.float32)
        return params

    def param_tags(self) -> Dict[str, str]:
        return {"wmat": "wmat", "bias": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        # Megatron-style column parallelism: split the output features
        return {"wmat": 0, "bias": 0}

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        b = x.shape[0]
        m = x.reshape(b, -1)
        if "wmat_q" in params:
            # int8 PTQ path (nnet/passes.py quantize_int8): the
            # quantize stage of make_param_fn delivered int8 weights
            # + frozen scales instead of wmat; contraction runs
            # int8 x int8 -> int32 (ops/int8.py picks the Pallas MXU
            # kernel or the lax fallback), dequant + bias + fused
            # activation in f32, output back at the input dtype
            from cxxnet_tpu.ops import int8 as int8_ops
            acc = int8_ops.int8_matmul(
                int8_ops.quantize_act(m, params["ascale"]),
                params["wmat_q"])
            out = int8_ops.dequantize(acc, params["ascale"],
                                      params["wscale"])
            if "bias" in params:
                out = out + params["bias"].astype(jnp.float32)[None, :]
            if self.fused_act == "relu":
                out = ops.relu(out)
            return [out.astype(m.dtype).reshape(b, 1, 1, -1)]
        from cxxnet_tpu.parallel.mesh import batch_shardable, \
            get_active_mesh
        mesh = get_active_mesh()
        if (self.fullc_gather and batch_shardable(mesh, b)
                and mesh.shape.get("model", 1) == 1):
            # gather-mode wgrad needs a replicated weight (pure data
            # parallelism, the reference's only mode); under TP the
            # weight is column-sharded and the normal SPMD path applies
            out = _fullc_gather_matmul(m, params["wmat"], mesh)
        else:
            out = m @ params["wmat"].T
        if "bias" in params:
            out = out + params["bias"][None, :]
        if self.fused_act == "relu":
            out = ops.relu(out)
        return [out.reshape(b, 1, 1, -1)]


@register_layer
class FixConnectLayer(Layer):
    """fixconn (src/layer/fixconn_layer-inl.hpp:14-100): fully-connected
    with frozen weights loaded from a sparse text file; no gradients."""

    type_name = "fixconn"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.fname_weight = "NULL"
        self._wmat: Optional[np.ndarray] = None

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "fixconn_weight":
            self.fname_weight = val

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        if not is_mat(in_shapes[0]):
            raise ValueError("FixConnLayer: input needs to be a matrix")
        if self.param.num_hidden <= 0:
            raise ValueError("FixConnLayer: must set nhidden correctly")
        if self.fname_weight == "NULL":
            raise ValueError("FixConnLayer: must specify fixconn_weight")
        nin = in_shapes[0][3]
        w = np.zeros((self.param.num_hidden, nin), dtype=np.float32)
        with open(self.fname_weight, "r", encoding="utf-8") as f:
            toks = f.read().split()
        nrow, ncol, nonzero = int(toks[0]), int(toks[1]), int(toks[2])
        if (nrow, ncol) != w.shape:
            raise ValueError(
                "FixConnLayer: fixconn_weight shape does not match "
                "architecture")
        vals = toks[3:3 + 3 * nonzero]
        for i in range(nonzero):
            x, y, v = int(vals[3 * i]), int(vals[3 * i + 1]), float(
                vals[3 * i + 2])
            w[x, y] = v
        self._wmat = w
        return [(in_shapes[0][0], 1, 1, self.param.num_hidden)]

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        b = x.shape[0]
        m = x.reshape(b, -1)
        # frozen constant weight: stop_gradient keeps it out of the grads
        w = jax.lax.stop_gradient(jnp.asarray(self._wmat))
        out = m @ w.T
        return [out.reshape(b, 1, 1, -1)]


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

@register_layer
class ConvolutionLayer(Layer):
    """conv (src/layer/convolution_layer-inl.hpp:13-228).

    Weight stored natively as OIHW (nchannel, in_ch/ngroup, ky, kx); the
    reference's (ngroup, out/g, in/g*ky*kx) 3-D layout is the same memory
    order, used only at checkpoint conversion. Grouped conv maps to
    feature_group_count (no im2col on TPU).

    `space_to_depth = auto|0|1` (default auto): rewrite a strided
    few-channel conv (the input layer) as a stride-1 conv over
    in_ch*s*s channels - value-identical, MXU-dense in both forward
    and wgrad (ops/conv.py module docstring).
    """

    type_name = "conv"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.s2d = None  # None = auto heuristic in ops.conv2d
        self.fused_act = ""

    def set_param(self, name: str, val: str) -> None:
        if name == "space_to_depth":
            if val not in ("auto", "0", "1"):
                raise ValueError(
                    f"space_to_depth must be auto, 0 or 1, got {val!r}")
            self.s2d = None if val == "auto" else val == "1"
            return
        if name == "fused_act":
            # stamped by the fuse_activation graph pass (nnet/passes.py)
            if val not in ("", "relu"):
                raise ValueError(
                    f"fused_act must be '' or relu, got {val!r}")
            self.fused_act = val
            return
        super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, h, w = in_shapes[0]
        p = self.param
        if c % p.num_group != 0:
            raise ValueError("input channels must divide group size")
        if p.num_channel % p.num_group != 0:
            raise ValueError("output channels must divide group size")
        if p.num_channel <= 0:
            raise ValueError("must set nchannel correctly")
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError("must set kernel_size correctly")
        if p.kernel_width > w or p.kernel_height > h:
            raise ValueError("kernel size exceeds input")
        p.num_input_channel = c
        oh = ops.conv_out_dim(h, p.kernel_height, p.stride, p.pad_y)
        ow = ops.conv_out_dim(w, p.kernel_width, p.stride, p.pad_x)
        return [(b, p.num_channel, oh, ow)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        p = self.param
        c = in_shapes[0][1]
        ipg = c // p.num_group
        shape = (p.num_channel, ipg, p.kernel_height, p.kernel_width)
        # reference init args: in = in/g*ky*kx, out = out/g (InitModel:27-32)
        wmat = p.rand_init_weight(
            key, shape,
            in_num=ipg * p.kernel_height * p.kernel_width,
            out_num=p.num_channel // p.num_group)
        params = {"wmat": wmat}
        if p.no_bias == 0:
            params["bias"] = jnp.full((p.num_channel,), p.init_bias,
                                      dtype=jnp.float32)
        return params

    def param_tags(self) -> Dict[str, str]:
        return {"wmat": "wmat", "bias": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        # split output channels over 'model'; shardings_for checks only
        # O % axis_size, so shards may straddle group boundaries (legal
        # HLO - GSPMD partitions the grouped conv accordingly)
        return {"wmat": 0, "bias": 0}

    def apply(self, params, inputs, *, train, rng=None):
        p = self.param
        if "wmat_q" in params:
            # int8 PTQ path (nnet/passes.py quantize_int8): int8
            # convolution with int32 accumulation, frozen scales,
            # f32 dequant + bias + fused activation. The s2d rewrite
            # does not apply here (ops/int8.py docstring).
            from cxxnet_tpu.ops import int8 as int8_ops
            x = inputs[0]
            acc = int8_ops.int8_conv2d(
                int8_ops.quantize_act(x, params["ascale"]),
                params["wmat_q"], p.stride, p.pad_y, p.pad_x,
                p.num_group)
            out = int8_ops.dequantize(acc, params["ascale"],
                                      params["wscale"])
            if "bias" in params:
                out = out + params["bias"].astype(
                    jnp.float32)[None, :, None, None]
            if self.fused_act == "relu":
                out = ops.relu(out)
            return [out.astype(x.dtype)]
        out = ops.conv2d(inputs[0], params["wmat"], p.stride, p.pad_y,
                         p.pad_x, p.num_group, s2d=self.s2d)
        if "bias" in params:
            out = out + params["bias"][None, :, None, None]
        if self.fused_act == "relu":
            out = ops.relu(out)
        return [out]


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

class PoolingLayer(Layer):
    """max/sum/avg pooling (src/layer/pooling_layer-inl.hpp:17-114).

    `pool_grad = winner` opts max pooling into XLA's native
    single-winner backward instead of the reference's tie-duplicating
    unpool rule - a documented semantics change on tied windows
    (ops/pooling.py pool2d docstring)."""

    mode = "max"
    pre_relu = False

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.grad_mode = "ties"

    def _winner_ok(self) -> bool:
        """winner mode only exists for the max backward; accepting it
        elsewhere would silently run the tie rule anyway."""
        return self.mode == "max"

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "pool_grad":
            if val not in ("ties", "winner"):
                raise ValueError(
                    f"pool_grad must be 'ties' or 'winner', got {val!r}")
            if val == "winner" and not self._winner_ok():
                raise ValueError(
                    f"pool_grad=winner is a max-pool backward option; "
                    f"'{self.type_name}' has no single-winner rule")
            self.grad_mode = val

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, h, w = in_shapes[0]
        p = self.param
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError("must set kernel_size correctly")
        if p.pad_x >= p.kernel_width or p.pad_y >= p.kernel_height:
            raise ValueError(
                "pooling pad must be smaller than the kernel (all-padding "
                "windows would emit -inf/0)")
        if (p.kernel_width > w + 2 * p.pad_x
                or p.kernel_height > h + 2 * p.pad_y):
            raise ValueError("kernel size exceeds input")
        oh = ops.pool_out_dim(h, p.kernel_height, p.stride, p.pad_y)
        ow = ops.pool_out_dim(w, p.kernel_width, p.stride, p.pad_x)
        return [(b, c, oh, ow)]

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        if self.pre_relu:
            x = ops.relu(x)
        p = self.param
        return [ops.pool2d(x, self.mode, p.kernel_height, p.kernel_width,
                           p.stride, p.pad_y, p.pad_x,
                           grad_mode=self.grad_mode)]


@register_layer
class MaxPoolingLayer(PoolingLayer):
    type_name = "max_pooling"
    mode = "max"


@register_layer
class SumPoolingLayer(PoolingLayer):
    type_name = "sum_pooling"
    mode = "sum"


@register_layer
class AvgPoolingLayer(PoolingLayer):
    type_name = "avg_pooling"
    mode = "avg"


@register_layer
class ReluMaxPoolingLayer(PoolingLayer):
    """relu fused before max pooling (layer_impl-inl.hpp:55-56)."""
    type_name = "relu_max_pooling"
    mode = "max"
    pre_relu = True


@register_layer
class InsanityPoolingLayer(PoolingLayer):
    """insanity_max_pooling (src/layer/insanity_pooling_layer-inl.hpp):
    stochastic displaced max pooling at train, plain max pooling at eval.
    Param `keep` = probability a source pixel is read in place."""

    type_name = "insanity_max_pooling"
    mode = "max"

    def _winner_ok(self) -> bool:
        # the displaced-read backward is defined by the tie-duplicating
        # slot rule (ops/pooling.py insanity_pool2d); there is no
        # single-winner variant to opt into
        return False

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.p_keep = 1.0

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        if self.param.pad_x or self.param.pad_y:
            raise ValueError(
                "insanity_max_pooling does not support pad (the jitter "
                "clamps at the true image border)")
        return super().infer_shapes(in_shapes)

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "keep":
            self.p_keep = float(val)

    def apply(self, params, inputs, *, train, rng=None):
        p = self.param
        if train:
            return [ops.insanity_pool2d(inputs[0], rng, p.kernel_height,
                                        p.kernel_width, p.stride,
                                        self.p_keep)]
        return [ops.pool2d(inputs[0], "max", p.kernel_height, p.kernel_width,
                           p.stride)]


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

class ActivationLayer(Layer):
    """relu/sigmoid/tanh/softplus (activation_layer-inl.hpp:12-41)."""

    fn = staticmethod(ops.relu)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def apply(self, params, inputs, *, train, rng=None):
        return [self.fn(inputs[0])]


@register_layer
class ReluLayer(ActivationLayer):
    type_name = "relu"
    fn = staticmethod(ops.relu)


@register_layer
class SigmoidLayer(ActivationLayer):
    type_name = "sigmoid"
    fn = staticmethod(ops.sigmoid)


@register_layer
class TanhLayer(ActivationLayer):
    type_name = "tanh"
    fn = staticmethod(ops.tanh)


@register_layer
class SoftplusLayer(ActivationLayer):
    type_name = "softplus"
    fn = staticmethod(ops.softplus)


@register_layer
class GeluLayer(ActivationLayer):
    """gelu (tanh approximation): no reference analog - extension for
    the transformer family (layers/attention.py), where relu's dead
    zones cost accuracy in FFNs."""
    type_name = "gelu"
    fn = staticmethod(ops.gelu)


@register_layer
class XeluLayer(ActivationLayer):
    """xelu: x > 0 ? x : x / b, b default 5.0 (xelu_layer-inl.hpp:15-53)."""

    type_name = "xelu"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.b = 5.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "b":
            self.b = float(val)

    def apply(self, params, inputs, *, train, rng=None):
        return [ops.xelu(inputs[0], self.b)]


@register_layer
class InsanityLayer(ActivationLayer):
    """insanity / RReLU (insanity_layer-inl.hpp:14-102).

    Train: xelu with per-element random divisor uniform in [lb, ub];
    eval: fixed divisor (lb+ub)/2. The [lb, ub] range anneals toward
    its midpoint, advancing once per training forward exactly like the
    reference (insanity_layer-inl.hpp:52-63): the traced update counter
    (base.get_active_step, bound by the trainer inside the jitted step)
    drives a closed form of the reference's recurrence, including its
    freeze quirk - the reference's internal counter only increments
    INSIDE the (calm_start, calm_end) window, so with calm_start >= 0
    it never leaves 0 and no annealing ever happens. The midpoint is
    anneal-invariant, so eval needs no step. One deliberate deviation
    remains: reference EVAL forwards also advance the counter (clearly
    unintended); here only training steps count (docs/layer.md).
    """

    type_name = "insanity"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.lb = 5.0
        self.ub = 10.0
        self.saturation_start = 0
        self.saturation_end = 0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "lb":
            self.lb = float(val)
        if name == "ub":
            self.ub = float(val)
        if name == "calm_start":
            self.saturation_start = int(val)
        if name == "calm_end":
            self.saturation_end = int(val)

    def _range(self):
        """(lb, ub) at the current training step (traced when inside
        the jitted step; the static initial range otherwise)."""
        from cxxnet_tpu.layers.base import get_active_step
        step = get_active_step()
        s0, e = self.saturation_start, self.saturation_end
        span = e - s0
        if step is None or s0 >= 0 or span <= 0 or e <= 0:
            # no step binding (direct layer use), the reference's
            # frozen configurations (counter can never pass a
            # non-negative calm_start), or a degenerate window
            return self.lb, self.ub
        delta = (self.ub - (self.ub + self.lb) / 2.0) / span
        # the reference applies its event (shift by delta*counter, then
        # counter++) BEFORE masking in the same Forward, so training
        # step t reflects events 0..t (m = t+1 of them, capped at e):
        # cumulative shift = delta * triangular(m) = delta*m(m-1)/2
        m = jnp.clip(step + 1, 0, e).astype(jnp.float32)
        adj = delta * m * (m - 1.0) / 2.0
        return self.lb + adj, self.ub - adj

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        if train:
            lb, ub = self._range()
            if not isinstance(lb, float):
                # keep the compute dtype: the f32 traced bounds must
                # not promote a bf16 activation path
                lb, ub = lb.astype(x.dtype), ub.astype(x.dtype)
            u = jax.random.uniform(rng, x.shape, dtype=x.dtype)
            divisor = u * (ub - lb) + lb
            return [ops.xelu(x, divisor)]
        # the midpoint is invariant under the symmetric anneal
        return [ops.xelu(x, (self.lb + self.ub) / 2.0)]


@register_layer
class PReluLayer(Layer):
    """prelu (src/layer/prelu_layer-inl.hpp:48-173).

    Learnable per-channel slope (per-feature for matrix nodes), clipped to
    [0,1]; at train an optional multiplicative noise uniform in
    [1-random, 1+random] perturbs the slope. out = x>0 ? x : x*slope.
    """

    type_name = "prelu"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "init_slope":
            self.init_slope = float(val)
        if name == "random_slope":
            self.init_random = int(val)
        if name == "random":
            self.random = float(val)

    def _channels(self, shape: Shape) -> int:
        return shape[3] if shape[1] == 1 else shape[1]

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        c = self._channels(in_shapes[0])
        if self.init_random == 0:
            slope = jnp.full((c,), self.init_slope, dtype=jnp.float32)
        else:
            slope = self.init_slope * jax.random.uniform(
                key, (c,), dtype=jnp.float32)
        return {"slope": slope}

    def param_tags(self) -> Dict[str, str]:
        # reference visits the slope under the "bias" tag
        # (prelu_layer-inl.hpp ApplyVisitor)
        return {"slope": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        return {"slope": 0}

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        slope = params["slope"]
        if x.shape[1] != 1:
            mask = slope[None, :, None, None]
        else:
            mask = slope[None, None, None, :]
        mask = jnp.broadcast_to(mask, x.shape)
        if train and self.random > 0:
            noise = 1 + (jax.random.uniform(rng, x.shape, dtype=x.dtype)
                         * self.random * 2.0 - self.random)
            mask = mask * noise
        mask = jnp.clip(mask, 0.0, 1.0)
        return [ops.mxelu(x, mask)]


# ---------------------------------------------------------------------------
# normalization / regularization
# ---------------------------------------------------------------------------

@register_layer
class BatchNormLayer(Layer):
    """batch_norm (src/layer/batch_norm_layer-inl.hpp:14-197).

    Per-channel for conv nodes, per-feature for matrix nodes. The reference
    ALWAYS normalizes by the current minibatch statistics - even at eval
    (there is no running mean/var; its eval branch is just an algebraic
    rearrangement of the train branch). We preserve that quirk: train and
    eval compute identically.

    Data-parallel stats parity: the reference normalizes each device's
    batch slice with that slice's OWN statistics (each GPU runs its own
    BN). A naive jnp.mean over the sharded batch dim would instead make
    GSPMD insert an AllReduce per BN layer (global "sync-BN" stats +
    collective latency in every forward/backward). Default behavior
    computes per-shard stats inside a shard_map over the 'data' axis -
    reference semantics, zero collectives; `global_stats = 1` opts into
    the sync-BN extension.
    """

    type_name = "batch_norm"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.init_slope = 1.0
        self.init_bias = 0.0
        self.eps = 1e-10
        self.global_stats = 0
        self._conv_node: Optional[bool] = None

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "init_slope":
            self.init_slope = float(val)
        if name == "init_bias":
            self.init_bias = float(val)
        if name == "eps":
            self.eps = float(val)
        if name == "global_stats":
            self.global_stats = int(val)

    def _is_conv(self, shape) -> bool:
        """Node kind from the GLOBAL shape recorded at infer_shapes -
        never from a possibly-sharded local shape: under tensor
        parallelism a conv activation whose channel dim is sharded down
        to local size 1 inside shard_map must still normalize per
        channel over (b, h, w), not as a matrix node."""
        if self._conv_node is not None:
            return self._conv_node
        return shape[1] != 1

    def _axes(self, shape: Shape):
        # conv node: stats over (b, h, w) per channel; matrix node: over b
        if self._is_conv(shape):
            return (0, 2, 3), (None, slice(None), None, None)
        return (0, 1, 2), (None, None, None, slice(None))

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        self._conv_node = in_shapes[0][1] != 1
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        shape = in_shapes[0]
        c = shape[3] if shape[1] == 1 else shape[1]
        return {
            "slope": jnp.full((c,), self.init_slope, dtype=jnp.float32),
            "bias": jnp.full((c,), self.init_bias, dtype=jnp.float32),
        }

    def param_tags(self) -> Dict[str, str]:
        return {"slope": "wmat", "bias": "bias"}

    def model_shard_dims(self) -> Dict[str, int]:
        return {"slope": 0, "bias": 0}

    def _normalize(self, x, slope, bias):
        axes, _ = self._axes(x.shape)
        # stats in f32 regardless of compute dtype: a per-channel mean
        # over ~1M bf16 activations accumulated in bf16 (XLA does not
        # guarantee a wider accumulator) can be off by whole units,
        # and var inherits the error squared. One downcast at the end
        # keeps the layer's output dtype; f32 inputs are unchanged
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=axes, keepdims=True)
        xhat = (xf - mean) * lax.rsqrt(var + self.eps)
        if self._is_conv(x.shape):
            out = xhat * slope.astype(jnp.float32)[None, :, None, None] \
                + bias.astype(jnp.float32)[None, :, None, None]
        else:
            out = xhat * slope.astype(jnp.float32)[None, None, None, :] \
                + bias.astype(jnp.float32)[None, None, None, :]
        return out.astype(x.dtype)

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        slope, bias = params["slope"], params["bias"]
        from cxxnet_tpu.parallel.mesh import batch_shardable, \
            get_active_mesh
        mesh = get_active_mesh()
        if not self.global_stats and batch_shardable(mesh, x.shape[0]):
            from jax.sharding import PartitionSpec as P
            # channels are independent of the stats reduction, so the
            # channel dim additionally rides 'model' when the params do
            # (mirrors shardings_for's divisibility rule) - under TP the
            # BN then needs NO collectives at all instead of gathering
            # channel-sharded activations
            cdim = 1 if self._is_conv(x.shape) else 3
            msize = mesh.shape.get("model", 1)
            axes = [None] * x.ndim
            axes[0] = "data"
            pspec = P()
            if msize > 1 and x.shape[cdim] % msize == 0:
                axes[cdim] = "model"
                pspec = P("model")
            spec = P(*axes)
            out = jax.shard_map(
                self._normalize, mesh=mesh,
                in_specs=(spec, pspec, pspec), out_specs=spec,
                check_vma=False)(x, slope, bias)
            return [out]
        return [self._normalize(x, slope, bias)]


@register_layer
class LRNLayer(Layer):
    """lrn (src/layer/lrn_layer-inl.hpp:12-93)."""

    type_name = "lrn"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.local_size = 3
        self.alpha = 0.001
        self.beta = 0.75
        self.knorm = 1.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "local_size":
            self.local_size = int(val)
        if name == "alpha":
            self.alpha = float(val)
        if name == "beta":
            self.beta = float(val)
        if name == "knorm":
            self.knorm = float(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        return [in_shapes[0]]

    def apply(self, params, inputs, *, train, rng=None):
        return [ops.lrn(inputs[0], self.local_size, self.alpha, self.beta,
                        self.knorm)]


@register_layer
class DropoutLayer(Layer):
    """dropout (src/layer/dropout_layer-inl.hpp:12-66): inverted dropout,
    self-loop; identity at eval."""

    type_name = "dropout"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.threshold = 0.0

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "threshold":
            self.threshold = float(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        if not 0.0 <= self.threshold < 1.0:
            raise ValueError("DropoutLayer: invalid dropout threshold")
        return [in_shapes[0]]

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        if not train or self.threshold == 0.0:
            return [x]
        pkeep = 1.0 - self.threshold
        mask = (jax.random.uniform(rng, x.shape, dtype=x.dtype)
                < pkeep).astype(x.dtype) / pkeep
        return [x * mask]


@register_layer
class BiasLayer(Layer):
    """bias (src/layer/bias_layer-inl.hpp): self-loop additive bias on
    matrix nodes."""

    type_name = "bias"

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        if not is_mat(in_shapes[0]):
            raise ValueError("BiasLayer only works on flattened nodes")
        self.param.num_input_node = in_shapes[0][3]
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        n = in_shapes[0][3]
        return {"bias": jnp.full((n,), self.param.init_bias,
                                 dtype=jnp.float32)}

    def param_tags(self) -> Dict[str, str]:
        return {"bias": "bias"}

    def apply(self, params, inputs, *, train, rng=None):
        return [inputs[0] + params["bias"][None, None, None, :]]


# ---------------------------------------------------------------------------
# structural layers
# ---------------------------------------------------------------------------

@register_layer
class FlattenLayer(Layer):
    """flatten (src/layer/flatten_layer-inl.hpp): (b,c,h,w)->(b,1,1,chw)."""

    type_name = "flatten"

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, h, w = in_shapes[0]
        return [(b, 1, 1, c * h * w)]

    def apply(self, params, inputs, *, train, rng=None):
        x = inputs[0]
        return [x.reshape(x.shape[0], 1, 1, -1)]


@register_layer
class SplitLayer(Layer):
    """split (src/layer/split_layer-inl.hpp): 1->N copies; autodiff sums
    the output grads, exactly the reference backward."""

    type_name = "split"
    num_out = 1  # set by NetConfig from the connection arity

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        return [in_shapes[0]] * self.num_out

    def apply(self, params, inputs, *, train, rng=None):
        return [inputs[0]] * self.num_out


class ConcatBase(Layer):
    dim = 3

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        if len(in_shapes) < 2:
            raise ValueError("Concat layer only supports n-1 connection")
        if len(in_shapes) > 4:
            raise ValueError("more than 4 input nodes is unsupported")
        out = list(in_shapes[0])
        total = 0
        for s in in_shapes:
            total += s[self.dim]
            for j in range(4):
                if j != self.dim and s[j] != out[j]:
                    raise ValueError("Concat shape doesn't match")
        out[self.dim] = total
        return [tuple(out)]

    def apply(self, params, inputs, *, train, rng=None):
        return [jnp.concatenate(inputs, axis=self.dim)]


@register_layer
class ConcatLayer(ConcatBase):
    """concat along the feature dim (concat_layer-inl.hpp, dim=3)."""
    type_name = "concat"
    dim = 3


@register_layer
class ChConcatLayer(ConcatBase):
    """ch_concat along the channel dim (concat_layer-inl.hpp, dim=1)."""
    type_name = "ch_concat"
    dim = 1


@register_layer
class AddLayer(Layer):
    """add: elementwise sum of N same-shape inputs (no reference analog -
    extension enabling residual connections, e.g. transformer blocks in
    layers/attention.py; autodiff broadcasts the output grad to every
    input, the textbook residual backward)."""

    type_name = "add"

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        if len(in_shapes) < 2:
            raise ValueError("add layer needs at least 2 inputs")
        for s in in_shapes[1:]:
            if tuple(s) != tuple(in_shapes[0]):
                raise ValueError(
                    f"add: input shapes differ: {in_shapes}")
        return [in_shapes[0]]

    def apply(self, params, inputs, *, train, rng=None):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out]
