"""Layer system: all cxxnet layer types as pure JAX function bundles."""

from cxxnet_tpu.layers.base import (
    LAYER_REGISTRY, Layer, LayerParam, create_layer, is_mat,
    known_layer_types, register_layer)
# importing the modules populates the registry
from cxxnet_tpu.layers import attention as _attention  # noqa: F401
from cxxnet_tpu.layers import common as _common  # noqa: F401
from cxxnet_tpu.layers import loss as _loss  # noqa: F401
from cxxnet_tpu.layers import moe as _moe  # noqa: F401
from cxxnet_tpu.layers import transformer_stack as _tstack  # noqa: F401
from cxxnet_tpu.layers import pairtest as _pairtest  # noqa: F401
from cxxnet_tpu.layers.loss import LossLayer

__all__ = [
    "LAYER_REGISTRY", "Layer", "LayerParam", "LossLayer", "create_layer",
    "is_mat", "known_layer_types", "register_layer",
]
