"""PairTest: differential testing of layer implementations.

Parity with the reference's pairtest harness (pairtest_layer-inl.hpp:15-203;
type encoding layer.h:314-315,354-358): `layer[...] = pairtest-A-B` runs a
master implementation A and a slave implementation B of the same logical op
side by side on identical inputs and parameters, and reports relative errors
above a tolerance (reference threshold 1e-5) for forward outputs. Because
backprop here is autodiff, gradient comparison (the reference's
input-gradient and weight-gradient checks, Cmp/CmpResult :160-198) is done
eagerly by :func:`run_pairtest`, which differentiates through both
implementations and returns all max relative errors.

The module also registers `conv_im2col`, an im2col-GEMM convolution — the
reference's own conv algorithm (convolution_layer-inl.hpp:70-106) — which
serves as the trusted slave for the production `lax.conv` path, the same
role the plain template conv played for the cudnn path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from cxxnet_tpu.layers.base import (
    Layer, Params, Shape, create_layer, register_layer)
from cxxnet_tpu.layers.common import ConvolutionLayer


@register_layer
class ConvIm2ColLayer(ConvolutionLayer):
    """Grouped conv via explicit im2col + GEMM (the reference algorithm:
    unpack_patch2col → per-group dot — convolution_layer-inl.hpp:70-106).

    Numerically the same op as `conv`; exists as the differential-test
    slave (`pairtest-conv-conv_im2col`) and as an MXU-friendly
    demonstration that the patch+matmul formulation also lowers to HLO.
    """

    type_name = "conv_im2col"

    def apply(self, params, inputs, *, train, rng=None):
        p = self.param
        x = inputs[0]
        w = params["wmat"]
        ky, kx, s = p.kernel_height, p.kernel_width, p.stride
        g = p.num_group
        out_ch = p.num_channel
        ipg = x.shape[1] // g
        # (b, c*ky*kx, oh, ow), flattened channel-major: c outer, ky, kx
        col = lax.conv_general_dilated_patches(
            x, filter_shape=(ky, kx), window_strides=(s, s),
            padding=((p.pad_y, p.pad_y), (p.pad_x, p.pad_x)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        b, _, oh, ow = col.shape
        col = col.reshape(b, g, ipg * ky * kx, oh * ow)
        wg = w.reshape(g, out_ch // g, ipg * ky * kx)
        out = jnp.einsum("goi,bgix->bgox", wg, col)
        out = out.reshape(b, out_ch, oh, ow)
        if "bias" in params:
            out = out + params["bias"][None, :, None, None]
        return [out]


def _max_rel_err(a: jax.Array, b: jax.Array,
                 eps: float = 1e-6) -> jax.Array:
    """Max abs difference relative to the reference tensor's scale — the
    robust form of the reference's Cmp relative-error metric
    (pairtest_layer-inl.hpp:160-180; elementwise |a-b|/|b| blows up on
    near-zero elements, so normalize by max|b| instead)."""
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    return jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + eps)


class PairTestLayer(Layer):
    """Runs master and slave on the same inputs/params and forwards the
    MASTER's outputs (pairtest_layer-inl.hpp:61-78).

    With `pairtest_print = 1` it additionally emits an in-step warning
    (jax.debug.print) when forward outputs diverge beyond tol. This is
    off by default because some PJRT backends (e.g. the axon TPU tunnel)
    do not support the host callbacks debug.print needs; the full check
    set including gradients is :func:`run_pairtest`, which is eager and
    works on every backend."""

    type_name = "pairtest"

    def __init__(self, master_type: str, slave_type: str, name: str = ""):
        super().__init__(name)
        self.master = create_layer(master_type, name)
        self.slave = create_layer(slave_type, name)
        self.tol = 1e-5  # reference threshold (pairtest_layer-inl.hpp:168)
        self.print_divergence = False

    # `master:key` / `slave:key` routing (pairtest_layer-inl.hpp:128-137);
    # unprefixed keys go to both.
    def set_param(self, name: str, val: str) -> None:
        if name == "pairtest_tol":
            self.tol = float(val)
            return
        if name == "pairtest_print":
            self.print_divergence = bool(int(val))
            return
        if name.startswith("master:"):
            self.master.set_param(name[len("master:"):], val)
        elif name.startswith("slave:"):
            self.slave.set_param(name[len("slave:"):], val)
        else:
            self.master.set_param(name, val)
            self.slave.set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        m = self.master.infer_shapes(list(in_shapes))
        s = self.slave.infer_shapes(list(in_shapes))
        if m != s:
            raise ValueError(
                f"pairtest: master/slave shape mismatch {m} vs {s}")
        return m

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        # one param set, mirrored into both (SyncWeight role,
        # pairtest_layer-inl.hpp:84-101)
        mp = self.master.init_params(key, list(in_shapes))
        sp = self.slave.init_params(key, list(in_shapes))
        if jax.tree.structure(mp) != jax.tree.structure(sp):
            raise ValueError("pairtest: master/slave param mismatch")
        return mp

    def param_tags(self) -> Dict[str, str]:
        return self.master.param_tags()

    def model_shard_dims(self) -> Dict[str, int]:
        return self.master.model_shard_dims()

    def apply(self, params, inputs, *, train, rng=None):
        m_out = self.master.apply(params, inputs, train=train, rng=rng)
        s_out = self.slave.apply(params, inputs, train=train, rng=rng)
        if self.print_divergence:
            for i, (a, b) in enumerate(zip(m_out, s_out)):
                err = _max_rel_err(a, b)
                jax.lax.cond(
                    err > self.tol,
                    lambda e: jax.debug.print(
                        "PairTest[" + self.name + "] out[" + str(i) +
                        "] max rel err {e}", e=e),
                    lambda e: None,
                    err)
        return m_out


def run_pairtest(layer: PairTestLayer, in_shapes: List[Shape],
                 key: Optional[jax.Array] = None,
                 train: bool = True) -> Dict[str, float]:
    """Eager differential test: forward + input-grad + weight-grad max
    relative errors between master and slave (the full check set of
    pairtest_layer-inl.hpp:61-126).

    Returns {"out[i]": err, "in_grad[i]": err, "wgrad/<name>": err}.

    Runs under jax.default_matmul_precision("highest"): on TPU the MXU
    defaults to bfloat16 inputs, and two algorithms rounding differently
    at bf16 would report ~1e-3 divergence that says nothing about either
    implementation's correctness.
    """
    with jax.default_matmul_precision("highest"):
        return _run_pairtest(layer, in_shapes, key, train)


def _run_pairtest(layer: PairTestLayer, in_shapes: List[Shape],
                  key: Optional[jax.Array], train: bool) -> Dict[str, float]:
    if key is None:
        key = jax.random.PRNGKey(0)
    k_param, k_data, k_rng = jax.random.split(key, 3)
    layer.infer_shapes(list(in_shapes))
    params = layer.init_params(k_param, list(in_shapes))
    xs = [jax.random.normal(jax.random.fold_in(k_data, i), s,
                            dtype=jnp.float32)
          for i, s in enumerate(in_shapes)]
    rng = k_rng

    def scalar(impl, params, xs):
        outs = impl.apply(params, xs, train=train, rng=rng)
        return sum(jnp.sum(o * (i + 1.0)) for i, o in enumerate(outs)), outs

    report: Dict[str, float] = {}
    (_, m_out), m_grads = jax.value_and_grad(
        lambda p, x: scalar(layer.master, p, x), argnums=(0, 1),
        has_aux=True)(params, xs)
    (_, s_out), s_grads = jax.value_and_grad(
        lambda p, x: scalar(layer.slave, p, x), argnums=(0, 1),
        has_aux=True)(params, xs)

    for i, (a, b) in enumerate(zip(m_out, s_out)):
        report[f"out[{i}]"] = float(_max_rel_err(a, b))
    for i, (a, b) in enumerate(zip(m_grads[1], s_grads[1])):
        report[f"in_grad[{i}]"] = float(_max_rel_err(a, b))
    flat_m = jax.tree_util.tree_flatten_with_path(m_grads[0])[0]
    flat_s = jax.tree.leaves(s_grads[0])
    for (path, a), b in zip(flat_m, flat_s):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        report[f"wgrad/{name}"] = float(_max_rel_err(a, b))
    return report
