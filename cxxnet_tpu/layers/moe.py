"""Mixture-of-experts FFN layer with expert parallelism.

Pure TPU-native extension (the reference predates MoE entirely): a
switch-style top-k routed FFN over (batch, 1, seq, embed) sequence
nodes, designed for GSPMD expert parallelism rather than hand-written
all-to-all dispatch:

- every expert's FFN weights live in stacked tensors with a leading
  expert dim (w1 (E, H, e), w2 (E, e, H)); `expert_shard_dims` shards
  that dim over an 'expert' mesh axis the same way `model_shard_dims`
  drives tensor parallelism (parallel/sharding.py).
- two compute routes. Default (dense, exact): every expert runs on
  every token and the router's top-k one-hot (scaled by the softmax
  prob, the Switch-Transformer estimator) masks the sum; under an
  expert-sharded mesh each device computes only its local experts for
  all tokens and one psum combines - the all-to-all-free EP layout
  with no token dropping. `moe_capacity > 0` switches to Switch/GShard
  capacity-based sparse dispatch (per-device FLOPs O(top_k x dense)
  regardless of E, overflow tokens dropped) - the large-E perf route.
- the standard load-balance auxiliary loss (E * sum_e fraction_e *
  mean_prob_e) is returned through the `apply_with_aux` protocol
  (nnet/network.py adds it into total_loss; `moe_aux` scales it, 0
  disables).

Config keys: nexpert, nhidden (per-expert FFN hidden), moe_top_k
(default 1), moe_aux (default 0.01), moe_capacity (0 = dense exact
compute; >0 = Switch/GShard capacity-factor sparse dispatch, tokens
over capacity dropped), no_bias.

moe_capacity caveat: the layer itself adds NO residual - a dropped
(over-capacity) token's output is exactly 0, so a config using
`moe_capacity > 0` must wire a residual bypass around the layer or
dropped tokens lose their activations entirely, e.g.::

    layer[3->4,5] = split
    layer[4->6] = moe:moe1
      moe_capacity = 1.25
    layer[5,6->7] = add

(the Switch/GShard formulation assumes exactly this residual;
infer_shapes warns when capacity is enabled).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.layers.base import Layer, Params, Shape, register_layer


@register_layer
class MoELayer(Layer):
    """moe: top-k routed mixture-of-experts FFN on sequence nodes."""

    type_name = "moe"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.nexpert = 0
        self.top_k = 1
        self.aux_scale = 0.01
        self.capacity = 0.0   # 0 = dense (exact); >0 = sparse dispatch

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "nexpert":
            self.nexpert = int(val)
        if name == "moe_top_k":
            self.top_k = int(val)
        if name == "moe_aux":
            self.aux_scale = float(val)
        if name == "moe_capacity":
            self.capacity = float(val)

    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        b, c, s, e = in_shapes[0]
        if c != 1:
            raise ValueError("moe: input must be a sequence node")
        if self.nexpert < 2:
            raise ValueError("moe: must set nexpert >= 2")
        if self.param.num_hidden <= 0:
            raise ValueError("moe: must set nhidden correctly")
        if not (1 <= self.top_k <= self.nexpert):
            raise ValueError("moe: moe_top_k out of range")
        if self.capacity > 0:
            import warnings
            warnings.warn(
                f"moe:{self.name}: moe_capacity={self.capacity} drops "
                "over-capacity tokens (output 0); wire a residual "
                "bypass around this layer (split + add, see the moe "
                "module docstring) or dropped tokens lose their "
                "activations", stacklevel=2)
        return [in_shapes[0]]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        e = in_shapes[0][3]
        h, g = self.param.num_hidden, self.nexpert
        kg, k1, k2 = jax.random.split(key, 3)
        params = {
            "gate": self.param.rand_init_weight(kg, (g, e), in_num=e,
                                                out_num=g),
            "w1": self.param.rand_init_weight(k1, (g, h, e), in_num=e,
                                              out_num=h),
            "w2": self.param.rand_init_weight(k2, (g, e, h), in_num=h,
                                              out_num=e),
        }
        if self.param.no_bias == 0:
            params["b1"] = jnp.zeros((g, h), jnp.float32)
            params["b2"] = jnp.zeros((g, e), jnp.float32)
        return params

    def param_tags(self) -> Dict[str, str]:
        return {"gate": "wmat", "w1": "wmat", "w2": "wmat",
                "b1": "bias", "b2": "bias"}

    def expert_shard_dims(self) -> Dict[str, int]:
        # the gate stays replicated: its (E, e) matrix is tiny and its
        # logits are needed for every token on every expert shard
        return {"w1": 0, "w2": 0, "b1": 0, "b2": 0}

    def _route(self, probs, mask=None, need_combine=True):
        """(b, s, E) probs -> (combine (b, s, E) or None, aux scalar,
        topv (b, s, k), topi (b, s, k)). The top-k tensors are computed
        ONCE here and reused by whichever compute route runs.

        `mask` is the (b,) padded-batch validity mask: padding rows
        must not skew the load-balance statistics (their task loss is
        masked the same way - nnet/network.py)."""
        topv, topi = jax.lax.top_k(probs, self.top_k)
        onehot = jax.nn.one_hot(topi, self.nexpert,
                                dtype=probs.dtype)  # (b, s, k, E)
        combine = (jnp.sum(onehot * topv[..., None], axis=2)
                   if need_combine else None)
        # load-balance loss (Switch Transformer eq. 4): fraction of
        # tokens routed to e (top-1 assignment) x mean router prob
        top1 = jnp.sum(onehot[:, :, :1], axis=2)     # (b, s, E)
        if mask is not None:
            w = mask.astype(probs.dtype)[:, None, None]  # (b, 1, 1)
            total = jnp.maximum(jnp.sum(w) * probs.shape[1], 1.0)
            frac = jnp.sum(top1 * w, axis=(0, 1)) / total
            mean_p = jnp.sum(probs * w, axis=(0, 1)) / total
        else:
            frac = jnp.mean(top1, axis=(0, 1))
            mean_p = jnp.mean(probs, axis=(0, 1))
        aux = self.nexpert * jnp.sum(frac * mean_p)
        return combine, aux, topv, topi

    has_aux = True

    def _dense_compute(self, params, xs, combine):
        """Every expert on every token, masked by `combine` (b, s, E):
        exact, no token dropping; per-device FLOPs = dense x E/n under
        expert sharding."""
        h1 = jnp.einsum("bse,ghe->bsgh", xs,
                        params["w1"].astype(xs.dtype))
        if "b1" in params:
            h1 = h1 + params["b1"].astype(xs.dtype)[None, None]
        h1 = jnp.maximum(h1, 0.0)
        ye = jnp.einsum("bsgh,geh->bsge", h1,
                        params["w2"].astype(xs.dtype))
        if "b2" in params:
            ye = ye + params["b2"].astype(xs.dtype)[None, None]
        return jnp.einsum("bsge,bsg->bse", ye, combine.astype(xs.dtype))

    def _sparse_compute(self, params, xs, topv, topi, mask=None):
        """Capacity-based dispatch (Switch/GShard style): each expert
        processes at most C = ceil(top_k * tokens/E * moe_capacity)
        tokens; per-device FLOPs are O(top_k x dense) regardless of E,
        at the cost of DROPPING tokens that overflow an expert's buffer
        (their MoE output is 0; the residual connection still carries
        them). Chosen over the dense route when `moe_capacity > 0`.
        Padding rows (`mask`) claim no capacity - a padded batch must
        not displace real tokens' expert slots."""
        b, s, e = xs.shape
        t = b * s
        E, k = self.nexpert, self.top_k
        cap = int(np.ceil(k * t / E * self.capacity))
        cap = max(1, min(cap, t))
        xt = xs.reshape(t, e)
        dt = topv.dtype
        topv = topv.reshape(t, k)
        assign = jax.nn.one_hot(topi.reshape(t, k), E,
                                dtype=dt)              # (t, k, E)
        if mask is not None:
            tok = jnp.repeat(mask.astype(dt), s)       # (t,)
            assign = assign * tok[:, None, None]
        # position of each (token, slot) inside its expert's buffer:
        # cumulative count over the flattened (slot-major) order, so
        # k=1 assignments win buffer space before second choices
        flat = jnp.moveaxis(assign, 1, 0).reshape(k * t, E)
        pos = jnp.cumsum(flat, axis=0) - flat          # arrivals before
        pos = jnp.moveaxis(pos.reshape(k, t, E), 0, 1)  # (t, k, E)
        pos = jnp.sum(pos * assign, axis=2).astype(jnp.int32)  # (t, k)
        keep = (pos < cap).astype(dt)
        slot = jax.nn.one_hot(pos, cap, dtype=dt)  # (t, k, cap)
        # dispatch (t, E, cap): 1 where token t sits in expert e slot c
        disp = jnp.einsum("tke,tkc,tk->tec", assign, slot, keep)
        comb = jnp.einsum("tec,tk,tke->tec", disp, topv, assign)
        ein = jnp.einsum("tec,td->ecd", disp.astype(xt.dtype), xt)
        h1 = jnp.einsum("ecd,ehd->ech", ein, params["w1"].astype(xt.dtype))
        if "b1" in params:
            h1 = h1 + params["b1"].astype(xt.dtype)[:, None]
        h1 = jnp.maximum(h1, 0.0)
        ye = jnp.einsum("ech,edh->ecd", h1, params["w2"].astype(xt.dtype))
        if "b2" in params:
            ye = ye + params["b2"].astype(xt.dtype)[:, None]
        out = jnp.einsum("tec,ecd->td", comb.astype(xt.dtype), ye)
        return out.reshape(b, s, e)

    def apply_with_aux(self, params, inputs, *, train, rng=None,
                       mask=None) -> Tuple[List[jax.Array], jax.Array]:
        x = inputs[0]
        b, _, s, e = x.shape
        xs = x.reshape(b, s, e)
        logits = jnp.einsum("bse,ge->bsg", xs,
                            params["gate"].astype(x.dtype))
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sparse = self.capacity > 0
        combine, aux, topv, topi = self._route(
            probs, mask, need_combine=not sparse)
        if sparse:
            out = self._sparse_compute(params, xs, topv, topi, mask)
        else:
            # dense expert compute; the expert dim g rides the 'expert'
            # mesh axis, so each device computes its local experts only
            out = self._dense_compute(params, xs, combine)
        # scaled by batch so the trainer's 1/(batch*update_period)
        # normalization leaves the aux term batch-size-invariant
        aux_term = (self.aux_scale * b) * aux if self.aux_scale else \
            jnp.zeros((), jnp.float32)
        return [out.reshape(b, 1, s, e)], aux_term.astype(jnp.float32)

    def apply(self, params, inputs, *, train, rng=None):
        outs, _ = self.apply_with_aux(params, inputs, train=train, rng=rng)
        return outs
