"""Layer system core: LayerParam, the Layer protocol, and the type registry.

Design mapping from the reference (SURVEY.md par.1 critical idea #1):
a reference `ILayer` is a stateful object with Forward/Backprop and owned
weights; here a Layer is a *pure function bundle*:

    layer.infer_shapes(in_shapes)          shape inference (InitConnection)
    layer.init_params(key, in_shapes)      weight init      (InitModel)
    layer.apply(params, inputs, train, rng) forward          (Forward)

Backprop does not exist: the trainer differentiates through apply. The
Node/Connection split survives at the net level: a layer holds no per-node
state, so one layer's params can serve several connections (weight sharing,
kSharedLayer - layer.h:283-284).

Shapes are full NCHW tuples (batch, channel, y, x); "matrix" nodes are
(batch, 1, 1, n) exactly like the reference Node convention (layer.h:33-54).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp

Shape = Tuple[int, int, int, int]
Params = Dict[str, jax.Array]


def is_mat(shape: Sequence[int]) -> bool:
    """A node is a matrix when channel and y dims are 1 (layer.h:48-54)."""
    return shape[1] == 1 and shape[2] == 1


class LayerParam:
    """Common layer hyperparameters (src/layer/param.h:15-111)."""

    def __init__(self) -> None:
        self.init_sigma = 0.01
        self.init_uniform = -1.0
        self.init_sparse = 10
        self.init_bias = 0.0
        self.random_type = 0  # 0 gaussian, 1 uniform/xavier, 2 kaiming
        self.num_hidden = 0
        self.num_channel = 0
        self.num_group = 1
        self.kernel_width = 0
        self.kernel_height = 0
        self.stride = 1
        self.pad_x = 0
        self.pad_y = 0
        self.no_bias = 0
        self.silent = 0
        self.num_input_channel = 0
        self.num_input_node = 0
        # per-layer compute-dtype pin consumed by the autocast graph
        # pass (nnet/passes.py): overrides the policy for this layer
        # ("" = follow the policy). Stored here so the config schema
        # registry harvests the key.
        self.layer_dtype = ""
        # per-layer quantization pin consumed by the quantize_int8
        # graph pass (nnet/passes.py): "float" excludes the layer,
        # "int8" documents the default policy choice, "" follows the
        # policy. Stored here so the schema registry harvests the key.
        self.layer_quant = ""

    def set_param(self, name: str, val: str) -> None:
        if name == "init_sigma":
            self.init_sigma = float(val)
        if name == "init_uniform":
            self.init_uniform = float(val)
        if name == "init_bias":
            self.init_bias = float(val)
        if name == "init_sparse":
            self.init_sparse = int(val)
        if name == "random_type":
            if val == "gaussian":
                self.random_type = 0
            elif val in ("uniform", "xavier"):
                self.random_type = 1
            elif val == "kaiming":
                self.random_type = 2
            else:
                raise ValueError(f"invalid random_type {val}")
        if name == "nhidden":
            self.num_hidden = int(val)
        if name == "nchannel":
            self.num_channel = int(val)
        if name == "ngroup":
            self.num_group = int(val)
        if name == "kernel_size":
            self.kernel_width = self.kernel_height = int(val)
        if name == "kernel_height":
            self.kernel_height = int(val)
        if name == "kernel_width":
            self.kernel_width = int(val)
        if name == "stride":
            self.stride = int(val)
        if name == "pad":
            self.pad_y = self.pad_x = int(val)
        if name == "pad_y":
            self.pad_y = int(val)
        if name == "pad_x":
            self.pad_x = int(val)
        if name == "no_bias":
            self.no_bias = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "layer_dtype":
            if val not in ("", "float32", "bfloat16"):
                raise ValueError(
                    f"layer_dtype must be float32 or bfloat16, "
                    f"got {val!r}")
            self.layer_dtype = val
        if name == "layer_quant":
            if val not in ("", "int8", "float"):
                raise ValueError(
                    f"layer_quant must be int8 or float, got {val!r}")
            self.layer_quant = val

    def rand_init_weight(self, key: jax.Array, shape: Sequence[int],
                         in_num: int, out_num: int) -> jax.Array:
        """Weight init parity with RandInitWeight (param.h:113-138)."""
        shape = tuple(shape)
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(key, shape,
                                                       dtype=jnp.float32)
        if self.random_type == 1:
            a = math.sqrt(3.0 / (in_num + out_num))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(key, shape, minval=-a, maxval=a,
                                      dtype=jnp.float32)
        if self.random_type == 2:
            if self.num_hidden > 0:
                sigma = math.sqrt(2.0 / self.num_hidden)
            else:
                sigma = math.sqrt(
                    2.0 / (self.num_channel * self.kernel_width
                           * self.kernel_height))
            return sigma * jax.random.normal(key, shape, dtype=jnp.float32)
        raise ValueError(f"invalid random_type {self.random_type}")


class Layer:
    """Base layer: stateless transform with optional trainable params."""

    type_name: str = ""

    def __init__(self, name: str = ""):
        self.name = name
        self.param = LayerParam()

    # --- configuration ---------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)

    # --- structure -------------------------------------------------------
    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        raise NotImplementedError

    def init_params(self, key: jax.Array,
                    in_shapes: List[Shape]) -> Params:
        """Return the layer's trainable params ({} when it has none)."""
        return {}

    def param_tags(self) -> Dict[str, str]:
        """Updater scoping tag per param, mirroring ApplyVisitor names
        (e.g. fullc: wmat->'wmat', bias->'bias'; prelu slope->'bias')."""
        return {}

    def model_shard_dims(self) -> Dict[str, int]:
        """Tensor-parallel rule: param name -> dim sharded over the
        'model' mesh axis (parallel/sharding.py). {} = replicate all."""
        return {}

    def expert_shard_dims(self) -> Dict[str, int]:
        """Expert-parallel rule: param name -> dim sharded over the
        'expert' mesh axis (layers/moe.py). {} = replicate all."""
        return {}

    def pipe_shard_dims(self) -> Dict[str, int]:
        """Pipeline-parallel rule: param name -> dim sharded over the
        'pipe' mesh axis (layers/transformer_stack.py). {} = replicate
        all."""
        return {}

    # --- compute ---------------------------------------------------------
    def apply(self, params: Params, inputs: List[jax.Array], *,
              train: bool, rng: Optional[jax.Array] = None,
              ) -> List[jax.Array]:
        raise NotImplementedError

    #: layers contributing an auxiliary loss term (e.g. MoE load
    #: balancing) set this True and implement
    #:   apply_with_aux(params, inputs, *, train, rng=None, mask=None)
    #:     -> (outputs, aux_scalar)
    #: Network.forward adds aux_scalar into the same total the loss
    #: layers accumulate (scaled 1/(batch*update_period) by the
    #: trainer); `mask` is the (b,) padded-batch validity mask and must
    #: exclude padding rows from any statistics the aux term uses.
    has_aux: bool = False

    # --- checkpoint helpers ----------------------------------------------
    def check_one_to_one(self, in_shapes: List[Shape]) -> None:
        if len(in_shapes) != 1:
            raise ValueError(
                f"{self.type_name}: layer only supports 1-1 connection")


# ---------------------------------------------------------------------------
# ambient training-step binding
# ---------------------------------------------------------------------------

_ACTIVE_STEP: List[Optional[jax.Array]] = [None]


class active_step:
    """Context binding 'the (traced) update counter this forward runs
    at' so layers whose behavior is a function of training progress
    (insanity's per-forward anneal, insanity_layer-inl.hpp:52-63) can
    read it without threading a step argument through every
    Layer.apply. The trainer enters it around net.forward inside the
    traced train step (same pattern as parallel.mesh.active_mesh)."""

    def __init__(self, step: Optional[jax.Array]):
        self.step = step

    def __enter__(self):
        _ACTIVE_STEP.append(self.step)
        return self.step

    def __exit__(self, *exc):
        _ACTIVE_STEP.pop()
        return False


def get_active_step() -> Optional[jax.Array]:
    return _ACTIVE_STEP[-1]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

LAYER_REGISTRY: Dict[str, Type[Layer]] = {}

# layer types that are self-loops converting activations to gradients
LOSS_TYPES = ("softmax", "l2_loss", "multi_logistic")


def register_layer(cls: Type[Layer]) -> Type[Layer]:
    assert cls.type_name, "layer class must define type_name"
    LAYER_REGISTRY[cls.type_name] = cls
    return cls


def create_layer(type_name: str, name: str = "") -> Layer:
    """Factory: config layer type string -> Layer instance.

    Mirrors GetLayerType (layer.h:322-361) + CreateLayer_
    (layer_impl-inl.hpp:36-76). `share[...]` is handled by the net config;
    `pairtest-A-B` builds a differential-testing wrapper (layer.h:354-358).
    """
    if type_name.startswith("pairtest-"):
        from cxxnet_tpu.layers.pairtest import PairTestLayer
        parts = type_name.split("-", 2)
        if len(parts) != 3 or not parts[1] or not parts[2]:
            raise ValueError(
                f'unknown layer type: "{type_name}" '
                "(pairtest syntax is pairtest-<master>-<slave>)")
        return PairTestLayer(parts[1], parts[2], name)
    if type_name == "torch":
        # plugin layers register on first use (the analog of the
        # reference's compile-time CXXNET_USE_CAFFE_ADAPTOR gate)
        import cxxnet_tpu.plugin.torch_adapter  # noqa: F401
    if type_name not in LAYER_REGISTRY:
        raise ValueError(f'unknown layer type: "{type_name}"')
    return LAYER_REGISTRY[type_name](name)


def known_layer_types() -> List[str]:
    return sorted(LAYER_REGISTRY)
