// Native C ABI library: embeds CPython and delegates to cxxnet_tpu.capi.
//
// Role parity with the reference's wrapper/cxxnet_wrapper.cpp (which wraps
// INetTrainer behind a C ABI for the ctypes frontend); here the C side is
// the *outer* shell around the Python/JAX core, so any C-ABI language can
// drive the TPU trainer the way reference users drove the C++ one.
//
// Threading: the embed layer initializes Python once, releases the GIL,
// and re-acquires it per call (PyGILState), so calls may come from any
// thread (serialized by the GIL, like the reference's per-handle use).

#include "cxxnet_wrapper.h"

#include <Python.h>

#include <cstdarg>
#include <cstdio>
#include <mutex>
#include <string>

namespace {

PyObject *g_capi = nullptr;          // cxxnet_tpu.capi module
std::once_flag g_init_once;
thread_local std::string tls_error;  // CXNGetLastError storage
thread_local std::string tls_str;    // CXNNetEvaluate return storage

void InitPython() {
  const bool we_initialized = !Py_IsInitialized();
  if (we_initialized) {
    Py_InitializeEx(0);
  }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject *mod = PyImport_ImportModule("cxxnet_tpu.capi");
  if (mod == nullptr) {
    PyErr_Print();
    std::fprintf(stderr,
                 "cxxnet_wrapper: cannot import cxxnet_tpu.capi - is the "
                 "package on PYTHONPATH?\n");
  }
  g_capi = mod;  // leaked on purpose: lives for the process
  PyGILState_Release(st);
  // release the GIL acquired by Py_InitializeEx on this thread so
  // other threads (and later PyGILState_Ensure calls) can take it.
  // ONLY when this library did the initialization: in a host process
  // that already runs Python (ctypes.PyDLL / a C extension), the GIL
  // we would be releasing belongs to the CALLER.
  if (we_initialized && PyGILState_Check()) {
    PyEval_SaveThread();
  }
}

void RecordPyError() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tls_error = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) tls_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Calls capi.<fn>(...) with a Py_BuildValue-style format producing an
// argument tuple. Returns a new reference or nullptr (error recorded).
PyObject *CallCapi(const char *fn, const char *fmt, ...) {
  std::call_once(g_init_once, InitPython);
  if (g_capi == nullptr) {
    tls_error = "cxxnet_tpu.capi not importable";
    return nullptr;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject *result = nullptr;
  PyObject *func = PyObject_GetAttrString(g_capi, fn);
  if (func == nullptr) {
    RecordPyError();
  } else {
    va_list ap;
    va_start(ap, fmt);
    PyObject *args = Py_VaBuildValue(fmt, ap);
    va_end(ap);
    if (args != nullptr) {
      // Py_BuildValue yields a bare object for 1-arg formats
      PyObject *tuple = PyTuple_Check(args)
                            ? args
                            : PyTuple_Pack(1, args);
      if (tuple != args) Py_DECREF(args);
      if (tuple != nullptr) {
        result = PyObject_CallObject(func, tuple);
        Py_DECREF(tuple);
      }
    }
    if (result == nullptr) RecordPyError();
    Py_DECREF(func);
  }
  PyGILState_Release(st);
  return result;
}

int CallVoid(PyObject *r) {
  if (r == nullptr) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  Py_DECREF(r);
  PyGILState_Release(st);
  return 0;
}

int64_t CallInt(PyObject *r) {
  if (r == nullptr) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  int64_t v = PyLong_AsLongLong(r);
  Py_DECREF(r);
  PyGILState_Release(st);
  return v;
}

void *CallHandle(PyObject *r) {
  int64_t v = CallInt(r);
  return v <= 0 ? nullptr : reinterpret_cast<void *>(v);
}

uint64_t Id(void *h) { return reinterpret_cast<uint64_t>(h); }
uint64_t Addr(const void *p) { return reinterpret_cast<uint64_t>(p); }

}  // namespace

extern "C" {

const char *CXNGetLastError(void) { return tls_error.c_str(); }

CXNNetHandle CXNNetCreate(const char *device, const char *cfg) {
  return CallHandle(CallCapi("net_create", "(ss)", device, cfg));
}

int CXNNetFree(CXNNetHandle h) {
  return CallVoid(CallCapi("free", "(K)", Id(h)));
}

int CXNNetSetParam(CXNNetHandle h, const char *name, const char *val) {
  return CallVoid(CallCapi("net_set_param", "(Kss)", Id(h), name, val));
}

int CXNNetInitModel(CXNNetHandle h) {
  return CallVoid(CallCapi("net_init_model", "(K)", Id(h)));
}

int CXNNetLoadModel(CXNNetHandle h, const char *fname) {
  return CallVoid(CallCapi("net_load_model", "(Ks)", Id(h), fname));
}

int CXNNetSaveModel(CXNNetHandle h, const char *fname) {
  return CallVoid(CallCapi("net_save_model", "(Ks)", Id(h), fname));
}

int CXNNetStartRound(CXNNetHandle h, int round_counter) {
  return CallVoid(CallCapi("net_start_round", "(Ki)", Id(h),
                           round_counter));
}

int CXNNetUpdateIter(CXNNetHandle h, CXNIOHandle it) {
  return CallVoid(CallCapi("net_update_iter", "(KK)", Id(h), Id(it)));
}

int CXNNetUpdateBatch(CXNNetHandle h, const float *data,
                      const uint64_t dshape[4], const float *label,
                      uint64_t label_width) {
  return CallVoid(CallCapi(
      "net_update_batch", "(KKKKKKKK)", Id(h), Addr(data), dshape[0],
      dshape[1], dshape[2], dshape[3], Addr(label), label_width));
}

int64_t CXNNetPredictBatch(CXNNetHandle h, const float *data,
                           const uint64_t dshape[4], float *out) {
  return CallInt(CallCapi("net_predict_batch", "(KKKKKKK)", Id(h),
                          Addr(data), dshape[0], dshape[1], dshape[2],
                          dshape[3], Addr(out)));
}

int64_t CXNNetPredictIter(CXNNetHandle h, CXNIOHandle it, float *out,
                          uint64_t out_capacity) {
  return CallInt(CallCapi("net_predict_iter", "(KKKK)", Id(h), Id(it),
                          Addr(out), out_capacity));
}

int64_t CXNNetExtractBatch(CXNNetHandle h, const float *data,
                           const uint64_t dshape[4], const char *node_name,
                           float *out, uint64_t out_capacity) {
  return CallInt(CallCapi("net_extract_batch", "(KKKKKKsKK)", Id(h),
                          Addr(data), dshape[0], dshape[1], dshape[2],
                          dshape[3], node_name, Addr(out), out_capacity));
}

const char *CXNNetEvaluate(CXNNetHandle h, CXNIOHandle it,
                           const char *name) {
  PyObject *r = CallCapi("net_evaluate", "(KKs)", Id(h), Id(it), name);
  if (r == nullptr) return nullptr;
  PyGILState_STATE st = PyGILState_Ensure();
  const char *c = PyUnicode_AsUTF8(r);
  tls_str = (c != nullptr) ? c : "";
  Py_DECREF(r);
  PyGILState_Release(st);
  return tls_str.c_str();
}

int64_t CXNNetGetWeight(CXNNetHandle h, const char *layer_name,
                        const char *tag, float *out, uint64_t out_capacity,
                        uint64_t shape_out[2]) {
  return CallInt(CallCapi("net_get_weight", "(KssKKK)", Id(h), layer_name,
                          tag, Addr(out), out_capacity, Addr(shape_out)));
}

int CXNNetSetWeight(CXNNetHandle h, const float *data, uint64_t rows,
                    uint64_t cols, const char *layer_name,
                    const char *tag) {
  return CallVoid(CallCapi("net_set_weight", "(KKKKss)", Id(h), Addr(data),
                           rows, cols, layer_name, tag));
}

CXNIOHandle CXNIOCreateFromConfig(const char *cfg) {
  return CallHandle(CallCapi("io_create", "(s)", cfg));
}

int CXNIOFree(CXNIOHandle h) {
  return CallVoid(CallCapi("free", "(K)", Id(h)));
}

int CXNIONext(CXNIOHandle h) {
  return static_cast<int>(CallInt(CallCapi("io_next", "(K)", Id(h))));
}

int CXNIOBeforeFirst(CXNIOHandle h) {
  return CallVoid(CallCapi("io_before_first", "(K)", Id(h)));
}

int CXNIOGetDataShape(CXNIOHandle h, uint64_t shape_out[4]) {
  return CallVoid(CallCapi("io_get_data_shape", "(KK)", Id(h),
                           Addr(shape_out)));
}

int64_t CXNIOCopyData(CXNIOHandle h, float *out) {
  return CallInt(CallCapi("io_copy_data", "(KK)", Id(h), Addr(out)));
}

int CXNIOGetLabelShape(CXNIOHandle h, uint64_t shape_out[2]) {
  return CallVoid(CallCapi("io_get_label_shape", "(KK)", Id(h),
                           Addr(shape_out)));
}

int64_t CXNIOCopyLabel(CXNIOHandle h, float *out) {
  return CallInt(CallCapi("io_copy_label", "(KK)", Id(h), Addr(out)));
}

}  // extern "C"
