// Native data-plane for cxxnet_tpu: BinaryPage streaming + parallel
// image decode with an ordered hand-off to Python.
//
// Role parity with the reference's native io stack:
//   - BinaryPage format       src/utils/io.h:254-326 (64MiB packed pages)
//   - two-stage pipeline      src/io/iter_thread_imbin_x-inl.hpp:18-397
//     (page-loader thread -> decode worker pool -> ordered consumer)
//   - in-memory decoders      src/utils/decoder.h:21-130 (libjpeg + setjmp
//     error recovery; libpng instead of OpenCV for the PNG path)
//
// The consumer (Python, via ctypes) pulls records strictly in stream
// order; decode parallelism is hidden behind a reorder buffer. All
// buffers are owned by the handle and valid until the next cxio_next /
// cxio_before_first / cxio_close on that handle.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>
#include <png.h>

namespace {

constexpr int64_t kPageNumInts = 64 << 18;
constexpr int64_t kPageSize = 4 * kPageNumInts;  // 64 MiB

struct Decoded {
  std::vector<unsigned char> pixels;  // HWC RGB u8, or raw blob on failure
  std::vector<float> chw;             // CHW float32 (out_mode 1)
  std::vector<unsigned char> chw_u8;  // CHW uint8 (out_mode 2)
  int h = 0, w = 0, c = 0;            // c == 0 -> pixels holds the raw blob
};

// HWC u8 -> CHW (the DataInst layout), done on the worker thread so
// the Python consumer gets a ready tensor. T = float (out_mode 1) or
// unsigned char (out_mode 2, device-side augmentation staging: raw
// pixels stay uint8 end-to-end for a 1/4-size H2D transfer).
template <typename T>
void ToChw(const Decoded* d, std::vector<T>* out) {
  const size_t hw = static_cast<size_t>(d->h) * d->w;
  const size_t c = static_cast<size_t>(d->c);
  out->resize(hw * c);
  const unsigned char* src = d->pixels.data();
  for (size_t ch = 0; ch < c; ++ch) {
    T* dst = out->data() + ch * hw;
    const unsigned char* s = src + ch;
    for (size_t i = 0; i < hw; ++i) dst[i] = static_cast<T>(s[i * c]);
  }
}

// ---------------------------------------------------------------------------
// decoders
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

bool DecodeJpeg(const unsigned char* buf, size_t len, Decoded* out) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = cinfo.output_width;
  out->h = cinfo.output_height;
  out->c = 3;
  out->pixels.resize(static_cast<size_t>(out->h) * out->w * 3);
  const size_t stride = static_cast<size_t>(out->w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out->pixels.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool DecodePng(const unsigned char* buf, size_t len, Decoded* out) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, buf, len)) return false;
  image.format = PNG_FORMAT_RGB;
  out->w = image.width;
  out->h = image.height;
  out->c = 3;
  out->pixels.resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, out->pixels.data(), 0,
                             nullptr)) {
    png_image_free(&image);
    return false;
  }
  return true;
}

void DecodeBlob(std::vector<unsigned char> blob, Decoded* out) {
  bool ok = false;
  if (blob.size() >= 2 && blob[0] == 0xFF && blob[1] == 0xD8) {
    ok = DecodeJpeg(blob.data(), blob.size(), out);
  } else if (blob.size() >= 8 && blob[0] == 0x89 && blob[1] == 'P') {
    ok = DecodePng(blob.data(), blob.size(), out);
  }
  if (!ok) {  // unknown / corrupt: hand the raw blob back to Python
    out->pixels = std::move(blob);
    out->h = 0;
    out->w = static_cast<int>(out->pixels.size());
    out->c = 0;
  }
}

// ---------------------------------------------------------------------------
// pipeline
// ---------------------------------------------------------------------------

struct Task {
  int64_t seq;
  std::vector<unsigned char> blob;
};

class Pipeline {
 public:
  Pipeline(std::vector<std::string> paths, int n_threads, int max_inflight,
           int out_mode)
      : paths_(std::move(paths)),
        n_threads_(std::max(1, n_threads)),
        max_inflight_(std::max(2, max_inflight)),
        out_mode_(out_mode) {}

  ~Pipeline() { Stop(); }

  void Start() {
    Stop();
    stop_.store(false);
    eof_ = false;
    next_seq_ = 0;
    consume_seq_ = 0;
    tasks_.clear();
    done_.clear();
    error_.clear();
    reader_ = std::thread(&Pipeline::ReaderMain, this);
    for (int i = 0; i < n_threads_; ++i)
      workers_.emplace_back(&Pipeline::WorkerMain, this);
  }

  void Stop() {
    {
      // the stop flag and the notifies must be published under the
      // mutex: a waiter that has evaluated its predicate (stop_ ==
      // false) but not yet blocked would otherwise miss the wakeup
      // forever and hang the joins below
      std::lock_guard<std::mutex> lk(mu_);
      stop_.store(true);
      cv_task_.notify_all();
      cv_done_.notify_all();
      cv_space_.notify_all();
    }
    if (reader_.joinable()) reader_.join();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
  }

  // Pull the next record in stream order; false at end of stream.
  bool Next(Decoded* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [&] {
      return stop_.load() || !error_.empty() ||
             done_.count(consume_seq_) ||
             (eof_ && consume_seq_ >= next_seq_ && tasks_.empty() &&
              inflight_ == 0);
    });
    if (stop_.load() || !error_.empty()) return false;
    auto it = done_.find(consume_seq_);
    if (it == done_.end()) return false;  // clean EOF
    *out = std::move(it->second);
    done_.erase(it);
    ++consume_seq_;
    cv_space_.notify_one();
    return true;
  }

  std::string error() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_;
  }

 private:
  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu_);
    if (error_.empty()) error_ = msg;
    cv_done_.notify_all();
    cv_task_.notify_all();
  }

  void ReaderMain() {
    std::vector<unsigned char> page(kPageSize);
    for (const auto& path : paths_) {
      FILE* f = std::fopen(path.c_str(), "rb");
      if (!f) {
        Fail("cannot open " + path);
        return;
      }
      while (!stop_.load()) {
        size_t got = std::fread(page.data(), 1, kPageSize, f);
        if (got == 0) break;
        if (got < static_cast<size_t>(kPageSize)) {
          std::fclose(f);
          Fail("truncated page in " + path);
          return;
        }
        const int32_t* ints = reinterpret_cast<const int32_t*>(page.data());
        int32_t n = ints[0];
        if (n < 0 ||
            static_cast<int64_t>(n) + 2 > static_cast<int64_t>(kPageNumInts)) {
          std::fclose(f);
          Fail("corrupt page header in " + path);
          return;
        }
        for (int32_t r = 0; r < n && !stop_.load(); ++r) {
          int64_t start = ints[r + 1], end = ints[r + 2];
          if (start < 0 || end < start || end > kPageSize) {
            std::fclose(f);
            Fail("corrupt blob offsets in " + path);
            return;
          }
          std::vector<unsigned char> blob(
              page.data() + kPageSize - end, page.data() + kPageSize - start);
          std::unique_lock<std::mutex> lk(mu_);
          cv_space_.wait(lk, [&] {
            return stop_.load() ||
                   static_cast<int>(tasks_.size() + done_.size()) +
                           inflight_ < max_inflight_;
          });
          if (stop_.load()) {
            std::fclose(f);
            return;
          }
          tasks_.push_back(Task{next_seq_++, std::move(blob)});
          cv_task_.notify_one();
        }
      }
      std::fclose(f);
    }
    std::lock_guard<std::mutex> lk(mu_);
    eof_ = true;
    cv_task_.notify_all();
    cv_done_.notify_all();
  }

  void WorkerMain() {
    while (true) {
      Task task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_task_.wait(lk, [&] {
          return stop_.load() || !tasks_.empty() || eof_;
        });
        if (stop_.load()) return;
        if (tasks_.empty()) {
          if (eof_) return;
          continue;
        }
        task = std::move(tasks_.front());
        tasks_.pop_front();
        ++inflight_;
      }
      Decoded d;
      DecodeBlob(std::move(task.blob), &d);
      if (out_mode_ == 1 && d.c > 0) ToChw(&d, &d.chw);
      else if (out_mode_ == 2 && d.c > 0) ToChw(&d, &d.chw_u8);
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_.emplace(task.seq, std::move(d));
        --inflight_;
        cv_done_.notify_all();
      }
    }
  }

  std::vector<std::string> paths_;
  int n_threads_;
  int max_inflight_;
  int out_mode_;

  std::thread reader_;
  std::vector<std::thread> workers_;
  std::atomic<bool> stop_{true};

  std::mutex mu_;
  std::condition_variable cv_task_, cv_done_, cv_space_;
  std::deque<Task> tasks_;
  std::map<int64_t, Decoded> done_;
  int inflight_ = 0;
  int64_t next_seq_ = 0;
  int64_t consume_seq_ = 0;
  bool eof_ = false;
  std::string error_;
};

struct Handle {
  std::unique_ptr<Pipeline> pipe;
  Decoded current;          // owns the buffer returned by cxio_next
  std::string last_error;
  std::vector<std::string> paths;
  int n_threads = 4;
  int max_inflight = 64;
  int out_mode = 0;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

typedef struct {
  const unsigned char* data;  // HWC u8 / CHW f32, or raw blob when c == 0
  int h, w, c;                // c == 0: undecodable blob, byte length in w
} CxioRecord;

// out_mode 1: records come back as CHW float32 (DataInst layout);
// out_mode 2: CHW uint8 (device-side augmentation staging); 0: HWC u8.
// Conversion runs on the worker threads either way.
void* cxio_open(const char* const* bin_paths, int n_bins, int n_threads,
                int max_inflight, int out_mode) {
  auto* h = new Handle();
  for (int i = 0; i < n_bins; ++i) h->paths.emplace_back(bin_paths[i]);
  if (n_threads > 0) h->n_threads = n_threads;
  if (max_inflight > 0) h->max_inflight = max_inflight;
  h->out_mode = out_mode;
  return h;
}

void cxio_before_first(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  h->pipe.reset(new Pipeline(h->paths, h->n_threads, h->max_inflight,
                             h->out_mode));
  h->pipe->Start();
}

int cxio_next(void* handle, CxioRecord* rec) {
  auto* h = static_cast<Handle*>(handle);
  if (!h->pipe) cxio_before_first(handle);
  if (!h->pipe->Next(&h->current)) {
    h->last_error = h->pipe->error();
    return 0;
  }
  if (h->out_mode == 1 && h->current.c > 0) {
    rec->data = reinterpret_cast<const unsigned char*>(
        h->current.chw.data());
  } else if (h->out_mode == 2 && h->current.c > 0) {
    rec->data = h->current.chw_u8.data();
  } else {
    rec->data = h->current.pixels.data();
  }
  rec->h = h->current.h;
  rec->w = h->current.w;
  rec->c = h->current.c;
  return 1;
}

const char* cxio_last_error(void* handle) {
  return static_cast<Handle*>(handle)->last_error.c_str();
}

void cxio_close(void* handle) { delete static_cast<Handle*>(handle); }

}  // extern "C"
