/*
 * C ABI over the cxxnet_tpu trainer + data iterators.
 *
 * Capability parity with the reference C wrapper
 * (wrapper/cxxnet_wrapper.h:28-229): create/configure/train/predict/
 * extract/evaluate nets and drive config-built data iterators from any
 * C-ABI language. The implementation (cxxnet_wrapper.cc) embeds CPython
 * and delegates to cxxnet_tpu.capi; the JAX/XLA compute path underneath
 * is exactly the one the Python API uses.
 *
 * Conventions:
 *  - all functions return 0 / a handle / a count on success;
 *    -1 / NULL on failure. CXNGetLastError() describes the failure.
 *  - float buffers are caller-owned, row-major float32.
 *  - shapes are uint64[4] (batch, channel, height, width).
 */
#ifndef CXXNET_TPU_WRAPPER_H_
#define CXXNET_TPU_WRAPPER_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *CXNNetHandle;
typedef void *CXNIOHandle;

/* last error message of the calling thread (never NULL) */
const char *CXNGetLastError(void);

/* ---- net lifecycle ---------------------------------------------------- */
CXNNetHandle CXNNetCreate(const char *device, const char *cfg);
int CXNNetFree(CXNNetHandle h);
int CXNNetSetParam(CXNNetHandle h, const char *name, const char *val);
int CXNNetInitModel(CXNNetHandle h);
int CXNNetLoadModel(CXNNetHandle h, const char *fname);
int CXNNetSaveModel(CXNNetHandle h, const char *fname);
int CXNNetStartRound(CXNNetHandle h, int round_counter);

/* ---- training --------------------------------------------------------- */
int CXNNetUpdateIter(CXNNetHandle h, CXNIOHandle it);
int CXNNetUpdateBatch(CXNNetHandle h, const float *data,
                      const uint64_t dshape[4], const float *label,
                      uint64_t label_width);

/* ---- inference -------------------------------------------------------- */
/* returns number of floats written, -1 on error */
int64_t CXNNetPredictBatch(CXNNetHandle h, const float *data,
                           const uint64_t dshape[4], float *out);
int64_t CXNNetPredictIter(CXNNetHandle h, CXNIOHandle it, float *out,
                          uint64_t out_capacity);
int64_t CXNNetExtractBatch(CXNNetHandle h, const float *data,
                           const uint64_t dshape[4], const char *node_name,
                           float *out, uint64_t out_capacity);
/* evaluation string "\tname-metric:value..."; valid until the next call
 * on the same thread */
const char *CXNNetEvaluate(CXNNetHandle h, CXNIOHandle it,
                           const char *name);

/* ---- weight access ---------------------------------------------------- */
/* writes the 2-D flattened weight and its shape; returns element count,
 * 0 when no such weight exists, -1 on error */
int64_t CXNNetGetWeight(CXNNetHandle h, const char *layer_name,
                        const char *tag, float *out, uint64_t out_capacity,
                        uint64_t shape_out[2]);
int CXNNetSetWeight(CXNNetHandle h, const float *data, uint64_t rows,
                    uint64_t cols, const char *layer_name, const char *tag);

/* ---- data iterators ---------------------------------------------------- */
CXNIOHandle CXNIOCreateFromConfig(const char *cfg);
int CXNIOFree(CXNIOHandle h);
int CXNIONext(CXNIOHandle h);          /* 1 = has batch, 0 = end, -1 err */
int CXNIOBeforeFirst(CXNIOHandle h);
int CXNIOGetDataShape(CXNIOHandle h, uint64_t shape_out[4]);
int64_t CXNIOCopyData(CXNIOHandle h, float *out);
int CXNIOGetLabelShape(CXNIOHandle h, uint64_t shape_out[2]);
int64_t CXNIOCopyLabel(CXNIOHandle h, float *out);

#ifdef __cplusplus
}  /* extern "C" */
#endif
#endif  /* CXXNET_TPU_WRAPPER_H_ */
