"""Headline benchmark: AlexNet training throughput (images/sec).

Two numbers are measured on the same trainer:

- ``compute``:  the jitted train step driven on pre-staged device
  buffers - the kernel/compiler ceiling, what BENCH_r02 measured.
- ``e2e``:      the full product path the reference times
  (cxxnet_main.cpp:367-387): ``trainer.update()`` fed per-step from
  host batches - includes padding, H2D staging, the on-device metric
  accumulation, and the optimizer, i.e. what a user actually gets.

The headline ``value`` is the END-TO-END number. Extras (each optional,
each snapshotted, each individually guarded so a failure degrades to an
``*_error`` field instead of killing the headline) record:

- ``top_ops``/``profiled_device_ms``: top-5 device ops of the compiled
  e2e step (where the step time goes).
- ``host_prep_ms_p50``/``device_step_ms_p50``/``augment_ips``: the
  input-pipeline split - is training host-bound or device-bound, and
  can host-side crop/mirror/mean augmentation keep up with the chip
  (the device-side-augmentation go/no-go in docs/perf.md).
- ``attn_*``: Pallas flash-attention kernel vs the XLA blockwise path
  (fwd+bwd TFLOP/s) - the kernel's on-silicon validation.
- ``googlenet_ips``: second model family (BASELINE config #5),
  concat-heavy inception graph.
- ``e2e_eval_train_ips``: eval_train=1 (the reference's default mode)
  with device-side metric accumulators compiled into the step. Needs a
  second full AlexNet compile -> deliberately the LAST, most
  expendable extra.

Partial-result discipline: ``_PARTIAL`` is snapshotted after EVERY
measurement (compute first). If the watchdog fires mid-run, it emits
whatever is complete rather than re-exec'ing away a finished on-chip
number (round-3 post-mortem: a late crash zeroed a whole round's
artifact).

Compilation cache: a repo-local ``jax_compilation_cache_dir``
(``.jax_cache/``, gitignored) persists XLA executables across runs and
rounds, so repeat AlexNet/GoogLeNet compiles are near-instant and the
watchdog budget buys measurements, not recompiles. Disable with
``CXN_BENCH_CACHE=0``.

Prints ONE JSON line even when the backend is unreachable
(``{"metric": ..., "error": ...}``) - a backend hiccup must yield a
diagnosable artifact, not rc=1.

Baseline constant: the reference publishes no numbers (BASELINE.md), and
this sandbox has no A100 (and no egress to cite one), so the A100
anchor is an arithmetic estimate, documented at the constant. The
``achieved_tflops``/``mfu_pct`` fields ground the perf claim in the
chip's own peak instead.

Usage: python bench.py [--profile DIR] [--steps N]
    --profile DIR  additionally capture a jax.profiler trace of the
                   steady-state e2e loop into DIR.

A watchdog thread (CXN_BENCH_TIMEOUT, default 480 s) handles a hung
backend (e.g. a stuck tunnel lease blocking inside PJRT client
creation, where no Python signal can ever be delivered): if headline
numbers exist it prints them; else the first occurrence re-execs the
process onto the CPU backend so a real, clearly-labeled number (JSON
field "fallback") is still produced; if already on CPU (or the re-exec
fails) it prints the error JSON line and exits cleanly instead of
dying rc-143 with no artifact.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

# AlexNet training flops/image ~= 0.72 GMAC fwd x 2 flop/MAC x 3
# (fwd + dgrad + wgrad) ~= 4.3 GFLOP. A100 bf16 peak = 312 TFLOP/s;
# AlexNet's LRN/pooling/fc mix sustains well under full MFU - assume
# ~15%, in line with public convnet training MFU on Ampere, giving
# 312e12 * 0.15 / 4.3e9 ~= 10.9k img/s; rounded to 10k. An estimate,
# not a measurement: no A100 exists here and the reference publishes
# no throughput numbers (BASELINE.md).
A100_IMAGES_PER_SEC = 10000.0
ALEXNET_TRAIN_GFLOP_PER_IMG = 4.3

# bf16 peak TFLOP/s by device_kind substring - grounds the perf claim
# in the chip's own numbers (public TPU spec sheets)
_TPU_PEAK_TFLOPS = (
    ("v6e", 918.0), ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

# resolved at import, before anything can os.chdir: the re-exec path
# must not depend on the working directory
_BENCH_PATH = os.path.abspath(__file__)
_REPO = os.path.dirname(_BENCH_PATH)

# headline results land here as soon as they are measured; the watchdog
# prints these instead of throwing away a completed on-chip measurement
# with a CPU re-exec. _EMIT_LOCK serializes the "who prints the one
# JSON line" decision between the main thread and the watchdog timer.
_PARTIAL: dict = {}
_EMIT_LOCK = threading.Lock()


def _snapshot(out: dict) -> None:
    """Checkpoint the result dict so the watchdog can emit it as-is."""
    with _EMIT_LOCK:
        _PARTIAL.update(out)


def _alexnet_batch(rng, batch):
    """The bench's input shape in ONE place (matches _ALEXNET_CONF)."""
    return (rng.randn(batch, 3, 227, 227).astype(np.float32),
            rng.randint(0, 1000, size=(batch, 1)).astype(np.float32))


def _measure_compute(trainer, batch, steps):
    """Train-step-only throughput on pre-staged device buffers.

    Staging mirrors trainer.update(): data under _data_sharded with
    the host-side compute-dtype cast (_host_input), labels/mask under
    _batch_sharded, extras the () the conf declares - the exact
    in_shardings the compiled step was built with (trainer.py _compile).
    """
    import jax
    rng = np.random.RandomState(0)
    hdata, hlabel = _alexnet_batch(rng, batch)
    data = jax.device_put(trainer._host_input(hdata),
                          trainer._data_sharded)
    label = jax.device_put(hlabel, trainer._batch_sharded)
    mask = jax.device_put(np.ones(batch, np.float32),
                          trainer._batch_sharded)
    labels = {"label": label}
    key = jax.random.PRNGKey(0)

    state = trainer.state
    # warmup (compile + first run). block_until_ready, NEVER a host
    # readback: on the tunneled platform a single D2H transfer costs
    # tens of seconds AND stickily degrades all subsequent H2D staging
    # to ~25 MB/s (measured round 4: one scalar np.asarray() on an idle
    # queue took 48 s and cut the e2e loop from ~1,500 to ~70 img/s for
    # the rest of the process). block_until_ready waits for completion
    # without transferring - verified against the device profile
    # (33 ms/step blocked == 33 ms/step profiled device time).
    for i in range(3):
        state, loss = trainer._train_step(
            state, data, (), labels, mask, jax.random.fold_in(key, i))
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss = trainer._train_step(
            state, data, (), labels, mask, jax.random.fold_in(key, i))
    jax.block_until_ready(loss)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    trainer.state = state
    return steps * batch / dt


def _measure_e2e(trainer, batch, steps, profile_dir=""):
    """Full trainer.update() path fed from host batches."""
    import jax
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(1)
    # a few distinct host batches cycled through, like a RAM-resident
    # iterator (membuffer); fresh numpy arrays each step would measure
    # the RNG, identical ones would hide nothing - staging cost is the
    # same either way
    nbuf = min(8, steps)
    batches = [DataBatch(*_alexnet_batch(rng, batch))
               for _ in range(nbuf)]
    for i in range(2):  # warmup
        trainer.update(batches[i % nbuf])
    jax.block_until_ready(trainer.state)

    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.update(batches[i % nbuf])
    jax.block_until_ready(trainer.state)
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    return steps * batch / dt


def _bench_attention(platform: str) -> dict:
    """Flash-attention kernel micro-bench (TPU only): fwd+bwd TFLOP/s
    for the Pallas kernel vs the XLA blockwise path on a transformer
    shape (b4 h8 s4096 d128, bf16). This is the kernel's on-hardware
    validation - the sandbox's CPU mesh can only run it in interpret
    mode - so a kernel failure degrades to an error field, never kills
    the headline bench. Disable with CXN_BENCH_ATTN=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_ATTN") == "0":
        return {}
    try:
        import jax
        import jax.numpy as jnp
        from cxxnet_tpu.ops.attention import blockwise_attention
        from cxxnet_tpu.ops.pallas_attention import flash_attention

        b, h, s, d = 4, 8, 4096, 128
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
                   for _ in range(3))
        # fwd 2 matmuls (4bhs^2d flops) + bwd 5 matmuls (10bhs^2d)
        flops = 14.0 * b * h * s * s * d
        steps = 10

        def measure(core):
            # all three grads: argnums=0 alone would let XLA dead-code
            # the dK/dV matmuls out of the XLA path while the fused
            # Pallas bwd computes them regardless, skewing the ratio
            f = jax.jit(jax.grad(
                lambda q, k, v: core(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2)))
            g = f(q, k, v)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(steps):
                g = f(q, k, v)
            jax.block_until_ready(g)
            return steps * flops / (time.perf_counter() - t0) / 1e12

        pallas_tf = measure(
            lambda q, k, v: flash_attention(q, k, v, False, None, False))
        xla_tf = measure(
            lambda q, k, v: blockwise_attention(q, k, v, kv_block=512))
        return {"attn_pallas_tflops": round(pallas_tf, 2),
                "attn_xla_tflops": round(xla_tf, 2),
                "attn_pallas_speedup": round(pallas_tf / xla_tf, 3)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"attn_error": f"{type(e).__name__}: {e}"}


def _bench_top_ops(trainer, batch, platform: str) -> dict:
    """Compact device profile of the already-compiled e2e step (TPU
    only; no extra compile): 8 profiled updates -> top-5 ops by device
    time as [[name, pct], ...]. The driver records the JSON artifact,
    so this lands the step's time breakdown on every on-chip bench run.
    Disable with CXN_BENCH_PROFILE=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_PROFILE") == "0":
        return {}
    try:
        import glob
        import tempfile

        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.tools.profile_step import op_table
        rng = np.random.RandomState(2)
        db = DataBatch(*_alexnet_batch(rng, batch))
        d = tempfile.mkdtemp(prefix="cxn_bench_prof_")
        try:
            jax.profiler.start_trace(d)
            for _ in range(8):
                trainer.update(db)
            jax.block_until_ready(trainer.state)
            jax.profiler.stop_trace()
            xp = glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                           recursive=True)
            rows, total = op_table(xp[0], top=5)
        finally:
            import shutil
            shutil.rmtree(d, ignore_errors=True)
        return {"top_ops": [[n[:60], round(100.0 * ns / max(total, 1), 1)]
                            for n, ns in rows],
                "profiled_device_ms": round(total / 1e6, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"profile_error": f"{type(e).__name__}: {e}"}


def _bench_input_split(trainer, batch, platform: str) -> dict:
    """Host-prep vs device-step split (no extra compile) + host-side
    augmentation throughput - the numbers behind the device-side-
    augmentation go/no-go (docs/perf.md).

    - host_prep_ms_p50 / device_step_ms_p50: a short profile=1 loop
      through trainer.update() (pad + cast + H2D stage vs blocked
      device step). profile=1 serializes the async overlap, so this
      runs AFTER the headline e2e loop, on its own steps.
    - augment_ips: single-thread images/sec of the imgbin hot path per
      image - random 256->227 crop + mirror + mean-image subtract
      (io/augment.py:278-302) - measured on the bench host, so the
      artifact records whether CPU-side augmentation can keep up with
      the chip's e2e rate (augment_ips x decode threads vs value).
    Disable with CXN_BENCH_SPLIT=0."""
    if os.environ.get("CXN_BENCH_SPLIT") == "0":
        return {}
    try:
        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.profiler import StepProfiler
        rng = np.random.RandomState(3)
        db = DataBatch(*_alexnet_batch(rng, batch))
        prof = StepProfiler()
        old_profile, old_profiler = trainer.profile, trainer.profiler
        trainer.profile, trainer.profiler = 1, prof
        try:
            n = 8 if platform == "tpu" else 2
            trainer.update(db)  # warm the profiled path
            prof.reset()
            for _ in range(n):
                trainer.update(db)
            jax.block_until_ready(trainer.state)
        finally:
            trainer.profile, trainer.profiler = old_profile, old_profiler
        out = {}
        if prof.step_s and prof.data_s:
            host = float(np.percentile(prof.data_s, 50) * 1e3)
            dev = float(np.percentile(prof.step_s, 50) * 1e3)
            out.update(host_prep_ms_p50=round(host, 2),
                       device_step_ms_p50=round(dev, 2),
                       host_over_device=round(host / max(dev, 1e-9), 3))

        # augment hot path, per image, single thread: drive the REAL
        # AugmentIterator._set_data (mean-image subtract, contrast/
        # illumination, rand crop, mirror, scale) on the AlexNet.conf
        # recipe - an inline transcription would silently drift from
        # the pipeline this number gates (docs/perf.md go/no-go rule)
        from cxxnet_tpu.io.augment import AugmentIterator
        from cxxnet_tpu.io.data import DataInst

        class _Base:  # _set_data never touches the base iterator
            def set_param(self, name, val):
                pass

        it = AugmentIterator(_Base())
        for kv in (("input_shape", "3,227,227"), ("rand_crop", "1"),
                   ("rand_mirror", "1")):
            it.set_param(*kv)
        it.meanimg = rng.randn(3, 256, 256).astype(np.float32)
        insts = [DataInst(index=i, data=im, label=np.zeros(1, np.float32))
                 for i, im in enumerate(
                     rng.randint(0, 256, (32, 3, 256, 256))
                     .astype(np.float32))]
        t0 = time.perf_counter()
        reps = 4
        for _ in range(reps):
            for inst in insts:
                it._set_data(inst)
                it.value()
        dt = time.perf_counter() - t0
        out["augment_ips"] = round(reps * len(insts) / dt, 1)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"split_error": f"{type(e).__name__}: {e}"}


def _bench_stage_f32(trainer, batch, steps, platform: str) -> dict:
    """e2e with `stage_dtype = float32`: stage f32 (2x H2D bytes) and
    let the jitted step cast to bf16 ON DEVICE (fused into the first
    conv) instead of the host-side ml_dtypes cast (~70 ms single-thread
    for an AlexNet b256 batch - potentially several device-steps'
    worth). Whichever of `value` vs `e2e_f32stage_ips` wins tells
    round 5 which side of the host-CPU/link trade this environment
    sits on. Costs one retrace of the same step for the f32 aval.
    TPU only (the host-vs-link trade does not exist on the CPU
    backend, and the f32-aval retrace is a second full compile the
    fallback budget cannot afford). Disable with CXN_BENCH_STAGEF32=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_STAGEF32") == "0":
        return {}
    try:
        if trainer.compute_dtype == np.float32:
            return {}  # f32 compute already stages f32; nothing to vary
        trainer.stage_dtype = "float32"
        try:
            ips = _measure_e2e(trainer, batch, steps)
        finally:
            trainer.stage_dtype = ""
        return {"e2e_f32stage_ips": round(ips, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"stage_f32_error": f"{type(e).__name__}: {e}"}


def _bench_device_augment(batch, steps, platform: str) -> dict:
    """e2e with `device_augment = 1`: raw 3x256x256 uint8 batches
    (50 MB H2D vs 79 MB bf16 / 158 MB f32 crops) with crop / mirror /
    mean / scale fused into the jitted step - the measured AFTER for
    the device-side-augmentation go/no-go (docs/perf.md): compare
    `device_augment_ips` against `value` (host-prepped crops) and the
    host augment ceiling (`augment_ips` x cores). TPU only (one more
    full compile). Disable with CXN_BENCH_DAUG=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_DAUG") == "0":
        return {}
    try:
        import jax
        from __graft_entry__ import _ALEXNET_CONF, _make_trainer
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.config import parse_config_file
        tr = _make_trainer(
            parse_config_file(_ALEXNET_CONF),
            [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
             ("eval_train", "0"), ("save_model", "0"),
             ("device_augment", "1"), ("rand_crop", "1"),
             ("rand_mirror", "1"), ("mean_value", "104,117,123"),
             ("image_mean", "")])
        rng = np.random.RandomState(5)
        nbuf = min(8, steps)
        batches = [DataBatch(
            data=rng.randint(0, 256, (batch, 3, 256, 256),
                             dtype=np.uint8).astype(np.uint8),
            label=rng.randint(0, 1000, (batch, 1)).astype(np.float32))
            for _ in range(nbuf)]
        for i in range(2):
            tr.update(batches[i % nbuf])
        jax.block_until_ready(tr.state)
        t0 = time.perf_counter()
        for i in range(steps):
            tr.update(batches[i % nbuf])
        jax.block_until_ready(tr.state)
        dt = time.perf_counter() - t0
        return {"device_augment_ips": round(steps * batch / dt, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"device_augment_error": f"{type(e).__name__}: {e}"}


def _bench_googlenet(batch, steps, platform: str) -> dict:
    """Second model family (BASELINE config #5): GoogLeNet e2e
    images/sec at reduced steps - the concat-heavy inception graph
    stresses fusion patterns AlexNet doesn't. TPU only (a b256
    inception compile+run on the host CPU would blow the whole
    watchdog budget). Disable with CXN_BENCH_GOOGLENET=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_GOOGLENET") == "0":
        return {}
    try:
        import jax
        from __graft_entry__ import _make_trainer
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.config import parse_config_file
        conf = os.path.join(_REPO, "examples", "ImageNet",
                            "GoogLeNet.conf")
        tr = _make_trainer(
            parse_config_file(conf),
            [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
             ("eval_train", "0"), ("save_model", "0")])
        rng = np.random.RandomState(4)
        db = DataBatch(
            data=rng.randn(batch, 3, 224, 224).astype(np.float32),
            label=rng.randint(0, 1000, (batch, 1)).astype(np.float32))
        gsteps = max(2, steps // 5)
        for _ in range(2):
            tr.update(db)
        jax.block_until_ready(tr.state)
        t0 = time.perf_counter()
        for _ in range(gsteps):
            tr.update(db)
        jax.block_until_ready(tr.state)
        dt = time.perf_counter() - t0
        return {"googlenet_ips": round(gsteps * batch / dt, 2),
                "googlenet_steps": gsteps}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"googlenet_error": f"{type(e).__name__}: {e}"}


def _bench_chip_matmul(platform: str) -> dict:
    """Pure-matmul sustained TFLOP/s: 64 chained 4096^2 bf16 matmuls
    inside ONE jitted lax.scan, so per-call dispatch latency (measured
    ~3.3 ms through the tunnel - longer than the matmul itself)
    cannot bound the number. Grounds the MFU story: if the chip
    sustains near its spec peak here but AlexNet's step runs far
    below, the gap is model-shape-bound (conv1 11x11/s4, LRN, pools),
    not a chip or runtime artifact. TPU only; no readbacks. Disable
    with CXN_BENCH_MATMUL=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_MATMUL") == "0":
        return {}
    try:
        import jax
        import jax.numpy as jnp
        from jax import lax
        n, chain = 4096, 64

        def body(x, _):
            return (x @ x) * (1.0 / n), None

        @jax.jit
        def run(x):
            y, _ = lax.scan(body, x, None, length=chain)
            return y

        x = jnp.full((n, n), 1.0, jnp.bfloat16)
        jax.block_until_ready(run(x))
        reps = 5
        t0 = time.perf_counter()
        y = x
        for _ in range(reps):
            y = run(y)
        jax.block_until_ready(y)
        dt = time.perf_counter() - t0
        tflops = reps * chain * 2.0 * n ** 3 / dt / 1e12
        return {"chip_matmul_tflops": round(tflops, 1)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"matmul_probe_error": f"{type(e).__name__}: {e}"}


def _bench_pool_winner(make, batch, steps, platform: str) -> dict:
    """Compute-path throughput with `pool_grad = winner` (XLA's native
    single-winner max-pool backward) vs the default reference
    tie-duplicating rule - the flagship-level answer to whether the
    tie rule's ky*kx shifted-compare HBM traffic is a real cost on
    silicon (tools/bench_pool.py gives the per-shape view; CPU showed
    winner 2.2-2.9x faster per pool). One extra compile; TPU only.
    Disable with CXN_BENCH_POOLWINNER=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_POOLWINNER") == "0":
        return {}
    try:
        tr = make(0, [("pool_grad", "winner")])
        return {"compute_poolwinner_ips":
                round(_measure_compute(tr, batch, steps), 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"pool_winner_error": f"{type(e).__name__}: {e}"}


def _bench_eval_train(make, batch, steps) -> dict:
    """eval_train=1 (the reference's default mode): the conf's metric
    lines (error, rec@1, rec@5) compile into the step as device-side
    accumulators. Needs a SECOND full AlexNet compile, which is why it
    runs after the other throughput extras - if the watchdog budget
    dies here, every headline and extra before it is already
    snapshotted (only the profiler fetch, which needs no compile,
    comes later). Disable with CXN_BENCH_EVALTRAIN=0."""
    if os.environ.get("CXN_BENCH_EVALTRAIN") == "0":
        return {}
    try:
        trainer_m = make(1)
        return {"e2e_eval_train_ips":
                round(_measure_e2e(trainer_m, batch, steps), 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"eval_train_error": f"{type(e).__name__}: {e}"}


def _setup_compile_cache(platform: str = "") -> None:
    """Repo-local persistent XLA compile cache: AlexNet-sized TPU
    compiles cost 20-40 s each; the repo dir persists across rounds, so
    cached executables turn the watchdog budget into measurement time.
    TPU entries live at the cache root (device-targeted, host-
    independent). CPU entries are scoped per host-CPU fingerprint:
    XLA:CPU AOT results baked for another machine's features load with
    SIGILL warnings (seen round 4), and a bench crash is worse than a
    recompile. Disable with CXN_BENCH_CACHE=0."""
    try:
        from cxxnet_tpu.utils.platform import setup_scoped_cache
        setup_scoped_cache(platform)
    except Exception as e:  # noqa: BLE001 - cache is an optimization
        sys.stderr.write(f"bench: compile cache unavailable: {e}\n")


def _reexec_cpu(reason: str) -> None:
    """Re-exec this process onto the CPU backend (the only escape from
    a PJRT client init hanging in C with signals undeliverable). On
    execve failure it RETURNS (with a stderr note) so the caller can
    fall through to its own degradation path."""
    sys.stderr.write(f"bench: {reason}; re-exec on CPU\n")
    sys.stderr.flush()
    prior = os.environ.get("JAX_PLATFORMS", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CXN_BENCH_FALLBACK="1",
               CXN_BENCH_FALLBACK_FROM=prior or "default")
    try:
        os.execve(sys.executable,
                  [sys.executable, _BENCH_PATH] + sys.argv[1:], env)
    except OSError as e:
        sys.stderr.write(f"bench: re-exec failed: {e}\n")


def _probe_backend_or_reexec() -> None:
    """90 s SUBPROCESS probe of backend init before this process
    commits to it. A wedged tunnel hangs PJRT client creation
    unkillably (observed round 4: hung for hours); without the probe
    the watchdog burns its whole budget discovering that, leaving the
    CPU fallback to start with nothing. The probe child can be
    killed, so a dead tunnel costs ~90 s instead of the full budget.
    A healthy tunnel costs one extra client init (~10 s). Skipped on
    the fallback run and under an explicit cpu platform. Disable with
    CXN_BENCH_PROBE=0."""
    if (os.environ.get("CXN_BENCH_PROBE") == "0"
            or os.environ.get("CXN_BENCH_FALLBACK") == "1"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        return
    import subprocess
    try:
        rc = subprocess.run(
            [sys.executable, "-c",
             "from cxxnet_tpu.utils.platform import ensure_env_platform;"
             "ensure_env_platform();"
             "import jax; jax.devices()"],
            timeout=float(os.environ.get("CXN_BENCH_PROBE_S", "90")),
            cwd=_REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL).returncode
    except subprocess.TimeoutExpired:
        _reexec_cpu("backend probe hung (wedged tunnel?)")
        # reached only when the re-exec failed: proceed on the original
        # backend and let the in-process retry + watchdog degrade
        return
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        sys.stderr.write(f"bench: backend probe skipped: {e}\n")
        return
    if rc != 0:
        # init ERRORS (not hangs) are retried in-process by run();
        # don't fall back on a possibly-transient failure
        sys.stderr.write(f"bench: backend probe exited rc={rc}; "
                         "proceeding (in-process retry)\n")


def run(profile_dir="", steps_override=0, batch_override=0) -> dict:
    import jax
    from __graft_entry__ import _ALEXNET_CONF, _make_trainer
    from cxxnet_tpu.utils.config import parse_config_file

    # an explicit JAX_PLATFORMS env must actually win: a bare
    # jax.devices() initializes every registered plugin, including a
    # possibly-dead tunnel (utils/platform.py)
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()
    _probe_backend_or_reexec()
    # backend init is the one step that touches the (possibly tunneled)
    # platform - retry transient failures instead of dying rc=1
    last = None
    for attempt in range(3):
        try:
            devices = jax.devices()
            break
        except Exception as e:  # noqa: BLE001 - backend errors vary
            last = e
            time.sleep(5.0 * (attempt + 1))
    else:
        raise RuntimeError(f"jax backend unreachable: {last}")
    platform = devices[0].platform
    # after backend init so the CPU cache can be host-scoped; the cache
    # only has to be configured before the first compile
    _setup_compile_cache(platform)
    ndev = len(devices)
    kind = getattr(devices[0], "device_kind", "") or ""
    peak_tflops = next((p for sub, p in _TPU_PEAK_TFLOPS
                        if sub in kind.lower()), 0.0)

    # full headline config on an accelerator; shrunk on CPU so the
    # harness stays runnable anywhere (still the same code path -
    # AlexNet b256 on a host CPU would take tens of minutes)
    batch = batch_override or (256 if platform != "cpu" else 8)
    steps = steps_override or (50 if platform != "cpu" else 2)

    def make(eval_train, extra=()):
        return _make_trainer(
            parse_config_file(_ALEXNET_CONF),
            [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
             ("eval_train", str(eval_train)), ("save_model", "0"),
             *extra])

    trainer = make(0)
    out = {
        "metric": "alexnet_b%d_%s_train_e2e" % (batch, platform),
        "unit": "images/sec",
        "platform": platform,
        "device_count": ndev,
        "device_kind": kind,
        "per_device_batch": batch // ndev,
        "steps": steps,
    }
    if os.environ.get("CXN_BENCH_FALLBACK") == "1":
        src = os.environ.get("CXN_BENCH_FALLBACK_FROM", "default")
        out["fallback"] = f"backend '{src}' hung; CPU harness run"

    # headline part 1: the compute ceiling. Snapshot immediately - a
    # completed on-chip compute number must survive anything later
    # hanging (round-3 post-mortem).
    compute_ips = _measure_compute(trainer, batch, steps)
    # compute-only snapshot carries a compute-labeled metric name: a
    # truncated artifact must not report the (always-higher) compute
    # ceiling under the e2e headline name
    out.update(metric="alexnet_b%d_%s_train_compute" % (batch, platform),
               compute_ips=round(compute_ips, 2),
               value=round(compute_ips, 2),
               vs_baseline=round(compute_ips / A100_IMAGES_PER_SEC, 4),
               value_is="compute_only")
    _snapshot(out)

    # headline part 2: end-to-end (what the reference's train loop
    # delivers, cxxnet_main.cpp:367-387) - becomes the reported value
    if profile_dir and platform == "tpu":
        # stop_trace is the same large D2H fetch as the profiler
        # extra: on the tunneled platform it stickily degrades H2D, so
        # every EXTRA after the headline is suspect under --profile
        sys.stderr.write(
            "bench: --profile captures the headline loop but its "
            "trace fetch degrades tunneled H2D; treat the extras "
            "in this run as indicative only\n")
        out["profile_note"] = "extras degraded by --profile trace fetch"
    e2e_ips = _measure_e2e(trainer, batch, steps, profile_dir)
    out.update(
        metric="alexnet_b%d_%s_train_e2e" % (batch, platform),
        value=round(e2e_ips, 2),
        vs_baseline=round(e2e_ips / A100_IMAGES_PER_SEC, 4),
        value_is="e2e",
        e2e_over_compute=round(e2e_ips / compute_ips, 4),
        achieved_tflops=round(
            e2e_ips * ALEXNET_TRAIN_GFLOP_PER_IMG / 1e3, 2))
    if peak_tflops:
        # achieved_tflops aggregates the whole slice; peak is per chip
        out.update(peak_tflops=peak_tflops,
                   mfu_pct=round(100.0 * out["achieved_tflops"]
                                 / (peak_tflops * ndev), 2))
    _snapshot(out)

    # extras, snapshot after each so a hang in extra k never costs
    # extras 1..k-1. ORDER MATTERS on the tunneled platform: every
    # throughput measurement runs BEFORE the profiler trace
    # (_bench_top_ops), whose trace collection is a large D2H fetch -
    # D2H transfers stickily degrade subsequent H2D staging to
    # ~25 MB/s (see _measure_compute), which round 4 measured as a
    # 20x e2e collapse. Nothing before the profiler may transfer
    # device data to the host.
    out.update(_bench_stage_f32(trainer, batch, steps, platform))
    _snapshot(out)
    out.update(_bench_device_augment(batch, steps, platform))
    _snapshot(out)
    out.update(_bench_googlenet(batch, steps, platform))
    _snapshot(out)
    out.update(_bench_pool_winner(make, batch, steps, platform))
    _snapshot(out)
    out.update(_bench_chip_matmul(platform))
    _snapshot(out)
    out.update(_bench_input_split(trainer, batch, platform))
    _snapshot(out)
    out.update(_bench_attention(platform))
    _snapshot(out)
    out.update(_bench_eval_train(make, batch, steps))
    _snapshot(out)
    out.update(_bench_top_ops(trainer, batch, platform))
    _snapshot(out)
    return out


def _error_json(msg: str) -> str:
    return json.dumps({"metric": "alexnet_train_e2e", "value": 0.0,
                       "unit": "images/sec", "vs_baseline": 0.0,
                       "error": msg})


def main(argv) -> int:
    try:
        profile_dir = ""
        steps = 0
        if "--profile" in argv:
            profile_dir = argv[argv.index("--profile") + 1]
        if "--steps" in argv:
            steps = int(argv[argv.index("--steps") + 1])
        budget = int(os.environ.get("CXN_BENCH_TIMEOUT", "480"))
    except Exception as e:  # noqa: BLE001 - the JSON line is the contract
        print(_error_json(f"bad arguments {argv}: {e}"))
        return 0

    def watchdog():
        # a hung PJRT client creation blocks in C with the GIL state
        # such that signals never run - escaping from a daemon thread
        # is the only reliable move. If ANY headline number is already
        # measured (budget ran out mid-extras or mid-e2e), print the
        # snapshot and exit clean. Otherwise, first occurrence:
        # re-exec the whole process onto the CPU backend so the harness
        # still produces a real (clearly-labeled) number; second
        # occurrence: emit the error artifact and exit cleanly.
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return  # main thread already printed the full result
            if _PARTIAL.get("value"):
                _PARTIAL["emitted"] = True
                _PARTIAL["truncated"] = (
                    f"cut at the {budget}s watchdog")
                print(json.dumps(
                    {k: v for k, v in _PARTIAL.items()
                     if k != "emitted"}), flush=True)
                os._exit(0)
        if (os.environ.get("CXN_BENCH_FALLBACK") != "1"
                and os.environ.get("JAX_PLATFORMS", "") != "cpu"):
            _reexec_cpu(f"backend hung for {budget}s")
        print(_error_json(f"benchmark exceeded {budget}s "
                          "(hung backend / stuck tunnel?)"), flush=True)
        os._exit(0)

    if budget > 0:
        t = threading.Timer(budget, watchdog)
        t.daemon = True
        t.start()
    try:
        out = run(profile_dir, steps)
        # claim the single JSON line under the lock: a timer firing in
        # this window must neither double-print nor mislabel a full
        # run as truncated
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return 0  # watchdog already printed the partial line
            _PARTIAL["emitted"] = True
    except BaseException as e:  # noqa: BLE001 - always emit the JSON line
        # a CRASH after a completed measurement must emit the snapshot,
        # not a value=0.0 artifact (round-3 post-mortem: a late error
        # zeroed a whole round); claim the line under the lock so a
        # concurrently-firing watchdog cannot double-print
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return 0
            _PARTIAL["emitted"] = True
            if _PARTIAL.get("value"):
                _PARTIAL["truncated"] = (
                    f"crashed mid-run: {type(e).__name__}: {e}")
                print(json.dumps(
                    {k: v for k, v in _PARTIAL.items()
                     if k != "emitted"}), flush=True)
                return 0
        print(_error_json(f"{type(e).__name__}: {e}"))
        return 0
    finally:
        if budget > 0:
            t.cancel()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
