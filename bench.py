"""Headline benchmark: AlexNet training throughput (images/sec) on the
available accelerator, synthetic data (the reference publishes no
quantitative baseline — BASELINE.md — so the driver-supplied target is
per-chip A100 images/sec; A100_IMAGES_PER_SEC below is the comparison
constant).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

# Approximate per-chip A100 AlexNet training throughput (batch 256,
# synthetic data, mixed precision). The reference repo publishes no
# numbers (BASELINE.md); this constant anchors vs_baseline at the
# BASELINE.json target "≥90% of per-chip A100 images/sec".
A100_IMAGES_PER_SEC = 10000.0


def main() -> int:
    from __graft_entry__ import _ALEXNET_CONF, _make_trainer
    from cxxnet_tpu.utils.config import parse_config_file

    platform = jax.devices()[0].platform
    # full headline config on an accelerator; shrunk on CPU so the
    # harness stays runnable anywhere (still the same code path)
    batch = 256 if platform != "cpu" else 16
    steps = 50 if platform != "cpu" else 3
    trainer = _make_trainer(
        parse_config_file(_ALEXNET_CONF),
        [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
         ("eval_train", "0"), ("save_model", "0")])

    rng = np.random.RandomState(0)
    data = jax.device_put(
        rng.randn(batch, 3, 227, 227).astype(np.float32),
        trainer._batch_sharded)
    label = jax.device_put(
        rng.randint(0, 1000, size=(batch, 1)).astype(np.float32),
        trainer._batch_sharded)
    mask = jax.device_put(np.ones(batch, np.float32),
                          trainer._batch_sharded)
    labels = {"label": label}
    key = jax.random.PRNGKey(0)

    state = trainer.state
    # warmup (compile + first run); the host readback of the loss forces
    # true completion — block_until_ready alone does not flush the
    # dispatch queue on tunneled platforms
    for i in range(3):
        state, loss, _ = trainer._train_step(
            state, data, labels, mask, jax.random.fold_in(key, i))
    float(np.asarray(loss))

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss, _ = trainer._train_step(
            state, data, labels, mask, jax.random.fold_in(key, i))
    float(np.asarray(loss))
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    ips = steps * batch / dt
    print(json.dumps({
        "metric": "alexnet_b%d_%s_train" % (batch, platform),
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / A100_IMAGES_PER_SEC, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
