"""Headline benchmark: AlexNet training throughput (images/sec).

Two numbers are measured on the same trainer:

- ``compute``:  the jitted train step driven on pre-staged device
  buffers - the kernel/compiler ceiling, what BENCH_r02 measured.
- ``e2e``:      the full product path the reference times
  (cxxnet_main.cpp:367-387): ``trainer.update()`` fed per-step from
  host batches - includes padding, H2D staging, the on-device metric
  accumulation, and the optimizer, i.e. what a user actually gets.

The headline ``value`` is the END-TO-END number. Extras (each optional,
each snapshotted, each individually guarded so a failure degrades to an
``*_error`` field instead of killing the headline) record:

- ``top_ops``/``profiled_device_ms``: top-5 device ops of the compiled
  e2e step (where the step time goes).
- ``host_prep_ms_p50``/``device_step_ms_p50``/``augment_ips``: the
  input-pipeline split - is training host-bound or device-bound, and
  can host-side crop/mirror/mean augmentation keep up with the chip
  (the device-side-augmentation go/no-go in docs/perf.md).
- ``attn_*``: Pallas flash-attention kernel vs the XLA blockwise path
  (fwd+bwd TFLOP/s) - the kernel's on-silicon validation.
- ``googlenet_ips`` / ``resnet18_ips`` (+ ``*_devicedata_ips``):
  additional model families - GoogLeNet (BASELINE config #5,
  concat-heavy inception graph) and ResNet-18 (residual adds +
  per-shard batch norm; last in the registry).
- ``e2e_eval_train_ips``: eval_train=1 (the reference's default mode)
  with device-side metric accumulators compiled into the step. Needs a
  second full AlexNet compile -> a deliberately late, expendable
  extra.

Partial-result discipline: ``_PARTIAL`` is snapshotted after EVERY
measurement (compute first). If the watchdog fires mid-run, it emits
whatever is complete rather than re-exec'ing away a finished on-chip
number (round-3 post-mortem: a late crash zeroed a whole round's
artifact).

Compilation cache: a repo-local ``jax_compilation_cache_dir``
(``.jax_cache/``, gitignored) persists XLA executables across runs and
rounds, so repeat AlexNet/GoogLeNet compiles are near-instant and the
watchdog budget buys measurements, not recompiles. Disable with
``CXN_BENCH_CACHE=0``.

Prints ONE JSON line even when the backend is unreachable
(``{"metric": ..., "error": ...}``) - a backend hiccup must yield a
diagnosable artifact, not rc=1.

Baseline constant: the reference publishes no numbers (BASELINE.md), and
this sandbox has no A100 (and no egress to cite one), so the A100
anchor is an arithmetic estimate, documented at the constant. The
``achieved_tflops``/``mfu_pct`` fields ground the perf claim in the
chip's own peak instead.

Usage: python bench.py [--profile DIR] [--steps N]
    --profile DIR  additionally capture a jax.profiler trace of the
                   steady-state e2e loop into DIR.

A watchdog thread (CXN_BENCH_TIMEOUT, default 480 s) handles a hung
backend (e.g. a stuck tunnel lease blocking inside PJRT client
creation, where no Python signal can ever be delivered): if headline
numbers exist it prints them; else the first occurrence re-execs the
process onto the CPU backend so a real, clearly-labeled number (JSON
field "fallback") is still produced; if already on CPU (or the re-exec
fails) it prints the error JSON line and exits cleanly instead of
dying rc-143 with no artifact.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

# AlexNet training flops/image ~= 0.72 GMAC fwd x 2 flop/MAC x 3
# (fwd + dgrad + wgrad) ~= 4.3 GFLOP. A100 bf16 peak = 312 TFLOP/s;
# AlexNet's LRN/pooling/fc mix sustains well under full MFU - assume
# ~15%, in line with public convnet training MFU on Ampere, giving
# 312e12 * 0.15 / 4.3e9 ~= 10.9k img/s; rounded to 10k. An estimate,
# not a measurement: no A100 exists here and the reference publishes
# no throughput numbers (BASELINE.md).
A100_IMAGES_PER_SEC = 10000.0
ALEXNET_TRAIN_GFLOP_PER_IMG = 4.3

# bf16 peak TFLOP/s by device_kind substring - grounds the perf claim
# in the chip's own numbers (public TPU spec sheets)
_TPU_PEAK_TFLOPS = (
    ("v6e", 918.0), ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

# resolved at import, before anything can os.chdir: the re-exec path
# must not depend on the working directory
_BENCH_PATH = os.path.abspath(__file__)
_REPO = os.path.dirname(_BENCH_PATH)


def _peak_for(device_kind: str) -> float:
    """Spec bf16 peak for a device_kind, 0.0 if unknown - the ONE
    lookup both the parent's physics caps and each child's calibration
    ceiling share (they must not desynchronize)."""
    return next((p for sub, p in _TPU_PEAK_TFLOPS
                 if sub in device_kind.lower()), 0.0)


def _default_workload(platform: str, batch: int, steps: int):
    """Benchmark size defaults, shared by run() and the --only child
    path (full headline config on an accelerator; shrunk on CPU so the
    harness stays runnable anywhere - same code path either way)."""
    return (batch or (256 if platform != "cpu" else 8),
            steps or (50 if platform != "cpu" else 2))

# headline results land here as soon as they are measured; the watchdog
# prints these instead of throwing away a completed on-chip measurement
# with a CPU re-exec. _EMIT_LOCK serializes the "who prints the one
# JSON line" decision between the main thread and the watchdog timer.
_PARTIAL: dict = {}
_EMIT_LOCK = threading.Lock()

# the isolated-measurement child currently in flight, if any: the
# watchdog must kill it before os._exit - an orphaned child (spawned
# with CXN_BENCH_TIMEOUT=0, no parent left to enforce its timeout)
# wedged inside PJRT would hold the exclusive TPU forever. Spawn and
# kill are serialized under _EMIT_LOCK with _SHUTTING_DOWN so the
# main thread cannot spawn child B while the watchdog is between
# killing child A and exiting (B would be exactly such an orphan).
_CURRENT_CHILD = None
_SHUTTING_DOWN = False

# absolute monotonic instant the watchdog will fire, set by main()
# the moment it starts the Timer so run()'s isolation deadline and
# the watchdog share ONE clock (anchoring the deadline inside run()
# would silently donate the backend probe / PJRT init / calibration
# time - up to ~2 min - to the margin and race the watchdog)
_WATCHDOG_FIRE_AT = float("inf")


def _snapshot(out: dict) -> None:
    """Checkpoint the result dict so the watchdog can emit it as-is.
    REPLACES the previous snapshot rather than merging: keys the
    caller retracted (physics caps, run2 demotion renames) must not be
    resurrected in a crash- or watchdog-emitted artifact. The
    'emitted' print-claim flag is the one key that survives."""
    with _EMIT_LOCK:
        emitted = _PARTIAL.get("emitted")
        _PARTIAL.clear()
        _PARTIAL.update(out)
        if emitted:
            _PARTIAL["emitted"] = True
    # archive incrementally (outside the lock - file IO must not
    # stall the watchdog): numbers measured before a mid-run wedge
    # reach docs/last_good_tpu.json even if run() never returns
    try:
        _save_last_good(out)
    except Exception as e:  # noqa: BLE001 - archiving is best-effort
        sys.stderr.write(f"bench: last-good archive failed: {e}\n")


# How a measurement waits for the device. "block" = jax.block_until_ready
# is trusted (CPU, and TPU boots where it works). "readback" = the tunnel
# silently turns block_until_ready AND arr.is_ready() into no-ops
# (observed round 4: a 64-matmul scan "completed" in 0.2 ms, implying
# 50,000+ TFLOP/s on a 197-TFLOP/s chip), so the only true sync is a
# scalar D2H readback - which is accurate, but stickily degrades all
# later H2D staging in the process to ~21 MB/s. The readback mode
# therefore pairs with per-measurement subprocess isolation (fresh PJRT
# client per measurement; the poison is per-process).
_SYNC_MODE = "block"


def _readback_sync(x):
    """The readback sync primitive, shared with the tool modules
    (cxxnet_tpu.tools.bench_attn imports it): fetching ONE element of
    the last leaf forces the whole dispatched execution to complete
    (PJRT finishes an executable's outputs as a unit); bytes moved: 1
    element. Correct in every observed tunnel window, but stickily
    poisons the process's H2D - time its placement accordingly."""
    import jax
    import jax.numpy as jnp
    leaves = [l for l in jax.tree_util.tree_leaves(x)
              if hasattr(l, "dtype") and getattr(l, "size", 0)]
    if leaves:
        np.asarray(jnp.ravel(leaves[-1])[0])
    return x


def _sync(x):
    """Wait until the computation producing pytree ``x`` has finished."""
    import jax
    if _SYNC_MODE != "readback":
        return jax.block_until_ready(x)
    return _readback_sync(x)


def _warm_sync(x):
    """Post-warmup sync. In readback mode this is a NO-OP on purpose:
    a warmup readback would poison the H2D link the timed loop is
    about to measure. The 1-2 warmup steps' device tail then bleeds
    into the timed region - bounded by ~2 device steps, negligible
    against a 50-step loop - while the compile itself still happens
    host-side during the warmup dispatch."""
    import jax
    if _SYNC_MODE != "readback":
        jax.block_until_ready(x)
    return x


# the shared physics probe: one jitted 8-long 4096^2 bf16 matmul chain
_PROBE_CHAIN = 8
_PROBE_FLOPS = _PROBE_CHAIN * 2.0 * 4096 ** 3
_probe_fn = None


def _chain_probe():
    """(jitted fn, input) for the calibration/verification probe -
    built once per process so verification reuses the compiled
    executable from calibration."""
    global _probe_fn
    import jax
    import jax.numpy as jnp
    if _probe_fn is None:
        @jax.jit
        def run(x):
            def body(c, _):
                return (c @ c) * 2e-4, None
            y, _ = jax.lax.scan(body, x, None, length=_PROBE_CHAIN)
            return y

        _probe_fn = run
    return _probe_fn, jnp.full((4096, 4096), 0.07, jnp.bfloat16)


def _calibrate_sync(platform: str, peak_tflops: float) -> dict:
    """Decide the sync mode by physics: time the probe chain under
    block_until_ready; if the implied TFLOP/s exceeds 3x the chip's
    spec peak, blocking is a no-op and every blocked timing would
    measure dispatch, not compute (the round-4 artifact that
    "measured" 206k img/s compute and 355,311 TFLOP/s).

    The tunnel's semantics DRIFT within a boot (observed: the same
    --only compute child returned 160k img/s in one window - readback
    returning without waiting - and 4.7k img/s twenty minutes later),
    so every isolated child re-calibrates for itself, and verifies the
    readback AFTER its measurement (_verify_readback_sync).
    CXN_BENCH_SYNC=block|readback overrides the decision."""
    global _SYNC_MODE
    forced = os.environ.get("CXN_BENCH_SYNC", "")
    if forced and forced not in ("block", "readback"):
        sys.stderr.write(
            f"bench: ignoring unknown CXN_BENCH_SYNC={forced!r} "
            "(expected 'block' or 'readback')\n")
        forced = ""
    if forced:
        _SYNC_MODE = forced
        return {"sync_mode": forced}
    if platform != "tpu":
        return {}
    try:
        import jax
        run, x = _chain_probe()
        jax.block_until_ready(run(x))  # compile + warm
        t0 = time.perf_counter()
        jax.block_until_ready(run(x))
        dt = max(time.perf_counter() - t0, 1e-9)
        implied = _PROBE_FLOPS / dt / 1e12
        ceiling = 3.0 * (peak_tflops or 1000.0)
        _SYNC_MODE = "readback" if implied > ceiling else "block"
        return {"sync_mode": _SYNC_MODE,
                "sync_probe_tflops": round(implied, 1)}
    except Exception as e:  # noqa: BLE001 - stay on the safe default
        sys.stderr.write(f"bench: sync calibration failed: {e}\n")
        return {"sync_mode": _SYNC_MODE}


def _verify_readback_sync(peak_tflops: float) -> bool:
    """Time a READBACK-synced probe chain; True iff the implied
    TFLOP/s is physically possible, i.e. the readback actually waited.
    POISONS the process's H2D link (~21 MB/s sticky) - call only
    AFTER all measurement work, which also means it samples the same
    window the measurement just ran in. A child whose verification
    fails reports *_sync=readback_unverified and the parent treats
    its numbers as dispatch timing when picking between runs."""
    try:
        import jax
        import jax.numpy as jnp
        run, x = _chain_probe()
        run(x)  # ensure compiled/warm (no-op if calibration ran)
        t0 = time.perf_counter()
        np.asarray(jnp.ravel(run(x))[0])
        dt = max(time.perf_counter() - t0, 1e-9)
        implied = _PROBE_FLOPS / dt / 1e12
        return implied <= 3.0 * (peak_tflops or 1000.0)
    except Exception as e:  # noqa: BLE001 - unverifiable, say so
        sys.stderr.write(f"bench: readback verification failed: {e}\n")
        return False


def _alexnet_batch(rng, batch):
    """The bench's input shape in ONE place (matches _ALEXNET_CONF)."""
    return (rng.randn(batch, 3, 227, 227).astype(np.float32),
            rng.randint(0, 1000, size=(batch, 1)).astype(np.float32))


def _measure_compute(trainer, batch, steps):
    """Train-step-only throughput on pre-staged device buffers.

    Staging mirrors trainer.update(): data under _data_sharded with
    the host-side compute-dtype cast (_host_input), labels/mask under
    _batch_sharded, extras the () the conf declares - the exact
    in_shardings the compiled step was built with (trainer.py _compile).
    """
    import jax
    rng = np.random.RandomState(0)
    hdata, hlabel = _alexnet_batch(rng, batch)
    data = jax.device_put(trainer._host_input(hdata),
                          trainer._data_sharded)
    label = jax.device_put(hlabel, trainer._batch_sharded)
    mask = jax.device_put(np.ones(batch, np.float32),
                          trainer._batch_sharded)
    labels = {"label": label}
    key = jax.random.PRNGKey(0)

    state = trainer.state
    # warmup (compile + first run). The sync primitive is _sync: on
    # boots where block_until_ready works it avoids any D2H (a readback
    # here once cost 48 s and stickily degraded H2D to ~25 MB/s); on
    # boots where block_until_ready is a no-op (round 4: dispatch-only
    # timing implied 206k img/s) _sync falls back to a one-element
    # readback, and measurements run in isolated subprocesses so the
    # poison cannot cross. Inputs are already staged, so a readback
    # sync is harmless for THIS measurement either way.
    for i in range(3):
        state, loss = trainer._train_step(
            state, data, (), labels, mask, jax.random.fold_in(key, i))
    _sync(loss)

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss = trainer._train_step(
            state, data, (), labels, mask, jax.random.fold_in(key, i))
    # ONE sync: loss and state come from the same executable, which
    # PJRT completes as a unit - a second readback here would sit
    # inside the timed window and deflate compute_ips in readback mode
    _sync(loss)
    dt = time.perf_counter() - t0
    trainer.state = state
    return steps * batch / dt


def _warm_and_size(trainer, step_fn, steps, budget_s, floor=4):
    """Shared warmup + window-sizing for every host-paced (H2D) loop:
    compile + first step, ONE timed step to estimate this window's
    per-step cost (the tunnel link varies ~40x between windows - a
    fixed 50 steps is 10 s in a good window and a child-timeout in a
    bad one), then return how many steps fit budget_s (capped at
    `steps`, floored at `floor`). _warm_sync is a no-op in readback
    mode on purpose - the link must stay clean for the timed loop."""
    step_fn(0)  # compile + first step
    t0 = time.perf_counter()
    step_fn(1)
    per_step = max(time.perf_counter() - t0, 1e-6)
    _warm_sync(trainer.state)
    return int(min(steps, max(floor, budget_s / per_step)))


def _measure_e2e(trainer, batch, steps, profile_dir="", budget_s=60.0):
    """Full trainer.update() path fed from host batches.

    Returns (images_per_sec, steps_used); steps_used is window-sized
    by _warm_and_size."""
    import jax
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(1)
    # a few distinct host batches cycled through, like a RAM-resident
    # iterator (membuffer); fresh numpy arrays each step would measure
    # the RNG, identical ones would hide nothing - staging cost is the
    # same either way
    nbuf = min(8, steps)
    batches = [DataBatch(*_alexnet_batch(rng, batch))
               for _ in range(nbuf)]
    n = _warm_and_size(trainer,
                       lambda i: trainer.update(batches[i % nbuf]),
                       steps, budget_s)

    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    for i in range(n):
        trainer.update(batches[i % nbuf])
    _sync(trainer.state)
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    return n * batch / dt, n


def _bench_attention(platform: str) -> dict:
    """Flash-attention kernel micro-bench (TPU only): fwd+bwd TFLOP/s
    for the Pallas kernel vs the XLA blockwise path on a transformer
    shape (b4 h8 s4096 d128, bf16). This is the kernel's on-hardware
    validation - the sandbox's CPU mesh can only run it in interpret
    mode - so a kernel failure degrades to an error field, never kills
    the headline bench. Disable with CXN_BENCH_ATTN=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_ATTN") == "0":
        return {}
    try:
        import jax
        import jax.numpy as jnp
        from cxxnet_tpu.ops.attention import blockwise_attention
        from cxxnet_tpu.ops.pallas_attention import flash_attention

        b, h, s, d = 4, 8, 4096, 128
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
                   for _ in range(3))
        # fwd 2 matmuls (4bhs^2d flops) + bwd 5 matmuls (10bhs^2d)
        flops = 14.0 * b * h * s * s * d
        steps = 10

        def measure(core):
            # all three grads: argnums=0 alone would let XLA dead-code
            # the dK/dV matmuls out of the XLA path while the fused
            # Pallas bwd computes them regardless, skewing the ratio
            f = jax.jit(jax.grad(
                lambda q, k, v: core(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2)))
            g = f(q, k, v)
            _sync(g)  # inputs staged above: a readback sync is safe
            t0 = time.perf_counter()
            for _ in range(steps):
                g = f(q, k, v)
            _sync(g)
            return steps * flops / (time.perf_counter() - t0) / 1e12

        pallas_tf = measure(
            lambda q, k, v: flash_attention(q, k, v, False, None, False))
        xla_tf = measure(
            lambda q, k, v: blockwise_attention(q, k, v, kv_block=512))
        return {"attn_pallas_tflops": round(pallas_tf, 2),
                "attn_xla_tflops": round(xla_tf, 2),
                "attn_pallas_speedup": round(pallas_tf / xla_tf, 3)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"attn_error": f"{type(e).__name__}: {e}"}


def _bench_top_ops(trainer, batch, platform: str) -> dict:
    """Compact device profile of the already-compiled e2e step (TPU
    only; no extra compile): 8 profiled updates -> top-5 ops by device
    time as [[name, pct], ...]. The driver records the JSON artifact,
    so this lands the step's time breakdown on every on-chip bench run.
    Disable with CXN_BENCH_PROFILE=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_PROFILE") == "0":
        return {}
    try:
        import glob
        import tempfile

        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.tools.profile_step import op_table
        rng = np.random.RandomState(2)
        db = DataBatch(*_alexnet_batch(rng, batch))
        d = tempfile.mkdtemp(prefix="cxn_bench_prof_")
        try:
            jax.profiler.start_trace(d)
            for _ in range(8):
                trainer.update(db)
            # the trace must contain EXECUTED steps; in readback mode
            # this is the last measurement of its process anyway
            _sync(trainer.state)
            jax.profiler.stop_trace()
            xp = glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                           recursive=True)
            rows, total = op_table(xp[0], top=5)
        finally:
            import shutil
            shutil.rmtree(d, ignore_errors=True)
        return {"top_ops": [[n[:60], round(100.0 * ns / max(total, 1), 1)]
                            for n, ns in rows],
                "profiled_device_ms": round(total / 1e6, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"profile_error": f"{type(e).__name__}: {e}"}


def _bench_input_split(trainer, batch, platform: str) -> dict:
    """Host-prep vs device-step split (no extra compile) + host-side
    augmentation throughput - the numbers behind the device-side-
    augmentation go/no-go (docs/perf.md).

    - host_prep_ms_p50 / device_step_ms_p50: a short profile=1 loop
      through trainer.update() (pad + cast + H2D stage vs blocked
      device step). profile=1 serializes the async overlap, so this
      runs AFTER the headline e2e loop, on its own steps.
    - augment_ips: single-thread images/sec of the imgbin hot path per
      image - random 256->227 crop + mirror + mean-image subtract
      (io/augment.py:278-302) - measured on the bench host, so the
      artifact records whether CPU-side augmentation can keep up with
      the chip's e2e rate (augment_ips x decode threads vs value).
    Disable with CXN_BENCH_SPLIT=0."""
    if os.environ.get("CXN_BENCH_SPLIT") == "0":
        return {}
    try:
        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.profiler import StepProfiler
        rng = np.random.RandomState(3)
        db = DataBatch(*_alexnet_batch(rng, batch))
        prof = StepProfiler()
        old_profile, old_profiler = trainer.profile, trainer.profiler
        trainer.profile, trainer.profiler = 1, prof
        try:
            n = 8 if platform == "tpu" else 2
            trainer.update(db)  # warm the profiled path
            prof.reset()
            for _ in range(n):
                trainer.update(db)
            _sync(trainer.state)
        finally:
            trainer.profile, trainer.profiler = old_profile, old_profiler
        out = {}
        if prof.step_s and prof.data_s:
            host = float(np.percentile(prof.data_s, 50) * 1e3)
            out["host_prep_ms_p50"] = round(host, 2)
            # the profile=1 step timing blocks via block_until_ready
            # inside the trainer; when that is a no-op this boot the
            # number would be dispatch latency, not the device step -
            # omit it (host_over_device is then derived from
            # compute_ips by _derive)
            if _SYNC_MODE != "readback":
                dev = float(np.percentile(prof.step_s, 50) * 1e3)
                out.update(device_step_ms_p50=round(dev, 2),
                           host_over_device=round(
                               host / max(dev, 1e-9), 3))

        # augment hot path, per image, single thread: drive the REAL
        # AugmentIterator._set_data (mean-image subtract, contrast/
        # illumination, rand crop, mirror, scale) on the AlexNet.conf
        # recipe - an inline transcription would silently drift from
        # the pipeline this number gates (docs/perf.md go/no-go rule)
        from cxxnet_tpu.io.augment import AugmentIterator
        from cxxnet_tpu.io.data import DataInst

        class _Base:  # _set_data never touches the base iterator
            def set_param(self, name, val):
                pass

        it = AugmentIterator(_Base())
        for kv in (("input_shape", "3,227,227"), ("rand_crop", "1"),
                   ("rand_mirror", "1")):
            it.set_param(*kv)
        it.meanimg = rng.randn(3, 256, 256).astype(np.float32)
        insts = [DataInst(index=i, data=im, label=np.zeros(1, np.float32))
                 for i, im in enumerate(
                     rng.randint(0, 256, (32, 3, 256, 256))
                     .astype(np.float32))]
        t0 = time.perf_counter()
        reps = 4
        for _ in range(reps):
            for inst in insts:
                it._set_data(inst)
                it.value()
        dt = time.perf_counter() - t0
        out["augment_ips"] = round(reps * len(insts) / dt, 1)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"split_error": f"{type(e).__name__}: {e}"}


def _bench_stage_f32(trainer, batch, steps, platform: str) -> dict:
    """e2e with `stage_dtype = float32`: stage f32 (2x H2D bytes) and
    let the jitted step cast to bf16 ON DEVICE (fused into the first
    conv) instead of the host-side ml_dtypes cast (~70 ms single-thread
    for an AlexNet b256 batch - potentially several device-steps'
    worth). Whichever of `value` vs `e2e_f32stage_ips` wins tells
    round 5 which side of the host-CPU/link trade this environment
    sits on. Costs one retrace of the same step for the f32 aval.
    TPU only (the host-vs-link trade does not exist on the CPU
    backend, and the f32-aval retrace is a second full compile the
    fallback budget cannot afford). Disable with CXN_BENCH_STAGEF32=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_STAGEF32") == "0":
        return {}
    try:
        if trainer.compute_dtype == np.float32:
            return {}  # f32 compute already stages f32; nothing to vary
        trainer.stage_dtype = "float32"
        try:
            ips, n = _measure_e2e(trainer, batch, steps)
        finally:
            trainer.stage_dtype = ""
        return {"e2e_f32stage_ips": round(ips, 2), "f32stage_steps": n}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"stage_f32_error": f"{type(e).__name__}: {e}"}


def _bench_device_augment(batch, steps, platform: str) -> dict:
    """e2e with `device_augment = 1`: raw 3x256x256 uint8 batches
    (50 MB H2D vs 79 MB bf16 / 158 MB f32 crops) with crop / mirror /
    mean / scale fused into the jitted step - the measured AFTER for
    the device-side-augmentation go/no-go (docs/perf.md): compare
    `device_augment_ips` against `value` (host-prepped crops) and the
    host augment ceiling (`augment_ips` x cores). TPU only (one more
    full compile). Disable with CXN_BENCH_DAUG=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_DAUG") == "0":
        return {}
    try:
        import jax
        from __graft_entry__ import _ALEXNET_CONF, _make_trainer
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.config import parse_config_file
        tr = _make_trainer(
            parse_config_file(_ALEXNET_CONF),
            _flagship_overrides(batch, 0, (
                ("device_augment", "1"), ("rand_crop", "1"),
                ("rand_mirror", "1"), ("mean_value", "104,117,123"),
                ("image_mean", ""))))
        rng = np.random.RandomState(5)
        nbuf = min(8, steps)
        batches = [DataBatch(
            data=rng.randint(0, 256, (batch, 3, 256, 256),
                             dtype=np.uint8).astype(np.uint8),
            label=rng.randint(0, 1000, (batch, 1)).astype(np.float32))
            for _ in range(nbuf)]
        n = _warm_and_size(tr, lambda i: tr.update(batches[i % nbuf]),
                           steps, 60.0)
        t0 = time.perf_counter()
        for i in range(n):
            tr.update(batches[i % nbuf])
        _sync(tr.state)
        dt = time.perf_counter() - t0
        return {"device_augment_ips": round(n * batch / dt, 2),
                "device_augment_steps": n}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"device_augment_error": f"{type(e).__name__}: {e}"}


def _bench_model_family(conf_name, prefix, gate, batch, steps,
                        platform: str, seed: int) -> dict:
    """Shared e2e measurement for a non-flagship model family: streamed
    images/sec at reduced steps + the device-resident (staged-once)
    variant, fields named <prefix>_ips / <prefix>_devicedata_ips. TPU
    only (a b256 deep-net compile+run on the host CPU would blow the
    whole watchdog budget)."""
    if platform != "tpu" or os.environ.get(gate) == "0":
        return {}
    try:
        from __graft_entry__ import _make_trainer
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.utils.config import parse_config_file
        conf = os.path.join(_REPO, "examples", "ImageNet", conf_name)
        tr = _make_trainer(
            parse_config_file(conf),
            [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
             ("eval_train", "0"), ("save_model", "0")])
        rng = np.random.RandomState(seed)
        db = DataBatch(
            data=rng.randn(batch, 3, 224, 224).astype(np.float32),
            label=rng.randint(0, 1000, (batch, 1)).astype(np.float32))
        gsteps = _warm_and_size(tr, lambda i: tr.update(db),
                                max(2, steps // 5), 45.0, floor=2)
        t0 = time.perf_counter()
        for _ in range(gsteps):
            tr.update(db)
        _sync(tr.state)
        dt = time.perf_counter() - t0
        out = {f"{prefix}_ips": round(gsteps * batch / dt, 2),
               f"{prefix}_steps": gsteps}
        # device-resident variant (same compiled step, batch staged
        # once): the family's link-immune number, like
        # e2e_devicedata_ips for AlexNet - budget-bounded so it can
        # never push the child past its registry timeout and cost the
        # streamed number it supplements
        try:
            ips, _n = _time_staged(tr, [tr.stage_batch(db)],
                                   max(4, gsteps), batch, 25.0)
            out[f"{prefix}_devicedata_ips"] = round(ips, 2)
        except Exception as e:  # noqa: BLE001 - keep the streamed number
            out[f"{prefix}_devicedata_error"] = \
                f"{type(e).__name__}: {e}"
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {f"{prefix}_error": f"{type(e).__name__}: {e}"}


def _bench_googlenet(batch, steps, platform: str) -> dict:
    """Second model family (BASELINE config #5): GoogLeNet, the
    concat-heavy inception graph - stresses fusion patterns AlexNet
    doesn't. Disable with CXN_BENCH_GOOGLENET=0."""
    return _bench_model_family("GoogLeNet.conf", "googlenet",
                               "CXN_BENCH_GOOGLENET", batch, steps,
                               platform, seed=4)


def _bench_resnet(batch, steps, platform: str) -> dict:
    """Third model family: ResNet-18 (examples/ImageNet/ResNet18.conf)
    - residual adds + per-shard batch norm, the add/BN composition the
    other families don't exercise. Late in the registry: only a
    generous window measures it. Disable with CXN_BENCH_RESNET=0."""
    return _bench_model_family("ResNet18.conf", "resnet18",
                               "CXN_BENCH_RESNET", batch, steps,
                               platform, seed=6)


def _bench_chip_matmul(platform: str) -> dict:
    """Pure-matmul sustained TFLOP/s: 64 chained 4096^2 bf16 matmuls
    inside ONE jitted lax.scan, so per-call dispatch latency (measured
    ~3.3 ms through the tunnel - longer than the matmul itself)
    cannot bound the number. Grounds the MFU story: if the chip
    sustains near its spec peak here but AlexNet's step runs far
    below, the gap is model-shape-bound (conv1 11x11/s4, LRN, pools),
    not a chip or runtime artifact. TPU only; no readbacks. Disable
    with CXN_BENCH_MATMUL=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_MATMUL") == "0":
        return {}
    try:
        import jax
        import jax.numpy as jnp
        from jax import lax
        n, chain = 4096, 64

        def body(x, _):
            return (x @ x) * (1.0 / n), None

        @jax.jit
        def run(x):
            y, _ = lax.scan(body, x, None, length=chain)
            return y

        x = jnp.full((n, n), 1.0, jnp.bfloat16)
        _sync(run(x))
        reps = 5
        t0 = time.perf_counter()
        y = x
        for _ in range(reps):
            y = run(y)
        _sync(y)
        dt = time.perf_counter() - t0
        tflops = reps * chain * 2.0 * n ** 3 / dt / 1e12
        return {"chip_matmul_tflops": round(tflops, 1)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"matmul_probe_error": f"{type(e).__name__}: {e}"}


def _time_staged(tr, staged, steps, batch, budget_s):
    """Timed update(staged) loop - the device-resident measurement
    shared by the AlexNet and GoogLeNet children. The warmup ends in
    a FULL _sync (not _warm_sync): a staged loop stages nothing per
    step, so the readback poison is harmless, and the process's FIRST
    readback costs ~8 s of D2H warmup that must not land inside the
    timed region (measured: 1.4k vs 16k img/s for the identical loop
    with the tax in vs out). One sized step bounds the loop to
    budget_s so the child cannot blow its registry timeout."""
    n_st = len(staged)
    for i in range(2):
        tr.update(staged[i % n_st])
    _sync(tr.state)
    t0 = time.perf_counter()
    tr.update(staged[2 % n_st])
    _sync(tr.state)
    per = max(time.perf_counter() - t0, 1e-6)
    n = int(min(steps, max(4, budget_s / per)))
    t0 = time.perf_counter()
    for i in range(n):
        tr.update(staged[i % n_st])
    _sync(tr.state)
    return n * batch / (time.perf_counter() - t0), n


def _bench_device_data(ctx) -> dict:
    """e2e with a DEVICE-RESIDENT dataset: stage_batch() pre-stages
    the batches once, update(staged) streams zero bytes per step -
    the TPU-first analog of the reference's membuffer (RAM-resident
    host batches, iter_mem_buffer-inl.hpp). For any dataset that fits
    HBM this IS the product e2e path, and it is immune to the tunnel
    link, so `e2e_devicedata_ips` is the honest e2e number this
    environment can actually demonstrate (compare compute_ips: the
    remaining gap is the trainer's per-step host work - RNG fold,
    dispatch - not input streaming). Disable with CXN_BENCH_DEVDATA=0."""
    if (ctx.platform != "tpu"
            or os.environ.get("CXN_BENCH_DEVDATA") == "0"):
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        tr = ctx.trainer
        rng = np.random.RandomState(7)
        staged = [tr.stage_batch(DataBatch(*_alexnet_batch(rng,
                                                           ctx.batch)))
                  for _ in range(4)]
        ips, _n = _time_staged(tr, staged, ctx.steps, ctx.batch, 45.0)
        return {"e2e_devicedata_ips": round(ips, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"device_data_error": f"{type(e).__name__}: {e}"}


def _bench_prefetch(ctx) -> dict:
    """Streamed e2e THROUGH the H2D staging prefetcher
    (trainer.prefetch, io/prefetch.py): batch k+1's pad + cast +
    device_put runs on a worker thread while step k executes - the
    reference ThreadBuffer idea at the host->device edge
    (thread_buffer.h:22-202). The delta vs `e2e_ips` prices the
    double buffering; on a healthy host link (not this tunnel)
    e2e_prefetch_ips >= 0.9 x compute_ips is the product bar for
    streamed training. Runs on CPU too (the overlap logic is
    platform-free). Disable with CXN_BENCH_PREFETCH=0."""
    if os.environ.get("CXN_BENCH_PREFETCH") == "0":
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        tr = ctx.trainer
        batch = ctx.batch
        rng = np.random.RandomState(11)
        nbuf = min(8, ctx.steps)
        batches = [DataBatch(*_alexnet_batch(rng, batch))
                   for _ in range(nbuf)]

        class _Cycle:
            """Minimal DataIter serving n host batches."""

            def __init__(self, n):
                self.n, self.i = n, -1

            def before_first(self):
                self.i = -1

            def next(self):
                self.i += 1
                return self.i < self.n

            def value(self):
                return batches[self.i % nbuf]

        n = _warm_and_size(tr,
                           lambda i: tr.update(batches[i % nbuf]),
                           ctx.steps, 45.0)
        pf = tr.prefetch(_Cycle(n), depth=1)
        try:
            t0 = time.perf_counter()
            pf.before_first()
            while pf.next():
                tr.update(pf.value())
            _sync(tr.state)
            dt = time.perf_counter() - t0
        finally:
            pf.close()  # an update() error must not leak the worker
        return {"e2e_prefetch_ips": round(n * batch / dt, 2),
                "e2e_prefetch_steps": n}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"e2e_prefetch_error": f"{type(e).__name__}: {e}"}


def _bench_fused(ctx) -> dict:
    """e2e with fused multi-step dispatch (steps_per_dispatch=K,
    docs/PERFORMANCE.md): K host batches stage + stack into one
    StagedChunk and ONE jitted scan runs all K updates, so the host
    pays one dispatch + zero per-step readbacks per K steps. The
    derived `fused_over_e2e` ratio vs `e2e_ips` prices exactly the
    per-step dispatch overhead this removes (CPU harness ratios are
    meaningful - both sides pace the same host; the TPU field names
    are wired for the next verified-sync run). One extra compile (the
    chunk executable inlines K step bodies). K via CXN_BENCH_FUSED_K,
    default 4. Disable with CXN_BENCH_FUSED=0."""
    if os.environ.get("CXN_BENCH_FUSED") == "0":
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        tr = ctx.trainer
        batch = ctx.batch
        k = max(2, int(os.environ.get("CXN_BENCH_FUSED_K", "4")))
        rng = np.random.RandomState(13)
        nbuf = 8
        batches = [DataBatch(*_alexnet_batch(rng, batch))
                   for _ in range(nbuf)]

        def chunk_at(i):
            return [batches[(i * k + j) % nbuf] for j in range(k)]

        nchunks = _warm_and_size(
            tr, lambda i: tr.update_chunk(chunk_at(i)),
            max(2, ctx.steps // k), 45.0, floor=2)
        t0 = time.perf_counter()
        for i in range(nchunks):
            tr.update_chunk(chunk_at(i))
        _sync(tr.state)
        dt = time.perf_counter() - t0
        return {"e2e_fused_ips": round(nchunks * k * batch / dt, 2),
                "e2e_fused_k": k,
                "e2e_fused_steps": nchunks * k}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"e2e_fused_error": f"{type(e).__name__}: {e}"}


def _bench_zero(ctx) -> dict:
    """e2e with ZeRO-2 weight-update sharding (zero_stage=2,
    docs/parallel.md): gradients reduce-scattered over the data axis,
    the optimizer update run on each device's 1/N shard, fresh
    weights all-gathered. The derived `zero_over_e2e` ratio vs
    `e2e_ips` prices the trade (less update FLOPs + state HBM vs the
    extra gather latency); `opt_state_bytes_per_dev` is the measured
    per-device optimizer-state footprint - the HBM claim as a gauge
    through the telemetry registry, not an assertion (on a 1-device
    mesh it simply equals the full state and the stage degrades to
    replicated, which the ratio then shows as ~1.0). Second AlexNet
    compile. Disable with CXN_BENCH_ZERO=0."""
    if os.environ.get("CXN_BENCH_ZERO") == "0":
        return {}
    try:
        import jax
        from cxxnet_tpu import telemetry
        tr = ctx.make(0, [("zero_stage", "2")])
        out = {}
        state_bytes = sum(
            a.addressable_shards[0].data.nbytes
            for a in jax.tree_util.tree_leaves(tr.state["ustate"]))
        out["opt_state_bytes_per_dev"] = int(state_bytes)
        telemetry.set_gauge("zero.opt_state_bytes_per_dev",
                            float(state_bytes))
        ips, n = _measure_e2e(tr, ctx.batch, ctx.steps)
        out["zero2_ips"] = round(ips, 2)
        out["zero2_steps"] = n
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"zero2_error": f"{type(e).__name__}: {e}"}


def _bench_serve(ctx) -> dict:
    """Continuous-batching serving (serve/server.py, docs/SERVING.md):
    warmed bucket executables + replica fan-out driven by a threaded
    load generator of mixed-size requests. `serve_qps` is requests/s
    and `serve_rows_per_s` images/s through the server (the physics-
    capped field); `serve_p50_ms`/`serve_p99_ms` are the end-to-end
    request latencies from the telemetry histogram; the derived
    `serve_over_predict` prices continuous batching against the ideal
    batch-at-a-time predict loop over the SAME images in the SAME
    window (<1 = the bucket padding + admission wait you pay for
    bounded per-request latency; docs/SERVING.md's cost model).
    Queue depth rides the `serve.queue_depth` registry gauge.
    Compiles one fwd executable per bucket. Disable with
    CXN_BENCH_SERVE=0; CXN_BENCH_SERVE_MAXB bounds the bucket ladder
    (default 32)."""
    if os.environ.get("CXN_BENCH_SERVE") == "0":
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.serve import Server
        tr = ctx.trainer
        batch = ctx.batch
        rng = np.random.RandomState(17)
        data, label = _alexnet_batch(rng, batch)
        db = DataBatch(data, label)
        # batch-at-a-time baseline over the same infer executable:
        # compile + warm, one sizing rep, then a budgeted loop
        tr.predict_dist(db)
        t0 = time.perf_counter()
        tr.predict_dist(db)
        per_rep = max(time.perf_counter() - t0, 1e-6)
        nrep = max(2, min(8, int(20.0 / per_rep)))
        t0 = time.perf_counter()
        for _ in range(nrep):
            tr.predict_dist(db)
        predict_rps = nrep * batch / (time.perf_counter() - t0)
        mb = min(batch,
                 int(os.environ.get("CXN_BENCH_SERVE_MAXB", "32")))
        srv = Server(tr, max_batch=mb, max_wait_ms=2.0, replicas=2)
        t0 = time.perf_counter()
        srv.warmup()
        warm_s = time.perf_counter() - t0
        srv.start()
        # mixed request sizes covering the bucket ladder; total rows
        # sized to ~the baseline loop's traffic so both numbers come
        # from comparable windows
        sizes, total, i = [], 0, 0
        cycle = [1, mb // 2, mb, 3, mb // 4 or 1, mb, 7, mb // 2]
        target = max(2 * batch, nrep * batch // 2)
        while total < target:
            n = max(1, min(cycle[i % len(cycle)], mb))
            sizes.append(n)
            total += n
            i += 1
        reqs = [data[:n] for n in sizes]  # views: staging copies
        t0 = time.perf_counter()
        futs = [srv.submit(r) for r in reqs]
        for f in futs:
            f.result(timeout=600)
        dt = max(time.perf_counter() - t0, 1e-9)
        stats = srv.stop()
        if stats["errors"]:
            return {"serve_error":
                    f"{stats['errors']} dispatch errors"}
        out = {
            "serve_qps": round(len(reqs) / dt, 2),
            "serve_rows_per_s": round(total / dt, 2),
            "serve_p50_ms": stats["latency_p50_ms"],
            "serve_p99_ms": stats["latency_p99_ms"],
            "serve_warmup_s": round(warm_s, 2),
            "serve_buckets": len(srv.buckets),
            "serve_max_batch": mb,
            "serve_requests": len(reqs),
            "serve_padding_rows": stats["padding_rows"],
        }
        if predict_rps > 0:
            out["serve_over_predict"] = round(
                (total / dt) / predict_rps, 4)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"serve_error": f"{type(e).__name__}: {e}"}


def _bench_serve_storm(ctx) -> dict:
    """Overload behavior of the serving front (docs/SERVING.md
    "Serving over HTTP"): an OPEN-LOOP Poisson load generator - seeded
    exponential inter-arrivals, ragged request sizes - driven at ~2x
    the server's measured sustainable row rate with `queue_limit`
    armed, so the excess MUST be shed rather than queued. The numbers
    that matter under overload: `serve_storm_p99_ms` is the end-to-end
    p99 of the ACCEPTED requests (bounded latency is the whole point
    of shedding - an unbounded queue would show every request slow),
    and `serve_shed_frac` is the shed fraction of offered requests
    (~0.5 at 2x is healthy; ~0 means the storm never exceeded
    capacity, ~1 means admission collapsed). Open-loop matters:
    a closed-loop generator self-throttles when the server slows,
    hiding exactly the overload this measures. Disable with
    CXN_BENCH_SERVE_STORM=0."""
    if os.environ.get("CXN_BENCH_SERVE_STORM") == "0":
        return {}
    try:
        from cxxnet_tpu.serve import QueueFullError, Server
        tr = ctx.trainer
        batch = ctx.batch
        rng = np.random.RandomState(23)
        data, _ = _alexnet_batch(rng, batch)
        mb = min(batch,
                 int(os.environ.get("CXN_BENCH_SERVE_MAXB", "32")))
        # leg 1: closed-loop calibration of the sustainable row rate
        # over the same buckets (no limit, no storm)
        srv = Server(tr, max_batch=mb, max_wait_ms=2.0, replicas=2)
        srv.warmup()
        srv.start()
        cycle = [1, mb // 2, mb, 3, mb // 4 or 1, 7]
        cal_sizes = [max(1, min(s, mb)) for s in cycle * 6]
        t0 = time.perf_counter()
        futs = [srv.submit(data[:n]) for n in cal_sizes]
        for f in futs:
            f.result(timeout=600)
        cal_dt = max(time.perf_counter() - t0, 1e-9)
        cal_stats = srv.stop()
        sustainable_rows = sum(cal_sizes) / cal_dt
        # leg 2: the storm - offered load 2x sustainable, hard
        # queue_limit of ~4 buckets of backlog
        limit = 4 * mb
        srv = Server(tr, max_batch=mb, max_wait_ms=2.0, replicas=2,
                     queue_limit=limit)
        srv.warmup()
        srv.start()
        offered_rows = 2.0 * sustainable_rows
        mean_size = sum(cal_sizes) / len(cal_sizes)
        n_req = max(60, int(os.environ.get(
            "CXN_BENCH_STORM_REQS", "120")))
        gaps = rng.exponential(mean_size / offered_rows, n_req)
        sizes = [max(1, min(int(rng.choice(cycle)), mb))
                 for _ in range(n_req)]
        arrivals = np.cumsum(gaps)
        live, shed = [], 0
        t_start = time.perf_counter()
        for i in range(n_req):
            # open loop: sleep the Poisson gap regardless of how the
            # server is doing, then offer the request
            target = t_start + float(arrivals[i])
            pause = target - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            t_sub = time.perf_counter()
            try:
                live.append((srv.submit(data[:sizes[i]]), t_sub))
            except QueueFullError:
                shed += 1
        lat_ms = []
        for f, t_sub in live:
            f.result(timeout=600)
            lat_ms.append((time.perf_counter() - t_sub) * 1e3)
        stats = srv.stop()
        if stats["errors"]:
            return {"serve_storm_error":
                    f"{stats['errors']} dispatch errors"}
        lat_ms.sort()
        p99 = lat_ms[min(len(lat_ms) - 1,
                         int(0.99 * len(lat_ms)))] if lat_ms else 0.0
        return {
            "serve_storm_p99_ms": round(p99, 2),
            "serve_shed_frac": round(shed / max(n_req, 1), 4),
            "serve_storm_accepted": len(live),
            "serve_storm_offered": n_req,
            "serve_storm_offered_rows_per_s": round(offered_rows, 2),
            "serve_storm_sustainable_rows_per_s": round(
                sustainable_rows, 2),
            "serve_storm_queue_limit": limit,
            "serve_uncontended_p99_ms": cal_stats["latency_p99_ms"],
        }
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"serve_storm_error": f"{type(e).__name__}: {e}"}


def _bench_canary_swap(ctx) -> dict:
    """Cost of a canaried rollout (docs/SERVING.md "Canary runbook"):
    requests served WHILE a canary is active go through the same
    warmed bucket executables as steady state (the candidate is just
    a second params argument binding), so `serve_canary_p99_ms`
    should sit on top of the uncontended serve p99 - a gap means the
    judge's shadow dispatches or the routing split are stealing
    device time. `serve_canary_promote_lag_ms` is the judge's
    overhead beyond the configured window: how long after the window
    closes the promote actually lands. The candidate is the
    incumbent's own checkpoint (agreement 1.0 - promote guaranteed);
    this prices the machinery, not the model. Disable with
    CXN_BENCH_SERVE_CANARY=0."""
    if os.environ.get("CXN_BENCH_SERVE_CANARY") == "0":
        return {}
    try:
        import tempfile

        from cxxnet_tpu.serve import Server
        tr = ctx.trainer
        batch = ctx.batch
        rng = np.random.RandomState(27)
        data, _ = _alexnet_batch(rng, batch)
        mb = min(batch,
                 int(os.environ.get("CXN_BENCH_SERVE_MAXB", "32")))
        window_s = 0.6
        srv = Server(tr, max_batch=mb, max_wait_ms=2.0, replicas=2,
                     canary_frac=0.5, canary_window=window_s)
        srv.warmup()
        n_warm = srv.executable_cache_size()
        srv.start()
        with tempfile.TemporaryDirectory(
                prefix="bench_canary_") as d:
            ck = os.path.join(d, "cand.model")
            with open(ck, "wb") as f:
                tr.save_model(f)
            t_pub = time.perf_counter()
            if not srv.swap_to(ck):
                srv.stop()
                return {"serve_canary_error": "swap_to refused"}
            cycle = [1, mb // 2, mb, 3, mb // 4 or 1, 7]
            lat_ms = []
            # closed-loop probes for the whole canary lifetime: every
            # request lands on one side of the split or the other
            while srv.stats()["canary_active"]:
                n = max(1, min(int(rng.choice(cycle)), mb))
                t_sub = time.perf_counter()
                srv.submit(data[:n]).result(timeout=600)
                lat_ms.append((time.perf_counter() - t_sub) * 1e3)
            promote_lag_ms = (time.perf_counter() - t_pub
                              - window_s) * 1e3
            stats = srv.stats()
            flat = srv.executable_cache_size() == n_warm
            srv.stop()
        if stats["canary_promoted"] != 1:
            return {"serve_canary_error":
                    f"verdict was not promote: {stats}"}
        lat_ms.sort()
        p99 = lat_ms[min(len(lat_ms) - 1,
                         int(0.99 * len(lat_ms)))] if lat_ms else 0.0
        return {
            "serve_canary_p99_ms": round(p99, 2),
            "serve_canary_promote_lag_ms": round(promote_lag_ms, 1),
            "serve_canary_requests": stats["canary_requests"],
            "serve_canary_probes": len(lat_ms),
            "serve_canary_cache_flat": flat,
        }
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"serve_canary_error": f"{type(e).__name__}: {e}"}


_BN_CONVNET_CONF = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 24
  kernel_size = 3
  pad = 1
layer[+1:b1] = batch_norm:b1
layer[+1:r1] = relu
layer[+1:p1] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1:c2] = conv:c2
  nchannel = 32
  kernel_size = 3
  pad = 1
layer[+1:b2] = batch_norm:b2
layer[+1:r2] = relu
layer[+1:p2] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 3,48,48
eta = 0.1
silent = 1
seed = 19
"""

# fwd FLOP lower bound for the bn-convnet above: conv1 ~1.5M + conv2
# ~4.0M MACs = ~11 MFLOP/img; deliberately the low end (an
# under-estimate only loosens the physics cap, never flags a real
# number)
BN_CONVNET_FWD_GFLOP_PER_IMG = 0.01


def _bench_fold(ctx) -> dict:
    """Inference with the conv+bn folding graph pass
    (graph_passes=fold_conv_bn,dead_layer_elim - nnet/passes.py,
    docs/GRAPH_PASSES.md) vs the unfolded graph, on a bn-heavy
    convnet (AlexNet has LRN, not BN, so the flagship can't carry
    this field): the SAME predict_dist loop over the SAME images in
    the same window, so the derived `fold_over_infer` prices exactly
    what the fold removes - the per-batch moment/variance pipeline
    and the normalize pass over every BN activation. >1.0 = folding
    won; the fold leg calibrates once on the first batch (included
    in warmup, not the timed window - warmup cost is the one-time
    calibration executable). Two small compiles. Disable with
    CXN_BENCH_FOLD=0."""
    if os.environ.get("CXN_BENCH_FOLD") == "0":
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.nnet.trainer import NetTrainer
        from cxxnet_tpu.utils.config import parse_config_string
        batch = ctx.batch

        def build(extra=""):
            tr = NetTrainer()
            for k, v in parse_config_string(
                    _BN_CONVNET_CONF + f"batch_size = {batch}\n"
                    + extra):
                tr.set_param(k, v)
            tr.init_model()
            return tr

        rng = np.random.RandomState(31)
        db = DataBatch(
            data=rng.rand(batch, 3, 48, 48).astype(np.float32),
            label=rng.randint(0, 10, (batch, 1)).astype(np.float32))

        def ips_of(tr, budget_s=20.0):
            tr.predict_dist(db)  # compile (+ fold calibration)
            t0 = time.perf_counter()
            tr.predict_dist(db)
            per = max(time.perf_counter() - t0, 1e-6)
            n = max(3, min(64, int(budget_s / per)))
            t0 = time.perf_counter()
            for _ in range(n):
                tr.predict_dist(db)
            return n * batch / (time.perf_counter() - t0), n

        unfolded, n1 = ips_of(build())
        folded, n2 = ips_of(build(
            "graph_passes = fold_conv_bn,dead_layer_elim\n"))
        out = {"fold_infer_ips": round(folded, 2),
               "fold_unfolded_ips": round(unfolded, 2),
               "fold_steps": n1 + n2}
        if unfolded > 0:
            out["fold_over_infer"] = round(folded / unfolded, 4)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"fold_error": f"{type(e).__name__}: {e}"}


# int8 PTQ workload: a weight-bound wide-fullc MLP at a SERVING-shaped
# small batch - the regime the quantize_int8 pass exists for
# (docs/GRAPH_PASSES.md "when int8 loses": large batches go
# compute-bound and int8's extra quant/dequant work outweighs the
# weight-bandwidth saving; measured on this container's XLA:CPU the
# crossover sits between batch 16 and 64)
_INT8_MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 2048
  init_sigma = 0.05
layer[+1:bn1] = batch_norm:bn1
layer[+1:r1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 2048
  init_sigma = 0.05
layer[+1:bn2] = batch_norm:bn2
layer[+1:r2] = relu
layer[+1:fc3] = fullc:fc3
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,512
dev = cpu
eta = 0.1
silent = 1
seed = 19
"""

# fwd FLOP lower bound for the int8 MLP: 512*2048 + 2048*2048 +
# 2048*10 MACs ~ 10.5 MFLOP/img; low end on purpose (an
# under-estimate only loosens the physics cap)
_INT8_MLP_FWD_GFLOP_PER_IMG = 0.01

# fixed serving-shaped batch for the int8 pair: ctx.batch is the
# TRAINING workload size; quantized inference's claim is the
# small-batch weight-bound serving regime
_INT8_BATCH = 16


def _bench_int8(ctx) -> dict:
    """Int8 post-training-quantized inference (quantize_int8 pass +
    ops/int8.py kernels - docs/GRAPH_PASSES.md "Quantization") vs the
    folded-float pipeline, on a weight-bound wide-fullc MLP at a
    serving-shaped batch: the SAME predict_dist loop over the SAME
    rows in the same window, so `int8_over_fold` prices exactly what
    quantization changes - int8 weight traffic + MXU/VNNI-rate
    contraction against the extra quantize/dequantize elementwise
    work. >1.0 = int8 won. The speed claim ships with its accuracy
    cost: `int8_argmax_agree` is the fraction of a fixed 256-row
    synthetic eval set where the quantized argmax matches the float
    one (1.0 = no prediction changed). Calibration (one batch)
    happens in warmup, outside the timed window, like the fold leg.
    Disable with CXN_BENCH_INT8=0."""
    if os.environ.get("CXN_BENCH_INT8") == "0":
        return {}
    try:
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.nnet.trainer import NetTrainer
        from cxxnet_tpu.utils.config import parse_config_string
        batch = _INT8_BATCH

        def build(extra=""):
            tr = NetTrainer()
            for k, v in parse_config_string(
                    _INT8_MLP_CONF + f"batch_size = {batch}\n"
                    "graph_passes = dead_layer_elim,fold_conv_bn,"
                    "fuse_activation" + extra):
                tr.set_param(k, v)
            tr.init_model()
            return tr

        rng = np.random.RandomState(41)
        db = DataBatch(
            data=rng.rand(batch, 1, 1, 512).astype(np.float32),
            label=rng.randint(0, 10, (batch, 1)).astype(np.float32))

        def ips_of(tr, budget_s=20.0):
            tr.predict_dist(db)  # compile (+ calibration)
            t0 = time.perf_counter()
            tr.predict_dist(db)
            per = max(time.perf_counter() - t0, 1e-6)
            n = max(3, min(256, int(budget_s / per)))
            t0 = time.perf_counter()
            for _ in range(n):
                tr.predict_dist(db)
            return n * batch / (time.perf_counter() - t0), n

        fold_tr, int8_tr = build(), build(",quantize_int8")
        folded, n1 = ips_of(fold_tr)
        int8, n2 = ips_of(int8_tr)
        # accuracy delta on a fixed held-out set (same weights, same
        # rows): argmax agreement between the two inference paths
        agree = total = 0
        for i in range(256 // batch):
            r = np.random.RandomState(900 + i)
            eb = DataBatch(
                data=r.rand(batch, 1, 1, 512).astype(np.float32),
                label=r.randint(0, 10, (batch, 1)).astype(np.float32))
            pf = fold_tr.predict_dist(eb).argmax(axis=1)
            pq = int8_tr.predict_dist(eb).argmax(axis=1)
            agree += int((pf == pq).sum())
            total += batch
        out = {"int8_infer_ips": round(int8, 2),
               "int8_fold_ips": round(folded, 2),
               "int8_batch": batch,
               "int8_steps": n1 + n2,
               "int8_argmax_agree": round(agree / max(total, 1), 4)}
        if folded > 0:
            out["int8_over_fold"] = round(int8 / folded, 4)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"int8_error": f"{type(e).__name__}: {e}"}


def _bench_plan(ctx) -> dict:
    """The PER-LAYER autotuner's value proposition, measured
    (schema-v2 tuning_cache, docs/GRAPH_PASSES.md "per-layer
    autotuner"): run tools/autotune.py's bounded greedy per-layer
    search on the bf16 BN-convnet (autocast pass armed, so
    `layer_dtype` flips feed the dtype plan - on hosts without fast
    bf16 conv the per-layer f32 pins are a real win), persist the
    plan as a v2 cache, and drive the SAME predict loop with the
    plan replayed via `tuning_cache =` vs defaults in the same
    window. `plan_over_default` is the ratio the per-layer plan buys
    over global defaults; the plan itself lands in `plan_layers` so
    the artifact doubles as tuning evidence. Disable with
    CXN_BENCH_PLAN=0; CXN_BENCH_PLAN_SECS bounds the search
    (default 20)."""
    if os.environ.get("CXN_BENCH_PLAN") == "0":
        return {}
    try:
        import shutil
        import tempfile

        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.nnet import tuning
        from cxxnet_tpu.nnet.trainer import NetTrainer
        from cxxnet_tpu.tools import autotune
        from cxxnet_tpu.utils.config import parse_config_string
        batch = ctx.batch
        pairs = parse_config_string(
            _BN_CONVNET_CONF + f"batch_size = {batch}\n"
            "dtype = bfloat16\ngraph_passes = autocast\n")
        budget = float(os.environ.get("CXN_BENCH_PLAN_SECS", "20"))
        pl = autotune.per_layer_search(pairs, budget)
        d = tempfile.mkdtemp(prefix="cxn_bench_plan_")
        try:
            cache = os.path.join(d, "plan.json")
            tuning.save_entry(cache, jax.default_backend(), {},
                              layers=pl["layers"])

            def build(extra=()):
                tr = NetTrainer()
                for k, v in list(pairs) + list(extra):
                    tr.set_param(k, v)
                tr.init_model()
                return tr

            rng = np.random.RandomState(37)
            db = DataBatch(
                data=rng.rand(batch, 3, 48, 48).astype(np.float32),
                label=rng.randint(0, 10, (batch, 1))
                .astype(np.float32))

            def ips_of(tr, budget_s=10.0):
                tr.predict_dist(db)  # compile + warm
                t0 = time.perf_counter()
                tr.predict_dist(db)
                per = max(time.perf_counter() - t0, 1e-6)
                n = max(3, min(64, int(budget_s / per)))
                t0 = time.perf_counter()
                for _ in range(n):
                    tr.predict_dist(db)
                return n * batch / (time.perf_counter() - t0)

            default_ips = ips_of(build())
            tuned_ips = ips_of(build([("tuning_cache", cache)]))
        finally:
            shutil.rmtree(d, ignore_errors=True)
        out = {"plan_tuned_ips": round(tuned_ips, 2),
               "plan_default_ips": round(default_ips, 2),
               "plan_layers": pl["layers"]}
        if default_ips > 0:
            out["plan_over_default"] = round(
                tuned_ips / default_ips, 4)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"plan_error": f"{type(e).__name__}: {e}"}


# the autotuner's default workload is the dispatch-bound tiny MLP
# (tools/autotune.py): ~6k FLOP/img - the under-estimate convention
AUTOTUNE_MLP_GFLOP_PER_IMG = 1e-5


def _bench_autotune(ctx) -> dict:
    """The TVM-style autotuner's own value proposition, measured:
    run the bounded (steps_per_dispatch x prefetch_stage) search of
    tools/autotune.py on its dispatch-bound default workload and
    report the best cell (`autotune_best_ips`) against the shipped
    defaults' cell in the SAME window (`tuned_over_default` - the
    ratio a `tuning_cache =` pickup buys on this host). The serving
    ladder is skipped here (the serve family already prices bucket
    choice); the knob dict itself lands in `autotune_best` so a
    bench artifact doubles as tuning evidence. Disable with
    CXN_BENCH_AUTOTUNE=0; CXN_BENCH_AUTOTUNE_SECS bounds the search
    (default 30)."""
    if os.environ.get("CXN_BENCH_AUTOTUNE") == "0":
        return {}
    try:
        from cxxnet_tpu.tools import autotune
        from cxxnet_tpu.utils.config import parse_config_string
        budget = float(os.environ.get("CXN_BENCH_AUTOTUNE_SECS",
                                      "30"))
        pairs = parse_config_string(autotune._DEFAULT_CONF)
        # per_layer=False: the MLP workload has no per-layer
        # candidates, and the plan family has its own field
        # (_bench_plan's plan_over_default on the BN-convnet)
        res = autotune.search(pairs, budget, serve=False,
                              per_layer=False)
        m = res["measured"]
        out = {"autotune_best_ips": m["best_ips"],
               "autotune_best": {k: v for k, v
                                 in res["knobs"].items()},
               "autotune_grid": m["grid"]}
        if m.get("default_ips"):
            out["autotune_default_ips"] = m["default_ips"]
            out["tuned_over_default"] = round(
                m["best_ips"] / m["default_ips"], 4)
        return out
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"autotune_error": f"{type(e).__name__}: {e}"}


def _bench_pool_ties(make, batch, steps, platform: str) -> dict:
    """Compute-path throughput with `pool_grad = ties` (the reference's
    tie-duplicating max-pool backward) vs the bench flagship's
    `winner` default - the measured cost of exact mshadow tie parity.
    Round-4 on-chip (old ky*kx shifted-compare backward): ties 7,403
    vs winner 13,580 within one window (1.83x); best-of-round ties
    8,226 vs winner 16,067 across windows (~1.95x). Round 5
    replaced that with the separable two-stage unpool
    (ops/pooling.py: ~2*ceil(k/s) half-size passes, 4 vs 9 for the
    AlexNet pools), so THIS field is the defaults decision: if ties
    now meets the baseline, parity becomes the flagship config too.
    One extra compile; TPU only. Disable with CXN_BENCH_POOLTIES=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_POOLTIES") == "0":
        return {}
    try:
        tr = make(0, [("pool_grad", "ties")])
        return {"compute_poolties_ips":
                round(_measure_compute(tr, batch, steps), 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"pool_ties_error": f"{type(e).__name__}: {e}"}


def _bench_eval_train(make, batch, steps) -> dict:
    """eval_train=1 (the reference's default mode): the conf's metric
    lines (error, rec@1, rec@5) compile into the step as device-side
    accumulators. Needs a SECOND full AlexNet compile, which is why it
    runs after the other throughput extras - if the watchdog budget
    dies here, every headline and extra before it is already
    snapshotted (only the profiler fetch, which needs no compile,
    comes later). Disable with CXN_BENCH_EVALTRAIN=0."""
    if os.environ.get("CXN_BENCH_EVALTRAIN") == "0":
        return {}
    try:
        trainer_m = make(1)
        ips, n = _measure_e2e(trainer_m, batch, steps)
        return {"e2e_eval_train_ips": round(ips, 2),
                "eval_train_steps": n}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"eval_train_error": f"{type(e).__name__}: {e}"}


def _flagship_overrides(batch, eval_train, extra=()):
    """The ONE source of the flagship bench config - every trainer the
    bench builds (headline, eval_train, pool_ties, device_augment)
    derives from this list so the numbers stay comparable.
    pool_grad=winner is the flagship default: the reference's
    tie-duplicating max-pool backward costs 1.83x the whole AlexNet
    step on-chip (compute_poolties_ips measures that parity cost);
    FIRST in the list so an explicit extra still overrides it (later
    set_param wins)."""
    return [("pool_grad", "winner"),
            ("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
            ("eval_train", str(eval_train)), ("save_model", "0"),
            *extra]


class _Ctx:
    """Everything a measurement needs, built lazily: one shared
    instance on the inline (CPU) path so AlexNet compiles once; a
    fresh instance per isolated subprocess on TPU so each measurement
    gets its own PJRT client (and its own un-poisoned H2D link)."""

    def __init__(self, batch, steps, platform, profile_dir=""):
        self.batch, self.steps = batch, steps
        self.platform, self.profile_dir = platform, profile_dir
        self._trainers = {}

    def make(self, eval_train, extra=()):
        key = (eval_train, tuple(extra))
        if key not in self._trainers:
            from __graft_entry__ import _ALEXNET_CONF, _make_trainer
            from cxxnet_tpu.utils.config import parse_config_file
            self._trainers[key] = _make_trainer(
                parse_config_file(_ALEXNET_CONF),
                _flagship_overrides(self.batch, eval_train, extra))
        return self._trainers[key]

    @property
    def trainer(self):
        return self.make(0)


def _m_e2e(ctx) -> dict:
    """Headline: full trainer.update() loop + a link-health probe
    (h2d_mbps: one timed ~20 MB f32 device_put BEFORE the warmup, so
    the artifact records what the tunnel link was worth that boot -
    round 4 measured anywhere from 25 to 950 MB/s on the same chip;
    32 rows, not a full batch: the worst observed link would spend
    the child's whole timeout on a 158 MB probe)."""
    out = {}
    if ctx.platform == "tpu":
        try:
            import jax
            # a SMALL probe (~20 MB): at the worst observed link rate
            # (~3 MB/s) a full 158 MB f32 batch would eat the child's
            # whole timeout before the loop even starts
            probe = np.ones((min(ctx.batch, 32), 3, 227, 227),
                            np.float32)
            t0 = time.perf_counter()
            d = jax.device_put(probe)
            if _SYNC_MODE != "readback":
                jax.block_until_ready(d)
            dt = max(time.perf_counter() - t0, 1e-9)
            # in readback mode no sync is allowed before the loop (a
            # readback would poison it), so the probe only times the
            # put's dispatch - an UPPER bound, labeled as such
            # (observed: "935 MB/s" dispatch in a window whose real
            # staging ran ~30 MB/s)
            key = ("h2d_dispatch_mbps" if _SYNC_MODE == "readback"
                   else "h2d_mbps")
            out[key] = round(probe.nbytes / 1e6 / dt, 1)
            del d, probe
        except Exception as e:  # noqa: BLE001 - probe is best-effort
            out["h2d_probe_error"] = f"{type(e).__name__}: {e}"
    ips, n = _measure_e2e(ctx.trainer, ctx.batch, ctx.steps,
                          ctx.profile_dir)
    out["e2e_ips"] = round(ips, 2)
    out["e2e_steps"] = n
    return out


def _m_compute(ctx) -> dict:
    out = {"compute_ips": round(
        _measure_compute(ctx.trainer, ctx.batch, ctx.steps), 2)}
    try:
        # HBM high-water mark after a full train step - the parity
        # datum for the reference's ">3 GB GPU memory at batch 256"
        # claim (example/ImageNet/README.md:7-10). memory_stats is
        # client metadata, not a buffer transfer; absent on backends
        # that don't expose it.
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            out["hbm_peak_gb"] = round(peak / 2 ** 30, 2)
    except Exception:  # noqa: BLE001 - metadata only, never the number
        pass
    return out


# (name, fn(ctx) -> fragment, gate env var or "", isolated-child
# timeout seconds, pacing kind). ORDER = the isolation order on TPU:
# the VERDICT-critical numbers (e2e headline, compute ceiling, the
# Pallas kernel validation, the top-ops profile) land before the
# nice-to-have extras, so a watchdog cut truncates from the tail.
# kind "compute" = device-paced (the number is wrong unless the sync
# primitive truly waits); "h2d" = host-paced per-step staging (the
# loop itself paces the clock and the link must stay un-poisoned
# DURING it - the inline path uses this to flag loops that ran after
# a poisoning sync). Isolated children of BOTH kinds verify the
# readback AFTER their measurement (_child_run) - post-measurement,
# the poison no longer matters and the verdict samples the same
# window the measurement ran in.
_MEASUREMENTS = (
    # headline pair first, then the round's open DECISIONS (pool_ties:
    # defaults unification; googlenet: second family, never measured on
    # chip before r5; device_data: the e2e/compute ratio; e2e_prefetch:
    # the new overlap), then the established extras - a short tunnel
    # window spends its budget on what the round needs decided
    ("e2e", _m_e2e, "", 200, "h2d"),
    ("compute", _m_compute, "", 100, "compute"),
    ("pool_ties",
     lambda c: _bench_pool_ties(c.make, c.batch, c.steps, c.platform),
     "CXN_BENCH_POOLTIES", 90, "compute"),
    ("googlenet",
     lambda c: _bench_googlenet(c.batch, c.steps, c.platform),
     "CXN_BENCH_GOOGLENET", 100, "h2d"),
    ("device_data", _bench_device_data, "CXN_BENCH_DEVDATA", 100,
     "compute"),
    ("e2e_prefetch", _bench_prefetch, "CXN_BENCH_PREFETCH", 150, "h2d"),
    ("fused", _bench_fused, "CXN_BENCH_FUSED", 150, "h2d"),
    ("zero", _bench_zero, "CXN_BENCH_ZERO", 150, "h2d"),
    ("serve", _bench_serve, "CXN_BENCH_SERVE", 150, "h2d"),
    ("serve_storm", _bench_serve_storm, "CXN_BENCH_SERVE_STORM", 150,
     "h2d"),
    ("canary_swap", _bench_canary_swap, "CXN_BENCH_SERVE_CANARY", 150,
     "h2d"),
    ("fold", _bench_fold, "CXN_BENCH_FOLD", 150, "h2d"),
    ("int8", _bench_int8, "CXN_BENCH_INT8", 150, "h2d"),
    ("autotune", _bench_autotune, "CXN_BENCH_AUTOTUNE", 150, "h2d"),
    ("plan", _bench_plan, "CXN_BENCH_PLAN", 150, "h2d"),
    ("attention",
     lambda c: _bench_attention(c.platform), "CXN_BENCH_ATTN", 100,
     "compute"),
    ("top_ops",
     lambda c: _bench_top_ops(c.trainer, c.batch, c.platform),
     "CXN_BENCH_PROFILE", 150, "h2d"),
    ("device_augment",
     lambda c: _bench_device_augment(c.batch, c.steps, c.platform),
     "CXN_BENCH_DAUG", 150, "h2d"),
    ("stage_f32",
     lambda c: _bench_stage_f32(c.trainer, c.batch, c.steps, c.platform),
     "CXN_BENCH_STAGEF32", 150, "h2d"),
    ("chip_matmul",
     lambda c: _bench_chip_matmul(c.platform), "CXN_BENCH_MATMUL", 60,
     "compute"),
    ("input_split",
     lambda c: _bench_input_split(c.trainer, c.batch, c.platform),
     "CXN_BENCH_SPLIT", 60, "h2d"),
    ("eval_train",
     lambda c: _bench_eval_train(c.make, c.batch, c.steps),
     "CXN_BENCH_EVALTRAIN", 150, "h2d"),
    # truly last: a nice-to-have third family must never cost an
    # established field (chip_matmul anchors mfu_pct) its window budget
    ("resnet18",
     lambda c: _bench_resnet(c.batch, c.steps, c.platform),
     "CXN_BENCH_RESNET", 100, "h2d"),
)

# physics caps: an images/sec (x GFLOP/img) or TFLOP/s field whose
# implied rate exceeds 1.25x the chip's spec peak cannot be a real
# measurement - it is dispatch timing from a window where no sync
# primitive worked. The artifact must never carry it as a result.
_GFLOP_PER_IMG = {
    "compute_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "e2e_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "e2e_devicedata_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "e2e_prefetch_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "e2e_fused_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "zero2_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    # serving is forward-only (~1/3 of the fwd+dgrad+wgrad train
    # cost); an UNDER-estimate only loosens the cap, never flags a
    # real number. serve_qps is requests/s (>= 1 image each), so the
    # per-image cap applied to it is conservative in the same
    # direction; serve_rows_per_s carries the actual image rate
    "serve_rows_per_s": ALEXNET_TRAIN_GFLOP_PER_IMG / 3.0,
    "serve_qps": ALEXNET_TRAIN_GFLOP_PER_IMG / 3.0,
    # fold/autotune run their own (small) workloads - per-workload
    # fwd-FLOP lower bounds, same under-estimate convention
    "fold_infer_ips": BN_CONVNET_FWD_GFLOP_PER_IMG,
    "fold_unfolded_ips": BN_CONVNET_FWD_GFLOP_PER_IMG,
    "int8_infer_ips": _INT8_MLP_FWD_GFLOP_PER_IMG,
    "int8_fold_ips": _INT8_MLP_FWD_GFLOP_PER_IMG,
    "autotune_best_ips": AUTOTUNE_MLP_GFLOP_PER_IMG,
    "autotune_default_ips": AUTOTUNE_MLP_GFLOP_PER_IMG,
    # per-layer-plan family runs the BN-convnet forward
    "plan_tuned_ips": BN_CONVNET_FWD_GFLOP_PER_IMG,
    "plan_default_ips": BN_CONVNET_FWD_GFLOP_PER_IMG,
    "e2e_f32stage_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "device_augment_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "e2e_eval_train_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    "compute_poolties_ips": ALEXNET_TRAIN_GFLOP_PER_IMG,
    # GoogLeNet fwd ~1.5 GFLOP/img x3 (fwd+dgrad+wgrad); deliberately
    # the low end of published estimates - an UNDER-estimate can only
    # make this cap more permissive, never flag a real number
    "googlenet_ips": 4.5,
    "googlenet_devicedata_ips": 4.5,
    # ResNet-18 fwd ~1.8 GFLOP/img x3; deliberately the low end (an
    # under-estimate only loosens the cap, never flags a real number)
    "resnet18_ips": 5.0,
    "resnet18_devicedata_ips": 5.0,
}
_TFLOPS_FIELDS = ("chip_matmul_tflops", "attn_pallas_tflops",
                  "attn_xla_tflops")


def _physics_check(out: dict, peak_tflops: float, ndev: int) -> None:
    if not peak_tflops:
        return
    cap = 1.25 * peak_tflops * max(ndev, 1)
    for f, gflop in _GFLOP_PER_IMG.items():
        v = out.get(f)
        if v and v * gflop / 1e3 > cap:
            out[f + "_implausible"] = out.pop(f)
    for f in _TFLOPS_FIELDS:
        v = out.get(f)
        if v and v > cap:
            out[f + "_implausible"] = out.pop(f)
    if ("attn_pallas_tflops_implausible" in out
            or "attn_xla_tflops_implausible" in out):
        # a ratio of two dispatch timings says nothing about the kernel
        out.pop("attn_pallas_speedup", None)

# inline (single-process) execution order, DERIVED from the registry
# so a new measurement can never be silently skipped on the inline
# path: compute first (cheapest number to land, round-3 snapshot
# discipline), profiler trace LAST (its D2H fetch poisons tunneled
# H2D), registry order otherwise. In readback-sync mode e2e must
# precede the first readback, so run() moves it to the front.
_INLINE_ORDER = tuple(
    ["compute"]
    + [m[0] for m in _MEASUREMENTS if m[0] not in ("compute", "top_ops")]
    + ["top_ops"])


def _derive(out: dict, batch: int, platform: str, ndev: int,
            peak_tflops: float) -> None:
    """(Re)compute the headline + derived fields from whatever raw
    numbers are present - called after every fragment merge so the
    snapshot always carries a correctly-labeled best-so-far."""
    comp, e2e = out.get("compute_ips"), out.get("e2e_ips")
    if not (comp and e2e):
        # a physics check may have retracted a source a previous merge
        # derived from; stale ratios must not outlive their inputs
        out.pop("e2e_over_compute", None)
    fused = out.get("e2e_fused_ips")
    if fused and e2e:
        # the K>1 vs K=1 ratio: what fusing K steps into one dispatch
        # buys over the per-step e2e path (>1 = dispatch overhead was
        # a real cost in this window)
        out["fused_over_e2e"] = round(fused / e2e, 4)
    else:
        out.pop("fused_over_e2e", None)
    zero = out.get("zero2_ips")
    if zero and e2e:
        # ZeRO-2 vs replicated update: >1 = the sharded update's FLOP/
        # HBM saving beat its extra gather latency in this window
        out["zero_over_e2e"] = round(zero / e2e, 4)
    else:
        out.pop("zero_over_e2e", None)
    if not out.get("serve_rows_per_s"):
        # serve_over_predict is derived in-window by the serve child;
        # it must not outlive a physics-retracted serve_rows_per_s
        out.pop("serve_over_predict", None)
    # same rule for the in-window pass/autotune ratios: a retracted
    # base number takes its ratio with it
    if not out.get("fold_infer_ips"):
        out.pop("fold_over_infer", None)
    if not out.get("int8_infer_ips"):
        # the speed ratio AND its accuracy cost travel together: an
        # agreement number without the run it came from is meaningless
        out.pop("int8_over_fold", None)
        out.pop("int8_argmax_agree", None)
    if not out.get("autotune_best_ips"):
        out.pop("tuned_over_default", None)
    if not out.get("plan_tuned_ips"):
        out.pop("plan_over_default", None)
    if e2e:
        out["metric"] = "alexnet_b%d_%s_train_e2e" % (batch, platform)
        out["value"], out["value_is"] = e2e, "e2e"
        out["vs_baseline"] = round(e2e / A100_IMAGES_PER_SEC, 4)
        out["achieved_tflops"] = round(
            e2e * ALEXNET_TRAIN_GFLOP_PER_IMG / 1e3, 2)
        if comp:
            out["e2e_over_compute"] = round(e2e / comp, 4)
            if e2e < 0.1 * comp:
                # a 10x+ gap between the same step staged vs host-fed
                # is the tunnel link, not the framework (real TPU
                # hosts feed over local PCIe); say so in the artifact
                out["e2e_note"] = (
                    "e2e is tunnel-link-bound in this window (see "
                    "docs/perf.md); compute_ips is the chip-side "
                    "capability")
            else:
                out.pop("e2e_note", None)
        if peak_tflops:
            out["peak_tflops"] = peak_tflops
            out["mfu_pct"] = round(
                100.0 * out["achieved_tflops"] / (peak_tflops * ndev), 2)
    elif comp:
        out["metric"] = "alexnet_b%d_%s_train_compute" % (batch, platform)
        out["value"], out["value_is"] = comp, "compute_only"
        out["vs_baseline"] = round(comp / A100_IMAGES_PER_SEC, 4)
        # e2e-derived fields must not outlive a retracted e2e_ips
        for stale in ("achieved_tflops", "mfu_pct", "e2e_note"):
            out.pop(stale, None)
    if "host_prep_ms_p50" in out and "host_over_device" not in out:
        # readback mode omits the profiled device step; derive the
        # split against the compute ceiling instead (est marks it)
        if comp:
            dev_est = 1e3 * batch / comp
            out["device_step_ms_est"] = round(dev_est, 2)
            out["host_over_device"] = round(
                out["host_prep_ms_p50"] / max(dev_est, 1e-9), 3)


def _run_isolated(name: str, batch: int, steps: int, profile_dir: str,
                  timeout_s: float) -> dict:
    """Run ONE measurement in a fresh subprocess (own PJRT client, own
    H2D link state) and return its JSON fragment. A hang costs only
    this measurement's timeout; a crash degrades to a *_error field."""
    import subprocess
    cmd = [sys.executable, _BENCH_PATH, "--only", name,
           "--steps", str(steps), "--batch", str(batch)]
    if name == "e2e" and profile_dir:
        cmd += ["--profile", profile_dir]
    # no CXN_BENCH_SYNC injection: the tunnel's sync semantics drift
    # within a boot, so each child re-calibrates for its own window
    # (an explicit user-set CXN_BENCH_SYNC is inherited via os.environ)
    # flight-recorder forensics file (telemetry/flight.py): the child
    # arms the dispatch ring and snapshots its tail here every ~2 s,
    # so when the parent SIGKILLs a wedged child the last snapshot
    # still names the in-flight executable - the hung-TPU evidence
    # every fallback round since 2026-07-30 lacked
    import tempfile
    flight_path = os.path.join(
        tempfile.gettempdir(),
        f"cxn_bench_{name}_{os.getpid()}_flight.json")
    env = dict(os.environ, CXN_BENCH_PROBE="0", CXN_BENCH_TIMEOUT="0",
               CXN_BENCH_FLIGHT=flight_path)
    global _CURRENT_CHILD
    try:
        with _EMIT_LOCK:
            # spawn under the lock: the watchdog sets _SHUTTING_DOWN
            # and kills the current child under the same lock, so a
            # child can never be spawned into a dying parent
            if _SHUTTING_DOWN:
                return {f"{name}_error": "skipped: parent shutting down"}
            p = subprocess.Popen(cmd, cwd=_REPO, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True)
            _CURRENT_CHILD = p  # so the watchdog can kill it on exit
        try:
            stdout, stderr = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            # the ROADMAP "reclaim the chip numbers" contract: one
            # hung backend field records an explicit timeout marker
            # and the round continues - a single wedged measurement
            # can never zero the whole round into a CPU fallback.
            # The marker now ships WITH forensics: the child's last
            # flight-recorder snapshot (in-flight executable
            # fingerprint, bucket, age) rides the artifact next to
            # {field}_timeout, so the post-mortem starts from "which
            # executable", not from nothing
            out = {f"{name}_timeout": True,
                   f"{name}_error": f"timed out after {timeout_s}s"}
            forensics = _read_flight_forensics(flight_path)
            if forensics is not None:
                out[f"{name}_forensics"] = forensics
            return out
        finally:
            _CURRENT_CHILD = None
            _cleanup_flight_file(flight_path)
        line = stdout.strip().splitlines()[-1] if stdout.strip() else ""
        if p.returncode == 0 and line:
            return json.loads(line)
        return {f"{name}_error":
                f"rc={p.returncode}: {stderr[-300:].strip()}"}
    except Exception as e:  # noqa: BLE001 - isolation is containment
        return {f"{name}_error": f"{type(e).__name__}: {e}"}


def _read_flight_forensics(path: str):
    """The killed child's last flight snapshot, bounded for the
    artifact (a forensics blob must not bloat the round JSON)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(snap, dict):
        return None
    flights = snap.get("flight") or []
    return {
        "snapshot_ts": snap.get("ts"),
        "in_flight": snap.get("in_flight") or [],
        "flight_tail": flights[-16:],
        "executables": (snap.get("executables") or [])[:32],
    }


def _cleanup_flight_file(path: str) -> None:
    # a timed-out field's snapshot was already embedded in the
    # fragment; a successful field's snapshot is just noise - and a
    # child killed mid-write can leave the .tmp sibling behind
    for p in (path, path + ".tmp"):
        try:
            os.remove(p)
        except OSError:
            pass


def _start_flight_dump(name: str) -> None:
    """Child half of the timeout forensics: arm the dispatch flight
    recorder (telemetry/flight.py) and snapshot its tail + the
    executable registry to CXN_BENCH_FLIGHT every ~2 s (atomic
    replace). A SIGKILLed child cannot flush anything at death - the
    standing snapshot is what survives, and the parent embeds it next
    to the {field}_timeout marker."""
    path = os.environ.get("CXN_BENCH_FLIGHT", "")
    if not path:
        return
    from cxxnet_tpu import telemetry
    telemetry.get().flight.arm()

    def _dump():
        while True:
            time.sleep(2.0)
            try:
                tel = telemetry.get()
                # graftlint: disable=GL004 wall TIMESTAMP by design - the snapshot merges with the ts-stamped streams
                snap = {"field": name, "ts": time.time(),
                        "flight": tel.flight.tail(48),
                        "in_flight": tel.flight.in_flight(),
                        "executables": tel.executables.snapshot()}
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(snap, f)
                os.replace(tmp, path)
            except Exception:  # noqa: BLE001 - forensics never kill the child
                pass

    threading.Thread(target=_dump, name="bench-flight-dump",
                     daemon=True).start()


def _child_run(name: str, batch: int, steps: int,
               profile_dir: str) -> dict:
    """--only entry point: one measurement, one JSON fragment."""
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()
    _start_flight_dump(name)
    import jax
    devices = jax.devices()
    platform = devices[0].platform
    _setup_compile_cache(platform)
    batch, steps = _default_workload(platform, batch, steps)
    kind = getattr(devices[0], "device_kind", "") or ""
    peak = _peak_for(kind)
    spec = {m[0]: m for m in _MEASUREMENTS}[name]
    # re-calibrate in THIS process's window
    _calibrate_sync(platform, peak)
    ctx = _Ctx(batch, steps, platform, profile_dir)
    frag = spec[1](ctx)
    if _SYNC_MODE != "block":
        # verify the readback primitive AFTER the measurement (the
        # verification readback poisons H2D, and afterwards it samples
        # the same window the measurement ran in)
        mode = "readback" if _verify_readback_sync(peak) \
            else "readback_unverified"
        frag[f"{name}_sync"] = mode
    return frag


def _setup_compile_cache(platform: str = "") -> None:
    """Repo-local persistent XLA compile cache: AlexNet-sized TPU
    compiles cost 20-40 s each; the repo dir persists across rounds, so
    cached executables turn the watchdog budget into measurement time.
    TPU entries live at the cache root (device-targeted, host-
    independent). CPU entries are scoped per host-CPU fingerprint:
    XLA:CPU AOT results baked for another machine's features load with
    SIGILL warnings (seen round 4), and a bench crash is worse than a
    recompile. Disable with CXN_BENCH_CACHE=0."""
    try:
        from cxxnet_tpu.utils.platform import setup_scoped_cache
        setup_scoped_cache(platform)
    except Exception as e:  # noqa: BLE001 - cache is an optimization
        sys.stderr.write(f"bench: compile cache unavailable: {e}\n")


_LAST_GOOD_PATH = os.path.join(_REPO, "docs", "last_good_tpu.json")
# capability evidence worth carrying across rounds: throughput/TFLOPs
# fields (per-field best across verified-sync runs) + the labels that
# make them interpretable
_LAST_GOOD_MAX_FIELDS = (
    "compute_ips", "e2e_ips", "e2e_devicedata_ips", "e2e_prefetch_ips",
    "e2e_fused_ips", "zero2_ips", "serve_qps", "serve_rows_per_s",
    "fold_infer_ips", "fold_over_infer",
    "int8_infer_ips", "int8_over_fold",
    "autotune_best_ips", "tuned_over_default",
    "plan_tuned_ips", "plan_over_default",
    "compute_poolties_ips", "googlenet_ips", "googlenet_devicedata_ips",
    "resnet18_ips", "resnet18_devicedata_ips",
    "device_augment_ips", "chip_matmul_tflops", "attn_pallas_tflops",
    "attn_pallas_speedup", "achieved_tflops", "mfu_pct")
_LAST_GOOD_LABEL_FIELDS = ("device_kind", "per_device_batch",
                           "pool_grad", "sync_mode")


def _field_verified(out: dict, field: str) -> bool:
    """Is this field's number trustworthy enough to archive? Each
    isolated child annotates its measurement with <name>_sync
    (readback / readback_unverified); block-mode timings carry no
    annotation and are trusted (block_until_ready passed the physics
    calibration). Inline readback mode has no post-measurement
    verification at all - never archive from it."""
    ann = out.get(f"{_SYNC_SOURCE.get(field, field)}_sync")
    if ann is not None:
        return ann != "readback_unverified"
    return out.get("sync_mode", "block") == "block"


def _save_last_good(out: dict) -> None:
    """Persist trustworthy chip numbers from a real TPU run so a
    future wedged-window round's CPU fallback can still publish them
    (labeled) in its artifact. Per-field best with per-field dates and
    a per-field sync gate: a link-bound or unverified window must not
    erase (or launder into) better verified evidence for an unrelated
    field. Called from _snapshot after every merge, so numbers
    measured before a mid-run wedge are archived even when the
    watchdog, not run(), emits the artifact. No headline-value gate:
    a run whose e2e/compute children all failed can still carry
    verified extras (chip_matmul, attention) worth archiving."""
    if out.get("platform") != "tpu" or "fallback" in out:
        return
    try:
        with open(_LAST_GOOD_PATH) as f:
            rec = json.load(f)
    except Exception:  # noqa: BLE001 - absent/corrupt: start fresh
        rec = {}
    fields = rec.setdefault("fields", {})
    dates = rec.setdefault("dates", {})
    today = time.strftime("%Y-%m-%d")
    dirty = False
    for k in _LAST_GOOD_MAX_FIELDS:
        v = out.get(k)
        if v and _field_verified(out, k) and v > fields.get(k, 0.0):
            fields[k], dates[k] = v, today
            dirty = True
    if not dirty and os.path.exists(_LAST_GOOD_PATH):
        return  # nothing new: skip the rewrite (runs every snapshot)
    # labels describe a RUN, while fields are per-field maxima possibly
    # from different runs - so labels are archived per-date under
    # "contexts" (the per-field dates point into it) and the top-level
    # labels keep their first-written (seed) values instead of being
    # clobbered by whichever later run happened to improve one field
    if dirty:
        ctx = rec.setdefault("contexts", {}).setdefault(today, {})
        for k in _LAST_GOOD_LABEL_FIELDS:
            if k in out:
                ctx[k] = out[k]
                rec.setdefault(k, out[k])
    rec.setdefault("provenance", (
        "per-field best across verified-sync bench.py TPU runs of this "
        "checkout; labels per run under 'contexts' (dates point into "
        "it); cross-field ratios are cross-window estimates"))
    rec["updated"] = today
    try:
        tmp = _LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        os.replace(tmp, _LAST_GOOD_PATH)
    except OSError as e:
        sys.stderr.write(f"bench: could not save last-good: {e}\n")


# measurement-child sync annotations live under the MEASUREMENT name,
# not the field name; map archived fields back to their measurement
_SYNC_SOURCE = {
    "compute_ips": "compute", "e2e_ips": "e2e",
    "e2e_devicedata_ips": "device_data",
    "e2e_prefetch_ips": "e2e_prefetch",
    "e2e_fused_ips": "fused",
    "zero2_ips": "zero",
    "serve_qps": "serve", "serve_rows_per_s": "serve",
    "serve_over_predict": "serve",
    # overload numbers, NOT throughput maxima: p99 under storm and
    # shed fraction have no "last-good max" semantics
    "serve_storm_p99_ms": "serve_storm",
    "serve_shed_frac": "serve_storm",
    "fold_infer_ips": "fold", "fold_unfolded_ips": "fold",
    "fold_over_infer": "fold",
    "int8_infer_ips": "int8", "int8_fold_ips": "int8",
    "int8_over_fold": "int8", "int8_argmax_agree": "int8",
    "autotune_best_ips": "autotune",
    "autotune_default_ips": "autotune",
    "tuned_over_default": "autotune",
    "plan_tuned_ips": "plan", "plan_default_ips": "plan",
    "plan_over_default": "plan",
    "compute_poolties_ips": "pool_ties", "googlenet_ips": "googlenet",
    "googlenet_devicedata_ips": "googlenet",
    "resnet18_ips": "resnet18", "resnet18_devicedata_ips": "resnet18",
    "device_augment_ips": "device_augment",
    "chip_matmul_tflops": "chip_matmul",
    "attn_pallas_tflops": "attention", "attn_pallas_speedup": "attention",
    # derived from e2e_ips, so they share its verification
    "achieved_tflops": "e2e", "mfu_pct": "e2e",
}


def _merge_last_good(out: dict) -> None:
    """On a non-TPU (fallback) run, surface the committed last-good
    chip numbers under a clearly-labeled nested object so a wedged
    driver window never again publishes ONLY a CPU number (round-4
    post-mortem: BENCH_r04.json was 3.17 img/s CPU noise while the
    real chip evidence sat in a side file)."""
    try:
        with open(_LAST_GOOD_PATH) as f:
            rec = json.load(f)
    except Exception:  # noqa: BLE001 - no archive, nothing to merge
        return
    if rec.get("fields"):
        out["last_measured_tpu"] = rec


def _reexec_cpu(reason: str) -> None:
    """Re-exec this process onto the CPU backend (the only escape from
    a PJRT client init hanging in C with signals undeliverable). On
    execve failure it RETURNS (with a stderr note) so the caller can
    fall through to its own degradation path."""
    sys.stderr.write(f"bench: {reason}; re-exec on CPU\n")
    sys.stderr.flush()
    prior = os.environ.get("JAX_PLATFORMS", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CXN_BENCH_FALLBACK="1",
               CXN_BENCH_FALLBACK_FROM=prior or "default")
    try:
        os.execve(sys.executable,
                  [sys.executable, _BENCH_PATH] + sys.argv[1:], env)
    except OSError as e:
        sys.stderr.write(f"bench: re-exec failed: {e}\n")


def _probe_backend_or_reexec() -> None:
    """90 s SUBPROCESS probe of backend init before this process
    commits to it. A wedged tunnel hangs PJRT client creation
    unkillably (observed round 4: hung for hours); without the probe
    the watchdog burns its whole budget discovering that, leaving the
    CPU fallback to start with nothing. The probe child can be
    killed, so a dead tunnel costs ~90 s instead of the full budget.
    A healthy tunnel costs one extra client init (~10 s). Skipped on
    the fallback run and under an explicit cpu platform. Disable with
    CXN_BENCH_PROBE=0."""
    if (os.environ.get("CXN_BENCH_PROBE") == "0"
            or os.environ.get("CXN_BENCH_FALLBACK") == "1"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        return
    import subprocess
    try:
        rc = subprocess.run(
            [sys.executable, "-c",
             "from cxxnet_tpu.utils.platform import ensure_env_platform;"
             "ensure_env_platform();"
             "import jax; jax.devices()"],
            timeout=float(os.environ.get("CXN_BENCH_PROBE_S", "90")),
            cwd=_REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL).returncode
    except subprocess.TimeoutExpired:
        _reexec_cpu("backend probe hung (wedged tunnel?)")
        # reached only when the re-exec failed: proceed on the original
        # backend and let the in-process retry + watchdog degrade
        return
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        sys.stderr.write(f"bench: backend probe skipped: {e}\n")
        return
    if rc != 0:
        # init ERRORS (not hangs) are retried in-process by run();
        # don't fall back on a possibly-transient failure
        sys.stderr.write(f"bench: backend probe exited rc={rc}; "
                         "proceeding (in-process retry)\n")


def run(profile_dir="", steps_override=0, batch_override=0) -> dict:
    import jax

    # an explicit JAX_PLATFORMS env must actually win: a bare
    # jax.devices() initializes every registered plugin, including a
    # possibly-dead tunnel (utils/platform.py)
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()
    _probe_backend_or_reexec()
    # backend init is the one step that touches the (possibly tunneled)
    # platform - retry transient failures instead of dying rc=1
    last = None
    for attempt in range(3):
        try:
            devices = jax.devices()
            break
        except Exception as e:  # noqa: BLE001 - backend errors vary
            last = e
            time.sleep(5.0 * (attempt + 1))
    else:
        raise RuntimeError(f"jax backend unreachable: {last}")
    platform = devices[0].platform
    # after backend init so the CPU cache can be host-scoped; the cache
    # only has to be configured before the first compile
    _setup_compile_cache(platform)
    ndev = len(devices)
    kind = getattr(devices[0], "device_kind", "") or ""
    peak_tflops = _peak_for(kind)

    # full headline config on an accelerator; shrunk on CPU so the
    # harness stays runnable anywhere (still the same code path -
    # AlexNet b256 on a host CPU would take tens of minutes)
    batch, steps = _default_workload(platform, batch_override,
                                     steps_override)

    out = {
        "metric": "alexnet_b%d_%s_train_e2e" % (batch, platform),
        "unit": "images/sec",
        "platform": platform,
        "device_count": ndev,
        "device_kind": kind,
        "per_device_batch": batch // ndev,
        "steps": steps,
        # flagship config choice, stated in the artifact: industry-
        # standard single-winner max-pool backward (the reference tie
        # rule is the opt-in; compute_poolties_ips prices it)
        "pool_grad": "winner",
    }
    if os.environ.get("CXN_BENCH_FALLBACK") == "1":
        src = os.environ.get("CXN_BENCH_FALLBACK_FROM", "default")
        out["fallback"] = f"backend '{src}' hung; CPU harness run"
    if platform != "tpu":
        # merged before the first snapshot so even a watchdog-truncated
        # fallback artifact carries the archived chip evidence
        _merge_last_good(out)

    # which sync primitive can be trusted THIS boot (see _SYNC_MODE)
    out.update(_calibrate_sync(platform, peak_tflops))
    _snapshot(out)

    if profile_dir and platform == "tpu":
        # stop_trace is a large D2H fetch: on the tunneled platform it
        # stickily degrades H2D for the rest of that process. Under
        # isolation only the e2e child is affected (its trace fetch
        # runs after its timed loop); on the inline path every extra
        # AFTER the e2e loop rides the poisoned link
        if os.environ.get("CXN_BENCH_ISOLATE", "1") == "0":
            sys.stderr.write(
                "bench: --profile's trace fetch degrades tunneled H2D; "
                "treat inline extras after e2e as lower bounds\n")
            out["profile_note"] = ("extras after e2e degraded by "
                                   "--profile trace fetch (inline run)")
        else:
            out["profile_note"] = "profile trace captured from the e2e loop"

    gates_off = {m[0] for m in _MEASUREMENTS
                 if m[2] and os.environ.get(m[2]) == "0"}

    # TPU: one fresh subprocess per measurement. Two failure modes
    # demand it, both observed on the tunnel this round: (a) a D2H
    # readback (the only real sync when block_until_ready is a no-op)
    # stickily poisons that PROCESS's H2D to ~21 MB/s, and (b) any
    # hang costs only the child's timeout, not the whole watchdog
    # budget. The compile cache makes each child's compile a hit.
    # CXN_BENCH_ISOLATE=0 falls back to the inline path.
    isolate = (platform == "tpu"
               and os.environ.get("CXN_BENCH_ISOLATE", "1") != "0"
               and os.environ.get("CXN_BENCH_FALLBACK") != "1")
    if isolate:
        # live within the WATCHDOG's budget, don't race it: the child
        # timeouts sum to ~3x the default 480s, so each child's
        # timeout is capped to the time remaining (minus a margin for
        # the final print) and the tail is skipped outright when the
        # margin is gone. The parent then always exits cleanly with a
        # best-so-far artifact instead of the watchdog re-exec'ing a
        # half-finished TPU run onto the CPU. The deadline shares the
        # watchdog Timer's own anchor (main() sets _WATCHDOG_FIRE_AT
        # when it starts the Timer) - anchoring here would donate the
        # backend probe / PJRT init / calibration time to the margin.
        if _WATCHDOG_FIRE_AT != float("inf"):
            deadline = _WATCHDOG_FIRE_AT - 25.0
        else:  # run() called directly (tests, library use): no Timer
            budget = float(os.environ.get("CXN_BENCH_TIMEOUT", "480"))
            deadline = (time.monotonic() + budget - 25.0) if budget > 0 \
                else float("inf")
        for name, _fn, _gate, tmo, _kind in _MEASUREMENTS:
            if name in gates_off:
                continue
            remaining = deadline - time.monotonic()
            if remaining < 30.0:
                out.setdefault(
                    "truncated",
                    f"isolated tail from '{name}' skipped: watchdog "
                    "budget exhausted")
                break
            out.update(_run_isolated(name, batch, steps, profile_dir,
                                     min(tmo, remaining)))
            _physics_check(out, peak_tflops, ndev)
            _derive(out, batch, platform, ndev, peak_tflops)
            _snapshot(out)
        # the headline rides one child's link-health lottery (this
        # boot: 236 img/s in one window, 1,140 in another, same code);
        # a second run at the end takes the better window and records
        # both, so one bad window cannot misprice the framework
        remaining = deadline - time.monotonic()
        if remaining < 30.0:
            frag2 = {}
        else:
            frag2 = _run_isolated("e2e", batch, steps, "",
                                  min(200.0, remaining))
        # physics-check the fragment BEFORE promotion: a run2 from a
        # no-working-sync window must not overwrite run1's genuine
        # number only to be retracted afterwards
        _physics_check(frag2, peak_tflops, ndev)
        v2 = frag2.get("e2e_ips", 0.0)
        if v2:
            # pick the better WINDOW, not just the bigger number: a
            # verified-sync run beats an unverified one regardless of
            # magnitude (an unverified readback means the number may be
            # dispatch timing - inflated, not better)
            def _quality(frag_or_out):
                # no number at all < unverified number < verified
                if not frag_or_out.get("e2e_ips"):
                    return -1
                sync = frag_or_out.get("e2e_sync", "block")
                return 0 if sync == "readback_unverified" else 1
            q1 = (_quality(out), out.get("e2e_ips", 0.0))
            q2 = (_quality(frag2), v2)
            if q2 > q1:
                # demote run1's fields (incl. a failure or a physics
                # retraction), promote frag2 wholesale so every
                # unsuffixed e2e/h2d field describes the headline run
                for k in ("e2e_ips", "e2e_steps", "e2e_sync",
                          "e2e_error", "e2e_ips_implausible",
                          "h2d_mbps", "h2d_dispatch_mbps",
                          "h2d_probe_error"):
                    if k in out:
                        out[k + "_run1"] = out.pop(k)
                out.update(frag2)
                if profile_dir and platform == "tpu":
                    # the trace was captured from run1's loop, which
                    # is no longer the headline run
                    out["profile_note"] = (
                        "profile trace describes e2e run1 (demoted; "
                        "see *_run1 fields), not the headline run")
            else:
                out["e2e_ips_run2"] = v2
                # the sync annotation travels with the number: a
                # losing run2 is often losing BECAUSE it is unverified
                for k in ("e2e_sync", "h2d_mbps", "h2d_dispatch_mbps"):
                    if frag2.get(k):
                        out[k + "_run2"] = frag2[k]
        else:
            # "recording both runs" includes a failed/retracted run2:
            # its error or implausible value lands under _run2 keys
            for k in ("e2e_error", "e2e_ips_implausible", "e2e_sync"):
                if k in frag2:
                    out[k + "_run2"] = frag2[k]
        _physics_check(out, peak_tflops, ndev)
        _derive(out, batch, platform, ndev, peak_tflops)
        _snapshot(out)
    else:
        ctx = _Ctx(batch, steps, platform, profile_dir)
        specs = {m[0]: m for m in _MEASUREMENTS}
        order = list(_INLINE_ORDER)
        if _SYNC_MODE == "readback":
            # e2e must run before the first readback sync poisons H2D
            order.remove("e2e")
            order.insert(0, "e2e")
        first_h2d_done = False
        for name in order:
            if name in gates_off:
                continue
            # compute/e2e are the headline: exceptions propagate (the
            # main() snapshot/error paths own that contract); extras
            # degrade to *_error fields inside their own bodies
            out.update(specs[name][1](ctx))
            if _SYNC_MODE == "readback" and specs[name][4] == "h2d":
                # inline (non-isolated) readback mode: every H2D loop
                # after the first sync rides a poisoned link - the
                # artifact must say these are lower bounds
                if first_h2d_done:
                    out[f"{name}_note"] = "poisoned H2D link (inline " \
                        "readback mode); lower bound"
                first_h2d_done = True
            _physics_check(out, peak_tflops, ndev)
            _derive(out, batch, platform, ndev, peak_tflops)
            _snapshot(out)
    _measure_graftlint(out)
    _measure_obs(out)
    _measure_lock_audit(out)
    _snapshot(out)
    _finalize(out, platform)
    return out


def _measure_graftlint(out: dict) -> None:
    """Wall-time of the tier-1 static-analysis pass over the full
    package tree (docs/STATIC_ANALYSIS.md) - the analysis itself gets
    a perf trajectory, with a < 10 s CI budget the blocking job
    enforces (--max-seconds). Guarded like every extra: a failure
    degrades to graftlint_error, never kills the headline."""
    try:
        from cxxnet_tpu.analysis.astlint import lint_paths
        pkg = os.path.join(_REPO, "cxxnet_tpu")
        findings, n_files, elapsed = lint_paths([pkg])
        out["graftlint_s"] = round(elapsed, 3)
        out["graftlint_files"] = n_files
        out["graftlint_unwaived"] = sum(
            1 for f in findings if not f.waived)
        out["graftlint_budget_s"] = 10.0
    except Exception as e:  # noqa: BLE001 - extras must not kill bench
        out["graftlint_error"] = f"{type(e).__name__}: {e}"


def _measure_obs(out: dict) -> None:
    """Cost of the live observability plane's exposition path
    (docs/OBSERVABILITY.md): Prometheus render time over a
    realistically populated registry, and one localhost /metrics
    scrape round trip through the stdlib HTTP server - the per-scrape
    tax a metrics_port= run pays, which must stay far below any sane
    scrape interval. Guarded like every extra."""
    try:
        from cxxnet_tpu import telemetry
        from cxxnet_tpu.telemetry.http import (
            ObservabilityServer, render_prometheus, validate_exposition)
        tel = telemetry.Telemetry()
        # ~the instrument population of a long training run: a few
        # dozen series incl. full histogram windows
        for i in range(24):
            h = tel.histogram(f"bench.h{i:02d}_s")
            for k in range(512):
                h.observe((k % 97) * 1e-4)
        for i in range(24):
            tel.inc(f"bench.c{i:02d}", i * 7)
            tel.set_gauge(f"bench.g{i:02d}", i * 0.5)
        t0 = time.monotonic()
        n_render = 20
        for _ in range(n_render):
            text = render_prometheus(tel)
        out["obs_render_ms"] = round(
            (time.monotonic() - t0) / n_render * 1e3, 3)
        if validate_exposition(text):
            out["obs_error"] = "render produced malformed exposition"
            return
        import urllib.request
        srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            scrapes = []
            for _ in range(10):
                t0 = time.monotonic()
                with urllib.request.urlopen(url, timeout=5.0) as r:
                    r.read()
                scrapes.append(time.monotonic() - t0)
            scrapes.sort()
            out["obs_scrape_ms"] = round(
                scrapes[len(scrapes) // 2] * 1e3, 3)
        finally:
            srv.close()
    except Exception as e:  # noqa: BLE001 - extras must not kill bench
        out["obs_error"] = f"{type(e).__name__}: {e}"


def _measure_lock_audit(out: dict) -> None:
    """Wall-time + worst held-duration of the runtime lock audit's
    jax-free scenarios (docs/STATIC_ANALYSIS.md "Concurrency
    analysis") - the concurrency gate gets a perf trajectory like
    graftlint_s, and the contention gauges (`lock.audit.*`) land in
    the telemetry registry as a side effect. The serve-storm scenario
    stays in CI only: it rebuilds a trainer, which would perturb the
    bench window. Guarded like every extra."""
    try:
        from cxxnet_tpu.analysis.lock_audit import run_lock_audit
        rep = run_lock_audit(
            scenarios=("prefetch-round", "watchdog-stall"))
        out["lock_audit_s"] = rep["elapsed_s"]
        out["lock_max_held_ms"] = rep["max_held_ms"]
        if rep["failed"]:
            out["lock_audit_failed"] = rep["failed"]
    except Exception as e:  # noqa: BLE001 - extras must not kill bench
        out["lock_audit_error"] = f"{type(e).__name__}: {e}"


def _finalize(out: dict, platform: str) -> None:
    """run()'s tail: label an all-failed artifact, archive a good one."""
    if "value" not in out:
        # every measurement failed: the metric name still says "e2e",
        # so the zero must be self-describing (value_is=none), not
        # readable as an e2e result of 0
        out.update(value=0.0, vs_baseline=0.0, value_is="none")
        # an all-failed run ON the TPU platform (tunnel wedged mid-run)
        # is exactly the wedged-window class the archive exists for -
        # the zeroed artifact must still carry the chip evidence
        _merge_last_good(out)
    elif platform == "tpu":
        _save_last_good(out)


def _error_json(msg: str) -> str:
    return json.dumps({"metric": "alexnet_train_e2e", "value": 0.0,
                       "unit": "images/sec", "vs_baseline": 0.0,
                       "error": msg})


def main(argv) -> int:
    try:
        profile_dir = ""
        steps = batch = 0
        only = ""
        if "--profile" in argv:
            profile_dir = argv[argv.index("--profile") + 1]
        if "--steps" in argv:
            steps = int(argv[argv.index("--steps") + 1])
        if "--batch" in argv:
            batch = int(argv[argv.index("--batch") + 1])
        if "--only" in argv:
            only = argv[argv.index("--only") + 1]
        budget = int(os.environ.get("CXN_BENCH_TIMEOUT", "480"))
    except Exception as e:  # noqa: BLE001 - the JSON line is the contract
        print(_error_json(f"bad arguments {argv}: {e}"))
        return 0

    if only:
        # isolated-measurement child: one fragment on stdout, rc=0 on
        # success; errors go to rc=1 + stderr. When bench.py is the
        # spawner it sets CXN_BENCH_TIMEOUT=0 and enforces the timeout
        # itself (it can SIGKILL a child wedged inside PJRT); a child
        # run BY HAND still sees the default budget, so honor it with
        # a local watchdog - a wedged tunnel must never hang a
        # hand-run child forever
        if budget > 0:
            def _only_watchdog():
                sys.stderr.write(
                    f"bench --only {only}: exceeded {budget}s "
                    "(hung backend / stuck tunnel?)\n")
                sys.stderr.flush()
                os._exit(1)
            wt = threading.Timer(budget, _only_watchdog)
            wt.daemon = True
            wt.start()
        else:
            wt = None
        try:
            print(json.dumps(_child_run(only, batch, steps,
                                        profile_dir)), flush=True)
            return 0
        except Exception as e:  # noqa: BLE001 - parent needs the text
            sys.stderr.write(f"{type(e).__name__}: {e}\n")
            return 1
        finally:
            # a completed measurement must not be os._exit(1)'d later
            # by the leaked Timer when main() is called in-process
            if wt is not None:
                wt.cancel()

    def watchdog():
        # a hung PJRT client creation blocks in C with the GIL state
        # such that signals never run - escaping from a daemon thread
        # is the only reliable move. If ANY headline number is already
        # measured (budget ran out mid-extras or mid-e2e), print the
        # snapshot and exit clean. Otherwise, first occurrence:
        # re-exec the whole process onto the CPU backend so the harness
        # still produces a real (clearly-labeled) number; second
        # occurrence: emit the error artifact and exit cleanly.
        def _kill_child_locked():
            # an orphaned isolated child would hold the exclusive TPU
            # forever (it runs with CXN_BENCH_TIMEOUT=0); caller holds
            # _EMIT_LOCK, and _SHUTTING_DOWN (set under the same lock)
            # stops the main thread from spawning a successor
            global _SHUTTING_DOWN
            _SHUTTING_DOWN = True
            p = _CURRENT_CHILD
            if p is not None:
                try:
                    p.kill()
                except Exception:  # noqa: BLE001 - already gone
                    pass
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return  # main thread already printed the full result
            if _PARTIAL.get("value"):
                _PARTIAL["emitted"] = True
                _PARTIAL["truncated"] = (
                    f"cut at the {budget}s watchdog")
                _kill_child_locked()
                print(json.dumps(
                    {k: v for k, v in _PARTIAL.items()
                     if k != "emitted"}), flush=True)
                os._exit(0)
            _kill_child_locked()
        if (os.environ.get("CXN_BENCH_FALLBACK") != "1"
                and os.environ.get("JAX_PLATFORMS", "") != "cpu"):
            _reexec_cpu(f"backend hung for {budget}s")
        print(_error_json(f"benchmark exceeded {budget}s "
                          "(hung backend / stuck tunnel?)"), flush=True)
        os._exit(0)

    if budget > 0:
        global _WATCHDOG_FIRE_AT
        _WATCHDOG_FIRE_AT = time.monotonic() + budget
        t = threading.Timer(budget, watchdog)
        t.daemon = True
        t.start()
    try:
        out = run(profile_dir, steps, batch)
        # claim the single JSON line under the lock: a timer firing in
        # this window must neither double-print nor mislabel a full
        # run as truncated
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return 0  # watchdog already printed the partial line
            _PARTIAL["emitted"] = True
    except BaseException as e:  # noqa: BLE001 - always emit the JSON line
        # a CRASH after a completed measurement must emit the snapshot,
        # not a value=0.0 artifact (round-3 post-mortem: a late error
        # zeroed a whole round); claim the line under the lock so a
        # concurrently-firing watchdog cannot double-print
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return 0
            _PARTIAL["emitted"] = True
            if _PARTIAL.get("value"):
                _PARTIAL["truncated"] = (
                    f"crashed mid-run: {type(e).__name__}: {e}")
                print(json.dumps(
                    {k: v for k, v in _PARTIAL.items()
                     if k != "emitted"}), flush=True)
                return 0
        print(_error_json(f"{type(e).__name__}: {e}"))
        return 0
    finally:
        if budget > 0:
            t.cancel()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
