"""Headline benchmark: AlexNet training throughput (images/sec).

Two numbers are measured on the same trainer:

- ``compute``:  the jitted train step driven on pre-staged device
  buffers - the kernel/compiler ceiling, what BENCH_r02 measured.
- ``e2e``:      the full product path the reference times
  (cxxnet_main.cpp:367-387): ``trainer.update()`` fed per-step from
  host batches - includes padding, H2D staging, the on-device metric
  accumulation, and the optimizer, i.e. what a user actually gets.

The headline ``value`` is the END-TO-END number. Extra fields record the
compute ceiling, the eval_train=1 variant, and the device topology so
per-chip claims are verifiable from the artifact alone.

Prints ONE JSON line even when the backend is unreachable
(``{"metric": ..., "error": ...}``) - a backend hiccup must yield a
diagnosable artifact, not rc=1.

Baseline constant: the reference publishes no numbers (BASELINE.md), and
this sandbox has no A100 (and no egress to cite one), so the A100
anchor is an arithmetic estimate, documented at the constant.

Usage: python bench.py [--profile DIR] [--steps N]
    --profile DIR  additionally capture a jax.profiler trace of the
                   steady-state e2e loop into DIR.

A watchdog thread (CXN_BENCH_TIMEOUT, default 480 s) handles a hung
backend (e.g. a stuck tunnel lease blocking inside PJRT client
creation, where no Python signal can ever be delivered): the first
occurrence re-execs the process onto the CPU backend so a real,
clearly-labeled number (JSON field "fallback") is still produced; if
already on CPU (or the re-exec fails) it prints the error JSON line
and exits cleanly instead of dying rc-143 with no artifact.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

# AlexNet training flops/image ~= 0.72 GMAC fwd x 2 flop/MAC x 3
# (fwd + dgrad + wgrad) ~= 4.3 GFLOP. A100 bf16 peak = 312 TFLOP/s;
# AlexNet's LRN/pooling/fc mix sustains well under full MFU - assume
# ~15%, in line with public convnet training MFU on Ampere, giving
# 312e12 * 0.15 / 4.3e9 ~= 10.9k img/s; rounded to 10k. An estimate,
# not a measurement: no A100 exists here and the reference publishes
# no throughput numbers (BASELINE.md).
A100_IMAGES_PER_SEC = 10000.0

# resolved at import, before anything can os.chdir: the re-exec path
# must not depend on the working directory
_BENCH_PATH = os.path.abspath(__file__)

# headline results land here as soon as they are measured; if the
# watchdog fires during the OPTIONAL extras (top-ops profile, attention
# micro-bench), it prints these instead of throwing away a completed
# on-chip measurement with a CPU re-exec. _EMIT_LOCK serializes the
# "who prints the one JSON line" decision between the main thread and
# the watchdog timer.
_PARTIAL: dict = {}
_EMIT_LOCK = threading.Lock()


def _alexnet_batch(rng, batch):
    """The bench's input shape in ONE place (matches _ALEXNET_CONF)."""
    return (rng.randn(batch, 3, 227, 227).astype(np.float32),
            rng.randint(0, 1000, size=(batch, 1)).astype(np.float32))


def _measure_compute(trainer, batch, steps):
    """Train-step-only throughput on pre-staged device buffers."""
    import jax
    rng = np.random.RandomState(0)
    hdata, hlabel = _alexnet_batch(rng, batch)
    data = jax.device_put(hdata, trainer._batch_sharded)
    label = jax.device_put(hlabel, trainer._batch_sharded)
    mask = jax.device_put(np.ones(batch, np.float32),
                          trainer._batch_sharded)
    labels = {"label": label}
    key = jax.random.PRNGKey(0)

    state = trainer.state
    # warmup (compile + first run); the host readback of the loss forces
    # true completion - block_until_ready alone does not flush the
    # dispatch queue on tunneled platforms
    for i in range(3):
        state, loss = trainer._train_step(
            state, data, labels, mask, jax.random.fold_in(key, i))
    float(np.asarray(loss))

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss = trainer._train_step(
            state, data, labels, mask, jax.random.fold_in(key, i))
    float(np.asarray(loss))
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    trainer.state = state
    return steps * batch / dt


def _measure_e2e(trainer, batch, steps, profile_dir=""):
    """Full trainer.update() path fed from host batches."""
    import jax
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(1)
    # a few distinct host batches cycled through, like a RAM-resident
    # iterator (membuffer); fresh numpy arrays each step would measure
    # the RNG, identical ones would hide nothing - staging cost is the
    # same either way
    nbuf = min(8, steps)
    batches = [DataBatch(*_alexnet_batch(rng, batch))
               for _ in range(nbuf)]
    for i in range(2):  # warmup
        trainer.update(batches[i % nbuf])
    jax.block_until_ready(trainer.state)

    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.update(batches[i % nbuf])
    jax.block_until_ready(trainer.state)
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    return steps * batch / dt


def _bench_attention(platform: str) -> dict:
    """Flash-attention kernel micro-bench (TPU only): fwd+bwd TFLOP/s
    for the Pallas kernel vs the XLA blockwise path on a transformer
    shape (b4 h8 s4096 d128, bf16). This is the kernel's on-hardware
    validation - the sandbox's CPU mesh can only run it in interpret
    mode - so a kernel failure degrades to an error field, never kills
    the headline bench. Disable with CXN_BENCH_ATTN=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_ATTN") == "0":
        return {}
    try:
        import jax
        import jax.numpy as jnp
        from cxxnet_tpu.ops.attention import blockwise_attention
        from cxxnet_tpu.ops.pallas_attention import flash_attention

        b, h, s, d = 4, 8, 4096, 128
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
                   for _ in range(3))
        # fwd 2 matmuls (4bhs^2d flops) + bwd 5 matmuls (10bhs^2d)
        flops = 14.0 * b * h * s * s * d
        steps = 10

        def measure(core):
            # all three grads: argnums=0 alone would let XLA dead-code
            # the dK/dV matmuls out of the XLA path while the fused
            # Pallas bwd computes them regardless, skewing the ratio
            f = jax.jit(jax.grad(
                lambda q, k, v: core(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2)))
            g = f(q, k, v)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(steps):
                g = f(q, k, v)
            jax.block_until_ready(g)
            return steps * flops / (time.perf_counter() - t0) / 1e12

        pallas_tf = measure(
            lambda q, k, v: flash_attention(q, k, v, False, None, False))
        xla_tf = measure(
            lambda q, k, v: blockwise_attention(q, k, v, kv_block=512))
        return {"attn_pallas_tflops": round(pallas_tf, 2),
                "attn_xla_tflops": round(xla_tf, 2),
                "attn_pallas_speedup": round(pallas_tf / xla_tf, 3)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"attn_error": f"{type(e).__name__}: {e}"}


def _bench_top_ops(trainer, batch, platform: str) -> dict:
    """Compact device profile of the already-compiled e2e step (TPU
    only; no extra compile): 8 profiled updates -> top-5 ops by device
    time as [[name, pct], ...]. The driver records the JSON artifact,
    so this lands the step's time breakdown on every on-chip bench run.
    Disable with CXN_BENCH_PROFILE=0."""
    if platform != "tpu" or os.environ.get("CXN_BENCH_PROFILE") == "0":
        return {}
    try:
        import glob
        import tempfile

        import jax
        from cxxnet_tpu.io.data import DataBatch
        from cxxnet_tpu.tools.profile_step import op_table
        rng = np.random.RandomState(2)
        db = DataBatch(*_alexnet_batch(rng, batch))
        d = tempfile.mkdtemp(prefix="cxn_bench_prof_")
        try:
            jax.profiler.start_trace(d)
            for _ in range(8):
                trainer.update(db)
            jax.block_until_ready(trainer.state)
            jax.profiler.stop_trace()
            xp = glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                           recursive=True)
            rows, total = op_table(xp[0], top=5)
        finally:
            import shutil
            shutil.rmtree(d, ignore_errors=True)
        return {"top_ops": [[n[:60], round(100.0 * ns / max(total, 1), 1)]
                            for n, ns in rows],
                "profiled_device_ms": round(total / 1e6, 2)}
    except Exception as e:  # noqa: BLE001 - never kill the headline
        return {"profile_error": f"{type(e).__name__}: {e}"}


def run(profile_dir="", steps_override=0) -> dict:
    import jax
    from __graft_entry__ import _ALEXNET_CONF, _make_trainer
    from cxxnet_tpu.utils.config import parse_config_file

    # an explicit JAX_PLATFORMS env must actually win: a bare
    # jax.devices() initializes every registered plugin, including a
    # possibly-dead tunnel (utils/platform.py)
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()
    # backend init is the one step that touches the (possibly tunneled)
    # platform - retry transient failures instead of dying rc=1
    last = None
    for attempt in range(3):
        try:
            devices = jax.devices()
            break
        except Exception as e:  # noqa: BLE001 - backend errors vary
            last = e
            time.sleep(5.0 * (attempt + 1))
    else:
        raise RuntimeError(f"jax backend unreachable: {last}")
    platform = devices[0].platform
    ndev = len(devices)

    # full headline config on an accelerator; shrunk on CPU so the
    # harness stays runnable anywhere (still the same code path -
    # AlexNet b256 on a host CPU would take tens of minutes)
    batch = 256 if platform != "cpu" else 8
    steps = steps_override or (50 if platform != "cpu" else 2)

    def make(eval_train):
        return _make_trainer(
            parse_config_file(_ALEXNET_CONF),
            [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
             ("eval_train", str(eval_train)), ("save_model", "0")])

    trainer = make(0)
    compute_ips = _measure_compute(trainer, batch, steps)
    e2e_ips = _measure_e2e(trainer, batch, steps, profile_dir)
    # eval_train=1 (the reference's default mode): the conf's metric
    # lines (error, rec@1, rec@5) compile into the step as device-side
    # accumulators
    trainer_m = make(1)
    e2e_metric_ips = _measure_e2e(trainer_m, batch, steps)

    out = {
        "metric": "alexnet_b%d_%s_train_e2e" % (batch, platform),
        "value": round(e2e_ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(e2e_ips / A100_IMAGES_PER_SEC, 4),
        "compute_ips": round(compute_ips, 2),
        "e2e_eval_train_ips": round(e2e_metric_ips, 2),
        "e2e_over_compute": round(e2e_ips / compute_ips, 4),
        "platform": platform,
        "device_count": ndev,
        "per_device_batch": batch // ndev,
        "steps": steps,
    }
    # headline complete: the watchdog now emits this rather than
    # re-execing away a finished on-chip measurement; re-snapshot after
    # each extra so a completed extra survives the next one hanging
    # (under the lock: the watchdog iterates _PARTIAL concurrently)
    with _EMIT_LOCK:
        _PARTIAL.update(out)
    out.update(_bench_top_ops(trainer, batch, platform))
    with _EMIT_LOCK:
        _PARTIAL.update(out)
    out.update(_bench_attention(platform))
    with _EMIT_LOCK:
        _PARTIAL.update(out)
    if os.environ.get("CXN_BENCH_FALLBACK") == "1":
        src = os.environ.get("CXN_BENCH_FALLBACK_FROM", "default")
        out["fallback"] = (f"backend '{src}' hung; CPU harness run")
    return out


def _error_json(msg: str) -> str:
    return json.dumps({"metric": "alexnet_train_e2e", "value": 0.0,
                       "unit": "images/sec", "vs_baseline": 0.0,
                       "error": msg})


def main(argv) -> int:
    try:
        profile_dir = ""
        steps = 0
        if "--profile" in argv:
            profile_dir = argv[argv.index("--profile") + 1]
        if "--steps" in argv:
            steps = int(argv[argv.index("--steps") + 1])
        budget = int(os.environ.get("CXN_BENCH_TIMEOUT", "480"))
    except Exception as e:  # noqa: BLE001 - the JSON line is the contract
        print(_error_json(f"bad arguments {argv}: {e}"))
        return 0

    def watchdog():
        # a hung PJRT client creation blocks in C with the GIL state
        # such that signals never run - escaping from a daemon thread
        # is the only reliable move. If the HEADLINE numbers are
        # already measured (budget ran out inside the optional extras),
        # print them and exit clean. Otherwise, first occurrence:
        # re-exec the whole process onto the CPU backend so the harness
        # still produces a real (clearly-labeled) number; second
        # occurrence: emit the error artifact and exit cleanly.
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return  # main thread already printed the full result
            if _PARTIAL.get("value"):
                _PARTIAL["emitted"] = True
                _PARTIAL["truncated"] = (
                    f"extras cut at the {budget}s watchdog")
                print(json.dumps(
                    {k: v for k, v in _PARTIAL.items()
                     if k != "emitted"}), flush=True)
                os._exit(0)
        prior = os.environ.get("JAX_PLATFORMS", "")
        if os.environ.get("CXN_BENCH_FALLBACK") != "1" and prior != "cpu":
            sys.stderr.write(
                f"bench: backend hung for {budget}s; re-exec on CPU\n")
            sys.stderr.flush()
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       CXN_BENCH_FALLBACK="1",
                       CXN_BENCH_FALLBACK_FROM=prior or "default")
            try:
                os.execve(sys.executable,
                          [sys.executable, _BENCH_PATH] + argv, env)
            except OSError as e:
                sys.stderr.write(f"bench: re-exec failed: {e}\n")
        print(_error_json(f"benchmark exceeded {budget}s "
                          "(hung backend / stuck tunnel?)"), flush=True)
        os._exit(0)

    if budget > 0:
        t = threading.Timer(budget, watchdog)
        t.daemon = True
        t.start()
    try:
        out = run(profile_dir, steps)
        # claim the single JSON line under the lock: a timer firing in
        # this window must neither double-print nor mislabel a full
        # run as truncated
        with _EMIT_LOCK:
            if _PARTIAL.get("emitted"):
                return 0  # watchdog already printed the partial line
            _PARTIAL["emitted"] = True
    except BaseException as e:  # noqa: BLE001 - always emit the JSON line
        print(_error_json(f"{type(e).__name__}: {e}"))
        return 0
    finally:
        if budget > 0:
            t.cancel()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
