"""Same-split external baselines for the MLP acceptance number.

docs/acceptance/README.md reports 96.7% for examples/MNIST/MNIST.conf
(784-100-10 sigmoid MLP) on the digits proxy corpus, vs the reference's
published ~98% on true MNIST (reference example/MNIST/README.md:104-109).
The claim that this gap is DATA (8x8-resolution scans, 1,438 train
samples), not framework, needs an ablation on the identical split -
not an appeal to external folklore.

This script trains two known-good external baselines of the same
architecture class on EXACTLY the split the framework trains on
(cxxnet_tpu.tools.digits_to_idx.load_split - one function owns the
upsampling + shuffle):

- sklearn MLPClassifier, hidden (100,), logistic activation, SGD +
  momentum (the closest library twin of MNIST.conf's net + updater)
- a torch 784-100-10 sigmoid MLP trained with the conf's exact
  hyperparameters (eta 0.1, momentum 0.9, minibatch 100)

If these land in the same ~96-97% band, the gap to the published 98%
is a property of the corpus; committed output: baseline_mlp_log.txt.

Usage: python docs/acceptance/baseline_mlp.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

# runnable from any cwd without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _data():
    from cxxnet_tpu.tools.digits_to_idx import load_split
    tr_x, tr_y, te_x, te_y = load_split()
    flat = lambda a: a.reshape(len(a), -1).astype(np.float32) / 255.0
    return flat(tr_x), tr_y.astype(np.int64), flat(te_x), te_y.astype(
        np.int64)


def sklearn_mlp(tr_x, tr_y, te_x, te_y) -> float:
    from sklearn.neural_network import MLPClassifier
    clf = MLPClassifier(hidden_layer_sizes=(100,), activation="logistic",
                        solver="sgd", learning_rate_init=0.1,
                        momentum=0.9, batch_size=100, max_iter=400,
                        random_state=0)
    clf.fit(tr_x, tr_y)
    return float(np.mean(clf.predict(te_x) == te_y))


def torch_mlp(tr_x, tr_y, te_x, te_y, rounds: int = 60) -> float:
    """MNIST.conf's net + schedule verbatim: 784-100(sigmoid)-10,
    SGD eta 0.1 momentum 0.9, minibatch 100, 60 passes (the acceptance
    run's round count)."""
    import torch
    torch.manual_seed(0)
    net = torch.nn.Sequential(
        torch.nn.Linear(784, 100), torch.nn.Sigmoid(),
        torch.nn.Linear(100, 10))
    opt = torch.optim.SGD(net.parameters(), lr=0.1, momentum=0.9)
    loss_fn = torch.nn.CrossEntropyLoss()
    x = torch.from_numpy(tr_x)
    y = torch.from_numpy(tr_y)
    n = len(x)
    g = torch.Generator().manual_seed(1)
    for _ in range(rounds):
        order = torch.randperm(n, generator=g)
        for i in range(0, n - n % 100, 100):
            idx = order[i:i + 100]
            opt.zero_grad()
            loss_fn(net(x[idx]), y[idx]).backward()
            opt.step()
    with torch.no_grad():
        pred = net(torch.from_numpy(te_x)).argmax(1).numpy()
    return float(np.mean(pred == te_y))


def main() -> int:
    tr_x, tr_y, te_x, te_y = _data()
    print(f"split: {len(tr_x)} train / {len(te_x)} test "
          "(digits_to_idx.load_split, seed 0)")
    acc_sk = sklearn_mlp(tr_x, tr_y, te_x, te_y)
    print(f"sklearn MLP (100 logistic, sgd):  acc {acc_sk:.4f}")
    acc_th = torch_mlp(tr_x, tr_y, te_x, te_y)
    print(f"torch 784-100-10 sigmoid (conf hp): acc {acc_th:.4f}")
    print("framework (MNIST.conf, same split):  acc 0.9666 "
          "(digits_mlp_log.txt)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
