"""Int8 post-training quantization (docs/GRAPH_PASSES.md
"Quantization"): the quantize_int8 graph pass + ops/int8.py kernels -
scale math vs a numpy reference, calibration determinism across the
single/multi-batch paths, the `layer_quant` pin (config, plan and
schema), checkpoint/resume invariance, the Server's
uncalibrated-serves-float leg, and the tuning-cache `layer_quant`
plan key."""

import hashlib
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet import tuning
from cxxnet_tpu.nnet.passes import find_quant_sites
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.ops import int8 as int8_ops
from cxxnet_tpu.utils.config import ConfigError, parse_config_string

BN_MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:bn1] = batch_norm:bn1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 11
"""

_QUANT_PASSES = "graph_passes = fold_conv_bn,dead_layer_elim," \
                "quantize_int8\n"


def _build(conf, extra=""):
    tr = NetTrainer()
    for k, v in parse_config_string(conf + extra):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _batch(i, b=8, shape=(1, 1, 36), nclass=3):
    r = np.random.RandomState(700 + i)
    return DataBatch(
        data=r.rand(b, *shape).astype(np.float32),
        label=r.randint(0, nclass, size=(b, 1)).astype(np.float32))


# ---------------------------------------------------------------------------
# ops/int8.py scale math vs a numpy reference
# ---------------------------------------------------------------------------
def test_per_channel_scale_matches_numpy_reference():
    r = np.random.RandomState(3)
    w = (r.randn(5, 7) * np.asarray(
        [0.1, 1.0, 10.0, 0.0, 2.5])[:, None]).astype(np.float32)
    s = int8_ops.per_channel_scale(w)
    ref = np.abs(w).max(axis=1) / 127.0
    # the all-zero channel gets the floored (representable) scale
    ref[3] = 1e-8 / 127.0
    assert s.shape == (5,) and s.dtype == np.float32
    assert np.allclose(s, ref, rtol=1e-6, atol=0)


def test_quantize_weight_round_clip_and_dequant_roundtrip():
    r = np.random.RandomState(4)
    w = r.randn(6, 9).astype(np.float32)
    s = int8_ops.per_channel_scale(w)
    q = np.asarray(int8_ops.quantize_weight(w, s))
    assert q.dtype == np.int8
    ref = np.clip(np.round(w / s[:, None]), -127, 127)
    assert (q == ref.astype(np.int8)).all()
    # symmetric scheme: the per-channel absmax hits +-127 exactly
    assert np.abs(q).max(axis=1).tolist() == [127] * 6
    # dequantized weight is within half a quantization step
    assert np.abs(q * s[:, None] - w).max() <= (s.max() / 2) + 1e-7


def test_int8_matmul_dequant_close_to_float_matmul():
    r = np.random.RandomState(5)
    x = r.randn(4, 32).astype(np.float32)
    w = r.randn(10, 32).astype(np.float32)
    ascale = np.abs(x).max() / 127.0
    wscale = int8_ops.per_channel_scale(w)
    acc = int8_ops.int8_matmul(
        int8_ops.quantize_act(x, ascale),
        int8_ops.quantize_weight(w, wscale))
    assert np.asarray(acc).dtype == np.int32
    out = np.asarray(int8_ops.dequantize(acc, ascale, wscale))
    ref = x @ w.T
    # int8 quantization error budget: ~1% of the output scale
    assert np.abs(out - ref).max() <= 0.02 * np.abs(ref).max() + 0.05


def test_pallas_kernel_matches_lax_fallback_interpret():
    """The Pallas MXU kernel (interpret-mode hook, the pallas_lrn
    idiom) is bit-identical to the lax preferred-element-type
    fallback on a tile-clean shape."""
    r = np.random.RandomState(6)
    xq = r.randint(-127, 128, (32, 128)).astype(np.int8)
    wq = r.randint(-127, 128, (128, 128)).astype(np.int8)
    lax_out = np.asarray(int8_ops.int8_matmul(xq, wq))
    assert int8_ops._pallas_blocks(32, 128, 128) is not None
    old = int8_ops._FORCE_INTERPRET
    int8_ops._FORCE_INTERPRET = True
    try:
        # the test platform is an 8-device virtual CPU mesh
        # (conftest): the route gate must refuse - pallas_call has no
        # GSPMD partitioning rule - while the kernel itself still
        # runs in interpret mode
        import jax
        assert (int8_ops.use_pallas_int8(32, 128, 128)
                == (jax.device_count() == 1))
        pl_out = np.asarray(int8_ops._matmul_pallas(xq, wq))
    finally:
        int8_ops._FORCE_INTERPRET = old
    assert pl_out.dtype == np.int32
    assert (pl_out == lax_out).all()


# ---------------------------------------------------------------------------
# calibration: determinism across the N=1 / N>1 batch paths
# ---------------------------------------------------------------------------
def test_quant_calibration_absmax_matches_numpy_and_is_deterministic():
    on1 = _build(BN_MLP_CONF, _QUANT_PASSES)
    on2 = _build(BN_MLP_CONF, _QUANT_PASSES)
    b = _batch(90)
    assert on1.calibrate_graph_passes(b)
    # a one-element sequence rides the pinned single-batch path
    assert on2.calibrate_graph_passes([b])
    assert on1._quant_stats.keys() == {"fc1", "fc2"}
    assert on1._quant_stats == on2._quant_stats
    # fc1's tapped input IS the data node: exact numpy reference
    assert on1._quant_stats["fc1"] == pytest.approx(
        float(np.abs(b.data).max()), rel=1e-6)


def test_quant_multi_batch_calibration_pools_by_max():
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    batches = [_batch(91), _batch(92), _batch(93)]
    assert on.calibrate_graph_passes(batches)
    single = []
    for b in batches:
        t = _build(BN_MLP_CONF, _QUANT_PASSES)
        t.calibrate_graph_passes(b)
        single.append(t._quant_stats)
    for key in ("fc1", "fc2"):
        assert on._quant_stats[key] == pytest.approx(
            max(s[key] for s in single), rel=1e-5)


def test_quant_multi_batch_masks_padding_rows():
    """round_batch=0 zero-pads the tail batch; padding rows at depth
    carry bias/activation garbage that must not widen the frozen
    activation range."""
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    full = _batch(94)
    short = _batch(95)
    # poison the padding rows with a huge activation
    data = np.concatenate([short.data[:5],
                           np.full_like(short.data[:3], 1e6)])
    padded = DataBatch(data=data, label=short.label.copy(),
                       num_batch_padd=3)
    assert on.calibrate_graph_passes([full, padded])
    real_absmax = max(float(np.abs(full.data).max()),
                      float(np.abs(short.data[:5]).max()))
    assert on._quant_stats["fc1"] == pytest.approx(real_absmax,
                                                   rel=1e-5)


def test_single_batch_calibration_masks_padding_rows():
    """The N=1 path (_calibrate_staged) must mask padding rows out
    of the activation absmax exactly like the N>1 path - a
    round_batch=0 tail batch's zero-fill garbage at depth must not
    widen the frozen range (regression: the mask was discarded)."""
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    short = _batch(95)
    data = np.concatenate([short.data[:5],
                           np.full_like(short.data[:3], 1e6)])
    padded = DataBatch(data=data, label=short.label.copy(),
                       num_batch_padd=3)
    assert on.calibrate_graph_passes(padded)
    assert on._quant_stats["fc1"] == pytest.approx(
        float(np.abs(short.data[:5]).max()), rel=1e-5)


def test_set_weight_invalidates_quant_stats():
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    on.calibrate_graph_passes(_batch(96))
    assert not on.passes_need_calibration()
    w = np.asarray(on.get_weight("fc2", "wmat")[0])
    on.set_weight(w * 2.0, "fc2", "wmat")
    # frozen scales went stale: the epoch-bump eviction recalibrates
    assert on._quant_stats is None
    assert on.passes_need_calibration()


# ---------------------------------------------------------------------------
# end-to-end: parity + int8 engagement on the traced program
# ---------------------------------------------------------------------------
def _dot_dtypes(tr, b=8):
    node = tr.net_cfg.num_nodes - 1
    g, ge = tr.stage_infer_rows(np.zeros((b, 1, 1, 36), np.float32))
    eqns = tr._infer_fn(node).trace(
        tr.state["params"], g, ge).jaxpr.jaxpr.eqns
    return [(str(e.invars[0].aval.dtype), str(e.outvars[0].aval.dtype))
            for e in eqns if e.primitive.name == "dot_general"
            if e.invars[0].aval.shape
            and e.invars[0].aval.shape[0] == b]


def test_quantized_predict_agrees_with_fold_and_trace_is_int8():
    """Int8-only error isolation: compare against the FOLDED float
    trainer calibrated on the same batch (vs the unfolded baseline
    the comparison would also price the fold's frozen-vs-per-batch
    BN statistics - the GRAPH_PASSES.md fold semantics note)."""
    fold = _build(BN_MLP_CONF,
                  "graph_passes = fold_conv_bn,dead_layer_elim\n")
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    for i in range(4):
        fold.update(_batch(i))
        on.update(_batch(i))
    cb = _batch(79)
    fold.calibrate_graph_passes(cb)
    on.calibrate_graph_passes(cb)
    agree, total = 0, 0
    for i in range(4):
        b = _batch(80 + i)
        po, pn = fold.predict_dist(b), on.predict_dist(b)
        assert np.abs(po - pn).max() <= 0.02  # int8 error budget
        agree += int((po.argmax(1) == pn.argmax(1)).sum())
        total += po.shape[0]
    assert agree / total >= 0.9
    # every data-path matmul of the quantized trace is int8 -> int32;
    # the float trace keeps f32 dots (vacuity guard)
    q_dots = _dot_dtypes(on, b=8)
    assert q_dots and all(d == ("int8", "int32") for d in q_dots)
    f_dots = _dot_dtypes(fold, b=8)
    assert f_dots and all(d[0] == "float32" for d in f_dots)


def test_quantized_weights_stay_live_functions_of_params():
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    on.calibrate_graph_passes(_batch(97))
    b = _batch(98)
    p1 = on.predict_dist(b)
    # zero fc2's weight THROUGH the live params (no set_weight, no
    # eviction): the in-jit quantize stage must see the new weight
    import jax.numpy as jnp
    on.state["params"]["fc2"]["wmat"] = jnp.zeros_like(
        on.state["params"]["fc2"]["wmat"])
    p2 = on.predict_dist(b)
    assert not np.allclose(p1, p2)
    # zero logits -> uniform softmax rows
    assert np.allclose(p2, 1.0 / 3.0, atol=1e-6)


# ---------------------------------------------------------------------------
# the layer_quant pin
# ---------------------------------------------------------------------------
def test_layer_quant_float_pin_excludes_site():
    conf = BN_MLP_CONF.replace(
        "  nhidden = 16",
        "  nhidden = 16\n  layer_quant = float")
    tr = _build(conf, _QUANT_PASSES)
    idx = [tr.net_cfg.layers[i].name
           for i in find_quant_sites(tr.net_cfg)]
    assert idx == ["fc2"]
    # the pinned layer's dot stays float while fc2 quantizes
    tr.calibrate_graph_passes(_batch(99))
    dts = _dot_dtypes(tr)
    assert ("float32", "float32") in dts
    assert ("int8", "int32") in dts


def test_layer_quant_rejects_bad_value():
    with pytest.raises(ValueError, match="layer_quant"):
        _build(BN_MLP_CONF.replace(
            "  nhidden = 16",
            "  nhidden = 16\n  layer_quant = int4"))


# ---------------------------------------------------------------------------
# checkpoint bytes + two-way resume across the quant flag flip
# ---------------------------------------------------------------------------
def test_checkpoint_bytes_identical_quant_on_off():
    off = _build(BN_MLP_CONF)
    on = _build(BN_MLP_CONF, _QUANT_PASSES)
    for i in range(4):
        off.update(_batch(i))
        on.update(_batch(i))
    on.predict(_batch(81))  # calibrate + build the quantized graph
    bo, bq = io.BytesIO(), io.BytesIO()
    off.save_model(bo)
    on.save_model(bq)
    assert bo.getvalue() == bq.getvalue()


def test_resume_across_quant_flag_both_directions(tmp_path):
    """`continue = 1` resumes across quantize_int8 on<->off in both
    directions: the pass never touches the training graph or the
    checkpoint format (the fold-pass resume matrix, quant edition)."""
    from cxxnet_tpu.tools.pass_smoke import CONF
    from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist
    d = str(tmp_path)
    write_synth_mnist(d, 192, 0, "train")
    write_synth_mnist(d, 96, 1, "test")
    with open(os.path.join(d, "t.conf"), "w") as f:
        f.write(CONF.format(d=d))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    passes_arg = ("graph_passes=fold_conv_bn,dead_layer_elim,"
                  "quantize_int8")

    def run(mdir, *overrides):
        r = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu.main",
             os.path.join(d, "t.conf"), f"model_dir={mdir}",
             *overrides],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]

    def sha(mdir, n):
        with open(os.path.join(mdir, f"{n:04d}.model"), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()

    ma, mb = os.path.join(d, "ma"), os.path.join(d, "mb")
    run(ma)
    run(mb, passes_arg)
    assert sha(ma, 2) == sha(mb, 2)
    # resume ACROSS the flag flip, both directions
    run(ma, "continue=1", "num_round=3", "max_round=1", passes_arg)
    run(mb, "continue=1", "num_round=3", "max_round=1")
    assert sha(ma, 3) == sha(mb, 3)


# ---------------------------------------------------------------------------
# serving: uncalibrated warns and serves float
# ---------------------------------------------------------------------------
def test_server_uncalibrated_warns_and_serves_float(capsys):
    from cxxnet_tpu.serve import Server
    off = _build(BN_MLP_CONF)
    on = _build(BN_MLP_CONF, "graph_passes = quantize_int8\n")
    assert on.passes_need_calibration()
    srv = Server(on, max_batch=8, max_wait_ms=1.0, replicas=1)
    assert "have no calibration stats" in capsys.readouterr().err
    srv.warmup()
    srv.start()
    b = _batch(56, b=8)
    try:
        rows = srv.submit(b.data).result(timeout=60)
    finally:
        srv.stop()
    # float serving: matches the passes-off trainer exactly (the
    # un-rewritten graph is the same program)
    expect = off.infer_rows(*off.stage_infer_rows(b.data))
    assert np.allclose(rows, np.asarray(expect).reshape(8, -1),
                       rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# tuning cache: the layer_quant plan key
# ---------------------------------------------------------------------------
def test_cache_layer_quant_roundtrip_and_garbage_rejected(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {},
                      layers={"fc1": {"layer_quant": "float"},
                              "fc2": {"layer_quant": "int8"}})
    assert tuning.tuned_layer_plan(p, "cpu") == {
        "fc1": {"layer_quant": "float"},
        "fc2": {"layer_quant": "int8"}}
    with open(p) as f:
        assert json.load(f)["version"] == 2
    # the typo'd knob is untunable at save AND rejected at load
    with pytest.raises(ValueError, match="untunable per-layer"):
        tuning.save_entry(str(tmp_path / "x.json"), "cpu", {},
                          layers={"fc1": {"layer_qunat": "int8"}})
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump({"version": 2, "platforms": {
            "cpu": {"layers": {"fc1": {"layer_qunat": "int8"}}}}}, f)
    with pytest.raises(ConfigError):
        tuning.load_cache(bad)


def test_trainer_applies_layer_quant_plan_and_explicit_wins(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {},
                      layers={"fc1": {"layer_quant": "float"},
                              "bn1": {"layer_quant": "float"}})
    tr = _build(BN_MLP_CONF, f"tuning_cache = {p}\n" + _QUANT_PASSES)
    idx = tr.net_cfg.layer_name_map["fc1"]
    assert ("layer_quant", "float") in tr.net_cfg.layercfg[idx]
    # the plan stamp drives the pattern exclusion
    assert [tr.net_cfg.layers[i].name
            for i in find_quant_sites(tr.net_cfg)] == ["fc2"]
    # layer_quant on a non-conv/fullc layer is inapplicable: skipped
    bidx = tr.net_cfg.layer_name_map["bn1"]
    assert not any(k == "layer_quant"
                   for k, _ in tr.net_cfg.layercfg[bidx])
    # explicit per-layer key beats the plan
    conf2 = BN_MLP_CONF.replace(
        "  nhidden = 16",
        "  nhidden = 16\n  layer_quant = int8")
    tr2 = _build(conf2, f"tuning_cache = {p}\n" + _QUANT_PASSES)
    idx2 = tr2.net_cfg.layer_name_map["fc1"]
    vals = [v for k, v in tr2.net_cfg.layercfg[idx2]
            if k == "layer_quant"]
    assert vals == ["int8"]


# ---------------------------------------------------------------------------
# config schema: keys registered, the layer_qunat typo pinned
# ---------------------------------------------------------------------------
def test_schema_registers_quant_keys_and_pins_layer_qunat():
    from cxxnet_tpu.analysis import schema
    reg = schema.build_registry()
    for key in ("layer_quant", "pass_quantize_int8",
                "pass_elim_reshape", "pass_calibration_batches"):
        assert reg.recognizes(key), key
    # the serve_max_batchh treatment, quant edition
    assert reg.suggest("layer_qunat") == "layer_quant"
    with pytest.raises(ConfigError, match="layer_quant"):
        schema.validate_pairs([("layer_qunat", "int8")],
                              source="x.conf")


def test_pass_toggle_quantize_int8_via_prefix():
    tr = NetTrainer()
    tr.set_param("pass_quantize_int8", "1")
    assert tr._pass_toggles["quantize_int8"] == 1
    tr.set_param("pass_elim_reshape", "0")
    assert tr._pass_toggles["elim_reshape"] == 0
