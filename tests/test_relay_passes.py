"""PR-11 Relay-class optimizer surface (docs/GRAPH_PASSES.md):
activation fusion, conv+1x1 merging, common-subexpression sharing,
the per-layer autotuner plans (tuning-cache schema v2 + migration),
the telemetry-shaped serve bucket ladder, and multi-batch fold
calibration."""

import io
import json

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet import passes, tuning
from cxxnet_tpu.nnet.passes import PassPipeline
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.serve import (bucket_sizes, ladder_buckets,
                              ladder_from_histogram)
from cxxnet_tpu.utils.config import ConfigError, parse_config_string

ACT_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+0] = bias:bs1
  init_bias = 0.05
layer[+1:r1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 7
"""

MERGE_CONF = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 4
  kernel_size = 3
  pad = 1
layer[+1:c2] = conv:c2
  nchannel = 6
  kernel_size = 1
layer[+1:r1] = relu
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 5
"""

FOLD_MERGE_CONF = MERGE_CONF.replace(
    "layer[+1:c2] = conv:c2",
    "layer[+1:b1] = batch_norm:b1\nlayer[+1:c2] = conv:c2")

CSE_CONF = """
netconfig=start
layer[0->a] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[0->b] = share[fc1]
layer[a,b->c] = concat
layer[+1:fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,12
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 3
"""

# two DISTINCT primaries with identical configs: same function shape,
# but equal weights cannot be proven - must NOT dedupe
CSE_DISTINCT_CONF = CSE_CONF.replace(
    "layer[0->b] = share[fc1]",
    "layer[0->b] = fullc:fc1b\n  nhidden = 8\n  init_sigma = 0.1")

BN_MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:bn1] = batch_norm:bn1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 11
"""


def _build(conf, extra=""):
    tr = NetTrainer()
    for k, v in parse_config_string(conf + extra):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _batch(i, b=8, shape=(1, 1, 36), nclass=3):
    r = np.random.RandomState(500 + i)
    return DataBatch(
        data=r.rand(b, *shape).astype(np.float32),
        label=r.randint(0, nclass, size=(b, 1)).astype(np.float32))


def _train_pair(conf, passes_arg, shape=(1, 1, 36), steps=3):
    off = _build(conf)
    on = _build(conf, f"graph_passes = {passes_arg}\n")
    for i in range(steps):
        off.update(_batch(i, shape=shape))
        on.update(_batch(i, shape=shape))
    return off, on


def _prims(tr, shape):
    node = tr.net_cfg.num_nodes - 1
    g, ge = tr.stage_infer_rows(np.zeros((8,) + shape, np.float32))
    eqns = tr._infer_fn(node).trace(
        tr.state["params"], g, ge).jaxpr.jaxpr.eqns
    out = {}
    for e in eqns:
        out[e.primitive.name] = out.get(e.primitive.name, 0) + 1
    return len(eqns), out


# ---------------------------------------------------------------------------
# fuse_activation
# ---------------------------------------------------------------------------
def test_act_fusion_parity_and_smaller_trace():
    off, on = _train_pair(ACT_CONF,
                          "dead_layer_elim,fuse_activation")
    b = _batch(50)
    po, pn = off.predict_dist(b), on.predict_dist(b)
    assert np.allclose(po, pn, rtol=1e-5, atol=1e-6)
    assert (po.argmax(1) == pn.argmax(1)).all()
    eo, po_ = _prims(off, (1, 1, 36))
    en, pn_ = _prims(on, (1, 1, 36))
    # strictly fewer eqns, equal matmul count (the pass-audit claim)
    assert en < eo
    assert pn_["dot_general"] == po_["dot_general"]
    gm = on._build_infer_graph(on.net_cfg.num_nodes - 1)[2]
    assert gm.act_fuses and gm.act_fuses[0].bias_keys == ["bs1"]
    assert any("fuse_activation" in line for line in gm.log)


def test_act_fusion_relu_only_parity():
    conf = ACT_CONF.replace(
        "layer[+0] = bias:bs1\n  init_bias = 0.05\n", "")
    off, on = _train_pair(conf, "dead_layer_elim,fuse_activation")
    b = _batch(51)
    po, pn = off.predict_dist(b), on.predict_dist(b)
    # relu-only fusion reorders nothing: bitwise
    assert (po == pn).all()


def test_act_fusion_skips_when_intermediate_is_target():
    _off, on = _train_pair(ACT_CONF,
                           "dead_layer_elim,fuse_activation")
    # extracting the raw fc1 output (pre-bias) must keep the chain
    # unfused on that executable
    b = _batch(52)
    raw_on = on.extract_feature(b, "fc1")
    off = _build(ACT_CONF)
    buf = io.BytesIO()
    on.save_model(buf)
    buf.seek(0)
    off.copy_model_from(buf)
    raw_off = off.extract_feature(b, "fc1")
    assert np.allclose(raw_on, raw_off, rtol=1e-5, atol=1e-6)


def test_fused_act_rejects_bad_value():
    from cxxnet_tpu.layers.common import (ConvolutionLayer,
                                          FullConnectLayer)
    for lay in (ConvolutionLayer(), FullConnectLayer()):
        with pytest.raises(ValueError, match="fused_act"):
            lay.set_param("fused_act", "tanh")


# ---------------------------------------------------------------------------
# merge_conv_1x1
# ---------------------------------------------------------------------------
def test_merge_1x1_parity_and_one_conv_fewer():
    off, on = _train_pair(MERGE_CONF,
                          "dead_layer_elim,merge_conv_1x1",
                          shape=(3, 8, 8))
    b = _batch(60, shape=(3, 8, 8))
    po, pn = off.predict_dist(b), on.predict_dist(b)
    assert np.allclose(po, pn, rtol=5e-4, atol=1e-6)
    _eo, po_ = _prims(off, (3, 8, 8))
    _en, pn_ = _prims(on, (3, 8, 8))
    assert po_["conv_general_dilated"] == 2
    assert pn_["conv_general_dilated"] == 1


def test_merge_tracks_live_weights():
    """The merged W' = W2 . W1 is computed in-jit from the LIVE
    params: a set_weight on either conv is picked up without any
    rebuild."""
    _off, on = _train_pair(MERGE_CONF,
                           "dead_layer_elim,merge_conv_1x1",
                           shape=(3, 8, 8))
    b = _batch(61, shape=(3, 8, 8))
    before = on.predict_dist(b)
    w, _shape = on.get_weight("c2", "wmat")
    on.set_weight(w * 2.0, "c2", "wmat")
    after = on.predict_dist(b)
    assert not np.allclose(before, after)
    fresh = _build(MERGE_CONF)
    buf = io.BytesIO()
    on.save_model(buf)
    buf.seek(0)
    fresh.copy_model_from(buf)
    expect = fresh.predict_dist(b)
    assert np.allclose(after, expect, rtol=5e-4, atol=1e-6)


def test_merge_excluded_for_shared_weights_and_multi_consumer():
    # second conv shared: folding it would specialize shared weights
    shared = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 4
  kernel_size = 1
layer[+1:c2] = conv:c2
  nchannel = 4
  kernel_size = 1
layer[+1] = share[c2]
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,4,4
batch_size = 4
dev = cpu
eta = 0.1
silent = 1
"""
    tr = _build(shared)
    assert passes.find_merge_site(tr.net_cfg, None) is None
    # multi-consumer intermediate: another reader needs the raw value
    multi = MERGE_CONF.replace(
        "layer[+1:r1] = relu",
        "layer[c1->s1,s2] = split\nlayer[s1->r1] = relu")
    # c1's node now feeds a split BEFORE c2... rebuild: c2 reads c1
    multi = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 4
  kernel_size = 3
  pad = 1
layer[c1_out->x1] = conv:c2
  nchannel = 6
  kernel_size = 1
layer[c1_out->x2] = relu
layer[x1->fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 4
dev = cpu
eta = 0.1
silent = 1
"""
    multi = multi.replace("layer[+1:c1] = conv:c1",
                          "layer[0->c1_out] = conv:c1")
    tr2 = _build(multi)
    assert passes.find_merge_site(tr2.net_cfg, None) is None


def test_merge_respects_layer_dtype_pin():
    """A `layer_dtype = float32` pin on the 1x1 conv under bf16
    autocast must BLOCK the merge - the merged conv would run at the
    first conv's bf16 and silently override the explicit pin
    (explicit-keys-always-win; regression)."""
    pinned = MERGE_CONF.replace(
        "  kernel_size = 1",
        "  kernel_size = 1\n  layer_dtype = float32")
    on = _build(pinned + "dtype = bfloat16\n",
                "graph_passes = autocast,merge_conv_1x1\n")
    gm = on._build_infer_graph(on.net_cfg.num_nodes - 1)[2]
    assert not any("merge_conv_1x1" in line for line in gm.log)
    # vacuity control: without the pin the same net merges (both
    # convs carry the same bf16 stamp)
    on2 = _build(MERGE_CONF + "dtype = bfloat16\n",
                 "graph_passes = autocast,merge_conv_1x1\n")
    gm2 = on2._build_infer_graph(on2.net_cfg.num_nodes - 1)[2]
    assert any("merge_conv_1x1" in line for line in gm2.log)


def test_fold_then_merge_then_fuse_compose():
    """conv -> bn -> 1x1 conv -> relu: the fold, the merge and the
    activation stamp all land on ONE conv, with the staged param
    function composing the transforms."""
    off, on = _train_pair(
        FOLD_MERGE_CONF,
        "dead_layer_elim,fold_conv_bn,merge_conv_1x1,fuse_activation",
        shape=(3, 8, 8))
    b = _batch(62, shape=(3, 8, 8))
    po = off.predict_dist(b)
    pn = on.predict_dist(b)  # calibrates fold on this batch
    assert np.allclose(po, pn, rtol=5e-4, atol=1e-5)
    _en, pn_ = _prims(on, (3, 8, 8))
    assert pn_["conv_general_dilated"] == 1
    assert "rsqrt" not in str(
        on._infer_fn(on.net_cfg.num_nodes - 1).trace(
            on.state["params"],
            *on.stage_infer_rows(np.zeros((8, 3, 8, 8),
                                          np.float32))).jaxpr)


def test_fold_on_second_conv_composes_with_merge():
    """conv -> 1x1 conv -> bn: the fold lands on the SECOND conv, so
    the merge stage must contract the FOLDED 1x1 weights (live view)
    - reading the raw params would silently drop the BN scale/shift
    from the merged conv (regression)."""
    conf = MERGE_CONF.replace(
        "layer[+1:r1] = relu",
        "layer[+1:b2] = batch_norm:b2\nlayer[+1:r1] = relu")
    off, on = _train_pair(
        conf, "dead_layer_elim,fold_conv_bn,merge_conv_1x1",
        shape=(3, 8, 8))
    b = _batch(63, shape=(3, 8, 8))
    po = off.predict_dist(b)
    pn = on.predict_dist(b)  # calibrates fold on this batch
    assert np.allclose(po, pn, rtol=5e-4, atol=1e-5)
    _en, pn_ = _prims(on, (3, 8, 8))
    assert pn_["conv_general_dilated"] == 1


# ---------------------------------------------------------------------------
# cse_share
# ---------------------------------------------------------------------------
def test_cse_dedupes_share_sibling_bitwise():
    off, on = _train_pair(CSE_CONF, "dead_layer_elim,cse_share",
                          shape=(1, 1, 12))
    b = _batch(70, shape=(1, 1, 12))
    po, pn = off.predict_dist(b), on.predict_dist(b)
    # the duplicate computes the identical value; dedupe is bitwise
    assert (po == pn).all()
    _eo, po_ = _prims(off, (1, 1, 12))
    _en, pn_ = _prims(on, (1, 1, 12))
    assert pn_["dot_general"] == po_["dot_general"] - 1


def test_cse_must_not_dedupe_distinct_params():
    off, on = _train_pair(CSE_DISTINCT_CONF,
                          "dead_layer_elim,cse_share",
                          shape=(1, 1, 12))
    _eo, po_ = _prims(off, (1, 1, 12))
    _en, pn_ = _prims(on, (1, 1, 12))
    # fc1 and fc1b own distinct weights: equal dots, nothing deduped
    assert pn_["dot_general"] == po_["dot_general"]
    gm = on._build_infer_graph(on.net_cfg.num_nodes - 1)[2]
    assert not any("cse_share" in line for line in gm.log)


def test_cse_dedupes_paramless_siblings():
    conf = CSE_CONF.replace("layer[0->b] = share[fc1]",
                            "layer[a->t1] = tanh\nlayer[a->t2] = tanh")
    conf = conf.replace("layer[a,b->c] = concat",
                        "layer[t1,t2->c] = concat")
    off, on = _train_pair(conf, "dead_layer_elim,cse_share",
                          shape=(1, 1, 12))
    b = _batch(71, shape=(1, 1, 12))
    assert (off.predict_dist(b) == on.predict_dist(b)).all()
    gm = on._build_infer_graph(on.net_cfg.num_nodes - 1)[2]
    assert any("cse_share" in line for line in gm.log)


# ---------------------------------------------------------------------------
# elim_reshape
# ---------------------------------------------------------------------------
def test_elim_reshape_bitwise_parity_and_fewer_eqns():
    """The flatten feeding a single fullc is eliminated: bitwise
    value-identical (the fullc's apply flattens in the same memory
    order), strictly fewer traced equations at equal contraction
    count (the pass-audit claim, at the test surface)."""
    off, on = _train_pair(MERGE_CONF, "dead_layer_elim,elim_reshape",
                          shape=(3, 8, 8))
    b = _batch(60, shape=(3, 8, 8))
    assert (off.predict_dist(b) == on.predict_dist(b)).all()
    gm = on._build_infer_graph(on.net_cfg.num_nodes - 1)[2]
    assert any("elim_reshape" in line for line in gm.log)
    e_off, p_off = _prims(off, (3, 8, 8))
    e_on, p_on = _prims(on, (3, 8, 8))
    assert e_on < e_off
    assert (p_on.get("dot_general", 0)
            == p_off.get("dot_general", 0))
    assert (p_on.get("conv_general_dilated", 0)
            == p_off.get("conv_general_dilated", 0))


def test_elim_reshape_kept_when_flat_node_is_target():
    """extract of the flat node itself must keep the flatten."""
    tr = _build(MERGE_CONF, "graph_passes = elim_reshape\n")
    flat_node = tr.net.node_index("fl")
    gm = tr._build_infer_graph(flat_node)[2]
    assert not any("elim_reshape" in line for line in gm.log)
    assert any(li.type_name == "flatten" for li in gm.cfg.layers)


# ---------------------------------------------------------------------------
# pipeline integration
# ---------------------------------------------------------------------------
def test_canonical_order_and_all_includes_new_passes():
    pl = PassPipeline.from_config("all")
    names = pl.names()
    for n in ("cse_share", "merge_conv_1x1", "fuse_activation"):
        assert n in names
    assert names.index("dead_layer_elim") < names.index("cse_share")
    assert names.index("cse_share") < names.index("fold_conv_bn")
    assert names.index("fold_conv_bn") < names.index("merge_conv_1x1")
    assert names.index("merge_conv_1x1") < names.index(
        "fuse_activation")


def test_checkpoint_bytes_identical_with_all_passes():
    """All infer-stage passes on vs off: the training trajectory and
    the checkpoint bytes are untouched."""
    off, on = _train_pair(BN_MLP_CONF, "all", steps=4)
    on.predict(_batch(80))  # calibrate + build the transformed graph
    bo, bn_ = io.BytesIO(), io.BytesIO()
    off.save_model(bo)
    on.save_model(bn_)
    assert bo.getvalue() == bn_.getvalue()


# ---------------------------------------------------------------------------
# tuning cache v2: plans, ladder, migration
# ---------------------------------------------------------------------------
def test_cache_v2_roundtrip_plan_and_ladder(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {"steps_per_dispatch": 2},
                      layers={"c1": {"space_to_depth": "1"},
                              "fc6": {"layer_dtype": "float32"}},
                      serve_ladder=[2, 6, 16])
    assert tuning.tuned_layer_plan(p, "cpu") == {
        "c1": {"space_to_depth": "1"},
        "fc6": {"layer_dtype": "float32"}}
    assert tuning.tuned_serve_ladder(p, "cpu") == [2, 6, 16]
    assert tuning.tuned_layer_plan(p, "tpu") == {}
    assert tuning.tuned_serve_ladder(p, "tpu") is None
    with open(p) as f:
        assert json.load(f)["version"] == 2


def test_cache_v1_one_shot_migration(tmp_path):
    p = str(tmp_path / "v1.json")
    with open(p, "w") as f:
        json.dump({"version": 1, "platforms": {
            "cpu": {"knobs": {"steps_per_dispatch": 4}}}}, f)
    blob = tuning.load_cache(p)
    assert blob["version"] == 2
    assert blob["platforms"]["cpu"]["layers"] == {}
    assert tuning.tuned_knobs(p, "cpu") == {"steps_per_dispatch": "4"}
    # on-disk file untouched (migration is in-memory)
    with open(p) as f:
        assert json.load(f)["version"] == 1


def test_cache_garbage_still_raises(tmp_path):
    cases = [
        {"version": 3, "platforms": {}},
        {"version": "two", "platforms": {}},
        {"version": 2, "platforms": {"cpu": {"layers": ["x"]}}},
        {"version": 2, "platforms": {
            "cpu": {"layers": {"c1": {"bogus_knob": 1}}}}},
        {"version": 2, "platforms": {"cpu": {"serve_ladder": [0]}}},
        {"version": 2, "platforms": {
            "cpu": {"serve_ladder": [8, 4]}}},
        {"version": 2, "platforms": {
            "cpu": {"serve_ladder": "2,4"}}},
    ]
    for payload in cases:
        p = str(tmp_path / "bad.json")
        with open(p, "w") as f:
            json.dump(payload, f)
        with pytest.raises(ConfigError):
            tuning.load_cache(p)
    with pytest.raises(ValueError, match="untunable per-layer"):
        tuning.save_entry(str(tmp_path / "x.json"), "cpu", {},
                          layers={"c1": {"nope": "1"}})


def test_trainer_applies_layer_plan_and_explicit_wins(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {},
                      layers={"fc1": {"layer_dtype": "float32"},
                              "nosuch": {"layer_dtype": "float32"},
                              "bn1": {"space_to_depth": "1"}})
    tr = _build(BN_MLP_CONF, f"tuning_cache = {p}\n")
    idx = tr.net_cfg.layer_name_map["fc1"]
    assert ("layer_dtype", "float32") in tr.net_cfg.layercfg[idx]
    # s2d on a non-conv layer is inapplicable: skipped silently
    bidx = tr.net_cfg.layer_name_map["bn1"]
    assert not any(k == "space_to_depth"
                   for k, _ in tr.net_cfg.layercfg[bidx])
    # explicit per-layer key wins over the plan
    conf2 = BN_MLP_CONF.replace(
        "  nhidden = 16",
        "  nhidden = 16\n  layer_dtype = bfloat16")
    tr2 = _build(conf2, f"tuning_cache = {p}\n")
    idx2 = tr2.net_cfg.layer_name_map["fc1"]
    vals = [v for k, v in tr2.net_cfg.layercfg[idx2]
            if k == "layer_dtype"]
    assert vals == ["bfloat16"]


def test_trainer_layer_plan_drives_autocast_dtype_plan(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {},
                      layers={"fc1": {"layer_dtype": "float32"}})
    import jax.numpy as jnp
    tr = _build(BN_MLP_CONF,
                f"dtype = bfloat16\ngraph_passes = autocast\n"
                f"tuning_cache = {p}\n")
    idx = tr.net_cfg.layer_name_map["fc1"]
    assert tr._graph_dtype_plan[idx] == jnp.float32
    idx2 = tr.net_cfg.layer_name_map["fc2"]
    assert tr._graph_dtype_plan[idx2] == jnp.bfloat16


# ---------------------------------------------------------------------------
# serve bucket ladder
# ---------------------------------------------------------------------------
def test_ladder_from_histogram_shapes_buckets():
    hist = {3: 50, 7: 30, 12: 15, 30: 5}
    lad = ladder_from_histogram(hist, 32, data_axis=1, rungs=4)
    assert lad[-1] == 32
    assert 3 in lad and 7 in lad
    assert all(lad[i] < lad[i + 1] for i in range(len(lad) - 1))
    # data-axis rounding: every rung divisible by the axis
    lad2 = ladder_from_histogram(hist, 32, data_axis=4, rungs=4)
    assert all(b % 4 == 0 for b in lad2)
    # empty histogram falls back to the power-of-two set
    assert ladder_from_histogram({}, 16) == bucket_sizes(16)


def test_ladder_buckets_drops_inapplicable_rungs():
    assert ladder_buckets([2, 3, 8, 64], 16, data_axis=2) == (2, 8, 16)
    with pytest.raises(ValueError, match="multiple"):
        ladder_buckets([2], 15, data_axis=2)


def test_server_uses_trainer_ladder_and_counts_sizes():
    from cxxnet_tpu.serve import Server
    tr = _build(BN_MLP_CONF, "serve_bucket_ladder = 2,6\n")
    assert tr.serve_ladder == [2, 6]
    srv = Server(tr, max_batch=8, max_wait_ms=1.0, replicas=1)
    assert srv.buckets == (2, 6, 8)
    srv.warmup()
    srv.start()
    try:
        r = np.random.RandomState(0)
        for n in (1, 5, 5):
            srv.submit(r.rand(n, 1, 1, 36).astype(np.float32)) \
               .result(timeout=60)
    finally:
        stats = srv.stop()
    assert stats["request_sizes"] == {1: 1, 5: 2}


def test_server_ladder_from_cache_and_explicit_wins(tmp_path):
    from cxxnet_tpu.serve import Server
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {}, serve_ladder=[2, 4])
    tr = _build(BN_MLP_CONF, f"tuning_cache = {p}\n")
    assert tr.serve_ladder == [2, 4]
    assert Server(tr, max_batch=8).buckets == (2, 4, 8)
    # explicit serve_bucket_ladder beats the cache
    tr2 = _build(BN_MLP_CONF,
                 f"serve_bucket_ladder = 3,6\ntuning_cache = {p}\n")
    assert tr2.serve_ladder == [3, 6]
    assert Server(tr2, max_batch=8).buckets == (3, 6, 8)


def test_serve_bucket_ladder_validation():
    tr = NetTrainer()
    with pytest.raises(ValueError, match="serve_bucket_ladder"):
        tr.set_param("serve_bucket_ladder", "4,2")
    with pytest.raises(ValueError, match="serve_bucket_ladder"):
        tr.set_param("serve_bucket_ladder", "0,2")


# ---------------------------------------------------------------------------
# multi-batch fold calibration
# ---------------------------------------------------------------------------
def test_single_batch_calibration_unchanged_by_sequence_form():
    on1 = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    on2 = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    b = _batch(90)
    on1.calibrate_graph_passes(b)
    on2.calibrate_graph_passes([b])
    m1, r1 = on1._fold_stats["bn1"]
    m2, r2 = on2._fold_stats["bn1"]
    # one-element sequence rides the pinned single-batch path: bitwise
    assert (np.asarray(m1) == np.asarray(m2)).all()
    assert (np.asarray(r1) == np.asarray(r2)).all()


def test_multi_batch_calibration_pools_moments():
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    batches = [_batch(91), _batch(92), _batch(93)]
    assert on.calibrate_graph_passes(batches)
    mean, rstd = on._fold_stats["bn1"]
    # reference pooled moments over the concatenated calibration set
    single = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    big = DataBatch(
        data=np.concatenate([b.data for b in batches]),
        label=np.concatenate([b.label for b in batches]))
    # equal-sized batches: pooled mean == mean of per-batch means,
    # pooled var == mean(E[x^2]) - mean^2 - compare against direct
    # stats over the fc1 activations of the union
    w = np.asarray(single.state["params"]["fc1"]["wmat"])
    bias = np.asarray(single.state["params"]["fc1"]["bias"])
    # both trainers share the seed, so fc1 weights are identical
    acts = big.data.reshape(24, -1) @ w.T + bias
    assert np.allclose(mean, acts.mean(0), rtol=1e-4, atol=1e-5)
    var = acts.var(0)
    eps = on.net.layer_objs[1].eps
    assert np.allclose(rstd, 1.0 / np.sqrt(var + eps), rtol=1e-3,
                       atol=1e-4)
    # parity: folded predict stays close to unfolded on a member batch
    off = _build(BN_MLP_CONF)
    pn = on.predict_dist(batches[0])
    po = off.predict_dist(batches[0])
    assert np.allclose(po, pn, rtol=0.2, atol=0.05)


def test_multi_batch_calibration_masks_padding_rows():
    """A round_batch=0 iterator zero-fills its tail batch; those
    padding rows must not drag the pooled frozen stats toward zero
    (regression: the mask was computed and discarded)."""
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    full = _batch(94)
    short = _batch(95)
    padded = DataBatch(
        data=np.concatenate([short.data[:5],
                             np.zeros_like(short.data[:3])]),
        label=short.label.copy(), num_batch_padd=3)
    assert on.calibrate_graph_passes([full, padded])
    mean, rstd = on._fold_stats["bn1"]
    # reference: direct moments over the 13 REAL rows only (the
    # valid-row-weighted pooling of exact per-batch moments IS the
    # union statistic)
    ref = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    w = np.asarray(ref.state["params"]["fc1"]["wmat"])
    bias = np.asarray(ref.state["params"]["fc1"]["bias"])
    real = np.concatenate([full.data,
                           padded.data[:5]]).reshape(13, -1)
    acts = real @ w.T + bias
    eps = on.net.layer_objs[1].eps
    assert np.allclose(mean, acts.mean(0), rtol=1e-4, atol=1e-5)
    assert np.allclose(rstd, 1.0 / np.sqrt(acts.var(0) + eps),
                       rtol=1e-3, atol=1e-4)


def test_pass_calibration_batches_key_validated():
    tr = NetTrainer()
    tr.set_param("pass_calibration_batches", "3")
    assert tr.pass_calibration_batches == 3
    with pytest.raises(ValueError):
        tr.set_param("pass_calibration_batches", "0")
    # the pass_ prefix toggle handler must NOT swallow it as a pass
    assert "calibration_batches" not in tr._pass_toggles


# ---------------------------------------------------------------------------
# config schema: new keys registered with did-you-mean
# ---------------------------------------------------------------------------
def test_schema_registers_new_keys():
    from cxxnet_tpu.analysis import schema
    reg = schema.build_registry()
    for key in ("serve_bucket_ladder", "pass_calibration_batches",
                "pass_calibration_iter", "fused_act",
                "pass_cse_share", "pass_merge_conv_1x1",
                "pass_fuse_activation"):
        assert reg.recognizes(key), key
    assert reg.suggest("serve_bucket_ladderr") == "serve_bucket_ladder"
    with pytest.raises(ConfigError, match="serve_bucket_ladder"):
        schema.validate_pairs([("serve_bucket_ladderr", "2,4")],
                              source="x.conf")
