"""Compiled-HLO structure checks for the sp/pp extensions.

Same philosophy as test_scaling_analysis.py: the docs' communication
claims (ring = neighbor ppermutes, no K/V all-gather; Ulysses = two
all-to-alls; pipeline = ppermute activation flow, stage params never
gathered) are asserted against the actual compiled artifacts on the
8-device CPU mesh, not taken on faith.
"""

import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cxxnet_tpu.parallel import ring as R

# any HLO element type (f32, bf16, s32, pred, f8e4m3, ...): a
# non-f32 collective must not slip past the no-all-gather assertions
_SHAPE = re.compile(r"\b\w+\[([0-9,]*)\]")


def _count(hlo: str, op: str) -> int:
    return len([l for l in hlo.splitlines()
                if re.search(rf"{op}(-start)?\(", l)])


def _ag_elems(hlo: str) -> int:
    """Total f32 elements moved by all-gather ops."""
    total = 0
    for line in hlo.splitlines():
        if re.search(r"all-gather(-start)?\(", line):
            head = re.split(r"all-gather(?:-start)?\(", line)[0]
            for dims in _SHAPE.findall(head):
                total += int(np.prod(
                    [int(d) for d in dims.split(",") if d]) if dims
                    else 1)
    return total


def _mesh(axes):
    sizes = [n for _, n in axes]
    devs = np.asarray(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
    return Mesh(devs, tuple(a for a, _ in axes))


def test_ring_attention_uses_ppermute_not_allgather():
    mesh = _mesh([("seq", 4)])
    q = jnp.zeros((2, 4, 32, 8))
    spec = R._bhsd_spec(mesh, 4)
    qs = jax.device_put(q, NamedSharding(mesh, spec))
    hlo = jax.jit(
        lambda q, k, v: R.ring_attention(q, k, v, mesh, causal=True)
    ).lower(qs, qs, qs).compile().as_text()
    assert _count(hlo, "collective-permute") >= 1, "no ppermute in ring"
    assert _ag_elems(hlo) == 0, "ring must not all-gather K/V"


def test_ulysses_uses_all_to_all():
    mesh = _mesh([("seq", 4)])
    q = jnp.zeros((2, 4, 32, 8))
    spec = R._bhsd_spec(mesh, 4)
    qs = jax.device_put(q, NamedSharding(mesh, spec))
    hlo = jax.jit(
        lambda q, k, v: R.ulysses_attention(q, k, v, mesh)
    ).lower(qs, qs, qs).compile().as_text()
    assert _count(hlo, "all-to-all") >= 2, "ulysses needs 2 all-to-alls"
    assert _ag_elems(hlo) == 0, "ulysses must not all-gather K/V"


def test_composed_mesh_collective_set():
    """dp x sp x pp composed in ONE mesh and ONE jitted train step
    (the 8-device slice of dryrun_multichip's phase 5; the 16-device
    run adds 'model'): the compiled HLO must carry the whole collective
    set docs/parallel.md's scaling analysis claims - an all-reduce
    (gradient dp sum), collective-permutes from BOTH the ring K/V
    rotation and the GPipe activation flow, and no all-gather of the
    stacked stage params."""
    from __graft_entry__ import _TINY_COMPOSED, _make_trainer
    from cxxnet_tpu.utils.config import parse_config_string

    # no ZeRO here: shard_optimizer=1 all-gathers every updated param
    # by design, which would swamp the no-stage-param-gather bound (the
    # ZeRO + composed-mesh execution is dryrun_multichip phase 5)
    t = _make_trainer(
        parse_config_string(_TINY_COMPOSED),
        [("batch_size", "4"), ("mesh", "data:2,seq:2,pipe:2"),
         ("silent", "1"), ("eval_train", "0")])
    assert "seq" in str(t._data_sharded.spec)
    assert t._pshard["ts1"]["wqkv"].spec[0] == "pipe"
    data = np.zeros((4, 1, 8, 16), np.float32)
    labels = {"label": np.zeros((4, 1), np.float32)}
    mask = np.ones(4, np.float32)
    hlo = t._train_step.lower(
        t.state, data, (), labels, mask,
        jax.random.PRNGKey(0)).compile().as_text()
    assert _count(hlo, "all-reduce") >= 1, "no gradient AllReduce"
    # ring rotation (n-1 = 1 fwd step + transpose) and pipeline flow
    # are distinct ppermutes; both schedules must appear
    assert _count(hlo, "collective-permute") >= 2, (
        "ring + pipeline ppermutes missing: "
        f"{_count(hlo, 'collective-permute')}")
    stack_elems = sum(int(np.prod(p.shape))
                      for p in t.state["params"]["ts1"].values())
    assert _ag_elems(hlo) < stack_elems, (
        "stacked stage params appear to be gathered: "
        f"all-gather elems {_ag_elems(hlo)} >= stack {stack_elems}")


def test_pipeline_step_keeps_stage_params_sharded():
    """The pipelined train step moves activations with ppermute and
    never all-gathers the stacked stage params (the 1/P weight-HBM
    claim in docs/parallel.md)."""
    from cxxnet_tpu.io.data import DataBatch  # noqa: F401
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from tests.test_pipeline import STACK_NET

    t = NetTrainer()
    for k, v in parse_config_string(STACK_NET):
        t.set_param(k, v)
    t.set_param("mesh", "data:2,pipe:4")
    t.init_model()
    data = np.zeros((8, 1, 8, 16), np.float32)
    labels = {"label": np.zeros((8, 1), np.float32)}
    mask = np.ones(8, np.float32)
    hlo = t._train_step.lower(
        t.state, data, (), labels, mask,
        jax.random.PRNGKey(0)).compile().as_text()
    assert _count(hlo, "collective-permute") >= 1, "no pipeline flow"
    stack_elems = sum(int(np.prod(p.shape))
                      for p in t.state["params"]["ts1"].values())
    assert _ag_elems(hlo) < stack_elems, (
        "stacked stage params appear to be gathered: "
        f"all-gather elems {_ag_elems(hlo)} >= stack {stack_elems}")
