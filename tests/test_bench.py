"""bench.py harness smoke test - ALWAYS in the default suite.

Round-3 post-mortem: bench.py called the jitted train step with a
stale 5-arg signature; nothing in the (green) suite imported the
measurement functions, so the regression reached the driver's on-chip
run and zeroed the round's headline artifact (BENCH_r03 value=0.0).
This test runs the REAL harness end-to-end on the CPU backend at a
tiny batch so any drift in the train-step signature, sharding specs,
or the extras plumbing fails the suite, not the round.
"""

import json
import subprocess
import sys

import numpy as np
import pytest


def test_bench_run_end_to_end(monkeypatch, tmp_path):
    """bench.run() produces a complete artifact with nonzero numbers
    and no *_error fields from any CPU-reachable path."""
    # keep the suite's compile cache out of the repo checkout
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench.run(steps_override=1, batch_override=4)

    assert out["platform"] == "cpu"
    assert out["value"] > 0 and out["compute_ips"] > 0
    assert out["value_is"] == "e2e"
    assert out["unit"] == "images/sec"
    # the eval_train variant exercises the metric-compiled step
    assert out["e2e_eval_train_ips"] > 0
    # the input-split extra runs on CPU too
    assert out["host_prep_ms_p50"] > 0
    assert out["device_step_ms_p50"] > 0
    assert out["augment_ips"] > 0
    errors = {k: v for k, v in out.items() if k.endswith("_error")}
    assert not errors, errors
    # the artifact is the driver contract: one JSON-serializable dict
    json.dumps(out)


def test_bench_partial_snapshot_discipline(monkeypatch, tmp_path):
    """The watchdog's emergency artifact (_PARTIAL) must carry the
    headline fields after the first measurement: a hang in ANY later
    stage may only truncate extras, never zero the value."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setenv("CXN_BENCH_EVALTRAIN", "0")
    monkeypatch.setenv("CXN_BENCH_SPLIT", "0")
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})
    bench.run(steps_override=1, batch_override=4)
    snap = bench._PARTIAL
    assert snap["value"] > 0
    assert snap["value_is"] == "e2e"
    assert snap["compute_ips"] > 0


def test_bench_crash_after_measurement_emits_snapshot(monkeypatch, capsys):
    """A CRASH (not just a hang) after a completed measurement must
    emit the snapshotted headline, never the value=0.0 error artifact
    (the round-3 failure mode applied to the exception path)."""
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})

    def boom(profile_dir="", steps_override=0, batch_override=0):
        bench._snapshot({"metric": "m", "value": 123.0, "unit":
                         "images/sec", "compute_ips": 123.0})
        raise RuntimeError("late explosion")

    monkeypatch.setattr(bench, "run", boom)
    monkeypatch.setenv("CXN_BENCH_TIMEOUT", "0")
    assert bench.main([]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 123.0
    assert "late explosion" in out["truncated"]


def test_bench_crash_before_measurement_emits_error(monkeypatch, capsys):
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})
    monkeypatch.setattr(bench, "run", lambda *a, **k: (_ for _ in ()
                                                      ).throw(
        ValueError("early explosion")))
    monkeypatch.setenv("CXN_BENCH_TIMEOUT", "0")
    assert bench.main([]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "early explosion" in out["error"]


def test_bench_device_augment_extra_runs(monkeypatch, tmp_path):
    """The device_augment bench extra builds its own AlexNet trainer
    with override keys that must track the trainer's config surface -
    run it for real (tiny batch; the platform gate is bypassed, the
    CPU backend executes) so drift degrades a test, not the artifact."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench._bench_device_augment(4, 1, "tpu")
    assert out.get("device_augment_ips", 0) > 0, out


def test_bench_error_artifact_is_json():
    """A crash before any measurement must still print the one-line
    JSON contract (value 0.0 + error), rc=0."""
    import bench
    line = bench._error_json("boom")
    d = json.loads(line)
    assert d["value"] == 0.0 and "boom" in d["error"]
