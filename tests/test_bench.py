"""bench.py harness smoke test - ALWAYS in the default suite.

Round-3 post-mortem: bench.py called the jitted train step with a
stale 5-arg signature; nothing in the (green) suite imported the
measurement functions, so the regression reached the driver's on-chip
run and zeroed the round's headline artifact (BENCH_r03 value=0.0).
This test runs the REAL harness end-to-end on the CPU backend at a
tiny batch so any drift in the train-step signature, sharding specs,
or the extras plumbing fails the suite, not the round.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_run_end_to_end(monkeypatch, tmp_path):
    """bench.run() produces a complete artifact with nonzero numbers
    and no *_error fields from any CPU-reachable path."""
    # keep the suite's compile cache out of the repo checkout
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench.run(steps_override=1, batch_override=4)

    assert out["platform"] == "cpu"
    assert out["value"] > 0 and out["compute_ips"] > 0
    assert out["value_is"] == "e2e"
    assert out["unit"] == "images/sec"
    # the eval_train variant exercises the metric-compiled step
    assert out["e2e_eval_train_ips"] > 0
    # the continuous-batching serving family (docs/SERVING.md):
    # qps + latency percentiles + the vs-batch-predict ratio
    assert out["serve_qps"] > 0
    assert out["serve_rows_per_s"] > 0
    assert out["serve_p99_ms"] is not None
    assert out["serve_over_predict"] > 0
    assert out["serve_buckets"] >= 1
    # the input-split extra runs on CPU too
    assert out["host_prep_ms_p50"] > 0
    assert out["device_step_ms_p50"] > 0
    assert out["augment_ips"] > 0
    errors = {k: v for k, v in out.items() if k.endswith("_error")}
    assert not errors, errors
    # the artifact is the driver contract: one JSON-serializable dict
    json.dumps(out)


def test_bench_partial_snapshot_discipline(monkeypatch, tmp_path):
    """The watchdog's emergency artifact (_PARTIAL) must carry the
    headline fields after the first measurement: a hang in ANY later
    stage may only truncate extras, never zero the value."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setenv("CXN_BENCH_EVALTRAIN", "0")
    monkeypatch.setenv("CXN_BENCH_SPLIT", "0")
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})
    bench.run(steps_override=1, batch_override=4)
    snap = bench._PARTIAL
    assert snap["value"] > 0
    assert snap["value_is"] == "e2e"
    assert snap["compute_ips"] > 0


def test_bench_crash_after_measurement_emits_snapshot(monkeypatch, capsys):
    """A CRASH (not just a hang) after a completed measurement must
    emit the snapshotted headline, never the value=0.0 error artifact
    (the round-3 failure mode applied to the exception path)."""
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})

    def boom(profile_dir="", steps_override=0, batch_override=0):
        bench._snapshot({"metric": "m", "value": 123.0, "unit":
                         "images/sec", "compute_ips": 123.0})
        raise RuntimeError("late explosion")

    monkeypatch.setattr(bench, "run", boom)
    monkeypatch.setenv("CXN_BENCH_TIMEOUT", "0")
    assert bench.main([]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 123.0
    assert "late explosion" in out["truncated"]


def test_bench_crash_before_measurement_emits_error(monkeypatch, capsys):
    import bench
    monkeypatch.setattr(bench, "_PARTIAL", {})
    monkeypatch.setattr(bench, "run", lambda *a, **k: (_ for _ in ()
                                                      ).throw(
        ValueError("early explosion")))
    monkeypatch.setenv("CXN_BENCH_TIMEOUT", "0")
    assert bench.main([]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "early explosion" in out["error"]


def test_bench_device_augment_extra_runs(monkeypatch, tmp_path):
    """The device_augment bench extra builds its own AlexNet trainer
    with override keys that must track the trainer's config surface -
    run it for real (tiny batch; the platform gate is bypassed, the
    CPU backend executes) so drift degrades a test, not the artifact."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench._bench_device_augment(4, 1, "tpu")
    assert out.get("device_augment_ips", 0) > 0, out


def test_cpu_fallback_carries_last_good_tpu_numbers(monkeypatch,
                                                    tmp_path):
    """Round-4 post-mortem: the driver's BENCH_r04.json was a 3.17
    img/s CPU fallback while the real chip evidence sat in a side
    file. A non-TPU run must merge the committed archive under a
    labeled last_measured_tpu object."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    # gate off every optional extra (names from the registry itself so
    # a renamed gate can't silently leave a measurement enabled)
    for _n, _f, gate, _t, _k in bench._MEASUREMENTS:
        if gate:
            monkeypatch.setenv(gate, "0")
    out = bench.run(steps_override=1, batch_override=4)
    lg = out.get("last_measured_tpu")
    assert lg, "CPU artifact must carry the archived chip numbers"
    assert lg["fields"]["compute_ips"] > 10000  # round-4 evidence
    assert "provenance" in lg and "dates" in lg
    json.dumps(out)


def test_save_last_good_keeps_per_field_best(monkeypatch, tmp_path):
    """_save_last_good archives per-field maxima from verified-sync
    TPU runs only; unverified readbacks and fallback runs never
    overwrite the archive."""
    import bench
    path = str(tmp_path / "lg.json")
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", path)
    base = {"platform": "tpu", "value": 100.0, "value_is": "e2e",
            "e2e_sync": "readback", "compute_sync": "readback",
            "compute_ips": 16000.0, "e2e_ips": 100.0,
            "device_kind": "TPU v5 lite", "per_device_batch": 256}
    bench._save_last_good(dict(base))
    rec = json.load(open(path))
    assert rec["fields"]["compute_ips"] == 16000.0
    assert rec["per_device_batch"] == 256

    # labels must NOT be clobbered by a later run with a different
    # config that improves one field; they land per-date in contexts
    bench._save_last_good(dict(base, per_device_batch=128,
                               googlenet_ips=2000.0))
    rec = json.load(open(path))
    assert rec["fields"]["googlenet_ips"] == 2000.0
    assert rec["per_device_batch"] == 256          # first write wins
    assert any(c.get("per_device_batch") == 128
               for c in rec["contexts"].values())  # run context kept

    # a worse later window must not erase the better number...
    worse = dict(base, compute_ips=9000.0, e2e_ips=250.0)
    bench._save_last_good(worse)
    rec = json.load(open(path))
    assert rec["fields"]["compute_ips"] == 16000.0
    # ...but a better field updates independently
    assert rec["fields"]["e2e_ips"] == 250.0

    # per-FIELD sync gate: an unverified e2e must not be archived, but
    # a verified compute from the SAME run must be (mixed-verification
    # runs are the common case on the drifting tunnel link)
    bench._save_last_good(dict(base, e2e_ips=9999.0, compute_ips=17000.0,
                               e2e_sync="readback_unverified"))
    rec = json.load(open(path))
    assert rec["fields"]["e2e_ips"] == 250.0          # unverified: no
    assert rec["fields"]["compute_ips"] == 17000.0    # verified: yes

    # same per-field rule for extras (annotation lives under the
    # measurement's registry name, e.g. attention_sync)
    bench._save_last_good(dict(base, attn_pallas_tflops=500.0,
                               attention_sync="readback_unverified"))
    assert "attn_pallas_tflops" not in \
        json.load(open(path))["fields"]
    bench._save_last_good(dict(base, attn_pallas_tflops=60.0,
                               attention_sync="readback"))
    assert json.load(open(path))["fields"]["attn_pallas_tflops"] == 60.0

    # a field with NO annotation in a readback-mode run (inline path:
    # no post-measurement verification exists) is never archived
    bench._save_last_good(dict(base, sync_mode="readback",
                               chip_matmul_tflops=150.0))
    assert "chip_matmul_tflops" not in json.load(open(path))["fields"]
    # ...but block-mode (calibration passed) timings are trusted
    bench._save_last_good(dict(base, sync_mode="block",
                               chip_matmul_tflops=150.0))
    assert json.load(open(path))["fields"]["chip_matmul_tflops"] == 150.0
    # fallback/CPU runs: not archived
    bench._save_last_good(dict(base, platform="cpu",
                               compute_ips=99999.0))
    bench._save_last_good(dict(base, fallback="x", compute_ips=99999.0))
    # still the verified 17000 from the mixed-verification run above
    assert json.load(open(path))["fields"]["compute_ips"] == 17000.0


def test_all_failed_artifact_is_self_describing(monkeypatch, tmp_path):
    """When every measurement fails the artifact keeps an e2e-flavored
    metric name; value_is must say 'none' so a zeroed artifact cannot
    read as a measured e2e of 0. A good artifact is archived instead."""
    import bench
    out = {"metric": "alexnet_b256_tpu_train_e2e"}
    bench._finalize(out, "tpu")
    assert out["value"] == 0.0 and out["value_is"] == "none"

    path = str(tmp_path / "lg.json")
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", path)
    good = {"platform": "tpu", "value": 50.0, "value_is": "e2e",
            "e2e_sync": "readback", "e2e_ips": 50.0}
    bench._finalize(good, "tpu")
    assert good["value_is"] == "e2e"  # untouched
    assert json.load(open(path))["fields"]["e2e_ips"] == 50.0


def test_physics_check_retracts_impossible_numbers():
    """A field whose implied FLOP/s exceeds 1.25x the chip's spec peak
    is dispatch timing from a window where no sync primitive worked
    (round-4 on-chip: 206k img/s 'compute', 355,311 TFLOP/s 'matmul');
    the artifact must carry it as *_implausible, never as a result."""
    import bench
    out = {"compute_ips": 206825.51, "e2e_ips": 250.0,
           "chip_matmul_tflops": 355311.6,
           "attn_pallas_tflops": 39893.5, "attn_xla_tflops": 28606.0,
           "attn_pallas_speedup": 1.395,
           "googlenet_ips": 2198.0}
    bench._physics_check(out, 197.0, 1)
    assert "compute_ips" not in out
    assert out["compute_ips_implausible"] == 206825.51
    assert "chip_matmul_tflops" not in out
    # the ratio of two dispatch timings must go with its inputs
    assert "attn_pallas_speedup" not in out
    # plausible numbers survive untouched
    assert out["e2e_ips"] == 250.0
    assert out["googlenet_ips"] == 2198.0


def test_physics_check_keeps_real_on_chip_numbers():
    """The caps must never flag genuinely measured values (the real
    round-4 artifact: 13.6k img/s winner compute, 147 TFLOP/s chained
    matmul on a 197-peak v5e)."""
    import bench
    out = {"compute_ips": 13579.82, "e2e_ips": 1140.7,
           "chip_matmul_tflops": 147.2, "attn_pallas_tflops": 13.31,
           "attn_xla_tflops": 14.67, "attn_pallas_speedup": 0.907}
    before = dict(out)
    bench._physics_check(out, 197.0, 1)
    assert out == before


def test_derive_relabels_headline_and_drops_stale_ratio():
    """_derive must label the artifact by its best available number and
    retract derived ratios whose inputs a physics check removed."""
    import bench
    out = {"compute_ips": 7402.0}
    bench._derive(out, 256, "tpu", 1, 197.0)
    assert out["value"] == 7402.0 and out["value_is"] == "compute_only"
    assert out["metric"] == "alexnet_b256_tpu_train_compute"
    out["e2e_ips"] = 1140.0
    bench._derive(out, 256, "tpu", 1, 197.0)
    assert out["value"] == 1140.0 and out["value_is"] == "e2e"
    assert out["e2e_over_compute"] == pytest.approx(1140.0 / 7402.0,
                                                    rel=1e-3)
    # now a (simulated) physics check retracts compute
    out.pop("compute_ips")
    bench._derive(out, 256, "tpu", 1, 197.0)
    assert "e2e_over_compute" not in out
    assert out["value_is"] == "e2e"


def test_derive_estimates_device_step_in_readback_mode():
    """When the profiled device step is unavailable (readback sync),
    the host/device split is derived from compute_ips and marked est."""
    import bench
    out = {"compute_ips": 10000.0, "host_prep_ms_p50": 128.0}
    bench._derive(out, 256, "tpu", 1, 197.0)
    assert out["device_step_ms_est"] == pytest.approx(25.6)
    assert out["host_over_device"] == pytest.approx(5.0)


def test_run_isolated_wraps_failures(monkeypatch):
    """A child that dies or hangs must degrade to a *_error field."""
    import bench
    # pin the child to CPU: on a TPU-attached host the child would
    # otherwise initialize the (possibly wedged) tunnel backend before
    # hitting the unknown-name KeyError
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    frag = bench._run_isolated("no_such_measurement", 4, 1, "", 120)
    assert "no_such_measurement_error" in frag


def test_run_isolated_timeout_embeds_flight_forensics(tmp_path,
                                                      monkeypatch):
    """A hung child killed at the per-field timeout must leave a
    forensics payload next to the {field}_timeout marker: the child's
    last flight-recorder snapshot (the CXN_BENCH_FLIGHT file) names
    the in-flight executable the parent could never ask it for."""
    import bench
    fake = tmp_path / "fake_child.py"
    fake.write_text(
        "import json, os, time\n"
        "path = os.environ['CXN_BENCH_FLIGHT']\n"
        "ent = {'seq': 0, 'kind': 'train', 'fp': 'wedged123',\n"
        "       'bucket': 4, 'in_flight': True, 'age_s': 9.9}\n"
        "snap = {'field': 'e2e', 'ts': 1.0, 'flight': [ent],\n"
        "        'in_flight': [ent],\n"
        "        'executables': [{'fingerprint': 'wedged123',\n"
        "                         'name': 'train_step@b4'}]}\n"
        "with open(path + '.tmp', 'w') as f:\n"
        "    json.dump(snap, f)\n"
        "os.replace(path + '.tmp', path)\n"
        "time.sleep(120)\n")
    monkeypatch.setattr(bench, "_BENCH_PATH", str(fake))
    frag = bench._run_isolated("e2e", 4, 1, "", 8.0)
    assert frag["e2e_timeout"] is True
    forensics = frag["e2e_forensics"]
    assert forensics["in_flight"][0]["fp"] == "wedged123"
    assert forensics["flight_tail"][-1]["in_flight"] is True
    assert forensics["executables"][0]["name"] == "train_step@b4"


def test_read_flight_forensics_bounds_and_garbage(tmp_path):
    import bench
    # garbage / missing file degrade to None, never raise
    assert bench._read_flight_forensics(str(tmp_path / "nope")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{torn")
    assert bench._read_flight_forensics(str(bad)) is None
    big = tmp_path / "big.json"
    big.write_text(json.dumps({
        "ts": 5.0,
        "flight": [{"seq": i} for i in range(100)],
        "executables": [{"fingerprint": str(i)} for i in range(100)],
    }))
    out = bench._read_flight_forensics(str(big))
    # bounded: the artifact must not bloat the round JSON
    assert len(out["flight_tail"]) == 16
    assert out["flight_tail"][-1]["seq"] == 99
    assert len(out["executables"]) == 32
    assert out["snapshot_ts"] == 5.0


def test_child_flight_dump_writes_snapshots(tmp_path, monkeypatch):
    """The child half: _start_flight_dump arms the recorder and
    snapshots the ring to CXN_BENCH_FLIGHT (atomic replace)."""
    import bench
    from cxxnet_tpu import telemetry
    telemetry.reset_for_tests()
    path = tmp_path / "flight.json"
    monkeypatch.setenv("CXN_BENCH_FLIGHT", str(path))
    bench._start_flight_dump("compute")
    assert telemetry.flight().enabled
    telemetry.flight().start("train", fp="live1", bucket=4)
    deadline = time.monotonic() + 10.0
    while not path.exists() and time.monotonic() < deadline:
        time.sleep(0.1)
    snap = json.loads(path.read_text())
    assert snap["field"] == "compute"
    assert snap["in_flight"][0]["fp"] == "live1"
    telemetry.reset_for_tests()


def test_child_only_mode_emits_fragment(tmp_path, monkeypatch):
    """python bench.py --only NAME prints exactly one JSON fragment."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CXN_BENCH_CACHE_DIR=str(tmp_path / "cache"))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--only", "compute", "--steps", "1", "--batch", "4"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    frag = json.loads(r.stdout.strip().splitlines()[-1])
    assert frag["compute_ips"] > 0


@pytest.mark.slow
def test_bench_googlenet_extra_runs(monkeypatch, tmp_path):
    """The googlenet bench extra (streamed + device-resident variants)
    builds its own trainer with override keys that must track the
    config surface - run it for real at a tiny batch (platform gate
    bypassed, CPU executes; slow: a GoogLeNet compile)."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench._bench_googlenet(2, 1, "tpu")
    assert out.get("googlenet_ips", 0) > 0, out
    assert out.get("googlenet_devicedata_ips", 0) > 0, out


@pytest.mark.slow
def test_bench_resnet_extra_runs(monkeypatch, tmp_path):
    """Same protocol for the third family (shared _bench_model_family
    body, distinct conf/field prefix). Slow: full ResNet-18 compile."""
    monkeypatch.setenv("CXN_BENCH_CACHE_DIR", str(tmp_path / "cache"))
    import bench
    out = bench._bench_resnet(2, 1, "tpu")
    assert out.get("resnet18_ips", 0) > 0, out
    assert out.get("resnet18_devicedata_ips", 0) > 0, out


def test_bench_error_artifact_is_json():
    """A crash before any measurement must still print the one-line
    JSON contract (value 0.0 + error), rc=0."""
    import bench
    line = bench._error_json("boom")
    d = json.loads(line)
    assert d["value"] == 0.0 and "boom" in d["error"]
