"""MoE layer: routing math, aux loss, expert-parallel sharding, and the
EP == single-device training invariant."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.layers import create_layer
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

MOE_NET = """
netconfig=start
layer[0->1] = layernorm:ln1
layer[1->2] = moe:moe1
  nexpert = 4
  nhidden = 16
  moe_top_k = 2
  init_sigma = 0.1
layer[2->3] = flatten
layer[3->4] = fullc:head
  nhidden = 4
layer[4->4] = softmax
netconfig=end
input_shape = 1,4,8
random_type = gaussian
init_sigma = 0.1
eta = 0.05
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
"""


def _make(mesh: str, extra: str = "") -> NetTrainer:
    t = NetTrainer()
    net = MOE_NET if not extra else MOE_NET.replace(
        "moe_top_k = 2", "moe_top_k = 2\n  " + extra)
    for k, v in parse_config_string(net):
        t.set_param(k, v)
    if mesh:
        t.set_param("mesh", mesh)
    t.init_model()
    return t


def _batches(n=3, b=8):
    rng = np.random.RandomState(5)
    return [DataBatch(
        data=rng.randn(b, 1, 4, 8).astype(np.float32),
        label=rng.randint(0, 4, size=(b, 1)).astype(np.float32))
        for _ in range(n)]


def _layer(**kw):
    m = create_layer("moe")
    m.set_param("nexpert", str(kw.get("nexpert", 4)))
    m.set_param("nhidden", str(kw.get("nhidden", 8)))
    m.set_param("moe_top_k", str(kw.get("top_k", 1)))
    return m


def test_shape_and_validation():
    m = _layer()
    assert m.infer_shapes([(2, 1, 4, 8)]) == [(2, 1, 4, 8)]
    with pytest.raises(ValueError, match="nexpert"):
        _layer(nexpert=1).infer_shapes([(2, 1, 4, 8)])
    with pytest.raises(ValueError, match="sequence node"):
        _layer().infer_shapes([(2, 3, 4, 8)])
    with pytest.raises(ValueError, match="top_k"):
        _layer(top_k=9).infer_shapes([(2, 1, 4, 8)])


def test_capacity_warns_about_residual():
    """moe_capacity > 0 zeroes dropped tokens' outputs; the layer must
    tell the config author to wire a residual bypass (the layer itself
    adds none). The default dense route stays silent."""
    import warnings
    m = _layer()
    m.set_param("moe_capacity", "1.25")
    with pytest.warns(UserWarning, match="residual"):
        m.infer_shapes([(2, 1, 4, 8)])
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _layer().infer_shapes([(2, 1, 4, 8)])


def test_full_topk_equals_dense_mixture():
    """top_k == nexpert makes the routed sum the full softmax mixture -
    an analytically checkable reference."""
    m = _layer(nexpert=3, nhidden=8, top_k=3)
    m.infer_shapes([(2, 1, 4, 8)])
    params = m.init_params(jax.random.PRNGKey(0), [(2, 1, 4, 8)])
    x = np.random.RandomState(0).randn(2, 1, 4, 8).astype(np.float32)
    (y,), _ = m.apply_with_aux(params, [x], train=True)

    xs = x.reshape(2, 4, 8)
    logits = np.einsum("bse,ge->bsg", xs, np.asarray(params["gate"]))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    h1 = np.maximum(
        np.einsum("bse,ghe->bsgh", xs, np.asarray(params["w1"]))
        + np.asarray(params["b1"])[None, None], 0.0)
    ye = (np.einsum("bsgh,geh->bsge", h1, np.asarray(params["w2"]))
          + np.asarray(params["b2"])[None, None])
    ref = np.einsum("bsge,bsg->bse", ye, probs).reshape(2, 1, 4, 8)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_top1_uses_single_expert():
    """With top_k=1, the output equals the argmax expert's FFN scaled by
    its router prob, token by token."""
    m = _layer(nexpert=4, nhidden=8, top_k=1)
    m.infer_shapes([(1, 1, 4, 8)])
    params = m.init_params(jax.random.PRNGKey(1), [(1, 1, 4, 8)])
    x = np.random.RandomState(1).randn(1, 1, 4, 8).astype(np.float32)
    (y,), _ = m.apply_with_aux(params, [x], train=True)
    xs = x.reshape(4, 8)
    logits = xs @ np.asarray(params["gate"]).T
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    for t in range(4):
        g = int(np.argmax(logits[t]))
        h1 = np.maximum(np.asarray(params["w1"])[g] @ xs[t]
                        + np.asarray(params["b1"])[g], 0)
        ref = (np.asarray(params["w2"])[g] @ h1
               + np.asarray(params["b2"])[g]) * probs[t, g]
        np.testing.assert_allclose(np.asarray(y)[0, 0, t], ref,
                                   rtol=1e-4, atol=1e-5)


def test_aux_loss_balanced_is_one():
    """Zero gate weights -> uniform router: the Switch load-balance
    term is exactly 1 (times moe_aux times batch)."""
    m = _layer(nexpert=4, nhidden=8, top_k=1)
    m.set_param("moe_aux", "0.5")
    m.infer_shapes([(2, 1, 4, 8)])
    params = m.init_params(jax.random.PRNGKey(0), [(2, 1, 4, 8)])
    params["gate"] = jnp.zeros_like(params["gate"])
    x = np.random.RandomState(2).randn(2, 1, 4, 8).astype(np.float32)
    _, aux = m.apply_with_aux(params, [x], train=True)
    np.testing.assert_allclose(float(aux), 0.5 * 2 * 1.0, rtol=1e-5)


def test_aux_loss_ignores_padding_rows():
    """A padded batch's aux term (with the validity mask) must equal
    the unpadded batch's aux term, scaled for the batch-dim change -
    padding rows carry no router statistics."""
    m = _layer(nexpert=4, nhidden=8, top_k=1)
    m.set_param("moe_aux", "1.0")
    m.infer_shapes([(4, 1, 4, 8)])
    params = m.init_params(jax.random.PRNGKey(3), [(4, 1, 4, 8)])
    rng = np.random.RandomState(7)
    x = rng.randn(2, 1, 4, 8).astype(np.float32)
    _, aux_ref = m.apply_with_aux(params, [x], train=True)
    xpad = np.concatenate([x, np.zeros((2, 1, 4, 8), np.float32)])
    mask = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    _, aux_pad = m.apply_with_aux(params, [xpad], train=True, mask=mask)
    # aux_term scales by the (padded) batch dim: 4 vs 2
    np.testing.assert_allclose(float(aux_pad) / 4.0,
                               float(aux_ref) / 2.0, rtol=1e-5)


def test_sparse_dispatch_equals_dense_with_ample_capacity():
    """moe_capacity large enough that nothing drops: the sparse
    gather/scatter route must equal the dense masked-sum exactly (same
    per-token expert outputs, same prob weights)."""
    m = _layer(nexpert=4, nhidden=8, top_k=2)
    m.infer_shapes([(2, 1, 8, 8)])
    params = m.init_params(jax.random.PRNGKey(2), [(2, 1, 8, 8)])
    x = np.random.RandomState(4).randn(2, 1, 8, 8).astype(np.float32)
    (dense,), _ = m.apply_with_aux(params, [x], train=True)
    m.set_param("moe_capacity", "4.0")  # cap = t, cannot drop
    (sparse,), _ = m.apply_with_aux(params, [x], train=True)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)


def test_sparse_dispatch_drops_overflow_tokens():
    """Tiny capacity: overflowing tokens get a zero MoE output (their
    residual path carries them) - never NaN, and the kept tokens still
    match the dense computation."""
    m = _layer(nexpert=2, nhidden=8, top_k=1)
    m.set_param("moe_capacity", "0.25")
    m.infer_shapes([(1, 1, 8, 8)])
    params = m.init_params(jax.random.PRNGKey(5), [(1, 1, 8, 8)])
    # drive every token to expert 0 so capacity must overflow
    params["gate"] = params["gate"].at[0].set(5.0).at[1].set(-5.0)
    x = np.random.RandomState(6).randn(1, 1, 8, 8).astype(np.float32)
    (y,), _ = m.apply_with_aux(params, [x], train=True)
    y = np.asarray(y)[0, 0]
    assert np.all(np.isfinite(y))
    # cap = ceil(1 * 8/2 * 0.25) = 1: at most one token kept per
    # expert (sign of sum(x_t) picks the expert under this gate)
    nonzero = np.abs(y).sum(axis=1) > 0
    assert 1 <= nonzero.sum() <= 2, nonzero
    m2 = _layer(nexpert=2, nhidden=8, top_k=1)
    m2.infer_shapes([(1, 1, 8, 8)])
    (dense,), _ = m2.apply_with_aux(params, [x], train=True)
    np.testing.assert_allclose(y[nonzero],
                               np.asarray(dense)[0, 0][nonzero],
                               rtol=1e-5, atol=1e-5)


def test_sparse_expert_parallel_equals_single_device():
    ep = _make("data:2,expert:2", extra="moe_capacity = 4.0")
    base = _make("", extra="moe_capacity = 4.0")
    for b in _batches():
        base.update(b)
        ep.update(b)
    for a, b in zip(jax.tree.leaves(jax.device_get(base.state["params"])),
                    jax.tree.leaves(jax.device_get(ep.state["params"]))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_expert_parallel_equals_single_device():
    base = _make("")
    ep = _make("data:2,expert:2")
    # the stacked expert weights really ride the 'expert' axis
    assert ep._pshard["moe1"]["w1"].spec[0] == "expert"
    assert ep._pshard["moe1"]["gate"].spec == ()  # replicated
    for b in _batches():
        base.update(b)
        ep.update(b)
    for a, b in zip(jax.tree.leaves(jax.device_get(base.state["params"])),
                    jax.tree.leaves(jax.device_get(ep.state["params"]))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_indivisible_expert_axis_replicates():
    t = _make("data:2,expert:3")  # 4 experts % 3 != 0
    assert t._pshard["moe1"]["w1"].spec == ()


def test_moe_training_learns():
    t = _make("")
    rng = np.random.RandomState(9)
    data = rng.randn(64, 1, 4, 8).astype(np.float32)
    label = rng.randint(0, 4, size=(64, 1)).astype(np.float32)
    for i in range(64):
        data[i, 0, :, int(label[i, 0])] += 2.5
    batches = [DataBatch(data=data[i:i + 8], label=label[i:i + 8])
               for i in range(0, 64, 8)]
    for _ in range(10):
        for b in batches:
            t.update(b)
    preds = np.concatenate([t.predict(b) for b in batches])
    err = float((preds != label[:, 0]).mean())
    assert err < 0.3, f"moe net failed to learn: err={err}"
