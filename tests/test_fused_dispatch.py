"""Fused multi-step dispatch (steps_per_dispatch=K): trajectory
equality is the acceptance proof (docs/PERFORMANCE.md).

A fused chunk must reproduce K streamed updates - same RNG stream
(folded on device from the same (seed, step_counter) pairs), same
divergence-guard decisions, same on-device train-metric accumulator.

Two rigor levels, split by XLA:CPU backend determinism: the default
thunk runtime's codegen picks different float contractions per
PROGRAM SHAPE (~1 ULP drift between the per-step executable and the
fused scan of the same math - backend noise, not a property of the
dispatch path). So the in-process tests assert trajectory equality to
tight tolerance plus EXACT guard/metric/counter semantics, and the
bitwise proof runs in subprocesses pinned to the legacy runtime
(--xla_cpu_use_thunk_runtime=false), where both executables compile
identically. The CI fused-smoke job (tools/fused_smoke.py) runs the
same way.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.io.prefetch import StagedPrefetcher
from cxxnet_tpu.nnet.trainer import NetTrainer, StagedChunk
from cxxnet_tpu.utils.config import parse_config_string

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:ac1] = tanh
layer[ac1->fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.5
momentum = 0.9
wd = 0.0
metric = error
eval_train = 1
silent = 1
"""


def make_trainer(extra=""):
    t = NetTrainer()
    for k, v in parse_config_string(MLP_CFG + extra):
        t.set_param(k, v)
    t.init_model()
    return t


def synth_batches(n_batches=8, batch_size=16, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(8)
    out = []
    for _ in range(n_batches):
        x = rng.randn(batch_size, 8).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        out.append(DataBatch(data=x.reshape(batch_size, 1, 1, 8),
                             label=y.reshape(batch_size, 1)))
    return out


class ListIter:
    def __init__(self, batches):
        self.batches = batches
        self.i = -1

    def before_first(self):
        self.i = -1

    def next(self):
        self.i += 1
        return self.i < len(self.batches)

    def value(self):
        return self.batches[self.i]


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the deterministic-codegen env for the bitwise subprocesses (see
# module docstring): legacy CPU runtime + the suite's device count
PARITY_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    XLA_FLAGS="--xla_force_host_platform_device_count=8 "
              "--xla_cpu_use_thunk_runtime=false")


def params_of(t):
    return jax.tree.leaves(jax.tree.map(np.asarray, t.state["params"]))


def assert_traj_close(a, b, msg=""):
    """In-process equality bar: identical dtypes/shapes, values equal
    to well under any training-visible scale (the residual is the
    thunk runtime's per-program-shape contraction noise; the bitwise
    bar lives in the legacy-runtime subprocess tests)."""
    for x, y in zip(a, b):
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_allclose(x, y, rtol=5e-6, atol=1e-7,
                                   err_msg=msg)


def run_streamed(batches, extra=""):
    t = make_trainer(extra)
    for b in batches:
        t.update(b)
    return t


def run_fused(batches, k, extra=""):
    t = make_trainer(extra + f"steps_per_dispatch = {k}\n")
    for i in range(0, len(batches), k):
        t.update_chunk(batches[i:i + k])
    return t


@pytest.mark.parametrize("k", [1, 2, 4])
def test_fused_trajectory_matches_streamed(k):
    batches = synth_batches(8)
    ta = run_streamed(batches)
    tb = run_fused(batches, k)
    assert_traj_close(params_of(ta), params_of(tb), f"K={k}")
    # identical train-metric accumulator -> identical metric STRING
    assert ta.eval_train_metric() == tb.eval_train_metric()
    assert ta.epoch == tb.epoch
    assert ta._step_counter == tb._step_counter


@pytest.mark.parametrize("k", [2, 4])
def test_fused_update_period_crosses_chunks(k):
    """Grad accumulation (update_period>1) folds into the scan: the
    carried accumulator crosses chunk boundaries exactly as it crosses
    streamed steps."""
    batches = synth_batches(8)
    ta = run_streamed(batches, "update_period = 2\n")
    tb = run_fused(batches, k, "update_period = 2\n")
    assert_traj_close(params_of(ta), params_of(tb), f"up=2 K={k}")
    assert ta.epoch == tb.epoch == 4
    assert ta.eval_train_metric() == tb.eval_train_metric()


def test_fused_short_final_chunk():
    """7 updates at K=4 -> a full chunk + a short (3-step) round-end
    chunk; the scan reads its length from the stacked axis."""
    batches = synth_batches(7)
    ta = run_streamed(batches)
    tb = run_fused(batches, 4)
    assert_traj_close(params_of(ta), params_of(tb), "short tail")
    assert tb._step_counter == 7
    assert ta.eval_train_metric() == tb.eval_train_metric()


def test_fused_nan_guard_drops_exact_microstep(capfd):
    """check_nan=1 with a NaN batch mid-chunk: the in-jit rollback
    drops EXACTLY that microstep; counters, consecutive accounting and
    the guard's stderr line match streaming."""
    batches = synth_batches(8)
    bad = DataBatch(
        data=np.full((16, 1, 1, 8), np.nan, np.float32),
        label=batches[5].label)
    seq = batches[:5] + [bad] + batches[6:]
    ta = run_streamed(seq, "check_nan = 1\n")
    err_streamed = capfd.readouterr().err
    tb = run_fused(seq, 4, "check_nan = 1\n")
    err_fused = capfd.readouterr().err
    assert_traj_close(params_of(ta), params_of(tb), "nan mid-chunk")
    assert ta.bad_rounds == tb.bad_rounds == 1
    assert ta._skipped_steps == tb._skipped_steps == 1
    assert ta.epoch == tb.epoch == 7
    assert "at update 5" in err_streamed
    assert err_fused == err_streamed
    assert ta.eval_train_metric() == tb.eval_train_metric()


def test_fused_divergence_abort_raises():
    """max_bad_rounds consecutive NaN microsteps inside chunks still
    raise DivergenceError (detection may land at the chunk boundary,
    the rollback semantics are per microstep)."""
    from cxxnet_tpu.utils.fault import DivergenceError
    batches = synth_batches(8)
    bad = DataBatch(
        data=np.full((16, 1, 1, 8), np.nan, np.float32),
        label=batches[0].label)
    seq = batches[:2] + [bad, bad, bad] + batches[5:]
    t = make_trainer("check_nan = 1\nsteps_per_dispatch = 4\n")
    with pytest.raises(DivergenceError):
        for i in range(0, len(seq), 4):
            t.update_chunk(seq[i:i + 4])
    assert t.bad_rounds == 3


def test_fused_accepts_staged_batches_and_chunks():
    """stage_chunk accepts StagedBatch/DataBatch mixed; update()
    routes a StagedChunk to update_chunk."""
    batches = synth_batches(4)
    ta = run_streamed(batches)
    tb = make_trainer()
    staged = [tb.stage_batch(b) for b in batches[:2]] + batches[2:]
    chunk = tb.stage_chunk(staged)
    assert isinstance(chunk, StagedChunk)
    assert chunk.n_steps == 4
    assert chunk.n_examples == (16, 16, 16, 16)
    tb.update(chunk)
    assert_traj_close(params_of(ta), params_of(tb), "mixed staging")


def test_fused_empty_chunk_rejected():
    t = make_trainer()
    with pytest.raises(ValueError):
        t.stage_chunk([])
    with pytest.raises(ValueError):
        t.set_param("steps_per_dispatch", "0")


def test_prefetcher_assembles_chunks_with_partial_tail():
    """chunk=K on the staging prefetcher: the worker ships StagedChunk
    items, flushing a SHORT chunk at the end of the pass, and the
    trajectory equals streaming."""
    batches = synth_batches(7)
    ta = run_streamed(batches)
    tb = make_trainer("steps_per_dispatch = 3\n")
    pf = tb.prefetch(ListIter(batches), depth=2, chunk=3)
    sizes = []
    pf.before_first()
    while pf.next():
        sizes.append(pf.value().n_steps)
        tb.update(pf.value())
    pf.close()
    assert sizes == [3, 3, 1]
    assert_traj_close(params_of(ta), params_of(tb), "prefetched chunks")
    assert ta.eval_train_metric() == tb.eval_train_metric()


def test_prefetcher_chunk_requires_chunk_fn():
    with pytest.raises(ValueError):
        StagedPrefetcher(lambda b: b, ListIter([]), chunk=2)


def test_prefetcher_chunk_restart_and_close():
    """before_first() restarts a chunked pass cleanly; close() mid-pass
    does not hang or leak."""
    t = make_trainer()
    pf = t.prefetch(ListIter(synth_batches(6)), depth=1, chunk=2)
    pf.before_first()
    assert pf.next() and pf.value().n_steps == 2
    pf.before_first()  # restart mid-pass
    n = 0
    while pf.next():
        n += pf.value().n_steps
    assert n == 6
    pf.close()
    assert not pf.next()


BITWISE_MATRIX_SCRIPT = r"""
# Bitwise trajectory-equality matrix, run under the legacy XLA:CPU
# runtime (see test module docstring). Raises on the first mismatch.
import numpy as np, jax
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

CFG = '''
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:ac1] = tanh
layer[ac1->fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.5
momentum = 0.9
wd = 0.0
metric = error
eval_train = 1
silent = 1
'''

def mk(extra=""):
    t = NetTrainer()
    for k, v in parse_config_string(CFG + extra):
        t.set_param(k, v)
    t.init_model()
    return t

rng = np.random.RandomState(0)
w = rng.randn(8)
batches = []
for _ in range(7):
    x = rng.randn(16, 8).astype(np.float32)
    batches.append(DataBatch(
        data=x.reshape(16, 1, 1, 8),
        label=(x @ w > 0).astype(np.float32).reshape(16, 1)))

def leaves(t):
    return jax.tree.leaves(jax.tree.map(np.asarray, t.state["params"]))

def check(pa, pb, tag):
    for a, b in zip(pa, pb):
        assert a.dtype == b.dtype and np.array_equal(a, b), (
            tag, float(np.abs(a.astype(np.float64)
                              - b.astype(np.float64)).max()))

class ListIter:
    def __init__(self, bs): self.bs, self.i = bs, -1
    def before_first(self): self.i = -1
    def next(self):
        self.i += 1
        return self.i < len(self.bs)
    def value(self): return self.bs[self.i]

for extra, tag in (("", "plain"), ("update_period = 2\n", "up2")):
    ta = mk(extra)
    for b in batches:
        ta.update(b)
    pa = leaves(ta)
    ma = ta.eval_train_metric()
    for K in (1, 2, 4):  # 7 batches -> short final chunk every time
        tb = mk(extra + f"steps_per_dispatch = {K}\n")
        for i in range(0, 7, K):
            tb.update_chunk(batches[i:i + K])
        check(pa, leaves(tb), f"{tag} K={K}")
        assert tb.eval_train_metric() == ma, (tag, K)

# NaN mid-chunk under the divergence guard
bad = DataBatch(data=np.full((16, 1, 1, 8), np.nan, np.float32),
                label=batches[5].label)
seq = batches[:5] + [bad] + batches[6:]
ta = mk("check_nan = 1\n")
for b in seq:
    ta.update(b)
tb = mk("check_nan = 1\nsteps_per_dispatch = 4\n")
for i in range(0, 7, 4):
    tb.update_chunk(seq[i:i + 4])
check(leaves(ta), leaves(tb), "nan")
assert ta.bad_rounds == tb.bad_rounds == 1

# prefetcher-assembled chunks (worker staging + partial tail)
ta = mk()
for b in batches:
    ta.update(b)
tb = mk("steps_per_dispatch = 3\n")
pf = tb.prefetch(ListIter(batches), depth=2, chunk=3)
pf.before_first()
sizes = []
while pf.next():
    sizes.append(pf.value().n_steps)
    tb.update(pf.value())
pf.close()
assert sizes == [3, 3, 1], sizes
check(leaves(ta), leaves(tb), "prefetched")
print("BITWISE-OK")
"""


def test_fused_trajectory_bitwise_exact():
    """THE acceptance proof: under deterministic codegen the fused
    trajectory is bit-for-bit the streamed one - K in {1,2,4}, grad
    accumulation, NaN-guard mid-chunk, short final chunks, and
    worker-assembled (prefetched) chunks."""
    r = subprocess.run(
        [sys.executable, "-c", BITWISE_MATRIX_SCRIPT], env=PARITY_ENV,
        cwd=REPO, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, f"\nstdout:{r.stdout}\nstderr:{r.stderr}"
    assert "BITWISE-OK" in r.stdout


def test_cli_fused_vs_streamed_checkpoint_identical(tmp_path):
    """The CI smoke assertion: a K=4 CLI run's final checkpoint is
    byte-identical to the K=1 run's, and the per-round eval lines
    match (subprocesses under the deterministic-codegen env)."""
    from test_cli import write_conf, write_synth_mnist
    tr = write_synth_mnist(tmp_path, n=256, seed=0, prefix="train")
    te = write_synth_mnist(tmp_path, n=64, seed=1, prefix="test")
    conf = write_conf(tmp_path, *tr, *te, extra="num_round = 3\n")

    def run(k, tag):
        mdir = tmp_path / f"models_{tag}"
        r = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu.main", conf,
             f"model_dir={mdir}", f"steps_per_dispatch={k}"],
            env=PARITY_ENV, cwd=REPO, capture_output=True, text=True,
            timeout=560)
        assert r.returncode == 0, r.stderr
        with open(mdir / "0003.model", "rb") as f:
            blob = f.read()
        evals = [l for l in r.stderr.splitlines() if l.startswith("[")]
        return blob, evals

    blob1, evals1 = run(1, "k1")
    blob4, evals4 = run(4, "k4")
    assert blob1 == blob4
    assert evals1 == evals4 and len(evals1) == 3


def test_wrapper_honors_steps_per_dispatch():
    """The numpy-wrapper train() wires steps_per_dispatch through both
    its paths (device-resident chunk stacking and the chunked
    prefetcher) - the knob must not be CLI-only."""
    from cxxnet_tpu import wrapper
    cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = tanh
layer[a1->fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
eta = 0.5
metric = error
"""
    rng = np.random.RandomState(0)
    w = rng.randn(8)
    x = rng.randn(96, 8).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    def preds(net):
        return np.concatenate(
            [net.predict(x[i:i + 16].reshape(-1, 1, 1, 8))
             for i in range(0, 96, 16)])

    n1 = wrapper.train(cfg, x.reshape(-1, 1, 1, 8), y, 3,
                       {"silent": "1"}, batch_size=16)
    n2 = wrapper.train(cfg, x.reshape(-1, 1, 1, 8), y, 3,
                       {"silent": "1", "steps_per_dispatch": "3"},
                       batch_size=16)
    assert np.array_equal(preds(n1), preds(n2))
    old = wrapper._STAGE_BYTES_LIMIT
    wrapper._STAGE_BYTES_LIMIT = 0  # force the streaming/prefetch path
    try:
        n3 = wrapper.train(cfg, x.reshape(-1, 1, 1, 8), y, 3,
                           {"silent": "1", "steps_per_dispatch": "3"},
                           batch_size=16)
    finally:
        wrapper._STAGE_BYTES_LIMIT = old
    assert np.array_equal(preds(n1), preds(n3))


def test_eval_inflight_config():
    """eval_inflight=N bounds the eval loop's in-flight staging; any
    value (including 0 = never sync) yields the same metrics."""
    batches = synth_batches(6)
    ta = run_streamed(batches)
    base = ta.evaluate(ListIter(batches), "eval")
    for v in ("1", "2", "0"):
        ta.set_param("eval_inflight", v)
        assert ta.evaluate(ListIter(batches), "eval") == base
    with pytest.raises(ValueError):
        ta.set_param("eval_inflight", "-1")


def test_profiler_add_chunk_per_step_stats():
    from cxxnet_tpu.utils.profiler import StepProfiler
    p = StepProfiler()
    p.round_start()
    p.add_chunk(0.4, 4, 64)
    p.add_chunk(0.1, 1, 16)
    st = p.stats()
    assert st["steps"] == 5
    assert st["examples"] == 80
    assert abs(st["step_total_s"] - 0.5) < 1e-9
    assert abs(st["step_p50_ms"] - 100.0) < 1e-6


def test_fused_telemetry_chunk_span(tmp_path):
    """Fused updates emit one train.chunk span per dispatch carrying
    the per-microstep loss vector; the step-time histogram keeps
    per-STEP scale (K amortized observations per chunk)."""
    from cxxnet_tpu import telemetry
    from cxxnet_tpu.telemetry.sink import read_jsonl
    log = str(tmp_path / "ev.jsonl")
    tel = telemetry.get()
    tel.configure(log_file=log)
    try:
        # deltas, not absolutes: the registry is process-global and
        # other tests in the session may already have fed it
        img0 = tel.registry.counter("train.images").value
        cnt0 = tel.registry.histogram("train.step_s").count
        batches = synth_batches(4)
        t = make_trainer("steps_per_dispatch = 4\n")
        t.update_chunk(batches)
        assert tel.registry.counter("train.images").value - img0 == 64
        assert tel.registry.histogram("train.step_s").count - cnt0 == 4
    finally:
        tel.close()
    chunks = [e for e in read_jsonl(log)
              if e.get("name") == "train.chunk"]
    assert len(chunks) == 1
    assert chunks[0]["steps"] == 4
    assert len(chunks[0]["loss"]) == 4
    assert chunks[0]["examples"] == 64
