"""Tests for NetConfig DAG parsing and the functional Network."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.nnet.network import Network, param_key
from cxxnet_tpu.utils.config import parse_config_string


def build(text):
    cfg = NetConfig()
    cfg.configure(parse_config_string(text))
    return cfg


MLP = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
random_type = gaussian
"""


def test_mlp_structure():
    cfg = build(MLP)
    assert cfg.num_layers == 4
    assert cfg.node_names == ["in", "fc1", "sg1", "fc2"]
    l0, l1, l2, l3 = cfg.layers
    assert (l0.type_name, l0.nindex_in, l0.nindex_out) == ("fullc", [0], [1])
    assert (l1.type_name, l1.nindex_in, l1.nindex_out) == ("sigmoid", [1], [2])
    assert (l2.type_name, l2.nindex_in, l2.nindex_out) == ("fullc", [2], [3])
    assert (l3.type_name, l3.nindex_in, l3.nindex_out) == ("softmax", [3], [3])
    assert cfg.layer_name_map == {"fc1": 0, "se1": 1, "fc2": 2}
    # per-layer vs default config scoping
    assert ("nhidden", "100") in cfg.layercfg[0]
    assert ("nhidden", "10") in cfg.layercfg[2]
    assert ("random_type", "gaussian") in cfg.defcfg
    assert ("input_shape", "1,1,784") in cfg.defcfg


def test_numeric_node_names():
    cfg = build("""
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  nchannel = 8
layer[1->2] = relu
layer[2->2] = dropout
netconfig=end
input_shape = 3,8,8
""")
    assert cfg.node_names == ["in", "1", "2"]
    assert cfg.layers[2].nindex_in == cfg.layers[2].nindex_out == [2]


def test_undefined_input_node_raises():
    with pytest.raises(ValueError):
        build("""
netconfig=start
layer[bogus->x] = relu
netconfig=end
""")


def test_multi_input_and_split():
    cfg = build("""
netconfig=start
layer[0->a,b] = split
layer[a->c] = relu
layer[b->d] = sigmoid
layer[c,d->e] = ch_concat
netconfig=end
input_shape = 4,6,6
""")
    assert cfg.layers[0].nindex_out == [1, 2]
    assert cfg.layers[3].nindex_in == [3, 4]
    net = Network(cfg, batch_size=2)
    assert net.node_shapes[5] == (2, 8, 6, 6)


def test_shared_layer():
    cfg = build("""
netconfig=start
layer[0->a] = fullc:shared_fc
  nhidden = 16
layer[a->b] = relu
layer[b->c] = flatten
layer[c->d] = share[shared_fc]
netconfig=end
input_shape = 1,1,16
""")
    assert cfg.layers[3].is_shared
    assert cfg.layers[3].primary_layer_index == 0
    net = Network(cfg, batch_size=2)
    params = net.init_params(jax.random.PRNGKey(0))
    assert list(params) == ["shared_fc"]  # one param set for both conns
    # forward runs and produces the right shapes
    x = jnp.ones((2, 1, 1, 16))
    values, _ = net.forward(params, {0: x}, train=False)
    assert values[4].shape == (2, 1, 1, 16)


def test_shared_layer_params_rejected():
    with pytest.raises(ValueError):
        build("""
netconfig=start
layer[0->a] = fullc:f1
  nhidden = 4
layer[a->b] = share[f1]
  nhidden = 8
netconfig=end
""")


def test_label_vec_slicing():
    cfg = build("""
label_vec[0,1) = label
label_vec[1,4) = extra
netconfig=start
layer[+1] = fullc
  nhidden = 4
netconfig=end
input_shape = 1,1,8
""")
    # explicit label_vec lines append ranges; the default (0,1) stays at 0
    assert cfg.label_name_map == {"label": 1, "extra": 2}
    assert cfg.label_range == [(0, 1), (0, 1), (1, 4)]


def test_layer_plus0_self_loop_and_anon_nodes():
    cfg = build("""
netconfig=start
layer[+1] = fullc
  nhidden = 4
layer[+0] = dropout
layer[+1] = fullc
  nhidden = 2
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
""")
    assert cfg.num_nodes == 3
    assert cfg.layers[1].nindex_in == cfg.layers[1].nindex_out == [1]


def test_structure_roundtrip():
    cfg = build(MLP)
    d = cfg.to_dict()
    cfg2 = NetConfig.from_dict(d)
    assert cfg2.num_layers == cfg.num_layers
    for a, b in zip(cfg.layers, cfg2.layers):
        assert a.structure_equals(b)
    # re-configuring a loaded net with the same config succeeds...
    cfg2.configure(parse_config_string(MLP))
    # ...and with a mismatched one fails
    cfg3 = NetConfig.from_dict(d)
    with pytest.raises(ValueError):
        cfg3.configure(parse_config_string(MLP.replace("sigmoid", "tanh")))


def test_mnist_conv_net_shapes():
    cfg = build("""
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 32
layer[1->2] = max_pooling
  kernel_size = 3
  stride = 2
layer[2->3] = flatten
layer[3->3] = dropout
  threshold = 0.5
layer[3->4] = fullc:fc1
  nhidden = 100
layer[4->5] = sigmoid
layer[5->6] = fullc:fc2
  nhidden = 10
layer[6->6] = softmax
netconfig=end
input_shape = 1,28,28
""")
    net = Network(cfg, batch_size=100)
    # conv: (28+2-3)//2+1 = 14; pool: min(14-3+1,13)//2+1 = 7
    assert net.node_shapes[1] == (100, 32, 14, 14)
    assert net.node_shapes[2] == (100, 32, 7, 7)
    assert net.node_shapes[3] == (100, 1, 1, 32 * 49)
    assert net.node_shapes[6] == (100, 1, 1, 10)


def test_forward_loss_and_grad():
    cfg = build(MLP)
    net = Network(cfg, batch_size=4)
    params = net.init_params(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(0).randn(4, 1, 1, 784)
                    .astype(np.float32))
    labels = {"label": jnp.asarray([[1.0], [2.0], [3.0], [4.0]])}

    def loss_fn(p):
        _, loss = net.forward(p, {0: x}, train=True,
                              rng=jax.random.PRNGKey(1), labels=labels)
        return loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # CE of uniform-ish init ~ log(10) per example * 4 examples
    assert 0.5 * 4 * np.log(10) < float(loss) < 2 * 4 * np.log(10)
    g = grads["fc1"]["wmat"]
    assert g.shape == (100, 784)
    assert float(jnp.abs(g).sum()) > 0


def test_forward_mask_zeroes_padding_loss():
    cfg = build(MLP)
    net = Network(cfg, batch_size=4)
    params = net.init_params(jax.random.PRNGKey(0))
    x = jnp.ones((4, 1, 1, 784))
    labels = {"label": jnp.zeros((4, 1))}
    _, loss_full = net.forward(params, {0: x}, train=True,
                               rng=jax.random.PRNGKey(1), labels=labels)
    _, loss_half = net.forward(params, {0: x}, train=True,
                               rng=jax.random.PRNGKey(1), labels=labels,
                               mask=jnp.array([1.0, 1.0, 0.0, 0.0]))
    assert abs(float(loss_half) - float(loss_full) / 2) < 1e-4


def test_param_key_naming():
    cfg = build(MLP)
    assert param_key(cfg, 0) == "fc1"
    assert param_key(cfg, 1) == "se1"
    assert param_key(cfg, 3) == "layer_3"


def test_anonymous_nodes_unique_after_retarget():
    """Two layer[+1] declarations whose top is the same node (after an
    explicit re-target) must allocate DISTINCT anonymous output nodes -
    the reference allocates positionally (regression: name-keyed
    anonymous nodes aliased)."""
    cfg = NetConfig()
    cfg.configure(parse_config_string("""
netconfig=start
layer[0->b] = fullc:f1
  nhidden = 4
layer[+1] = relu
layer[!node-of-layer-1->b2] = fullc:f2
  nhidden = 4
layer[b2->b] = fullc:f3
  nhidden = 4
layer[+1] = sigmoid
netconfig=end
input_shape = 1,1,4
batch_size = 2
"""))
    relu_out = cfg.layers[1].nindex_out[0]
    sig_out = cfg.layers[4].nindex_out[0]
    assert relu_out != sig_out, (relu_out, sig_out)
