"""Differential tests: pallas LRN kernel (interpret mode) vs the XLA
reduce_window implementation - the pairtest discipline (SURVEY.md par.4.1)
applied to the hand-written TPU kernel."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.nn import lrn
from cxxnet_tpu.ops.pallas_lrn import lrn_pallas, use_pallas_lrn


@pytest.mark.parametrize("shape,n", [
    ((2, 16, 7, 9), 5),
    ((2, 8, 5, 5), 3),
    ((1, 32, 3, 3), 7),
    ((3, 8, 1, 1), 1),
])
def test_forward_matches_xla(shape, n):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    ref = lrn(x, n, 0.001, 0.75, 1.0)
    got = lrn_pallas(x, n, 0.001, 0.75, 1.0, True)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("shape,n", [((2, 16, 7, 9), 5), ((2, 8, 5, 5), 3)])
def test_grad_matches_xla(shape, n):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    gr = jax.grad(lambda x: jnp.sum(lrn(x, n, 0.001, 0.75, 1.0) * g))(x)
    gp = jax.grad(
        lambda x: jnp.sum(lrn_pallas(x, n, 0.001, 0.75, 1.0, True) * g))(x)
    np.testing.assert_allclose(gr, gp, rtol=1e-4, atol=1e-5)


def test_sharded_matches_xla_multi_device(monkeypatch):
    """shard_map route on the 8-device virtual mesh (interpret mode) ==
    XLA path, forward and grad - the multi-chip flagship scenario the
    kernel used to be hard-disabled in."""
    from cxxnet_tpu.ops import pallas_lrn
    from cxxnet_tpu.parallel.mesh import MeshSpec, build_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert len(jax.devices()) == 8
    monkeypatch.setattr(pallas_lrn, "_FORCE_INTERPRET", True)
    mesh = build_mesh(MeshSpec(device_indices=list(range(8))), 16)
    rng = np.random.RandomState(2)
    x = jax.device_put(rng.randn(16, 16, 5, 7).astype(np.float32),
                       NamedSharding(mesh, P("data")))
    n, alpha, beta, knorm = 5, 0.001, 0.75, 1.0
    assert pallas_lrn.use_pallas_lrn_sharded(x, mesh)

    ref = lrn(x, n, alpha, beta, knorm)  # XLA (CPU backend -> not pallas)
    got = jax.jit(lambda x: pallas_lrn.lrn_pallas_sharded(
        x, mesh, n, alpha, beta, knorm))(x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-6)

    g = rng.randn(*x.shape).astype(np.float32)
    gr = jax.grad(lambda x: jnp.sum(lrn(x, n, alpha, beta, knorm) * g))(x)
    gp = jax.jit(jax.grad(lambda x: jnp.sum(
        pallas_lrn.lrn_pallas_sharded(x, mesh, n, alpha, beta, knorm)
        * g)))(x)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gp),
                               rtol=1e-4, atol=1e-5)


def test_sharded_eligibility():
    from cxxnet_tpu.ops import pallas_lrn
    from cxxnet_tpu.parallel.mesh import MeshSpec, build_mesh
    mesh = build_mesh(MeshSpec(device_indices=list(range(8))), 16)
    x = jnp.zeros((16, 16, 5, 7), jnp.float32)
    # CPU backend without the interpret override -> ineligible
    assert not pallas_lrn.use_pallas_lrn_sharded(x, mesh)
    # batch not divisible by the data axis -> ineligible even forced
    try:
        pallas_lrn._FORCE_INTERPRET = True
        bad = jnp.zeros((12, 16, 5, 7), jnp.float32)
        assert not pallas_lrn.use_pallas_lrn_sharded(bad, mesh)
        assert pallas_lrn.use_pallas_lrn_sharded(x, mesh)
    finally:
        pallas_lrn._FORCE_INTERPRET = False


def test_eligibility_gate():
    # CPU backend in tests -> never eligible; odd channel counts never
    x32 = jnp.zeros((1, 96, 4, 4), jnp.float32)
    assert not use_pallas_lrn(x32) or jax.default_backend() == "tpu"
    x_odd = jnp.zeros((1, 7, 4, 4), jnp.float32)
    from cxxnet_tpu.ops.pallas_lrn import _tile_ok
    assert not _tile_ok(x_odd)
    x_bf = jnp.zeros((1, 24, 4, 4), jnp.bfloat16)
    assert not _tile_ok(x_bf)       # 24 % 16 != 0
    assert _tile_ok(jnp.zeros((1, 32, 4, 4), jnp.bfloat16))
