"""Differential tests: pallas LRN kernel (interpret mode) vs the XLA
reduce_window implementation - the pairtest discipline (SURVEY.md par.4.1)
applied to the hand-written TPU kernel."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.nn import lrn
from cxxnet_tpu.ops.pallas_lrn import lrn_pallas, use_pallas_lrn


@pytest.mark.parametrize("shape,n", [
    ((2, 16, 7, 9), 5),
    ((2, 8, 5, 5), 3),
    ((1, 32, 3, 3), 7),
    ((3, 8, 1, 1), 1),
])
def test_forward_matches_xla(shape, n):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    ref = lrn(x, n, 0.001, 0.75, 1.0)
    got = lrn_pallas(x, n, 0.001, 0.75, 1.0, True)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("shape,n", [((2, 16, 7, 9), 5), ((2, 8, 5, 5), 3)])
def test_grad_matches_xla(shape, n):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    gr = jax.grad(lambda x: jnp.sum(lrn(x, n, 0.001, 0.75, 1.0) * g))(x)
    gp = jax.grad(
        lambda x: jnp.sum(lrn_pallas(x, n, 0.001, 0.75, 1.0, True) * g))(x)
    np.testing.assert_allclose(gr, gp, rtol=1e-4, atol=1e-5)


def test_eligibility_gate():
    # CPU backend in tests -> never eligible; odd channel counts never
    x32 = jnp.zeros((1, 96, 4, 4), jnp.float32)
    assert not use_pallas_lrn(x32) or jax.default_backend() == "tpu"
    x_odd = jnp.zeros((1, 7, 4, 4), jnp.float32)
    from cxxnet_tpu.ops.pallas_lrn import _tile_ok
    assert not _tile_ok(x_odd)
    x_bf = jnp.zeros((1, 24, 4, 4), jnp.bfloat16)
    assert not _tile_ok(x_bf)       # 24 % 16 != 0
    assert _tile_ok(jnp.zeros((1, 32, 4, 4), jnp.bfloat16))
