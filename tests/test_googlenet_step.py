"""Deep example configs (GoogLeNet inception v1, ResNet-18) as real
train-step evidence: each compiles and executes fwd+bwd+update with
finite results — beyond the shape-check in test_example_configs.py.

~60 s each on CPU (compile-dominated): marked slow, excluded from the
default run (pyproject addopts); run with `pytest -m slow`.
"""

import numpy as np
import pytest

import jax

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.utils.config import parse_config_file

pytestmark = pytest.mark.slow


@pytest.mark.parametrize("conf,batch", [
    ("examples/ImageNet/GoogLeNet.conf", 4),
    ("examples/ImageNet/ResNet18.conf", 2),
])
def test_deep_example_train_step_runs(conf, batch):
    from __graft_entry__ import _make_trainer
    tr = _make_trainer(
        parse_config_file(conf),
        [("batch_size", str(batch)), ("dev", "cpu"), ("silent", "1"),
         ("eval_train", "1"), ("save_model", "0")])
    rng = np.random.RandomState(0)
    db = DataBatch(
        data=rng.randn(batch, 3, 224, 224).astype(np.float32),
        label=rng.randint(0, 1000, (batch, 1)).astype(np.float32))
    tr.update(db)
    tr.update(db)
    jax.block_until_ready(tr.state)
    leaves = jax.tree.leaves(tr.state["params"])
    assert all(bool(np.isfinite(np.asarray(p)).all()) for p in leaves)
    out = tr.eval_train_metric()
    assert "train-error:" in out and "train-rec@5:" in out
