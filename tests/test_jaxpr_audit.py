"""graftlint tier 2: the lowered-artifact audit against the LIVE
trainer executables (donation applied, no f64, no host callbacks, no
captured weight constants, stable recompile counts - the PR 3
program-shape trap guard). docs/STATIC_ANALYSIS.md."""

import pytest

from cxxnet_tpu.analysis import jaxpr_audit


@pytest.fixture(scope="module")
def audit():
    return jaxpr_audit.run_audit()


def _by(audit, target, check):
    hits = [c for c in audit["checks"]
            if c["target"] == target and c["check"] == check]
    assert hits, f"missing check {target}/{check}"
    return hits[0]


def test_all_checks_pass(audit):
    bad = [c for c in audit["checks"] if not c["ok"]]
    assert not bad, "\n".join(
        f"{c['target']}: {c['check']} - {c['detail']}" for c in bad)
    assert audit["failed"] == 0


@pytest.mark.parametrize("target", [
    "train_step", "train_chunk[K=1]", "train_chunk[K=4]"])
def test_donation_applied_on_train_executables(audit, target):
    chk = _by(audit, target, "donation-applied")
    assert chk["ok"], chk["detail"]
    # the lowered module really carries aliased params
    assert "aliased params" in chk["detail"]


@pytest.mark.parametrize("target", ["eval_step", "eval_metric_step"])
def test_eval_executables_do_not_donate(audit, target):
    assert _by(audit, target, "no-spurious-donation")["ok"]


@pytest.mark.parametrize("target", [
    "train_step", "train_chunk[K=1]", "train_chunk[K=4]",
    "eval_step", "eval_metric_step", "infer_step"])
def test_no_f64_no_callbacks_no_consts(audit, target):
    assert _by(audit, target, "no-f64")["ok"]
    assert _by(audit, target, "no-host-callback")["ok"]
    assert _by(audit, target, "no-captured-consts")["ok"]


def test_recompile_counts(audit):
    """A 4+4+1 round costs exactly 2 chunk executables (K=4 + the
    short-chunk K=1), stays 2 on round 2, and padded short batches
    add no step/infer programs."""
    sizes = audit["cache_sizes"]
    assert sizes["train_chunk_round1"] == 2
    assert sizes["train_chunk_round2"] == 2
    assert sizes["train_step"] == 1
    assert sizes["infer_step"] == 1


def test_pass_audit(audit):
    """The graph-pass pipeline's artifact contract (nnet/passes.py):
    the folded infer jaxpr has no BN moment/variance pipeline (and
    the unfolded one provably does, so the check isn't vacuous), the
    dead-layer-eliminated extract never traces the pruned subgraph,
    and the fold adds zero steady-state executables."""
    assert _by(audit, "passes/fold", "no-bn-moment-ops")["ok"]
    assert _by(audit, "passes/fold",
               "strictly-smaller-traced-program")["ok"]
    assert _by(audit, "passes/dle", "pruned-subgraph-absent")["ok"]
    assert _by(audit, "passes/fold",
               "zero-new-steady-state-executables")["ok"]
    sizes = audit["cache_sizes"]
    assert sizes["pass_infer_final"] == 1
    assert sizes["pass_infer_early"] == 1


def test_serve_bucket_executables(audit):
    """Serving warmup compiles exactly one executable per bucket and
    100 mixed-size requests add none (the zero-steady-state-recompile
    SLO); serve executables never donate (a freed weight buffer under
    a concurrent replica would be a use-after-free)."""
    assert _by(audit, "serve", "bucket-executables==bucket-count")["ok"]
    assert _by(audit, "serve",
               "no-recompile-over-100-mixed-requests")["ok"]
    sizes = audit["cache_sizes"]
    assert sizes["serve_infer_warm"] == sizes["serve_infer_after"] == 4
    for b in (1, 2, 4, 8):
        assert _by(audit, f"serve[b={b}]", "no-spurious-donation")["ok"]
        assert _by(audit, f"serve[b={b}]", "no-host-callback")["ok"]
