"""transformer_stack + GPipe pipeline parallelism over the 'pipe' axis.

Invariant as everywhere in parallel/: the pipelined schedule changes
the execution order, never the math - a pipe:P mesh must reproduce the
single-device scan-over-layers trajectory exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.layers import create_layer
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

STACK_NET = """
netconfig=start
layer[0->1] = transformer_stack:ts1
  nlayer = 4
  nhead = 2
  nhidden = 32
  causal = 1
  init_sigma = 0.05
layer[1->2] = flatten
layer[2->3] = fullc:head
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,8,16
random_type = gaussian
init_sigma = 0.05
eta = 0.05
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
"""


def _make(mesh: str, extra=()) -> NetTrainer:
    t = NetTrainer()
    for k, v in parse_config_string(STACK_NET):
        t.set_param(k, v)
    if mesh:
        t.set_param("mesh", mesh)
    for k, v in extra:
        t.set_param(k, v)
    t.init_model()
    return t


def _batches(n=3, b=8):
    rng = np.random.RandomState(13)
    return [DataBatch(
        data=rng.randn(b, 1, 8, 16).astype(np.float32),
        label=rng.randint(0, 4, size=(b, 1)).astype(np.float32))
        for _ in range(n)]


def _stack(nlayer=4, nhead=2, nhidden=16):
    m = create_layer("transformer_stack")
    m.set_param("nlayer", str(nlayer))
    m.set_param("nhead", str(nhead))
    m.set_param("nhidden", str(nhidden))
    return m


def test_shapes_and_validation():
    m = _stack()
    assert m.infer_shapes([(2, 1, 8, 16)]) == [(2, 1, 8, 16)]
    with pytest.raises(ValueError, match="nlayer"):
        _stack(nlayer=0).infer_shapes([(2, 1, 8, 16)])
    with pytest.raises(ValueError, match="divisible"):
        _stack(nhead=3).infer_shapes([(2, 1, 8, 16)])
    p = m.init_params(jax.random.PRNGKey(0), [(2, 1, 8, 16)])
    assert p["wqkv"].shape == (4, 48, 16)
    assert m.pipe_shard_dims()["w1"] == 0


def test_scan_matches_manual_blocks():
    """The L-layer scan equals applying _block L times by hand."""
    m = _stack(nlayer=3)
    m.infer_shapes([(2, 1, 8, 16)])
    params = m.init_params(jax.random.PRNGKey(1), [(2, 1, 8, 16)])
    x = np.random.RandomState(0).randn(2, 1, 8, 16).astype(np.float32)
    (y,) = m.apply(params, [x], train=True)
    ref = jnp.asarray(x).reshape(2, 8, 16)
    for i in range(3):
        bp = jax.tree.map(lambda a: a[i], params)
        ref = m._block(bp, ref)
    np.testing.assert_allclose(np.asarray(y).reshape(2, 8, 16),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mesh,extra", [
    ("pipe:4", ()),
    ("data:2,pipe:2", ()),
    ("data:2,pipe:2", (("microbatch", "4"),)),
    ("data:2,pipe:2", (("shard_optimizer", "1"),)),
])
def test_pipeline_equals_single_device(mesh, extra):
    base = _make("")
    pp = _make(mesh, (("microbatch", "0"),) if not extra else extra)
    # stage params really ride the 'pipe' axis
    assert pp._pshard["ts1"]["wqkv"].spec[0] == "pipe"
    if ("shard_optimizer", "1") in extra:
        # ZeRO-1 composes: updater state additionally shards over
        # 'data' on the first free divisible dim
        assert tuple(pp._ustate_shard["ts1"]["wqkv"].spec)[:2] \
                == ("pipe", "data")
    for b in _batches():
        base.update(b)
        pp.update(b)
    for a, b in zip(jax.tree.leaves(jax.device_get(base.state["params"])),
                    jax.tree.leaves(jax.device_get(pp.state["params"]))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
def test_stack_seq_parallel_equals_single_device(scheme):
    """Without a 'pipe' axis, a 'seq' mesh routes the stack's attention
    cores through the configured sp scheme - same trajectory as a
    single device."""
    base = _make("")
    seqp = _make("data:2,seq:2", (("seq_parallel", scheme),))
    assert seqp._pshard["ts1"]["wqkv"].spec == ()  # no pipe: replicated
    for b in _batches():
        base.update(b)
        seqp.update(b)
    for a, b in zip(jax.tree.leaves(jax.device_get(base.state["params"])),
                    jax.tree.leaves(jax.device_get(seqp.state["params"]))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-5)


def test_indivisible_layers_fall_back():
    """nlayer % P != 0 -> sequential route, params replicated."""
    t = _make("pipe:3")
    assert t._pshard["ts1"]["wqkv"].spec == ()
    t.update(_batches(1)[0])  # runs the scan route on the mesh


def test_eval_path_on_pipe_mesh():
    t = _make("data:2,pipe:2")
    t.update(_batches(1)[0])
    pred = t.predict(_batches(1)[0])
    assert pred.shape == (8,)


def test_stack_training_learns():
    t = _make("")
    rng = np.random.RandomState(17)
    data = rng.randn(64, 1, 8, 16).astype(np.float32)
    label = rng.randint(0, 4, size=(64, 1)).astype(np.float32)
    for i in range(64):
        data[i, 0, :, int(label[i, 0])] += 2.0
    batches = [DataBatch(data=data[i:i + 8], label=label[i:i + 8])
               for i in range(0, 64, 8)]
    for _ in range(8):
        for b in batches:
            t.update(b)
    preds = np.concatenate([t.predict(b) for b in batches])
    err = float((preds != label[:, 0]).mean())
    assert err < 0.3, f"stack failed to learn: err={err}"

