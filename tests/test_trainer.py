"""Trainer end-to-end tests on synthetic data (CPU, 8 virtual devices)."""

import io

import numpy as np
import pytest

import jax

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:ac1] = tanh
layer[ac1->fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.5
momentum = 0.9
wd = 0.0
metric = error
eval_train = 1
"""


def make_trainer(extra="", cfg=MLP_CFG, silent=True):
    t = NetTrainer()
    for k, v in parse_config_string(cfg + extra):
        t.set_param(k, v)
    if silent:
        t.set_param("silent", "1")
    t.init_model()
    return t


def synth_batches(n_batches=20, batch_size=16, seed=0):
    """Linearly separable 2-class data."""
    rng = np.random.RandomState(seed)
    w = rng.randn(8)
    batches = []
    for _ in range(n_batches):
        x = rng.randn(batch_size, 8).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        batches.append(DataBatch(
            data=x.reshape(batch_size, 1, 1, 8),
            label=y.reshape(batch_size, 1)))
    return batches


class ListIter:
    def __init__(self, batches):
        self.batches = batches
        self.i = -1

    def before_first(self):
        self.i = -1

    def next(self):
        self.i += 1
        return self.i < len(self.batches)

    def value(self):
        return self.batches[self.i]


def test_training_converges():
    t = make_trainer()
    batches = synth_batches(30)
    for r in range(8):
        t.start_round(r)
        for b in batches:
            t.update(b)
        t.clear_train_metric()
    # eval error on held-out batches from the same distribution
    out = t.evaluate(ListIter(synth_batches(5, seed=0)), "test")
    err = float(out.split(":")[-1])
    assert err < 0.15, out
    assert out.startswith("\ttest-error:")


def test_update_all_runs_evals():
    """update_all's eval_iters/eval_names must actually evaluate (they
    were silently ignored until round 5) and return the reference-
    format metric string; without eval iters it returns ''."""
    t = make_trainer()
    batches = synth_batches(4)
    assert t.update_all(ListIter(batches)) == ""
    out = t.update_all(ListIter(batches),
                       eval_iters=[ListIter(synth_batches(2, seed=1)),
                                   ListIter(synth_batches(2, seed=2))],
                       eval_names=["test"])
    assert "\ttest-error:" in out
    assert "\teval2-error:" in out  # default name for unnamed iters


def test_epoch_counter_and_update_period():
    t = make_trainer(extra="update_period = 2\n")
    batches = synth_batches(4)
    p0 = np.asarray(t.state["params"]["fc1"]["wmat"]).copy()
    t.update(batches[0])
    assert t.epoch == 0  # no update yet
    p1 = np.asarray(t.state["params"]["fc1"]["wmat"])
    np.testing.assert_allclose(p0, p1)  # params unchanged before period
    t.update(batches[1])
    assert t.epoch == 1
    p2 = np.asarray(t.state["params"]["fc1"]["wmat"])
    assert np.abs(p2 - p0).max() > 0


def test_update_period_equals_two_small_steps():
    """grad accumulation over 2 half-batches == reference scaling."""
    t1 = make_trainer()
    t2 = make_trainer(extra="update_period = 2\n")
    # same params start
    b = synth_batches(2)
    t2.update(b[0])
    t2.update(b[1])
    assert t2.epoch == 1


def test_short_batch_padding_and_metrics():
    t = make_trainer()
    x = np.ones((10, 1, 1, 8), dtype=np.float32)
    y = np.zeros((10, 1), dtype=np.float32)
    short = DataBatch(data=x, label=y, num_batch_padd=0)
    # batch smaller than batch_size: padded internally
    t.update(short)
    out = t.evaluate(ListIter([short]), "t")
    assert np.isfinite(float(out.split(":")[-1]))


def test_num_batch_padd_trimming():
    t = make_trainer()
    x = np.random.RandomState(0).randn(16, 1, 1, 8).astype(np.float32)
    y = np.zeros((16, 1), dtype=np.float32)
    batch = DataBatch(data=x, label=y, num_batch_padd=6)
    p = t.predict(batch)
    assert p.shape == (10,)  # padding rows trimmed


def test_predict_and_extract():
    t = make_trainer()
    b = synth_batches(1)[0]
    pred = t.predict(b)
    assert pred.shape == (16,)
    assert set(np.unique(pred)) <= {0.0, 1.0}
    dist = t.predict_dist(b)
    assert dist.shape == (16, 2)
    np.testing.assert_allclose(dist.sum(axis=1), 1.0, rtol=1e-5)
    feat = t.extract_feature(b, "ac1")
    assert feat.shape == (16, 1, 1, 32)
    feat2 = t.extract_feature(b, "top[-1]")
    assert feat2.shape == (16, 1, 1, 2)
    feat3 = t.extract_feature(b, "top[-2]")
    assert feat3.shape == (16, 1, 1, 32)


def test_checkpoint_roundtrip():
    t = make_trainer()
    for b in synth_batches(3):
        t.update(b)
    buf = io.BytesIO()
    t.save_model(buf)

    t2 = make_trainer()
    buf.seek(0)
    t2.load_model(buf)
    assert t2.epoch == t.epoch
    np.testing.assert_allclose(
        np.asarray(t2.state["params"]["fc1"]["wmat"]),
        np.asarray(t.state["params"]["fc1"]["wmat"]))
    # both predict identically
    b = synth_batches(1, seed=7)[0]
    np.testing.assert_allclose(t.predict_dist(b), t2.predict_dist(b),
                               rtol=1e-5)


def test_checkpoint_with_optimizer_state():
    t = make_trainer(extra="save_optimizer = 1\n")
    for b in synth_batches(3):
        t.update(b)
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    t2 = make_trainer(extra="save_optimizer = 1\n")
    t2.load_model(buf)
    np.testing.assert_allclose(
        np.asarray(t2.state["ustate"]["fc1"]["wmat"]["m"]),
        np.asarray(t.state["ustate"]["fc1"]["wmat"]["m"]))


def test_finetune_copy_model_from():
    t = make_trainer()
    for b in synth_batches(3):
        t.update(b)
    buf = io.BytesIO()
    t.save_model(buf)

    # new net with same fc1 but different fc2 width: fc1 copied, fc2 not
    cfg2 = MLP_CFG.replace("nhidden = 2", "nhidden = 4")
    t2 = make_trainer(cfg=cfg2)
    buf.seek(0)
    t2.copy_model_from(buf)
    np.testing.assert_allclose(
        np.asarray(t2.state["params"]["fc1"]["wmat"]),
        np.asarray(t.state["params"]["fc1"]["wmat"]))
    assert np.asarray(t2.state["params"]["fc2"]["wmat"]).shape == (4, 32)


def test_get_set_weight():
    t = make_trainer()
    w, shape = t.get_weight("fc1", "wmat")
    assert w.shape == (32, 8) and shape == (32, 8)
    new = np.zeros_like(w)
    t.set_weight(new, "fc1", "wmat")
    w2, _ = t.get_weight("fc1", "wmat")
    np.testing.assert_allclose(w2, 0.0)
    b = synth_batches(1)[0]
    dist = t.predict_dist(b)
    assert np.isfinite(dist).all()


def test_data_parallel_multi_device_matches_single():
    """dp over 8 virtual devices == single device (same jit program)."""
    assert len(jax.devices()) == 8
    t1 = make_trainer()  # single default device
    t8 = make_trainer(extra="dev = tpu:0-7\n")
    assert t8.mesh.devices.size == 8
    batches = synth_batches(5)
    for b in batches:
        t1.update(b)
        t8.update(b)
    np.testing.assert_allclose(
        np.asarray(t1.state["params"]["fc1"]["wmat"]),
        np.asarray(t8.state["params"]["fc1"]["wmat"]), rtol=2e-4, atol=1e-5)


def test_bfloat16_host_cast_input_path():
    """dtype=bfloat16 stages bf16 inputs from the host (half the H2D
    bytes); training, eval and predict all run through it."""
    import ml_dtypes
    t = make_trainer(extra="dtype = bfloat16\n")
    assert t._host_input(np.ones((2, 1), np.float32)).dtype \
        == ml_dtypes.bfloat16
    b = synth_batches(1)[0]
    t.update(b)
    out = t.evaluate(ListIter([b]), "e")
    assert np.isfinite(float(out.split(":")[-1]))
    assert t.predict(b).shape == (16,)


def test_stage_dtype_f32_matches_host_cast():
    """stage_dtype=float32 stages f32 and lets the jitted step cast to
    bf16 on device (fused) - the identical round-to-nearest-even, so
    the training trajectory matches the host-cast path exactly."""
    import ml_dtypes
    t1 = make_trainer(extra="dtype = bfloat16\n")
    t2 = make_trainer(extra="dtype = bfloat16\nstage_dtype = float32\n")
    assert t2._host_input(np.ones((2, 1), np.float32)).dtype == np.float32
    assert t1._host_input(np.ones((2, 1), np.float32)).dtype \
        == ml_dtypes.bfloat16
    for b in synth_batches(4):
        t1.update(b)
        t2.update(b)
    np.testing.assert_allclose(
        np.asarray(t1.state["params"]["fc1"]["wmat"]),
        np.asarray(t2.state["params"]["fc1"]["wmat"]),
        rtol=1e-6, atol=1e-7)
    with pytest.raises(ValueError, match="stage_dtype"):
        make_trainer(extra="stage_dtype = int8\n")
    # bf16 staging under f32 compute can never take effect: reject the
    # silent no-op instead of hiding a misconfiguration
    with pytest.raises(ValueError, match="requires dtype=bfloat16"):
        make_trainer(extra="stage_dtype = bfloat16\n")


def test_remat_matches_plain():
    """remat=1 (jax.checkpoint over the forward) changes memory, not
    math: training trajectories are identical."""
    t1 = make_trainer()
    t2 = make_trainer(extra="remat = 1\n")
    for b in synth_batches(4):
        t1.update(b)
        t2.update(b)
    np.testing.assert_allclose(
        np.asarray(t1.state["params"]["fc1"]["wmat"]),
        np.asarray(t2.state["params"]["fc1"]["wmat"]),
        rtol=1e-5, atol=1e-6)


def test_shard_optimizer_zero1_matches_replicated():
    """ZeRO-1 optimizer-state sharding (update_on_server analog,
    nnet_ps_server.cpp:20-170): same math, state sharded over 'data'."""
    t_rep = make_trainer(extra="dev = tpu:0-7\n")
    t_z1 = make_trainer(extra="dev = tpu:0-7\nshard_optimizer = 1\n")
    st = t_z1.state["ustate"]["fc1"]["wmat"]["m"]
    assert not st.sharding.is_fully_replicated, st.sharding
    assert "data" in t_z1._ustate_shard["fc1"]["wmat"].spec
    for b in synth_batches(5):
        t_rep.update(b)
        t_z1.update(b)
    np.testing.assert_allclose(
        np.asarray(t_rep.state["params"]["fc1"]["wmat"]),
        np.asarray(t_z1.state["params"]["fc1"]["wmat"]),
        rtol=2e-4, atol=1e-5)
    # momentum state agrees too (after gathering the shards)
    np.testing.assert_allclose(
        np.asarray(t_rep.state["ustate"]["fc1"]["wmat"]["m"]),
        np.asarray(t_z1.state["ustate"]["fc1"]["wmat"]["m"]),
        rtol=2e-4, atol=1e-5)


def test_shard_optimizer_checkpoint_roundtrip():
    t = make_trainer(
        extra="dev = tpu:0-7\nshard_optimizer = 1\nsave_optimizer = 1\n")
    for b in synth_batches(3):
        t.update(b)
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    t2 = make_trainer(extra="save_optimizer = 1\n")
    t2.load_model(buf)
    np.testing.assert_allclose(
        np.asarray(t2.state["ustate"]["fc1"]["wmat"]["m"]),
        np.asarray(t.state["ustate"]["fc1"]["wmat"]["m"]), rtol=1e-6)


def test_device_pruning_for_odd_batch():
    # batch 16 with 5 devices requested -> pruned to 4
    t = make_trainer(extra="dev = tpu:0-4\n")
    assert t.mesh.devices.size == 4


def test_on_device_eval_metric_matches_host():
    """evaluate()'s device-accumulated metrics == the host MetricSet
    path on the same batches (incl. a short batch + num_batch_padd)."""
    from cxxnet_tpu.utils.metric import MetricSet
    t = make_trainer()
    for b in synth_batches(3):
        t.update(b)
    batches = synth_batches(3, seed=5)
    short = DataBatch(data=batches[0].data[:10],
                      label=batches[0].label[:10], num_batch_padd=2)
    evset = [batches[1], short]
    out = t.evaluate(ListIter(evset), "ev")
    dev_err = float(out.split(":")[-1])
    host = MetricSet()
    host.add_metric("error", "label")
    for b in evset:
        nvalid = b.batch_size - b.num_batch_padd
        host.add_eval([t.predict_dist(b)[:nvalid]],
                      {"label": b.label[:nvalid]})
    assert abs(dev_err - host._metrics[0].get()) < 1e-6, out
    assert out.startswith("\tev-error:")


def test_on_device_train_metric_matches_host():
    """The jitted (sum,count) accumulation == the host MetricSet on the
    same forward outputs (update_period=2 so the first update leaves the
    params untouched and predict_dist reproduces the training forward)."""
    from cxxnet_tpu.utils.metric import MetricSet
    t = make_trainer(extra="update_period = 2\n")
    b = synth_batches(1)[0]
    t.update(b)
    out = t.eval_train_metric()
    dev_err = float(out.split(":")[-1])
    host = MetricSet()
    host.add_metric("error", "label")
    host.add_eval([t.predict_dist(b)], {"label": b.label})
    assert abs(dev_err - host._metrics[0].get()) < 1e-6
    assert out.startswith("\ttrain-error:")
    # accumulator was reset by the readback
    assert float(np.asarray(t.state["tmetric"]).sum()) == 0.0


def test_train_metric_ignores_padded_rows():
    t = make_trainer(extra="update_period = 4\n")
    x = np.random.RandomState(3).randn(10, 1, 1, 8).astype(np.float32)
    y = np.ones((10, 1), np.float32)
    t.update(DataBatch(data=x, label=y))  # padded 10 -> 16
    vals = np.asarray(t.state["tmetric"])
    assert vals.shape == (1, 3)  # (sum, kahan comp, count)
    assert vals[0, 2] == 10.0  # count == valid rows only


def test_multi_target_metrics():
    cfg = """
label_vec[0,1) = label
label_vec[1,3) = extra
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
layer[+1:act] = relu
layer[act->out1] = fullc:o1
  nhidden = 2
layer[+0] = softmax
layer[act->out2] = fullc:o2
  nhidden = 2
layer[+0] = l2_loss
  target = extra
netconfig=end
input_shape = 1,1,4
batch_size = 8
eta = 0.01
metric[label,out1] = error
metric[extra,out2] = rmse
"""
    t = make_trainer(cfg=cfg)
    x = np.random.RandomState(0).randn(8, 1, 1, 4).astype(np.float32)
    label = np.zeros((8, 3), dtype=np.float32)
    t.update(DataBatch(data=x, label=label))
    out = t.evaluate(ListIter([DataBatch(data=x, label=label)]), "e")
    assert "e-error:" in out and "e-rmse[extra]:" in out


def test_compile_cache_flag(tmp_path):
    """compile_cache=<dir> populates XLA's persistent compilation
    cache; the flag exists so TPU re-runs skip the first-compile cost
    (docs/global.md). The setting is process-global jax config, so the
    test restores it to keep later tests cache-free."""
    import jax
    saved = {k: getattr(jax.config, k) for k in (
        "jax_compilation_cache_dir",
        "jax_persistent_cache_min_compile_time_secs",
        "jax_persistent_cache_min_entry_size_bytes")}
    cache = tmp_path / "xlacache"
    try:
        t = make_trainer(extra=f"\ncompile_cache = {cache}\n")
        for b in synth_batches(2):
            t.update(b)
        jax.block_until_ready(t.state)
        assert cache.is_dir() and len(list(cache.iterdir())) > 0
    finally:
        for k, v in saved.items():
            jax.config.update(k, v)


def test_active_step_binding_end_to_end():
    """The trainer binds the traced update counter into every training
    forward (epoch*update_period + count), verified observably: a probe
    layer emits x*(step+1), and with rmse train metrics + zero labels
    the per-update rmse sequence must be 1, 2, 3, ... across an
    update_period boundary."""
    import jax.numpy as jnp
    from cxxnet_tpu.layers.base import (Layer, get_active_step,
                                        register_layer)

    class StepProbeLayer(Layer):
        type_name = "_step_probe"

        def infer_shapes(self, in_shapes):
            return [in_shapes[0]]

        def apply(self, params, inputs, *, train, rng=None):
            step = get_active_step()
            f = (step.astype(jnp.float32) + 1.0
                 if step is not None else jnp.float32(1000.0))
            return [inputs[0] * f]

    register_layer(StepProbeLayer)
    cfg = """
netconfig=start
layer[0->1] = _step_probe
layer[1->1] = l2_loss
netconfig=end
input_shape = 1,1,1
eta = 0.0
update_period = 2
batch_size = 4
silent = 1
metric = rmse
"""
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.init_model()
    ones = np.ones((4, 1, 1, 1), np.float32)
    zeros = np.zeros((4, 1), np.float32)
    seen = []
    for _ in range(3):
        t.update(DataBatch(data=ones, label=zeros))
        out = t.eval_train_metric()
        seen.append(float(out.split("rmse:")[1]))
    # probe output = step+1; the rmse metric keeps the reference's
    # no-sqrt quirk (squared error), so per-update values are
    # (step+1)^2 = 1, 4, 9 for steps 0, 1, 2 - spanning the
    # update_period=2 epoch boundary
    np.testing.assert_allclose(seen, [1.0, 4.0, 9.0], rtol=1e-5)


def test_extra_data_nodes_feed_through():
    """extra_data_num nets train and predict end to end: the trainer
    feeds DataBatch.extra_data into input nodes in_1.. (the attachtxt
    pipeline's consumer side - data.h:96-139)."""
    cfg = """
extra_data_num = 1
extra_data_shape[0] = 1,1,4
netconfig=start
layer[in,in_1->2] = concat
layer[2->3] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[3->4] = relu
layer[4->5] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[5->5] = softmax
netconfig=end
input_shape = 1,1,4
batch_size = 8
eta = 0.2
momentum = 0.9
metric = error
silent = 1
"""
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.init_model()
    rng = np.random.RandomState(6)
    # the label depends ONLY on the extra-data input: training can only
    # succeed if in_1 is actually fed
    for _ in range(30):
        x = rng.randn(8, 1, 1, 4).astype(np.float32)
        e = rng.randn(8, 1, 1, 4).astype(np.float32)
        y = (e.reshape(8, 4).sum(1) > 0).astype(np.float32)
        t.update(DataBatch(data=x, label=y.reshape(8, 1),
                           extra_data=[e]))
    x = rng.randn(8, 1, 1, 4).astype(np.float32)
    e = rng.randn(8, 1, 1, 4).astype(np.float32)
    y = (e.reshape(8, 4).sum(1) > 0).astype(np.float32)
    pred = t.predict(DataBatch(data=x, label=y.reshape(8, 1),
                               extra_data=[e]))
    assert (pred == y).mean() >= 0.75, (pred, y)
    # missing extras must fail loudly, not silently feed garbage
    with pytest.raises(ValueError, match="extra_data_num"):
        t.update(DataBatch(data=x, label=y.reshape(8, 1)))


def test_round_batch_wrap_rows_are_trained():
    """round_batch wrap-fill rows are REAL instances consumed early
    from the next epoch; training must include them (the reference
    trims num_batch_padd only at eval - nnet_impl-inl.hpp:239)."""
    t = make_trainer()
    x = np.random.RandomState(1).randn(16, 1, 1, 8).astype(np.float32)
    y = np.zeros((16, 1), np.float32)
    wrapped = DataBatch(data=x, label=y, num_batch_padd=6)
    p0 = np.asarray(t.state["params"]["fc1"]["wmat"]).copy()
    t.update(wrapped)
    # train metric counted ALL 16 rows (not 10)
    vals = np.asarray(t.state["tmetric"])
    assert vals[0, 2] == 16.0, vals
    # but eval still trims the wrap rows
    out = t.evaluate(ListIter([wrapped]), "e")
    assert np.isfinite(float(out.split(":")[-1]))
    assert np.abs(np.asarray(t.state["params"]["fc1"]["wmat"])
                  - p0).max() > 0


def test_checkpoint_slash_in_layer_name_and_corruption():
    """'/' in a layer name round-trips (separator recorded in the
    header) and corrupt/truncated files fail with clear ValueErrors."""
    cfg2 = MLP_CFG.replace("fullc:fc1", "fullc:stage1/fc")
    cfg2 = cfg2.replace("layer[+1:fc1]", "layer[+1:s1]")
    t = make_trainer(cfg=cfg2)
    for b in synth_batches(2):
        t.update(b)
    buf = io.BytesIO()
    t.save_model(buf)
    t2 = make_trainer(cfg=cfg2)
    buf.seek(0)
    t2.load_model(buf)
    np.testing.assert_allclose(
        np.asarray(t2.state["params"]["stage1/fc"]["wmat"]),
        np.asarray(t.state["params"]["stage1/fc"]["wmat"]))
    # corruption diagnostics
    from cxxnet_tpu.nnet import checkpoint as ckpt
    raw = bytearray(buf.getvalue())
    with pytest.raises(ValueError, match="truncated"):
        ckpt.load_model(io.BytesIO(bytes(raw[:len(raw) // 2])))
    bad = bytearray(raw)
    bad[8:16] = (1 << 60).to_bytes(8, "little")
    with pytest.raises(ValueError, match="header length"):
        ckpt.load_model(io.BytesIO(bytes(bad)))


def test_fast_bf16_cast_bitwise_matches_ml_dtypes():
    """The torch fast path of the host bf16 staging cast must be
    bitwise round-to-nearest-even identical to ml_dtypes (it sits on
    the e2e critical path; a semantic drift would silently change
    every staged batch)."""
    import ml_dtypes
    from cxxnet_tpu.nnet.trainer import _bf16_cast
    rng = np.random.RandomState(0)
    x = np.concatenate([
        rng.randn(1000).astype(np.float32) * 1e3,
        np.array([0.0, -0.0, 1e-40, np.inf, -np.inf], np.float32),
    ])
    a = _bf16_cast(x).view(np.uint16)
    b = x.astype(ml_dtypes.bfloat16).view(np.uint16)
    np.testing.assert_array_equal(a, b)


def test_staged_batch_trajectory_identical():
    """update(stage_batch(b)) must be bit-identical to update(b): the
    staging runs the exact per-step pipeline once, so a device-resident
    dataset (the membuffer analog, StagedBatch) changes throughput,
    never the training trajectory."""
    batches = synth_batches(6)
    t1 = make_trainer()
    t2 = make_trainer()
    for b in batches:
        t1.update(b)
    staged = [t2.stage_batch(b) for b in batches]
    for s in staged:
        t2.update(s)
    p1 = jax.tree_util.tree_leaves(t1.state["params"])
    p2 = jax.tree_util.tree_leaves(t2.state["params"])
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_staged_batch_counts_padded_rows_once():
    """A short batch staged with wrap rows keeps the same distinct-
    instance accounting (n_examples) the streamed path reports."""
    t = make_trainer()
    b = synth_batches(1, batch_size=16)[0]
    short = DataBatch(data=b.data[:12], label=b.label[:12],
                      num_batch_padd=2)
    s = t.stage_batch(short)
    assert s.n_examples == 10
    t.update(s)  # padded staged batch trains without error
