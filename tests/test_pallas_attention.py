"""Flash-attention Pallas kernel vs the XLA ground truth.

Interpret mode on CPU (same convention as test_pallas_lrn.py): the
kernel math - online-softmax tiling, causal tile skipping, lse/delta
backward recompute - is validated off-chip; on-TPU execution uses the
identical program with interpret=False.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.ops import attention as A
from cxxnet_tpu.ops import pallas_attention as PA


def _qkv(b=2, h=3, s=32, d=16, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, h, s, d).astype(dtype)  # noqa: E731
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


@pytest.fixture
def small_blocks(monkeypatch):
    """Force multi-tile grids at test sizes."""
    monkeypatch.setattr(PA, "BLOCK_Q", 8)
    monkeypatch.setattr(PA, "BLOCK_K", 8)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(causal, small_blocks):
    q, k, v = _qkv()
    ref = A.naive_attention(q, k, v, causal=causal)
    out = PA.flash_attention(q, k, v, causal, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_single_tile_and_uneven_blocks(small_blocks):
    # s not divisible by 8 -> _blocks falls back to a divisor
    q, k, v = _qkv(s=12)
    ref = A.naive_attention(q, k, v, causal=True)
    out = PA.flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_naive(causal, small_blocks):
    q, k, v = _qkv(b=1, h=2, s=16, d=8)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.cos(A.naive_attention(q, k, v, causal=causal)))

    def loss_pal(q, k, v):
        return jnp.sum(jnp.cos(
            PA.flash_attention(q, k, v, causal, None, True)))

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-5,
            err_msg=f"d{name} mismatch")


def test_bf16_forward(small_blocks):
    q, k, v = _qkv(s=16)
    ref = A.naive_attention(q, k, v, causal=True)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = PA.flash_attention(qb, kb, vb, True, None, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_custom_scale(small_blocks):
    q, k, v = _qkv(s=16)
    ref = A.naive_attention(q, k, v, scale=0.5)
    out = PA.flash_attention(q, k, v, False, 0.5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_routing_gate():
    from jax.sharding import Mesh
    q, _, _ = _qkv(b=8, s=32, d=16)
    assert not PA.use_flash(q)          # cpu backend, no hook
    assert not PA.use_flash_sharded(q, None)
    PA._FORCE_INTERPRET = True
    try:
        # single-device route stays off on the 8-device test platform
        # (pallas_call has no GSPMD rule); the shard_map route engages
        assert not PA.use_flash(q)
        mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("data",))
        assert PA.use_flash_sharded(q, mesh)
        # untileable sublane (seq 12 -> best divisor 12 or 4, not 8-mult)
        q2, _, _ = _qkv(s=12)
        assert not PA._tile_ok(q2, 12)
        # prime seq would degrade to 1-wide tiles: gated out
        q3, _, _ = _qkv(s=31)
        assert not PA._tile_ok(q3, 31)
    finally:
        PA._FORCE_INTERPRET = False


def test_sharded_matches_naive():
    from jax.sharding import Mesh
    q, k, v = _qkv(b=8, h=2, s=16, d=8)
    ref = A.naive_attention(q, k, v, causal=True)
    mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2),
                ("data", "model"))
    PA._FORCE_INTERPRET = True
    try:
        out = PA.flash_attention_sharded(q, k, v, mesh, causal=True)
    finally:
        PA._FORCE_INTERPRET = False
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
