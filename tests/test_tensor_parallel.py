"""Tensor parallelism over the 'model' mesh axis (parallel/sharding.py).

The reference has no TP (SURVEY.md par.2.7); this is the TPU-native
extension. The invariant under test: a (data x model) mesh trains to
numerically-identical weights as a pure-data mesh - sharding changes the
schedule, never the math.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

CONV_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = batch_norm:bn1
layer[4->5] = prelu:pr1
layer[5->6] = flatten
layer[6->7] = fullc:fc1
  nhidden = 32
layer[7->8] = relu
layer[8->9] = fullc:fc2
  nhidden = 4
layer[9->9] = softmax
netconfig=end
input_shape = 3,8,8
random_type = xavier
eta = 0.1
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
"""


def _make(mesh: str) -> NetTrainer:
    t = NetTrainer()
    for k, v in parse_config_string(CONV_NET):
        t.set_param(k, v)
    t.set_param("mesh", mesh)
    t.init_model()
    return t


def _batches(n=4, b=8):
    rng = np.random.RandomState(7)
    return [DataBatch(
        data=rng.randn(b, 3, 8, 8).astype(np.float32),
        label=rng.randint(0, 4, size=(b, 1)).astype(np.float32))
        for _ in range(n)]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_tp_matches_dp():
    # SAME data-axis size on both sides: batch_norm intentionally uses
    # per-shard statistics (the reference's per-GPU behavior), so the
    # data-axis size is part of the math; the invariant under test is
    # that the MODEL axis never changes it.
    dp = _make("data:4")
    tp = _make("data:4,model:2")
    # same seed -> identical init
    for batch in _batches():
        dp.update(batch)
        tp.update(batch)
    pd = jax.tree.map(np.asarray, dp.state["params"])
    pt = jax.tree.map(np.asarray, tp.state["params"])
    flat_d = jax.tree.leaves(pd)
    flat_t = jax.tree.leaves(pt)
    for a, b in zip(flat_d, flat_t):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_tp_param_shardings():
    tp = _make("data:4,model:2")
    ps = tp._pshard
    # divisible dims ride 'model'
    assert ps["fc1"]["wmat"].spec[0] == "model"
    assert ps["fc1"]["bias"].spec[0] == "model"
    assert ps["cv1"]["wmat"].spec[0] == "model"
    assert ps["bn1"]["slope"].spec[0] == "model"
    assert ps["pr1"]["slope"].spec[0] == "model"
    # real device placement: fc1 wmat lives as (16, n) shards
    shard_shapes = {s.data.shape
                    for s in tp.state["params"]["fc1"]["wmat"].addressable_shards}
    assert shard_shapes == {(16, 128)}


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_tp_indivisible_falls_back_to_replication():
    tp = _make("data:2,model:4")
    # fc2 nhidden=4 divides 4; cv1 nchannel=8 divides 4; fc1 nhidden=32 too
    assert tp._pshard["fc2"]["wmat"].spec[0] == "model"
    tp3 = NetTrainer()
    for k, v in parse_config_string(CONV_NET.replace(
            "nhidden = 4", "nhidden = 5")):
        tp3.set_param(k, v)
    tp3.set_param("mesh", "data:2,model:4")
    tp3.init_model()
    # 5 % 4 != 0 -> replicated
    assert tp3._pshard["fc2"]["wmat"].spec == P()
    # training still runs with the mixture
    tp3.update(_batches(1)[0])


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_tp_checkpoint_roundtrip(tmp_path):
    import io
    tp = _make("data:4,model:2")
    tp.update(_batches(1)[0])
    buf = io.BytesIO()
    tp.save_model(buf)
    buf.seek(0)
    dp = NetTrainer()
    for k, v in parse_config_string(CONV_NET):
        dp.set_param(k, v)
    dp.set_param("mesh", "data:8")
    dp.load_model(buf)
    a = jax.tree.map(np.asarray, tp.state["params"])
    b = jax.tree.map(np.asarray, dp.state["params"])
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(x, y, rtol=1e-6)


def test_batch_norm_channels_sharded_to_one():
    """Regression: a conv-node batch_norm whose channel count EQUALS
    the model-axis size (local C=1 inside shard_map) must still
    normalize per channel over (b, h, w) - the node kind comes from the
    global shape at infer_shapes, never the sharded local shape."""
    from cxxnet_tpu.layers import create_layer
    from cxxnet_tpu.parallel.mesh import active_mesh
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("data", "model"))
    bn = create_layer("batch_norm")
    shape = (8, 4, 3, 3)             # C=4 == model-axis size
    bn.infer_shapes([shape])
    params = bn.init_params(jax.random.PRNGKey(0), [shape])
    x = np.random.RandomState(0).randn(*shape).astype(np.float32)

    # reference: per-data-shard stats, full channels per shard
    ref_halves = []
    for half in (x[:4], x[4:]):
        m = half.mean(axis=(0, 2, 3), keepdims=True)
        v = ((half - m) ** 2).mean(axis=(0, 2, 3), keepdims=True)
        ref_halves.append((half - m) / np.sqrt(v + bn.eps))
    ref = np.concatenate(ref_halves)

    with active_mesh(mesh):
        (out,) = jax.jit(
            lambda p, xx: bn.apply(p, [xx], train=True))(params, x)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                               atol=1e-5)
