"""Layer numerics and shape-inference tests vs torch/numpy references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

from cxxnet_tpu.layers import create_layer, known_layer_types


def make(type_name, cfg=(), name=""):
    layer = create_layer(type_name, name)
    for k, v in cfg:
        layer.set_param(k, v)
    return layer


def run(layer, xs, train=False, seed=0, params=None):
    shapes = [x.shape for x in xs]
    layer.infer_shapes(list(shapes))
    if params is None:
        params = layer.init_params(jax.random.PRNGKey(seed), list(shapes))
    outs = layer.apply(params, [jnp.asarray(x) for x in xs], train=train,
                       rng=jax.random.PRNGKey(seed + 1))
    return [np.asarray(o) for o in outs], params


def test_registry_covers_reference_types():
    expected = {
        "fullc", "fixconn", "bias", "softmax", "relu", "sigmoid", "tanh",
        "softplus", "flatten", "dropout", "conv", "relu_max_pooling",
        "max_pooling", "sum_pooling", "avg_pooling", "lrn", "concat",
        "xelu", "split", "insanity", "insanity_max_pooling", "l2_loss",
        "multi_logistic", "ch_concat", "prelu", "batch_norm",
    }
    assert expected <= set(known_layer_types())


# ---------------------------------------------------------------------------
# fullc
# ---------------------------------------------------------------------------

def test_fullc_matches_manual():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 1, 1, 7).astype(np.float32)
    layer = make("fullc", [("nhidden", "5"), ("init_bias", "0.5")])
    (out,), params = run(layer, [x])
    expect = x.reshape(4, 7) @ np.asarray(params["wmat"]).T + 0.5
    np.testing.assert_allclose(out.reshape(4, 5), expect, rtol=1e-5)
    assert np.asarray(params["wmat"]).shape == (5, 7)


def test_fullc_no_bias():
    x = np.ones((2, 1, 1, 3), dtype=np.float32)
    layer = make("fullc", [("nhidden", "4"), ("no_bias", "1")])
    (_, ), params = run(layer, [x])
    assert "bias" not in params


def test_fullc_rejects_non_matrix():
    layer = make("fullc", [("nhidden", "4")])
    with pytest.raises(ValueError):
        layer.infer_shapes([(2, 3, 4, 4)])


# ---------------------------------------------------------------------------
# conv
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("h,w,k,s,p", [
    (28, 28, 3, 2, 1), (27, 27, 5, 1, 2), (11, 13, 3, 3, 0), (227, 227, 11, 4, 0),
])
def test_conv_output_shape_formula(h, w, k, s, p):
    layer = make("conv", [("kernel_size", str(k)), ("stride", str(s)),
                          ("pad", str(p)), ("nchannel", "4")])
    (out_shape,) = layer.infer_shapes([(2, 3, h, w)])
    assert out_shape == (2, 4, (h + 2 * p - k) // s + 1,
                         (w + 2 * p - k) // s + 1)


def test_conv_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    layer = make("conv", [("kernel_size", "3"), ("stride", "2"),
                          ("pad", "1"), ("nchannel", "6")])
    (out,), params = run(layer, [x])
    w = np.asarray(params["wmat"])
    b = np.asarray(params["bias"])
    expect = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                      torch.from_numpy(b), stride=2, padding=1).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_conv_space_to_depth_matches_direct():
    """The s2d rewrite must equal the direct strided conv - values AND
    both gradients - across kernel/stride/pad geometries including the
    AlexNet conv1 shape (227, 11x11/s4, no pad) and truncated tails."""
    from cxxnet_tpu.ops.conv import conv2d
    rng = np.random.RandomState(3)
    for h, w_, k, s, p in ((227, 227, 11, 4, 0), (16, 16, 3, 2, 1),
                           (15, 13, 5, 3, 2), (9, 9, 2, 4, 0),
                           (12, 10, 4, 2, 0)):
        x = rng.randn(2, 3, h, w_).astype(np.float32)
        w = rng.randn(8, 3, k, k).astype(np.float32)

        def loss(x, w, s2d):
            out = conv2d(jnp.asarray(x), jnp.asarray(w), s, p, p,
                         s2d=s2d)
            return out, jnp.sum(out * out)

        out_d, _ = loss(x, w, False)
        out_s, _ = loss(x, w, True)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"h={h} k={k} s={s} p={p}")
        gd = jax.grad(lambda a, b: loss(a, b, False)[1], (0, 1))(
            jnp.asarray(x), jnp.asarray(w))
        gs = jax.grad(lambda a, b: loss(a, b, True)[1], (0, 1))(
            jnp.asarray(x), jnp.asarray(w))
        for a, b, nm in ((gs[0], gd[0], "dx"), (gs[1], gd[1], "dw")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
                err_msg=f"{nm} h={h} k={k} s={s} p={p}")


def test_conv_space_to_depth_auto_gating():
    """auto engages only for ungrouped, strided, few-channel convs;
    the rewritten conv is stride-1 over in_ch*s*s channels."""
    from cxxnet_tpu.ops.conv import conv2d
    x = jnp.zeros((1, 3, 227, 227), jnp.bfloat16)
    w = jnp.zeros((96, 3, 11, 11), jnp.bfloat16)
    jaxpr = str(jax.make_jaxpr(
        lambda x, w: conv2d(x, w, 4, 0, 0))(x, w))
    # rewritten: a stride-1 conv (over in_ch*s*s = 48 channels), no
    # strided conv left in the program
    assert "window_strides=(1, 1)" in jaxpr, jaxpr
    assert "window_strides=(4, 4)" not in jaxpr, jaxpr
    # many channels: auto stays off (a strided conv remains)
    x2 = jnp.zeros((1, 96, 27, 27), jnp.bfloat16)
    w2 = jnp.zeros((256, 96, 5, 5), jnp.bfloat16)
    jaxpr2 = str(jax.make_jaxpr(
        lambda x, w: conv2d(x, w, 2, 2, 2))(x2, w2))
    assert "window_strides=(2, 2)" in jaxpr2
    # grouped: never rewritten even when forced via layer auto
    x3 = jnp.zeros((1, 4, 16, 16), jnp.bfloat16)
    w3 = jnp.zeros((8, 2, 3, 3), jnp.bfloat16)
    jaxpr3 = str(jax.make_jaxpr(
        lambda x, w: conv2d(x, w, 2, 1, 1, num_group=2))(x3, w3))
    assert "window_strides=(2, 2)" in jaxpr3


def test_conv_space_to_depth_param_validation():
    layer = make("conv", [("kernel_size", "3"), ("nchannel", "4")])
    layer.set_param("space_to_depth", "1")
    assert layer.s2d is True
    layer.set_param("space_to_depth", "auto")
    assert layer.s2d is None
    import pytest
    with pytest.raises(ValueError, match="space_to_depth"):
        layer.set_param("space_to_depth", "yes")
    # a force that cannot apply raises instead of silently dropping
    from cxxnet_tpu.ops.conv import conv2d
    with pytest.raises(ValueError, match="space_to_depth=1"):
        conv2d(jnp.zeros((1, 4, 8, 8)), jnp.zeros((8, 2, 3, 3)),
               2, 1, 1, num_group=2, s2d=True)
    with pytest.raises(ValueError, match="space_to_depth=1"):
        conv2d(jnp.zeros((1, 3, 8, 8)), jnp.zeros((8, 3, 3, 3)),
               1, 1, 1, s2d=True)


def test_grouped_conv_matches_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    layer = make("conv", [("kernel_size", "3"), ("ngroup", "2"),
                          ("nchannel", "6"), ("no_bias", "1")])
    (out,), params = run(layer, [x])
    w = np.asarray(params["wmat"])
    assert w.shape == (6, 2, 3, 3)
    expect = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                      groups=2).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("h,k,s", [(28, 3, 2), (27, 3, 2), (13, 3, 2),
                                   (6, 2, 2), (7, 3, 3), (5, 5, 1)])
def test_pool_output_shape_formula(h, k, s):
    layer = make("max_pooling", [("kernel_size", str(k)), ("stride", str(s))])
    (out_shape,) = layer.infer_shapes([(1, 2, h, h)])
    expect = min(h - k + s - 1, h - 1) // s + 1
    assert out_shape == (1, 2, expect, expect)


def test_max_pooling_values():
    # 13 -> ceil-style output 7 with truncated last window (torch ceil_mode)
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 13, 13).astype(np.float32)
    layer = make("max_pooling", [("kernel_size", "3"), ("stride", "2")])
    (out,), _ = run(layer, [x])
    expect = F.max_pool2d(torch.from_numpy(x), 3, 2, ceil_mode=True).numpy()
    assert out.shape == expect.shape
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_avg_pooling_divides_by_full_window():
    x = np.ones((1, 1, 6, 6), dtype=np.float32)
    layer = make("avg_pooling", [("kernel_size", "3"), ("stride", "2")])
    (out,), _ = run(layer, [x])
    # out = min(6-3+1, 5)//2 + 1 = 3; last window [4,7) truncated to 2 elems
    # but still divides by 9 (reference scales by 1/(ky*kx))
    assert out.shape == (1, 1, 3, 3)
    np.testing.assert_allclose(out[0, 0, 0, 0], 1.0)
    np.testing.assert_allclose(out[0, 0, 2, 2], 4.0 / 9.0)


def test_relu_max_pooling_fuses_relu():
    x = -np.ones((1, 1, 4, 4), dtype=np.float32)
    layer = make("relu_max_pooling", [("kernel_size", "2"), ("stride", "2")])
    (out,), _ = run(layer, [x])
    np.testing.assert_allclose(out, 0.0)


def test_insanity_pooling_eval_is_max_pool():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    layer = make("insanity_max_pooling",
                 [("kernel_size", "2"), ("stride", "2"), ("keep", "0.5")])
    (out_eval,), _ = run(layer, [x], train=False)
    ref = make("max_pooling", [("kernel_size", "2"), ("stride", "2")])
    (out_ref,), _ = run(ref, [x])
    np.testing.assert_allclose(out_eval, out_ref)
    # train mode with keep=1.0 must equal plain max pooling too
    layer2 = make("insanity_max_pooling",
                  [("kernel_size", "2"), ("stride", "2"), ("keep", "1.0")])
    (out_train,), _ = run(layer2, [x], train=True)
    np.testing.assert_allclose(out_train, out_ref)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def test_activations_match_torch():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    t = torch.from_numpy(x)
    cases = {
        "relu": F.relu(t), "sigmoid": torch.sigmoid(t),
        "tanh": torch.tanh(t), "softplus": F.softplus(t),
    }
    for name, expect in cases.items():
        (out,), _ = run(make(name), [x])
        np.testing.assert_allclose(out, expect.numpy(), rtol=1e-5, atol=1e-6)


def test_xelu():
    x = np.array([[[[-10.0, 10.0]]]], dtype=np.float32)
    (out,), _ = run(make("xelu", [("b", "5")]), [x])
    np.testing.assert_allclose(out, [[[[-2.0, 10.0]]]])


def test_insanity_eval_uses_midpoint():
    x = np.array([[[[-6.0, 6.0]]]], dtype=np.float32)
    layer = make("insanity", [("lb", "2"), ("ub", "4")])
    (out,), _ = run(layer, [x], train=False)
    np.testing.assert_allclose(out, [[[[-2.0, 6.0]]]])


def test_insanity_train_bounds():
    rng = np.random.RandomState(6)
    x = -np.abs(rng.randn(1, 1, 50, 50)).astype(np.float32)
    layer = make("insanity", [("lb", "2"), ("ub", "4")])
    (out,), _ = run(layer, [x], train=True)
    ratio = out / x  # in [1/4, 1/2]
    assert np.all(ratio >= 1 / 4 - 1e-6) and np.all(ratio <= 1 / 2 + 1e-6)


def test_prelu_conv_and_fc_modes():
    x = np.array([[[[-2.0]], [[4.0]]]], dtype=np.float32)  # (1,2,1,1)
    layer = make("prelu", [("init_slope", "0.25")])
    (out,), params = run(layer, [x])
    np.testing.assert_allclose(out, [[[[-0.5]], [[4.0]]]])
    assert np.asarray(params["slope"]).shape == (2,)

    xf = np.array([[[[-2.0, 4.0]]]], dtype=np.float32)  # (1,1,1,2) matrix
    (outf,), paramsf = run(make("prelu"), [xf])
    np.testing.assert_allclose(outf, [[[[-0.5, 4.0]]]])
    assert np.asarray(paramsf["slope"]).shape == (2,)


# ---------------------------------------------------------------------------
# batch norm / lrn
# ---------------------------------------------------------------------------

def test_batch_norm_conv_matches_torch_batch_stats():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    layer = make("batch_norm", [("eps", "1e-5")])
    (out,), params = run(layer, [x])
    expect = F.batch_norm(
        torch.from_numpy(x), None, None,
        torch.ones(3), torch.zeros(3), training=True, eps=1e-5).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_batch_norm_eval_still_uses_batch_stats():
    """Reference quirk: no running stats; eval == train numerics."""
    rng = np.random.RandomState(8)
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    layer = make("batch_norm")
    (out_train,), p = run(layer, [x], train=True)
    (out_eval,), _ = run(layer, [x], train=False, params=p)
    np.testing.assert_allclose(out_train, out_eval, rtol=1e-6)


def test_batch_norm_per_shard_stats_on_data_mesh():
    """Under data parallelism BN uses each shard's OWN statistics (the
    reference's per-GPU behavior) with no cross-device collective;
    global_stats=1 opts into whole-batch sync-BN."""
    from cxxnet_tpu.parallel.mesh import MeshSpec, active_mesh, build_mesh
    rng = np.random.RandomState(9)
    x = rng.randn(8, 3, 4, 4).astype(np.float32)
    mesh = build_mesh(MeshSpec(device_indices=list(range(4))), 8)

    layer = make("batch_norm", [("eps", "1e-5")])
    with active_mesh(mesh):
        (out,), p = run(layer, [x])
    # shard i (2 rows) == BN of those rows alone
    for i in range(4):
        (solo,), _ = run(make("batch_norm", [("eps", "1e-5")]),
                         [x[2 * i:2 * i + 2]])
        np.testing.assert_allclose(out[2 * i:2 * i + 2], solo,
                                   rtol=1e-4, atol=1e-5)

    sync = make("batch_norm", [("eps", "1e-5"), ("global_stats", "1")])
    with active_mesh(mesh):
        (out_sync,), _ = run(sync, [x])
    (whole,), _ = run(make("batch_norm", [("eps", "1e-5")]), [x])
    np.testing.assert_allclose(out_sync, whole, rtol=1e-4, atol=1e-5)


def test_batch_norm_fc_normalizes_features():
    rng = np.random.RandomState(9)
    x = rng.randn(16, 1, 1, 6).astype(np.float32)
    (out,), params = run(make("batch_norm", [("eps", "1e-5")]), [x])
    assert np.asarray(params["slope"]).shape == (6,)
    m = out.reshape(16, 6)
    np.testing.assert_allclose(m.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(m.std(axis=0), 1.0, atol=1e-3)


def test_batch_norm_bf16_stats_run_in_f32():
    """Under bf16 compute, BN stats must accumulate in f32 (XLA does
    not guarantee a wider accumulator for a bf16 reduce; a per-channel
    mean over ~1M activations accumulated in bf16 drifts by whole
    units). Structural: the jaxpr converts the input to f32 before the
    reductions; behavioral: an offset-heavy bf16 input still comes out
    centered; contract: the output dtype stays bf16."""
    layer = make("batch_norm", [("eps", "1e-5")])
    layer.infer_shapes([(64, 8, 16, 16)])
    params = layer.init_params(jax.random.PRNGKey(0), [(64, 8, 16, 16)])
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
    rng = np.random.RandomState(11)
    x = jnp.asarray(16.0 + rng.randn(64, 8, 16, 16), jnp.bfloat16)

    def fwd(x):
        return layer.apply(params, [x], train=True)[0]

    jaxpr = str(jax.make_jaxpr(fwd)(x))
    assert "convert_element_type[new_dtype=float32" in jaxpr, jaxpr
    out = fwd(x)
    assert out.dtype == jnp.bfloat16
    m = np.asarray(out, np.float32)
    np.testing.assert_allclose(m.mean(axis=(0, 2, 3)), 0.0, atol=0.05)
    np.testing.assert_allclose(m.std(axis=(0, 2, 3)), 1.0, atol=0.05)


def test_lrn_matches_torch():
    rng = np.random.RandomState(10)
    x = rng.randn(2, 8, 4, 4).astype(np.float32)
    layer = make("lrn", [("local_size", "5"), ("alpha", "0.001"),
                         ("beta", "0.75"), ("knorm", "1")])
    (out,), _ = run(layer, [x])
    expect = F.local_response_norm(torch.from_numpy(x), 5, alpha=0.001,
                                   beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# dropout / bias / structural
# ---------------------------------------------------------------------------

def test_dropout_eval_identity_train_mask():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 1, 1, 1000).astype(np.float32) + 5.0
    layer = make("dropout", [("threshold", "0.5")])
    (out_eval,), _ = run(layer, [x], train=False)
    np.testing.assert_allclose(out_eval, x)
    (out_train,), _ = run(layer, [x], train=True)
    kept = out_train != 0
    assert 0.3 < kept.mean() < 0.7  # ~half kept
    np.testing.assert_allclose(out_train[kept], (x * 2.0)[kept], rtol=1e-6)


def test_bias_layer():
    x = np.zeros((2, 1, 1, 3), dtype=np.float32)
    layer = make("bias", [("init_bias", "1.5")])
    (out,), _ = run(layer, [x])
    np.testing.assert_allclose(out, 1.5)


def test_flatten_roundtrip():
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    (out,), _ = run(make("flatten"), [x])
    assert out.shape == (2, 1, 1, 60)
    np.testing.assert_allclose(out.reshape(2, 3, 4, 5), x)


def test_split_and_concat():
    rng = np.random.RandomState(13)
    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    split = make("split")
    split.num_out = 3
    outs, _ = run(split, [x])
    assert len(outs) == 3
    for o in outs:
        np.testing.assert_allclose(o, x)

    y = rng.randn(2, 5, 4, 4).astype(np.float32)
    (cat,), _ = run(make("ch_concat"), [x, y])
    assert cat.shape == (2, 8, 4, 4)
    np.testing.assert_allclose(cat[:, :3], x)
    np.testing.assert_allclose(cat[:, 3:], y)

    a = rng.randn(2, 1, 1, 4).astype(np.float32)
    b = rng.randn(2, 1, 1, 6).astype(np.float32)
    (cat2,), _ = run(make("concat"), [a, b])
    assert cat2.shape == (2, 1, 1, 10)


def test_fixconn(tmp_path):
    # sparse text format: nrow ncol nnz then (row col val) triples
    fname = tmp_path / "w.txt"
    fname.write_text("2 3 2\n0 1 2.0\n1 2 -1.0\n")
    layer = make("fixconn", [("nhidden", "2"),
                             ("fixconn_weight", str(fname))])
    x = np.array([[[[1.0, 2.0, 3.0]]]], dtype=np.float32)
    (out,), _ = run(layer, [x])
    np.testing.assert_allclose(out.reshape(2), [4.0, -3.0])


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def test_softmax_forward_and_grad():
    rng = np.random.RandomState(14)
    x = rng.randn(3, 1, 1, 5).astype(np.float32)
    layer = make("softmax")
    (out,), _ = run(layer, [x])
    expect = F.softmax(torch.from_numpy(x.reshape(3, 5)), dim=1).numpy()
    np.testing.assert_allclose(out.reshape(3, 5), expect, rtol=1e-5)

    # grad of per-example loss == softmax(x) - onehot (reference SetGradCPU)
    label = np.array([[1], [4], [0]], dtype=np.float32)
    g = jax.grad(lambda z: jnp.sum(layer.per_example_loss(
        z, jnp.asarray(label))))(jnp.asarray(x.reshape(3, 5)))
    onehot = np.eye(5)[label[:, 0].astype(int)]
    np.testing.assert_allclose(np.asarray(g), expect - onehot, rtol=1e-4,
                               atol=1e-6)


def test_l2_loss_grad():
    x = np.array([[1.0, 2.0]], dtype=np.float32)
    label = np.array([[0.5, 1.0]], dtype=np.float32)
    layer = make("l2_loss")
    g = jax.grad(lambda z: jnp.sum(layer.per_example_loss(
        z, jnp.asarray(label))))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), x - label, rtol=1e-6)


def test_multi_logistic_grad():
    rng = np.random.RandomState(15)
    x = rng.randn(2, 4).astype(np.float32)
    label = (rng.rand(2, 4) > 0.5).astype(np.float32)
    layer = make("multi_logistic")
    g = jax.grad(lambda z: jnp.sum(layer.per_example_loss(
        z, jnp.asarray(label))))(jnp.asarray(x))
    expect = 1 / (1 + np.exp(-x)) - label
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# weight init semantics
# ---------------------------------------------------------------------------

def test_gaussian_init_sigma():
    layer = make("fullc", [("nhidden", "400"), ("init_sigma", "0.05")])
    _, params = run(layer, [np.zeros((1, 1, 1, 300), np.float32)])
    w = np.asarray(params["wmat"])
    assert abs(w.std() - 0.05) < 0.005


def test_xavier_init_bound():
    layer = make("fullc", [("nhidden", "100"), ("random_type", "xavier")])
    _, params = run(layer, [np.zeros((1, 1, 1, 200), np.float32)])
    w = np.asarray(params["wmat"])
    bound = np.sqrt(3.0 / (200 + 100))
    assert np.all(np.abs(w) <= bound + 1e-6)
    assert w.std() > bound / 3


def test_kaiming_init_fullc_uses_nhidden():
    layer = make("fullc", [("nhidden", "800"), ("random_type", "kaiming")])
    _, params = run(layer, [np.zeros((1, 1, 1, 100), np.float32)])
    w = np.asarray(params["wmat"])
    assert abs(w.std() - np.sqrt(2.0 / 800)) < 0.01


def test_insanity_anneal_matches_reference_recurrence():
    """The closed-form per-forward anneal equals the reference's
    literal loop (insanity_layer-inl.hpp:52-63), including the freeze
    quirk for calm_start >= 0."""
    from cxxnet_tpu.layers.base import active_step

    def oracle(lb0, ub0, s0, e, t):
        lb, ub, step_ = lb0, ub0, 0
        mid = (ub0 + lb0) / 2.0
        delta = (ub0 - mid) / (e - s0) if e != s0 else 0.0
        for _ in range(t + 1):          # anneal runs BEFORE masking
            if s0 < step_ < e:
                ub -= delta * step_
                lb += delta * step_
                step_ += 1
        return lb, ub

    for s0, e in ((-1, 5), (-3, 5), (0, 5), (2, 5), (-1, 0)):
        layer = make("insanity", [("lb", "2"), ("ub", "10"),
                                  ("calm_start", str(s0)),
                                  ("calm_end", str(e))])
        for t in range(9):
            with active_step(jnp.asarray(t, jnp.int32)):
                lb, ub = layer._range()
            lb_ref, ub_ref = oracle(2.0, 10.0, s0, e, t)
            np.testing.assert_allclose(float(lb), lb_ref, rtol=1e-6,
                                       err_msg=f"s0={s0} e={e} t={t}")
            np.testing.assert_allclose(float(ub), ub_ref, rtol=1e-6,
                                       err_msg=f"s0={s0} e={e} t={t}")
    # no binding (direct layer use): static initial range
    layer = make("insanity", [("lb", "2"), ("ub", "10"),
                              ("calm_start", "-1"), ("calm_end", "5")])
    assert layer._range() == (2.0, 10.0)


def test_conv_f32_uses_highest_precision():
    """f32 convs must request HIGHEST precision (reference f32 GEMM
    parity on TPU - default would run bf16 MXU passes); bf16 inputs
    keep the fast default."""
    from cxxnet_tpu.ops.conv import conv2d
    x32 = jnp.zeros((1, 3, 8, 8), jnp.float32)
    w32 = jnp.zeros((4, 3, 3, 3), jnp.float32)
    jaxpr = str(jax.make_jaxpr(
        lambda x, w: conv2d(x, w, 1, 1, 1))(x32, w32))
    assert "HIGHEST" in jaxpr, jaxpr
    xb = x32.astype(jnp.bfloat16)
    wb = w32.astype(jnp.bfloat16)
    jaxpr_b = str(jax.make_jaxpr(
        lambda x, w: conv2d(x, w, 1, 1, 1))(xb, wb))
    assert "HIGHEST" not in jaxpr_b, jaxpr_b
