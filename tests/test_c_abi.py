"""Builds and runs the native C ABI driver (native/test_driver.c) against
libcxxnetwrapper.so - the analog of the reference's wrapper/ test-by-use
(its C ABI had no tests; this is the improvement SURVEY.md par.4 calls
for). The C process embeds its own CPython, so it runs as a subprocess
with the venv's site-packages + repo on PYTHONPATH."""

import gzip
import os
import struct
import subprocess
import sysconfig

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
LIBDIR = os.path.join(REPO, "cxxnet_tpu", "lib")
LIB = os.path.join(LIBDIR, "libcxxnetwrapper.so")


def _build(tmp_path, cc: str) -> str:
    subprocess.run(["make", "-C", NATIVE], check=True,
                   capture_output=True)
    exe = str(tmp_path / "test_driver")
    subprocess.run(
        [cc, "-O1", "-o", exe, os.path.join(NATIVE, "test_driver.c"),
         "-I", NATIVE, "-L", LIBDIR, "-lcxxnetwrapper", "-lm",
         f"-Wl,-rpath,{LIBDIR}"],
        check=True, capture_output=True)
    return exe


def _write_mnist(tmp_path, n=96):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, size=(n, 28, 28)).astype(np.uint8)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    img = tmp_path / "img.gz"
    lab = tmp_path / "lab.gz"
    with gzip.open(img, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(lab, "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())
    return img, lab


def test_c_abi_driver(tmp_path):
    import shutil
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    exe = _build(tmp_path, cc)
    img, lab = _write_mnist(tmp_path)
    iter_cfg = (
        "iter = mnist\n"
        f'path_img = "{img}"\n'
        f'path_label = "{lab}"\n'
        "input_flat = 0\n"
        "batch_size = 32\n"
        "iter = end\n")
    env = dict(os.environ)
    site = sysconfig.get_paths()["purelib"]
    # drop any accelerator-tunnel site dirs (their sitecustomize would
    # make the embedded interpreter dial the TPU); CPU only here
    inherited = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO, site] + inherited)
    env["JAX_PLATFORMS"] = "cpu"  # embedded python must not try the TPU
    out = subprocess.run(
        [exe, str(tmp_path / "model.bin"), iter_cfg],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "all checks passed" in out.stdout
    assert "train accuracy" in out.stdout
