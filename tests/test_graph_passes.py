"""Graph-level optimizing passes over the NetConfig DAG
(cxxnet_tpu/nnet/passes.py, docs/GRAPH_PASSES.md): pattern engine,
the four shipped passes, the pass-aware inference path, checkpoint
compatibility, and the tuning cache."""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet import passes, tuning
from cxxnet_tpu.nnet.passes import PassPipeline, find_fold_sites
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import ConfigError, parse_config_string

BN_MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:bn1] = batch_norm:bn1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
metric = error
silent = 1
seed = 7
"""

BN_CONV_CONF = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 8
  kernel_size = 4
  stride = 2
layer[+1:b1] = batch_norm:b1
layer[+1:r1] = relu
layer[+1:c2] = conv:c2
  nchannel = 8
  kernel_size = 3
  pad = 1
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,16,16
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 5
"""


def _build(conf, extra=""):
    tr = NetTrainer()
    for k, v in parse_config_string(conf + extra):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _mlp_batch(i, b=32, width=36, nclass=3):
    r = np.random.RandomState(100 + i)
    return DataBatch(
        data=r.rand(b, 1, 1, width).astype(np.float32),
        label=r.randint(0, nclass, size=(b, 1)).astype(np.float32))


def _conv_batch(i, b=8):
    r = np.random.RandomState(200 + i)
    return DataBatch(
        data=r.rand(b, 3, 16, 16).astype(np.float32),
        label=r.randint(0, 3, size=(b, 1)).astype(np.float32))


@pytest.fixture(scope="module")
def mlp_pair():
    """(passes-off, fold+dle-on) BN-MLP trainers trained identically
    for a few steps - infer-stage passes must not touch training, so
    their weights are the same arrays."""
    off = _build(BN_MLP_CONF)
    on = _build(BN_MLP_CONF,
                "graph_passes = fold_conv_bn,dead_layer_elim\n")
    for i in range(5):
        off.update(_mlp_batch(i))
        on.update(_mlp_batch(i))
    return off, on


# ---------------------------------------------------------------------------
# pipeline construction + did-you-mean
# ---------------------------------------------------------------------------
def test_pipeline_from_config_names_and_order():
    pl = PassPipeline.from_config("fold_conv_bn,space_to_depth")
    assert pl.names() == ["space_to_depth", "fold_conv_bn"]
    assert [p.name for p in pl.infer_passes] == ["fold_conv_bn"]
    assert PassPipeline.from_config("").names() == []
    assert set(PassPipeline.from_config("all").names()) == set(
        passes.PASS_REGISTRY)


def test_pipeline_pass_name_did_you_mean():
    with pytest.raises(ValueError, match=r"did you mean "
                       r"'fold_conv_bn'"):
        PassPipeline.from_config("fold_conv_bnn")
    with pytest.raises(ValueError, match="unknown graph pass"):
        PassPipeline.from_config("totally_bogus")


def test_pipeline_toggles_layer_over_list():
    pl = PassPipeline.from_config("fold_conv_bn",
                                  {"fold_conv_bn": 0,
                                   "dead_layer_elim": 1})
    assert pl.names() == ["dead_layer_elim"]
    with pytest.raises(ValueError, match="did you mean"):
        PassPipeline.from_config("", {"fold_conv_bnn": 1})


def test_trainer_rejects_typo_pass_name():
    tr = NetTrainer()
    for k, v in parse_config_string(BN_MLP_CONF):
        tr.set_param(k, v)
    tr.set_param("graph_passes", "dead_layer_elimm")
    with pytest.raises(ValueError, match="dead_layer_elim"):
        tr.init_model()


def test_schema_registers_pass_and_tuning_keys():
    from cxxnet_tpu.analysis import schema
    reg = schema.build_registry()
    for key in ("graph_passes", "tuning_cache", "layer_dtype",
                "pass_fold_conv_bn", "pass_dead_layer_elim",
                "pass_autocast", "pass_space_to_depth"):
        assert reg.recognizes(key), key
    assert reg.suggest("graph_passess") == "graph_passes"
    with pytest.raises(ConfigError, match="graph_passes"):
        schema.validate_pairs([("graph_passess", "all")],
                              source="x.conf")


# ---------------------------------------------------------------------------
# pattern engine
# ---------------------------------------------------------------------------
def test_find_fold_sites_mlp_and_conv():
    off = _build(BN_MLP_CONF)
    assert find_fold_sites(off.net_cfg) == [(0, 1)]
    conv = _build(BN_CONV_CONF)
    assert find_fold_sites(conv.net_cfg) == [(0, 1)]


def test_fold_site_requires_single_consumer():
    conf = BN_MLP_CONF.replace(
        "layer[+1:bn1] = batch_norm:bn1",
        "layer[fc1->spl1,spl2] = split\n"
        "layer[spl1->bn1] = batch_norm:bn1")
    # fc1's output feeds a split, not the bn directly: no site
    tr = _build(conf.replace("layer[sg1->fc2]", "layer[sg1->fc2]"))
    assert find_fold_sites(tr.net_cfg) == []


def test_fold_site_excludes_shared_weights():
    conf = """
netconfig=start
layer[0->a] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[0->b] = share[fc1]
layer[a->c] = batch_norm:bn1
layer[a,b->d] = concat
layer[+1] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,12
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
"""
    tr = _build(conf)
    # fc1 is a share primary AND node a has two consumers: no site
    assert find_fold_sites(tr.net_cfg) == []


# ---------------------------------------------------------------------------
# fold_conv_bn
# ---------------------------------------------------------------------------
def test_fold_parity_on_calibration_batch(mlp_pair):
    off, on = mlp_pair
    b = _mlp_batch(50)
    po = off.predict_dist(b)
    pn = on.predict_dist(b)  # calibrates on this batch
    assert np.allclose(po, pn, rtol=1e-5, atol=1e-6)
    assert (po.argmax(1) == pn.argmax(1)).all()
    assert on._fold_stats is not None
    assert "bn1" in on._fold_stats


def test_folded_jaxpr_has_no_moment_pipeline(mlp_pair):
    off, on = mlp_pair
    on.predict(_mlp_batch(50))  # ensure calibrated
    node = on.net_cfg.num_nodes - 1
    data = np.zeros((32, 1, 1, 36), np.float32)
    g, ge = on.stage_infer_rows(data)
    folded = str(on._infer_fn(node)
                 .trace(on.state["params"], g, ge).jaxpr)
    g2, ge2 = off.stage_infer_rows(data)
    unfolded = str(off._infer_fn(node)
                   .trace(off.state["params"], g2, ge2).jaxpr)
    assert "rsqrt" not in folded
    assert "rsqrt" in unfolded


def test_fold_conv_parity():
    off = _build(BN_CONV_CONF)
    on = _build(BN_CONV_CONF, "graph_passes = fold_conv_bn\n")
    for i in range(3):
        off.update(_conv_batch(i))
        on.update(_conv_batch(i))
    b = _conv_batch(60)
    po, pn = off.predict_dist(b), on.predict_dist(b)
    assert np.allclose(po, pn, rtol=1e-4, atol=1e-6)
    assert (po.argmax(1) == pn.argmax(1)).all()
    # the folded graph lost its batch_norm layer
    node = on.net_cfg.num_nodes - 1
    _net2, _pfn, gm = on._build_infer_graph(node)
    assert "batch_norm" not in [li.type_name for li in gm.cfg.layers]


def test_fold_parity_self_loop_bn():
    """`layer[+0] = batch_norm` (classic cxxnet style) overwrites its
    own node: calibration must tap the BN INPUT before the overwrite,
    not read the post-normalization value after the forward - a
    wrong tap folds silently wrong weights (the stats would come out
    as ~(beta, 1/slope), not the conv-output moments)."""
    conf = BN_MLP_CONF.replace(
        "layer[+1:bn1] = batch_norm:bn1",
        "layer[+0] = batch_norm:bn1")
    off = _build(conf)
    on = _build(conf, "graph_passes = fold_conv_bn\n")
    for i in range(5):
        off.update(_mlp_batch(i))
        on.update(_mlp_batch(i))
    b = _mlp_batch(53)
    po = off.predict_dist(b)
    pn = on.predict_dist(b)  # calibrates on this batch
    assert find_fold_sites(on.net_cfg) == [(0, 1)]
    assert np.allclose(po, pn, rtol=1e-5, atol=1e-6)
    assert (po.argmax(1) == pn.argmax(1)).all()


def test_pass_toggle_prefix_covers_future_passes():
    """The pass_<name> toggle handler is prefix-form: any registered
    pass gets a toggle without a trainer edit, and the schema
    registry recognizes the prefix."""
    from cxxnet_tpu.analysis import schema
    assert schema.build_registry().recognizes("pass_anything_here")
    tr = NetTrainer()
    for k, v in parse_config_string(BN_MLP_CONF):
        tr.set_param(k, v)
    tr.set_param("pass_fold_conv_bnn", "1")  # typo'd toggle
    with pytest.raises(ValueError, match="fold_conv_bn"):
        tr.init_model()


def test_folded_weights_are_live(mlp_pair):
    """The fold bakes only the calibration STATS into the executable;
    W'/b' are in-jit functions of the params ARGUMENT - calling the
    compiled folded executable with a params tree whose fc2 weights
    are zeroed must flatten the logits, no rebuild involved."""
    _off, on = mlp_pair
    b = _mlp_batch(50)
    on.predict_dist(b)
    node = on.net_cfg.num_nodes - 1
    fn = on._infer_fn(node)  # the compiled folded executable
    g, ge = on.stage_infer_rows(b.data)
    import jax.numpy as jnp
    params = {lk: dict(d) for lk, d in on.state["params"].items()}
    params["fc2"] = {"wmat": jnp.zeros_like(params["fc2"]["wmat"]),
                     "bias": jnp.zeros_like(params["fc2"]["bias"])}
    flat = np.asarray(fn(params, g, ge)).reshape(32, -1)
    assert np.allclose(flat, 1.0 / flat.shape[1], atol=1e-6)


def test_set_weight_invalidates_fold_stats():
    """The visitor weight API changes activations like a model load
    does: frozen fold statistics must retire (and the folded path
    re-agree with an unfolded trainer after recalibration)."""
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    b = _mlp_batch(0)
    on.predict(b)  # calibrate
    epoch = on._fold_epoch
    w, _ = on.get_weight("fc1", "wmat")
    on.set_weight(w * 2.0, "fc1", "wmat")
    assert on._fold_stats is None
    assert on._fold_epoch == epoch + 1
    assert on.passes_need_calibration()
    pn = on.predict_dist(b)  # recalibrates on the new activations
    off = _build(BN_MLP_CONF)  # same seed -> same init
    off.set_weight(w * 2.0, "fc1", "wmat")
    po = off.predict_dist(b)
    assert np.allclose(po, pn, rtol=1e-5, atol=1e-6)
    assert (po.argmax(1) == pn.argmax(1)).all()


def test_fold_stats_reset_on_param_reload(mlp_pair):
    _off, on = mlp_pair
    on.predict(_mlp_batch(50))
    assert on._fold_stats is not None
    import io
    buf = io.BytesIO()
    on.save_model(buf)
    # copy_model_from re-inits state: frozen stats must drop so the
    # next inference recalibrates against the new activations
    buf.seek(0)
    on.copy_model_from(buf)
    assert on._fold_stats is None
    assert on.passes_need_calibration()


# ---------------------------------------------------------------------------
# dead_layer_elim
# ---------------------------------------------------------------------------
def test_dle_extract_parity_and_prune(mlp_pair):
    off, on = mlp_pair
    b = _mlp_batch(51)
    fo = off.extract_feature(b, "fc1")
    fn = on.extract_feature(b, "fc1")
    assert np.array_equal(fo, fn)
    nid = on.net.node_index("fc1")
    _net2, _pfn, gm = on._build_infer_graph(nid)
    assert [li.type_name for li in gm.cfg.layers] == ["fullc"]
    data = np.zeros((32, 1, 1, 36), np.float32)
    g, ge = on.stage_infer_rows(data)
    tr = on._infer_fn(nid).trace(on.state["params"], g, ge)
    dots = sum(1 for e in tr.jaxpr.jaxpr.eqns
               if e.primitive.name == "dot_general")
    assert dots == 1  # the pruned fc2 matmul is not even traced


def test_dle_promotes_share_with_dead_primary():
    conf = """
netconfig=start
layer[0->a] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[0->b] = share[fc1]
layer[a->c] = tanh
layer[c->d] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,12
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 9
"""
    off = _build(conf)
    on = _build(conf, "graph_passes = dead_layer_elim\n")
    b = DataBatch(
        data=np.random.RandomState(0).rand(8, 1, 1, 12)
        .astype(np.float32),
        label=np.zeros((8, 1), np.float32))
    fo = off.extract_feature(b, "b")
    fn = on.extract_feature(b, "b")
    assert np.array_equal(fo, fn)
    nid = on.net.node_index("b")
    _net2, _pfn, gm = on._build_infer_graph(nid)
    # only the promoted share survives, fed by fc1's live weights
    assert [li.type_name for li in gm.cfg.layers] == ["fullc"]
    assert not gm.cfg.layers[0].is_shared
    assert list(gm.param_map().values()) == ["fc1"]


def test_dle_keeps_raw_conv_output_unfolded(mlp_pair):
    """Extracting the PRE-BN node must return the raw fullc output:
    DLE prunes the bn (not an ancestor), the fold must not rewire
    the requested node away."""
    off, on = mlp_pair
    b = _mlp_batch(52)
    assert np.array_equal(off.extract_feature(b, "fc1"),
                          on.extract_feature(b, "fc1"))


# ---------------------------------------------------------------------------
# autocast + space_to_depth
# ---------------------------------------------------------------------------
def test_autocast_plan_policy_and_override():
    import jax.numpy as jnp
    on = _build(BN_CONV_CONF,
                "graph_passes = autocast\ndtype = bfloat16\n")
    plan = on.net.dtype_plan
    types = [li.type_name for li in on.net_cfg.layers]
    assert plan[types.index("batch_norm")] == jnp.float32
    assert plan[len(types) - 1] == jnp.float32  # softmax head
    assert plan[types.index("conv")] == jnp.bfloat16
    # layer_dtype pins a layer against the policy
    pinned = _build(
        BN_CONV_CONF.replace("  nchannel = 8\n  kernel_size = 4",
                             "  nchannel = 8\n  layer_dtype = float32"
                             "\n  kernel_size = 4"),
        "graph_passes = autocast\ndtype = bfloat16\n")
    assert pinned.net.dtype_plan[0] == jnp.float32
    on.update(_conv_batch(0))
    out = on.predict_dist(_conv_batch(1))
    assert np.isfinite(out).all()


def test_autocast_noop_under_f32():
    on = _build(BN_CONV_CONF, "graph_passes = autocast\n")
    assert on.net.dtype_plan is None


def test_layer_dtype_rejects_bad_value():
    with pytest.raises(ValueError, match="layer_dtype"):
        _build(BN_CONV_CONF.replace(
            "  kernel_size = 4", "  layer_dtype = float16\n"
            "  kernel_size = 4"))


def test_s2d_pass_stamps_and_matches_auto():
    off = _build(BN_CONV_CONF)
    on = _build(BN_CONV_CONF, "graph_passes = space_to_depth\n")
    # input conv (3ch, stride 2, k4) -> stamped on; mid conv -> off
    assert ("space_to_depth", "1") in on.net_cfg.layercfg[0]
    c2 = [li.type_name for li in on.net_cfg.layers].index("conv", 1)
    assert ("space_to_depth", "0") in on.net_cfg.layercfg[c2]
    assert on.net.layer_objs[0].s2d is True
    # the stamp encodes the SAME decision the in-op auto heuristic
    # takes: predictions are bitwise identical
    for i in range(2):
        off.update(_conv_batch(i))
        on.update(_conv_batch(i))
    b = _conv_batch(70)
    assert np.array_equal(off.predict_dist(b), on.predict_dist(b))


def test_s2d_explicit_flag_wins():
    on = _build(BN_CONV_CONF.replace(
        "  kernel_size = 4", "  space_to_depth = 0\n"
        "  kernel_size = 4"), "graph_passes = space_to_depth\n")
    # the pass must not stamp over an explicit per-layer setting
    assert ("space_to_depth", "1") not in on.net_cfg.layercfg[0]
    assert on.net.layer_objs[0].s2d is False


def test_s2d_auto_single_definition():
    from cxxnet_tpu.ops.conv import _S2D_MAX_IN_CH, s2d_auto
    assert s2d_auto(3, 4, 11, 11) is True
    assert s2d_auto(3, 1, 3, 3) is False       # stride 1
    assert s2d_auto(8, 2, 3, 3) is False       # too many channels
    assert s2d_auto(3, 4, 3, 3) is False       # kernel < stride
    assert s2d_auto(3, 2, 3, 3, num_group=3) is False
    assert _S2D_MAX_IN_CH == 4


# ---------------------------------------------------------------------------
# round-trips + checkpoint compatibility
# ---------------------------------------------------------------------------
def test_transformed_cfg_roundtrips_to_dict(mlp_pair):
    from cxxnet_tpu.nnet.net_config import NetConfig
    _off, on = mlp_pair
    on.predict(_mlp_batch(50))
    for node in (on.net_cfg.num_nodes - 1,
                 on.net.node_index("fc1")):
        _n2, _pf, gm = on._build_infer_graph(node)
        back = NetConfig.from_dict(gm.cfg.to_dict())
        assert back.node_names == gm.cfg.node_names
        assert len(back.layers) == len(gm.cfg.layers)
        for a, b in zip(back.layers, gm.cfg.layers):
            assert a.structure_equals(b)


def test_netconfig_clone_is_deep(mlp_pair):
    off, _on = mlp_pair
    c = off.net_cfg.clone()
    c.layers.pop()
    c.layercfg[0].append(("x", "y"))
    assert len(off.net_cfg.layers) == len(c.layers) + 1
    assert ("x", "y") not in off.net_cfg.layercfg[0]


def test_checkpoint_bytes_and_resume_across_passes(tmp_path):
    """Folding never rewrites saved weights: training with the
    infer-stage passes on produces byte-identical checkpoints, and
    `continue = 1` resumes across graph_passes on<->off - BOTH
    directions in one matrix (the off-trained dir resumes with
    passes on, the on-trained dir resumes with passes off) -
    continuing the identical trajectory."""
    direction = "off_then_on"
    from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist
    from cxxnet_tpu.tools.pass_smoke import CONF
    d = str(tmp_path)
    write_synth_mnist(d, 192, 0, "train")
    write_synth_mnist(d, 96, 1, "test")
    with open(os.path.join(d, "t.conf"), "w") as f:
        f.write(CONF.format(d=d))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    passes_arg = "graph_passes=fold_conv_bn,dead_layer_elim"
    first = [] if direction == "off_then_on" else [passes_arg]
    second = [passes_arg] if direction == "off_then_on" else []

    def run(mdir, *overrides):
        r = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu.main",
             os.path.join(d, "t.conf"), f"model_dir={mdir}",
             *overrides],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]

    def sha(mdir, n):
        with open(os.path.join(mdir, f"{n:04d}.model"), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()

    ma, mb = os.path.join(d, "ma"), os.path.join(d, "mb")
    run(ma, *first)
    run(mb, *second)
    # infer-stage passes leave the training byte-trajectory alone
    assert sha(ma, 2) == sha(mb, 2)
    # resume ACROSS the flag flip, both directions covered by the
    # parametrization; the continued round is identical either way
    run(ma, "continue=1", "num_round=3", "max_round=1", *second)
    run(mb, "continue=1", "num_round=3", "max_round=1", *first)
    assert sha(ma, 3) == sha(mb, 3)


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------
def test_server_calibrated_serves_folded(mlp_pair):
    from cxxnet_tpu.serve import Server
    _off, on = mlp_pair
    b = _mlp_batch(55)
    expect = on.predict_dist(b)  # calibrates + folds
    srv = Server(on, max_batch=8, max_wait_ms=1.0, replicas=1)
    srv.warmup()
    srv.start()
    try:
        rows = srv.submit(b.data[:8]).result(timeout=60)
    finally:
        srv.stop()
    # folded inference is batch-composition-independent, so the
    # bucket-padded serve rows match the batch-at-a-time predict
    assert np.allclose(rows, expect[:8], rtol=1e-5, atol=1e-6)


def test_server_uncalibrated_warns_and_serves_unfolded(capsys):
    from cxxnet_tpu.serve import Server
    off = _build(BN_MLP_CONF)
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    assert on.passes_need_calibration()
    srv = Server(on, max_batch=8, max_wait_ms=1.0, replicas=1)
    assert ("have no calibration stats"
            in capsys.readouterr().err)
    srv.warmup()
    srv.start()
    b = _mlp_batch(56, b=8)
    try:
        rows = srv.submit(b.data).result(timeout=60)
    finally:
        srv.stop()
    # unfolded serving: matches the passes-off trainer on the same
    # 8-row program shape (stats stay per-batch, batch == bucket)
    expect = off.infer_rows(*off.stage_infer_rows(b.data))
    assert np.allclose(rows, np.asarray(expect).reshape(8, -1),
                       rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# tuning cache
# ---------------------------------------------------------------------------
def test_tuning_cache_roundtrip_and_validation(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {"steps_per_dispatch": 4,
                                 "prefetch_stage": 2},
                      {"best_ips": 10.0}, "host")
    assert tuning.tuned_knobs(p, "cpu") == {
        "steps_per_dispatch": "4", "prefetch_stage": "2"}
    assert tuning.tuned_knobs(p, "tpu") == {}
    with pytest.raises(ValueError, match="untunable"):
        tuning.save_entry(p, "cpu", {"bogus_knob": 1})
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("not json")
    with pytest.raises(ConfigError, match="not JSON"):
        tuning.tuned_knobs(bad, "cpu")
    with open(bad, "w") as f:
        json.dump({"platforms": {"cpu": {"knobs": {"nope": 1}}}}, f)
    with pytest.raises(ConfigError, match="unknown knob"):
        tuning.tuned_knobs(bad, "cpu")


def test_save_entry_never_clobbers_unreadable_cache(tmp_path):
    """Merging into an EXISTING cache that fails validation must
    raise, not silently replace the file (which would destroy every
    other platform's tuned entries)."""
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "tpu", {"steps_per_dispatch": 8})
    with open(p, "w") as f:
        f.write("not json at all")
    with pytest.raises(ConfigError):
        tuning.save_entry(p, "cpu", {"steps_per_dispatch": 2})
    with open(p) as f:
        assert f.read() == "not json at all"  # untouched


def test_int_knob_shared_apply_rule():
    knobs = {"steps_per_dispatch": "4", "prefetch_stage": "4.0"}
    assert tuning.int_knob(knobs, "steps_per_dispatch", set(), 1) == 4
    # explicit key wins
    assert tuning.int_knob(knobs, "steps_per_dispatch",
                           {"steps_per_dispatch"}, 1) is None
    # malformed skips, never raises
    assert tuning.int_knob(knobs, "prefetch_stage", set(), 0) is None
    # below-minimum skips
    assert tuning.int_knob({"serve_max_batch": "-1"},
                           "serve_max_batch", set(), 0) is None


def test_recalibration_evicts_stale_infer_executables():
    """Each recalibration bumps the fold epoch; the previous epoch's
    transformed graphs and compiled executables must be evicted or a
    reload/predict loop leaks one executable per reload."""
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    b = _mlp_batch(0)
    on.predict(b)
    assert len(on._infer_graph_cache) == 1
    n_jits = len(on._infer_jits)
    import io
    buf = io.BytesIO()
    on.save_model(buf)
    for _ in range(3):
        buf.seek(0)
        on.copy_model_from(buf)   # drops stats -> next predict
        on.predict(b)             # recalibrates (epoch++)
    assert len(on._infer_graph_cache) == 1
    assert len(on._infer_jits) == n_jits
    assert all(k[1] == on._fold_epoch for k in on._infer_graph_cache)


def test_param_reload_retires_stale_folded_executables():
    """After a params reload (_init_state), the serving-path
    _infer_fn must NOT hand back the folded executable frozen with
    the OLD model's calibration statistics: the epoch bumps and the
    stale executables are evicted, so an uncalibrated infer builds
    the (safe) unfolded graph."""
    on = _build(BN_MLP_CONF, "graph_passes = fold_conv_bn\n")
    b = _mlp_batch(0)
    on.predict(b)  # calibrate + fold
    node = on.net_cfg.num_nodes - 1
    folded_fn = on._infer_fn(node)
    epoch = on._fold_epoch
    import io
    buf = io.BytesIO()
    on.save_model(buf)
    buf.seek(0)
    on.copy_model_from(buf)
    assert on._fold_epoch == epoch + 1
    assert on.passes_need_calibration()
    # the serving path now builds a FRESH (unfolded) executable
    # instead of re-dispatching the stale-stats folded one
    fresh_fn = on._infer_fn(node)
    assert fresh_fn is not folded_fn
    g, ge = on.stage_infer_rows(b.data)
    out = np.asarray(on.infer_rows(g, ge))
    # unfolded graph: matches a passes-off trainer with the same
    # weights on the same program shape
    off = _build(BN_MLP_CONF)
    buf.seek(0)
    off.copy_model_from(buf)
    g2, ge2 = off.stage_infer_rows(b.data)
    expect = np.asarray(off.infer_rows(g2, ge2))
    assert np.allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_load_cache_type_errors_are_config_errors(tmp_path):
    for payload in (["cpu"], {"platforms": ["cpu"]},
                    {"platforms": {"cpu": "bogus"}},
                    {"platforms": {"cpu": {"knobs": ["x"]}}}):
        p = str(tmp_path / "bad.json")
        with open(p, "w") as f:
            json.dump(payload, f)
        with pytest.raises(ConfigError):
            tuning.load_cache(p)


def test_tuning_cache_trainer_defaults_and_explicit_win(tmp_path):
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {"steps_per_dispatch": 4,
                                 "serve_max_batch": 16})
    tr = _build(BN_MLP_CONF, f"tuning_cache = {p}\n")
    assert tr.steps_per_dispatch == 4
    assert tr.serve_max_batch == 16
    tr2 = _build(BN_MLP_CONF,
                 f"steps_per_dispatch = 2\ntuning_cache = {p}\n")
    assert tr2.steps_per_dispatch == 2  # explicit key wins
    assert tr2.serve_max_batch == 16


def test_tuning_cache_task_level_knobs(tmp_path):
    from cxxnet_tpu.main import LearnTask
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {"prefetch_stage": 2,
                                 "steps_per_dispatch": 4})
    task = LearnTask()
    task.set_param("tuning_cache", p)
    task._apply_tuning_cache()
    assert task.prefetch_stage == 2
    assert task.steps_per_dispatch == 4
    task2 = LearnTask()
    task2.set_param("prefetch_stage", "0")
    task2.set_param("tuning_cache", p)
    task2._apply_tuning_cache()
    assert task2.prefetch_stage == 0  # explicit key wins
    assert task2.steps_per_dispatch == 4


def test_tuned_trainer_trains_fused(tmp_path):
    """A tuned steps_per_dispatch default really drives the fused
    path: the update loop consumes chunks bitwise-identically to the
    explicit-key run."""
    p = str(tmp_path / "tc.json")
    tuning.save_entry(p, "cpu", {"steps_per_dispatch": 2})
    tr = _build(BN_MLP_CONF, f"tuning_cache = {p}\n")
    tr.update_chunk([_mlp_batch(0), _mlp_batch(1)])
    assert tr._step_counter == 2
