"""Telemetry subsystem tests: metrics registry math, span nesting,
JSONL sink round-trip, StepProfiler percentiles/trace_round, retry
routing, and the end-to-end acceptance run (train with sinks armed ->
valid streams -> metrics_report renders)."""

import json
import math
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu import telemetry
from cxxnet_tpu.telemetry import Telemetry
from cxxnet_tpu.telemetry.registry import (
    Counter, Gauge, Histogram, MetricsRegistry)
from cxxnet_tpu.telemetry.sink import format_record, read_jsonl
from cxxnet_tpu.utils.profiler import StepProfiler


@pytest.fixture(autouse=True)
def _clean_singleton():
    """Every test starts and ends with the process-wide telemetry in
    the disabled state with an empty registry."""
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_counter_gauge_basics():
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = Gauge()
    g.set(3)
    g.set(1.5)
    assert g.value == 1.5


def test_histogram_percentile_math():
    h = Histogram()
    for v in range(1, 101):  # 1..100
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(5050.0)
    assert h.min == 1.0 and h.max == 100.0
    # numpy's linear-interpolation percentiles are the reference
    vals = np.arange(1, 101, dtype=np.float64)
    assert h.percentile(50) == pytest.approx(np.percentile(vals, 50))
    assert h.percentile(99) == pytest.approx(np.percentile(vals, 99))
    snap = h.snapshot()
    assert snap["p50"] == pytest.approx(np.percentile(vals, 50))
    assert snap["p99"] == pytest.approx(np.percentile(vals, 99))
    assert snap["mean"] == pytest.approx(50.5)


def test_histogram_empty_and_single():
    h = Histogram()
    assert math.isnan(h.percentile(50))
    assert h.snapshot()["p50"] is None
    h.observe(2.0)
    assert h.percentile(50) == 2.0
    assert h.percentile(99) == 2.0


def test_histogram_window_bounds_memory():
    h = Histogram(window=8)
    for v in range(100):
        h.observe(float(v))
    assert h.count == 100          # exact over the full stream
    assert h.max == 99.0
    assert h.percentile(0) >= 92.0  # window keeps only the newest 8


def test_registry_idempotent_and_type_checked():
    r = MetricsRegistry()
    assert r.counter("a") is r.counter("a")
    with pytest.raises(TypeError):
        r.gauge("a")
    r.counter("a").inc(2)
    r.gauge("b").set(1.0)
    r.histogram("c").observe(0.5)
    snap = r.snapshot()
    assert snap["a"] == 2 and snap["b"] == 1.0
    assert snap["c"]["count"] == 1


def test_registry_thread_safety():
    r = MetricsRegistry()

    def work():
        for _ in range(1000):
            r.counter("n").inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.counter("n").value == 8000


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def test_span_disabled_is_noop_singleton():
    tel = Telemetry()
    s1, s2 = tel.span("a"), tel.span("b")
    assert s1 is s2  # shared null context, zero allocation
    with s1:
        pass
    assert tel.registry.get("a") is None  # nothing recorded


def test_span_nesting_records_paths(tmp_path):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    tel.configure(log_file=log)
    with tel.span("round"):
        with tel.span("step", idx=3):
            time.sleep(0.01)
        with tel.span("step"):
            pass
    tel.close()
    assert tel.registry.get("round/step").count == 2
    assert tel.registry.get("round").count == 1
    assert tel.registry.get("round/step").sum >= 0.01
    events = list(read_jsonl(log))
    spans = [e for e in events if e["kind"] == "span"]
    assert [s["name"] for s in spans] == ["round/step", "round/step",
                                          "round"]
    assert spans[0]["idx"] == 3  # extra fields ride on the event
    assert all(s["secs"] >= 0 for s in spans)


# ---------------------------------------------------------------------------
# sinks / central logger
# ---------------------------------------------------------------------------
def test_jsonl_round_trip_with_tags(tmp_path):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    met = str(tmp_path / "me.jsonl")
    tel.configure(log_file=log, metrics_file=met,
                  tags={"device": "cpu"})
    tel.inc("fault.retry", 2)
    tel.observe("train.step_s", 0.25)
    tel.event("checkpoint", op="save", round=3, secs=0.5, bytes=123)
    tel.emit_metrics(kind="round", round=3)
    tel.close()
    events = list(read_jsonl(log))
    assert len(events) == 1
    e = events[0]
    assert e["kind"] == "checkpoint" and e["op"] == "save"
    assert e["bytes"] == 123
    for tag in ("ts", "host", "pid", "proc", "device"):
        assert tag in e
    recs = list(read_jsonl(met))
    assert len(recs) == 1
    m = recs[0]["metrics"]
    assert m["fault.retry"] == 2
    assert m["train.step_s"]["count"] == 1
    assert recs[0]["round"] == 3


def test_jsonl_skips_torn_last_line(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text(json.dumps({"kind": "round", "round": 1}) +
                 '\n{"kind": "round", "rou')  # killed mid-write
    recs = list(read_jsonl(str(p)))
    assert len(recs) == 1 and recs[0]["round"] == 1


def test_json_sanitizes_non_finite_floats(tmp_path):
    """A diverging run's NaN loss must not poison the stream: bare
    NaN/Infinity tokens are invalid JSON (rejected by jq/strict
    parsers); the sink writes null instead."""
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    met = str(tmp_path / "me.jsonl")
    tel.configure(log_file=log, metrics_file=met)
    tel.set_gauge("train.loss", float("nan"))
    tel.event("span", name="train.step", secs=0.1, loss=float("nan"),
              ips=float("inf"), np_nan=np.float32("nan"))
    tel.emit_metrics(kind="final")
    tel.close()
    for path in (log, met):
        for line in open(path):
            assert "NaN" not in line and "Infinity" not in line
            json.loads(line)  # strictly valid
    ev = list(read_jsonl(log))[0]
    assert ev["loss"] is None and ev["ips"] is None
    assert ev["np_nan"] is None
    snap = list(read_jsonl(met))[0]["metrics"]
    assert snap["train.loss"] is None


def test_metrics_report_deltas_survive_resume(tmp_path):
    """Append-mode streams restart counters at 0 when a resumed
    process takes over; per-round deltas must be tracked per process,
    not across the reset (negative or under-counted deltas)."""
    from cxxnet_tpu.tools.metrics_report import aggregate
    p = tmp_path / "m.jsonl"
    recs = [
        # first process: 6 saves, 5 retries by its last round
        {"kind": "round", "host": "h", "pid": 1, "round": 1,
         "metrics": {"checkpoint.saves": 6, "fault.retry": 5}},
        # resumed process: fresh counters, 7 retries before round 2
        {"kind": "round", "host": "h", "pid": 2, "round": 2,
         "metrics": {"checkpoint.saves": 0, "fault.retry": 7}},
        {"kind": "round", "host": "h", "pid": 2, "round": 3,
         "metrics": {"checkpoint.saves": 1, "fault.retry": 7}},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    rows = aggregate(str(p))["rounds"]
    assert [r["retries"] for r in rows] == [5, 7, 0]
    assert [r["saves"] for r in rows] == [6, 0, 1]


def test_sink_io_failure_never_raises(tmp_path, capfd):
    """ENOSPC/NFS blips on the stream file must not abort training:
    the sink disables itself (noted once on stderr) and later writes
    are silent no-ops."""
    from cxxnet_tpu.telemetry.sink import LineSink
    sink = LineSink(str(tmp_path / "ev.jsonl"))
    sink._f.close()  # simulate the handle dying under the sink
    sink.write({"kind": "x"})   # must not raise
    sink.write({"kind": "y"})
    sink.flush()
    sink.close()
    assert "telemetry: disabling sink" in capfd.readouterr().err


def test_metrics_report_multiproc_finals_and_rounds(tmp_path, capfd):
    """Merged multi-process streams: finals are reported per process
    (one last-wins snapshot would silently drop the other hosts'
    counters) and the round table grows a proc column."""
    from cxxnet_tpu.tools.metrics_report import aggregate, render
    p = tmp_path / "m.jsonl"
    recs = [
        {"kind": "round", "host": "a", "pid": 1, "round": 1,
         "steps": 2, "examples": 64, "images_per_sec": 10.0,
         "step_p50_ms": 1.0, "step_p99_ms": 2.0, "data_total_ms": 3.0,
         "metrics": {"fault.retry": 2}},
        {"kind": "round", "host": "b", "pid": 2, "round": 1,
         "steps": 2, "examples": 64, "images_per_sec": 11.0,
         "step_p50_ms": 1.0, "step_p99_ms": 2.0, "data_total_ms": 3.0,
         "metrics": {"fault.retry": 1}},
        {"kind": "final", "host": "a", "pid": 1,
         "metrics": {"fault.retry": 3}},
        {"kind": "final", "host": "b", "pid": 2,
         "metrics": {"fault.retry": 4}},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    agg = aggregate(str(p))
    assert [r["retries"] for r in agg["rounds"]] == [2, 1]
    assert agg["finals"]["a/1"]["fault.retry"] == 3
    assert agg["finals"]["b/2"]["fault.retry"] == 4
    out = render(agg)
    assert "final counters/gauges [a/1]:" in out
    assert "final counters/gauges [b/2]:" in out
    assert "proc" in out.splitlines()[1]  # proc column in the table


def test_text_format_renders_fields():
    line = format_record({"ts": 12.0, "kind": "eval", "round": 2,
                          "values": {"test-error": 0.1}}, "text")
    assert line.startswith("12.000 eval")
    assert "round=2" in line and "test-error" in line


def test_log_format_validation(tmp_path):
    tel = Telemetry()
    with pytest.raises(ValueError):
        tel.configure(log_file=str(tmp_path / "x"), log_format="xml")


def test_stdout_stderr_passthrough_and_mirror(tmp_path, capfd):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    tel.configure(log_file=log)
    tel.stdout("hello out")
    tel.stderr("[1]\ttest-error:0.5\n", event_kind="eval", round=1,
               values={"test-error": 0.5})
    tel.stderr("plain line\n")
    tel.close()
    out, err = capfd.readouterr()
    assert out == "hello out\n"
    assert err == "[1]\ttest-error:0.5\nplain line\n"  # byte-exact
    events = list(read_jsonl(log))
    kinds = [e["kind"] for e in events]
    assert kinds == ["log", "eval", "log"]
    assert events[1]["values"]["test-error"] == 0.5


def test_disabled_telemetry_writes_no_files(tmp_path, capfd):
    tel = Telemetry()
    tel.stderr("text\n")
    tel.event("x", a=1)
    tel.emit_metrics()
    assert capfd.readouterr().err == "text\n"
    assert list(tmp_path.iterdir()) == []


def test_heartbeat_emits_periodic_snapshots(tmp_path):
    tel = Telemetry()
    met = str(tmp_path / "hb.jsonl")
    tel.configure(metrics_file=met, heartbeat_secs=0.05)
    tel.inc("beats.seen")
    time.sleep(0.18)
    tel.close()
    hb = [r for r in read_jsonl(met) if r["kind"] == "heartbeat"]
    assert len(hb) >= 2
    assert hb[-1]["metrics"]["beats.seen"] == 1


def test_configure_is_idempotent_and_closes_previous(tmp_path):
    tel = Telemetry()
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    tel.configure(log_file=a)
    tel.event("one")
    tel.configure(log_file=b)
    tel.event("two")
    tel.configure()  # disarm
    tel.event("three")
    assert [e["kind"] for e in read_jsonl(a)] == ["one"]
    assert [e["kind"] for e in read_jsonl(b)] == ["two"]


# ---------------------------------------------------------------------------
# StepProfiler
# ---------------------------------------------------------------------------
def test_profiler_percentile_and_ips_math():
    p = StepProfiler()
    p.round_start()
    steps = [0.010, 0.020, 0.030, 0.040]
    for s in steps:
        p.add_step(s, 32)
    p.add_data(0.100)
    st = p.stats()
    assert st["steps"] == 4 and st["examples"] == 128
    assert st["step_p50_ms"] == pytest.approx(
        np.percentile(steps, 50) * 1e3)
    assert st["step_p99_ms"] == pytest.approx(
        np.percentile(steps, 99) * 1e3)
    assert st["data_total_ms"] == pytest.approx(100.0)
    assert st["images_per_sec"] == pytest.approx(128 / 0.2)
    assert "images/sec" in p.summary()


def test_profiler_zero_step_summary_robust():
    p = StepProfiler()
    assert p.stats() is None
    assert p.summary() == "\tprofile: no steps"
    # steps but EMPTY data_s (staged/membuffer rounds): must not crash
    p.add_step(0.01, 0)
    st = p.stats()
    assert st["data_total_ms"] == 0.0
    assert math.isnan(st["images_per_sec"]) or st["images_per_sec"] >= 0
    assert "profile: 1 steps" in p.summary()


def test_profiler_trace_round_selects_round(monkeypatch, tmp_path):
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    p = StepProfiler(str(tmp_path), trace_round=3)
    for _ in range(5):
        p.round_start()
        p.add_step(0.01, 1)
        p.round_end()
    # traced exactly once, on profiled round 3
    assert calls == [("start", str(tmp_path)), ("stop", None)]
    assert p._round_idx == 5 and p._traced_once


def test_profiler_default_traces_first_round(monkeypatch, tmp_path):
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    p = StepProfiler(str(tmp_path))
    p.round_start()
    assert calls == ["start"]
    p.round_end()
    p.round_start()
    p.round_end()
    assert calls == ["start", "stop"]


# ---------------------------------------------------------------------------
# fault routing
# ---------------------------------------------------------------------------
def test_retry_warning_routes_through_telemetry(tmp_path, capfd):
    from cxxnet_tpu.utils.fault import retry
    log = str(tmp_path / "ev.jsonl")
    telemetry.configure(log_file=log)
    attempts = []

    @retry(attempts=3, backoff=0.0, jitter=0.0)
    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        return "ok"

    assert flaky() == "ok"
    telemetry.close()
    err = capfd.readouterr().err
    # exact pre-telemetry stderr text preserved
    assert err.count("retry: ") == 2
    assert "(attempt 1/3: OSError: transient); retrying in 0.00s" in err
    assert telemetry.counter("fault.retry").value == 2
    faults = [e for e in read_jsonl(log) if e["kind"] == "fault"]
    assert len(faults) == 2
    assert all(f["type"] == "retry" for f in faults)


def test_retry_iterator_counts_io_retries(tmp_path, capfd):
    from cxxnet_tpu.io.iterators import DataIter, RetryIterator
    from cxxnet_tpu.utils import fault

    class Once(DataIter):
        def __init__(self):
            self.n = 0

        def before_first(self):
            self.n = 0

        def next(self):
            self.n += 1
            return self.n <= 2

        def value(self):
            return self.n

    it = RetryIterator(Once())
    it.set_param("io_retry_backoff", "0.0")
    fault.clear()
    fault.inject("io.next", "ioerror", at=1)
    try:
        it.before_first()
        served = sum(1 for _ in iter(lambda: it.next(), False))
    finally:
        fault.clear()
    assert served == 2
    assert telemetry.counter("io.retry").value == 1
    assert telemetry.counter("fault.retry").value == 1
    assert "retry: " in capfd.readouterr().err


# ---------------------------------------------------------------------------
# end-to-end acceptance: train with sinks armed -> streams -> report
# ---------------------------------------------------------------------------
def test_telemetry_steps_opt_out(tmp_path, capfd):
    """telemetry_steps=0 keeps event logging (checkpoint/eval/fault)
    but drops the per-step spans and their device-sync cost."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.tools.telemetry_smoke import CONF, write_synth_mnist
    d = str(tmp_path)
    write_synth_mnist(d, 256, 0, "train")
    write_synth_mnist(d, 64, 1, "test")
    conf = tmp_path / "t.conf"
    conf.write_text(CONF.format(d=d))
    LearnTask().run([str(conf), "telemetry_steps=0", "num_round=1",
                     "max_round=1"])
    capfd.readouterr()
    events = list(read_jsonl(d + "/events.jsonl"))
    assert not any(e["kind"] == "span" for e in events)
    assert any(e["kind"] == "checkpoint" and e.get("op") == "save"
               for e in events)
    assert any(e["kind"] == "eval" for e in events)
    # the round record still rides on the profiler-free path? no -
    # with per-step instrumentation off and profile=0 there is no
    # profiler, so no round stats record is expected
    assert not any(e["kind"] == "round" for e in events)


def test_round_records_include_own_checkpoint_save(tmp_path, capfd):
    """The per-round metrics record is emitted AFTER the round's
    checkpoint save, so metrics_report attributes save deltas to the
    round that paid them (initial save + round-1 save land in round
    1's row)."""
    from cxxnet_tpu.tools.metrics_report import aggregate
    from cxxnet_tpu.tools.telemetry_smoke import run_smoke
    assert run_smoke(str(tmp_path)) == 0
    capfd.readouterr()
    rows = aggregate(str(tmp_path / "metrics.jsonl"))["rounds"]
    assert [r["saves"] for r in rows] == [2, 1]


def test_e2e_train_produces_valid_streams(tmp_path, capfd):
    """The ISSUE acceptance run: 2-round digits training with
    log_file/metrics_file set produces valid JSONL with step/data span
    timings, a checkpoint save duration, and a fault counter, and
    metrics_report renders a per-round summary from it."""
    from cxxnet_tpu.tools.telemetry_smoke import run_smoke
    assert run_smoke(str(tmp_path)) == 0
    out = capfd.readouterr().out
    assert "per-round summary:" in out
    assert "telemetry_smoke: PASS" in out
