"""Live observability plane tests (docs/OBSERVABILITY.md): Prometheus
exposition correctness, /healthz + /varz endpoints, alert rule matrix
(threshold / rate / absence with hysteresis), watchdog stall dumps,
cross-host aggregation, multi-file metrics_report merge, heartbeat
shutdown hardening, and the off-by-default parity guarantees."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from cxxnet_tpu import telemetry
from cxxnet_tpu.telemetry import Telemetry
from cxxnet_tpu.telemetry.alerts import AlertEngine, load_rules
from cxxnet_tpu.telemetry.http import (
    PROM_CONTENT_TYPE, ObservabilityServer, prom_label_escape,
    prom_name, render_prometheus, validate_exposition)
from cxxnet_tpu.telemetry.sink import read_jsonl
from cxxnet_tpu.telemetry.watchdog import Watchdog
from cxxnet_tpu.tools.agg import Aggregator, make_source
from cxxnet_tpu.tools.metrics_report import aggregate


@pytest.fixture(autouse=True)
def _clean_singleton():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------
def test_prom_name_mapping():
    assert prom_name("train.step_s") == "cxxnet_train_step_s"
    assert prom_name("io.prefetch.depth") == "cxxnet_io_prefetch_depth"
    assert prom_name("9weird name") == "cxxnet__9weird_name"


def test_prom_label_escaping():
    assert prom_label_escape('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_render_every_instrument_kind():
    tel = Telemetry()
    tel.inc("fault.retry", 3)
    tel.set_gauge("train.loss", 0.25)
    for v in (0.01, 0.02, 0.03, 0.04):
        tel.observe("train.step_s", v)
    text = render_prometheus(tel)
    assert validate_exposition(text) == []
    lines = text.splitlines()
    assert "# TYPE cxxnet_fault_retry_total counter" in lines
    assert "cxxnet_fault_retry_total 3" in lines
    assert "# TYPE cxxnet_train_loss gauge" in lines
    assert "cxxnet_train_loss 0.25" in lines
    assert "# TYPE cxxnet_train_step_s summary" in lines
    assert any(l.startswith('cxxnet_train_step_s{quantile="0.5"} ')
               for l in lines)
    assert any(l.startswith('cxxnet_train_step_s{quantile="0.99"} ')
               for l in lines)
    assert "cxxnet_train_step_s_count 4" in lines
    assert any(l.startswith("cxxnet_train_step_s_sum 0.1")
               for l in lines)


def test_render_empty_histogram_and_weird_tags():
    tel = Telemetry()
    tel.histogram("serve.latency_s")  # no observations: NaN quantiles
    tel.set_tags(host='h"x\\y\nz')
    text = render_prometheus(tel)
    assert validate_exposition(text) == []
    assert 'cxxnet_serve_latency_s{quantile="0.5"} NaN' in text
    assert 'host="h\\"x\\\\y\\nz"' in text  # escaped, single line
    assert "cxxnet_serve_latency_s_count 0" in text


def test_validate_exposition_catches_garbage():
    assert validate_exposition("ok_metric 1\n") == []
    assert validate_exposition("bad metric name 1\n")
    assert validate_exposition("x{unclosed=\"v\" 1\n")
    assert validate_exposition("# FROB x y\n")


# ---------------------------------------------------------------------------
# endpoints
# ---------------------------------------------------------------------------
def test_http_endpoints_metrics_varz_healthz_404():
    tel = Telemetry()
    tel.inc("train.images", 64)
    srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, ctype, body = _get(base + "/metrics")
        assert code == 200 and ctype == PROM_CONTENT_TYPE
        assert validate_exposition(body.decode()) == []
        assert "cxxnet_train_images_total 64" in body.decode()

        code, ctype, body = _get(base + "/varz")
        assert code == 200 and ctype == "application/json"
        rec = json.loads(body)
        # the /varz body IS a metrics-stream record: same tags, same
        # metrics payload shape as emit_metrics writes
        assert rec["kind"] == "varz"
        for key in ("ts", "host", "pid", "proc"):
            assert key in rec
        assert rec["metrics"]["train.images"] == 64

        code, _, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["ok"] is True

        tel.health.set_unhealthy("watchdog", "no progress for 99s")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/healthz")
        assert ei.value.code == 503
        payload = json.loads(ei.value.read())
        assert payload["ok"] is False
        assert "watchdog" in payload["reasons"]

        tel.health.clear("watchdog")
        code, _, _ = _get(base + "/healthz")
        assert code == 200

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()
    # closed = socket really released
    with pytest.raises(OSError):
        _get(f"http://127.0.0.1:{srv.port}/healthz", timeout=0.5)


def test_server_scrapes_do_not_touch_std_streams(capfd):
    tel = Telemetry()
    srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
    try:
        _get(f"http://127.0.0.1:{srv.port}/metrics")
        _get(f"http://127.0.0.1:{srv.port}/varz")
    finally:
        srv.close()
    out, err = capfd.readouterr()
    assert out == "" and err == ""  # no BaseHTTPRequestHandler logging


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------
def _engine(tel, rules, **kw):
    eng = AlertEngine(tel, [dict(r) for r in rules], **kw)
    return eng


def test_threshold_rule_for_secs_and_recovery():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "q", "type": "threshold", "metric": "serve.queue_depth",
        "op": ">", "value": 10, "for_secs": 5}])
    tel.set_gauge("serve.queue_depth", 50)
    assert eng.check_now(now) == []          # pending, not yet firing
    assert eng.check_now(now + 4.9) == []
    assert eng.check_now(now + 5.0) == ["q"]  # sustained for_secs
    ok, reasons = tel.health.status()
    assert not ok and "alert:q" in reasons
    tel.set_gauge("serve.queue_depth", 2)
    assert eng.check_now(now + 6.0) == []     # resolved
    assert tel.health.ok
    assert tel.registry.counter("alert.fired").value == 1
    assert tel.registry.counter("alert.resolved").value == 1


def test_threshold_blip_does_not_fire():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "q", "type": "threshold", "metric": "serve.queue_depth",
        "op": ">", "value": 10, "for_secs": 5}])
    tel.set_gauge("serve.queue_depth", 50)
    assert eng.check_now(now) == []
    tel.set_gauge("serve.queue_depth", 0)    # recovered inside window
    assert eng.check_now(now + 3) == []
    tel.set_gauge("serve.queue_depth", 50)   # pending restarts
    assert eng.check_now(now + 4) == []
    assert eng.check_now(now + 8.9) == []
    assert eng.check_now(now + 9.0) == ["q"]


def test_threshold_hysteresis_clear_secs():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "q", "type": "threshold", "metric": "serve.queue_depth",
        "op": ">", "value": 10, "for_secs": 0, "clear_secs": 10}])
    tel.set_gauge("serve.queue_depth", 99)
    assert eng.check_now(now) == ["q"]
    tel.set_gauge("serve.queue_depth", 0)
    # below threshold but within the clear window: still firing (a
    # flapping metric must not strobe /healthz)
    assert eng.check_now(now + 5) == ["q"]
    assert not tel.health.ok
    tel.set_gauge("serve.queue_depth", 99)   # re-trips: clear resets
    assert eng.check_now(now + 8) == ["q"]
    tel.set_gauge("serve.queue_depth", 0)
    assert eng.check_now(now + 9) == ["q"]
    assert eng.check_now(now + 19.5) == []   # clear_secs elapsed
    assert tel.health.ok


def test_threshold_histogram_stat():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "slow", "type": "threshold",
        "metric": "serve.latency_s", "op": ">", "value": 0.5,
        "for_secs": 0, "stat": "p99"}])
    for _ in range(99):
        tel.observe("serve.latency_s", 0.01)
    assert eng.check_now(now) == []
    for _ in range(40):
        tel.observe("serve.latency_s", 2.0)
    assert eng.check_now(now + 1) == ["slow"]


def test_rate_rule_honors_for_secs_sustain():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "nan", "type": "rate", "metric": "fault.nan_rollback",
        "max_per_min": 3, "window_secs": 600, "for_secs": 100}])
    assert eng.check_now(now) == []
    tel.inc("fault.nan_rollback", 50)
    # rate exceeds 3/min from t=60 on, but must SUSTAIN for_secs
    assert eng.check_now(now + 60) == []
    assert eng.check_now(now + 120) == []
    assert eng.check_now(now + 161) == ["nan"]


def test_rule_numeric_fields_validated():
    tel = Telemetry()
    with pytest.raises(ValueError, match="must be a number"):
        _engine(tel, [{"name": "q", "type": "threshold",
                       "metric": "m.x", "op": ">", "value": "256"}])
    with pytest.raises(ValueError, match="must be a number"):
        _engine(tel, [{"name": "s", "type": "absence", "beacon": "b.c",
                       "for_secs": "120"}])


def test_broken_rule_does_not_block_later_rules(tmp_path, capfd):
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [
        {"name": "bad", "type": "threshold", "metric": "m.x",
         "op": ">", "value": 1, "for_secs": 0},
        {"name": "good", "type": "threshold", "metric": "m.y",
         "op": ">", "value": 1, "for_secs": 0}])
    # sabotage rule 0 post-validation (stands in for any eval blowup)
    eng.states[0].rule["op"] = "bogus"
    tel.observe("m.x", 5)
    tel.set_gauge("m.y", 5)
    assert eng.check_now(now) == ["good"]    # isolation: good still fires
    assert eng.check_now(now + 1) == ["good"]
    err = capfd.readouterr().err
    assert err.count("failed to evaluate") == 1  # noted once


def test_rate_rule_counts_increments_per_minute():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "nan", "type": "rate", "metric": "fault.nan_rollback",
        "max_per_min": 3, "window_secs": 60}])
    assert eng.check_now(now) == []          # baseline sample
    tel.inc("fault.nan_rollback", 2)
    assert eng.check_now(now + 60) == []     # 2/min: under
    tel.inc("fault.nan_rollback", 30)
    assert eng.check_now(now + 120) == ["nan"]  # burst
    # counter goes quiet: the window drains and the rule resolves
    assert eng.check_now(now + 300) == []


def test_absence_rule_beacon_and_startup_grace():
    tel = Telemetry()
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "stall", "type": "absence", "beacon": "train.step",
        "for_secs": 10, "startup_grace_secs": 60}])
    eng._armed_at = now
    # never seen: quiet through the startup grace, then fires
    assert eng.check_now(now + 30) == []
    assert eng.check_now(now + 61) == ["stall"]
    tel.beacon("train.step")                 # progress: real monotonic
    real = time.monotonic()
    assert eng.check_now(real) == []         # resolved
    assert tel.health.ok
    assert eng.check_now(real + 10.5) == ["stall"]  # went quiet again


def test_alert_cmd_hook_runs(tmp_path):
    tel = Telemetry()
    now = time.monotonic()
    marker = tmp_path / "hook.out"
    eng = _engine(
        tel,
        [{"name": "q", "type": "threshold", "metric": "x.y",
          "op": ">", "value": 1, "for_secs": 0}],
        alert_cmd=f'echo "$ALERT_NAME $ALERT_STATE" >> {marker}')
    tel.set_gauge("x.y", 5)
    assert eng.check_now(now) == ["q"]
    tel.set_gauge("x.y", 0)
    assert eng.check_now(now + 1) == []
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if (marker.exists()
                and len(marker.read_text().splitlines()) >= 2):
            break
        time.sleep(0.05)
    lines = marker.read_text().splitlines()
    assert lines[0] == "q firing"
    assert lines[1] == "q resolved"


def test_alert_events_on_stream(tmp_path):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    tel.configure(log_file=log)
    now = time.monotonic()
    eng = _engine(tel, [{
        "name": "q", "type": "threshold", "metric": "x.y",
        "op": ">=", "value": 1, "for_secs": 0}])
    tel.set_gauge("x.y", 1)
    eng.check_now(now)
    tel.set_gauge("x.y", 0)
    eng.check_now(now + 1)
    tel.close()
    alerts = [e for e in read_jsonl(log) if e["kind"] == "alert"]
    assert [a["state"] for a in alerts] == ["firing", "resolved"]
    assert alerts[0]["name"] == "q"
    assert "x.y" in alerts[0]["message"]


def test_engine_close_clears_firing_health():
    tel = Telemetry()
    eng = _engine(tel, [{
        "name": "q", "type": "threshold", "metric": "x.y",
        "op": ">", "value": 1, "for_secs": 0}])
    tel.set_gauge("x.y", 5)
    eng.check_now(time.monotonic())
    assert not tel.health.ok
    eng.close()
    assert tel.health.ok


def test_load_rules_validation(tmp_path):
    def write(rules):
        p = tmp_path / "r.json"
        p.write_text(json.dumps(rules))
        return str(p)

    ok = load_rules(write([{"type": "absence", "beacon": "train.step",
                            "for_secs": 5}]))
    assert ok[0]["name"] == "rule0"  # defaulted
    with pytest.raises(ValueError, match="unknown type"):
        load_rules(write([{"type": "frobnicate"}]))
    with pytest.raises(ValueError, match="unknown key"):
        load_rules(write([{"type": "absence", "beacon": "b",
                           "for_secs": 5, "for_sec": 5}]))
    with pytest.raises(ValueError, match="op"):
        load_rules(write([{"type": "threshold", "metric": "m",
                           "op": "~", "value": 1}]))
    with pytest.raises(ValueError, match="duplicate"):
        load_rules(write([
            {"name": "a", "type": "absence", "beacon": "b",
             "for_secs": 1},
            {"name": "a", "type": "absence", "beacon": "c",
             "for_secs": 1}]))
    with pytest.raises(ValueError, match="JSON list"):
        load_rules(write({"rules": "nope"}))


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_stall_dump_and_recovery(tmp_path, capfd):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    tel.configure(log_file=log)
    with tel.span("train.chunk"):
        pass
    now = time.monotonic()
    wd = Watchdog(tel, 5.0)
    wd._armed_at = now
    tel.beacon("train.step")
    base = time.monotonic()
    assert wd.check_now(base + 1) is False
    assert wd.check_now(base + 6) is True     # stalled
    assert wd.check_now(base + 7) is True     # same episode: one dump
    ok, reasons = tel.health.status()
    assert not ok and "watchdog" in reasons
    tel.beacon("train.step")
    assert wd.check_now(time.monotonic()) is False
    assert tel.health.ok
    tel.close()
    err = capfd.readouterr().err
    # the stderr dump names this very test frame and the recent span
    assert "watchdog: no progress" in err
    assert "test_watchdog_stall_dump_and_recovery" in err
    assert "train.chunk" in err
    events = list(read_jsonl(log))
    dumps = [e for e in events if e.get("kind") == "watchdog"
             and e.get("op") == "stall_dump"]
    assert len(dumps) == 1                    # one dump per episode
    assert "test_watchdog_stall_dump_and_recovery" in dumps[0]["stacks"]
    assert dumps[0]["spans"][-1]["name"] == "train.chunk"
    recs = [e for e in events if e.get("kind") == "watchdog"
            and e.get("op") == "recovered"]
    assert len(recs) == 1
    assert tel.registry.counter("watchdog.stalls").value == 1


def test_watchdog_startup_grace_before_first_beacon():
    tel = Telemetry()
    now = time.monotonic()
    wd = Watchdog(tel, 2.0, startup_secs=60.0)
    wd._armed_at = now
    # no beacon yet: compile/init time far past stall_secs stays green
    assert wd.check_now(now + 30) is False
    assert wd.check_now(now + 61) is True


def test_watchdog_close_clears_health():
    tel = Telemetry()
    now = time.monotonic()
    wd = Watchdog(tel, 1.0, startup_secs=1.0)
    wd._armed_at = now
    assert wd.check_now(now + 2) is True
    assert not tel.health.ok
    wd.close()
    assert tel.health.ok


# ---------------------------------------------------------------------------
# heartbeat hardening (fake clock)
# ---------------------------------------------------------------------------
class _FakeClockWaiter:
    """Stands in for Event.wait: the test releases one tick at a time;
    wait() returns False to tick, True to stop."""

    def __init__(self):
        self.tick = threading.Semaphore(0)
        self.stopped = threading.Event()
        self.ticked = 0

    def __call__(self, interval):
        self.tick.acquire()
        self.ticked += 1
        return self.stopped.is_set()


def test_heartbeat_no_snapshot_after_final(tmp_path):
    tel = Telemetry()
    met = str(tmp_path / "m.jsonl")
    waiter = _FakeClockWaiter()
    tel._hb_waiter = waiter
    tel.configure(metrics_file=met, heartbeat_secs=9999.0)
    waiter.tick.release()            # one beat
    deadline = time.monotonic() + 5.0
    while waiter.ticked < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)                 # let the beat finish writing
    tel.emit_metrics(kind="final")
    waiter.tick.release()            # a tick racing the shutdown...
    time.sleep(0.1)
    kinds = [r["kind"] for r in read_jsonl(met)]
    # ...must emit nothing: `final` is the stream's terminal record
    assert kinds == ["heartbeat", "final"]
    waiter.stopped.set()
    waiter.tick.release()
    tel.close()


def test_heartbeat_close_is_bounded_with_huge_interval(tmp_path):
    tel = Telemetry()
    met = str(tmp_path / "m.jsonl")
    tel.configure(metrics_file=met, heartbeat_secs=9999.0)
    t0 = time.monotonic()
    tel.close()                      # must not wait out the interval
    assert time.monotonic() - t0 < 3.0
    assert [r["kind"] for r in read_jsonl(met)] == []


def test_heartbeat_tick_after_close_emits_nothing(tmp_path):
    tel = Telemetry()
    met = str(tmp_path / "m.jsonl")
    waiter = _FakeClockWaiter()
    tel._hb_waiter = waiter
    tel.configure(metrics_file=met, heartbeat_secs=9999.0)
    tel._hb_waiter = None
    # close() while the thread is blocked on the fake clock: the
    # bounded join returns, the zombie's next tick sees its own
    # (already-set) stop event and emits nothing
    tel.close()
    waiter.tick.release()
    time.sleep(0.1)
    assert not os.path.exists(met) or \
        [r["kind"] for r in read_jsonl(met)] == []


# ---------------------------------------------------------------------------
# cross-host aggregation (tools/agg.py)
# ---------------------------------------------------------------------------
def _host_stream(path, host, pid, p50, rounds=(1, 2), ts0=1000.0):
    recs = []
    for i, rnd in enumerate(rounds):
        recs.append({
            "ts": ts0 + 10 * i, "kind": "round", "host": host,
            "pid": pid, "proc": 0 if host == "a" else 1, "round": rnd,
            "images_per_sec": 100.0,
            "metrics": {
                "train.step_s": {"count": 8 * rnd, "sum": p50 * 8,
                                 "p50": p50, "p99": p50 * 2},
                "train.loss": 0.5 / rnd,
                "train.images": 256 * rnd,
                "fault.nan_rollback": 0,
            }})
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_agg_merges_two_host_streams_and_flags_straggler(tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _host_stream(a, "a", 1, p50=0.010)
    _host_stream(b, "b", 2, p50=0.050)   # 5x slower: the straggler
    agg = Aggregator([make_source(a), make_source(b)])
    assert agg.poll() == 4
    d = agg.to_dict(now=1020.0)
    assert set(d["hosts"]) == {"a/1", "b/2"}
    assert d["hosts"]["a/1"]["round"] == 2
    assert d["hosts"]["a/1"]["step_p50_ms"] == pytest.approx(10.0)
    assert d["hosts"]["b/2"]["step_p50_ms"] == pytest.approx(50.0)
    assert d["spread"]["ratio"] == pytest.approx(5.0)
    assert "STRAGGLER" in d["hosts"]["b/2"]["flags"]
    assert "STRAGGLER" not in d["hosts"]["a/1"]["flags"]
    table = agg.render(now=1020.0)
    assert "a/1" in table and "b/2" in table
    assert "STRAGGLER" in table
    assert "step p50 spread" in table


def test_agg_tails_appended_records_and_flags_stale(tmp_path):
    a = str(tmp_path / "a.jsonl")
    _host_stream(a, "a", 1, p50=0.010, rounds=(1,))
    src = make_source(a)
    agg = Aggregator([src], stale_secs=30.0)
    agg.poll()
    assert agg.hosts["a/1"].round == 1
    # live tail: append one more round + a torn partial line
    with open(a, "a") as f:
        f.write(json.dumps({
            "ts": 1100.0, "kind": "round", "host": "a", "pid": 1,
            "round": 5, "metrics": {}}) + "\n")
        f.write('{"ts": 1200.0, "kind": "rou')   # torn mid-write
    agg.poll()
    assert agg.hosts["a/1"].round == 5
    assert agg.hosts["a/1"].last_ts == 1100.0
    d = agg.to_dict(now=1400.0)   # 300s quiet > 30s stale threshold
    assert "STALE" in d["hosts"]["a/1"]["flags"]


def test_agg_scrapes_varz_endpoint():
    tel = Telemetry()
    tel.inc("train.images", 512)
    for v in (0.01, 0.02):
        tel.observe("train.step_s", v)
    srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
    try:
        src = make_source(f"http://127.0.0.1:{srv.port}")
        agg = Aggregator([src])
        assert agg.poll() == 1
        (key, host), = agg.hosts.items()
        assert host.steps == 2
        assert host.step_p50_ms == pytest.approx(15.0)
    finally:
        srv.close()
    # endpoint gone: polls degrade to counted errors, state survives
    assert agg.poll() == 0
    assert src.errors == 1
    assert list(agg.hosts) == [key]


def test_make_source_kinds(tmp_path):
    from cxxnet_tpu.tools.agg import _JsonlSource, _VarzSource
    assert isinstance(make_source("x/y.jsonl"), _JsonlSource)
    assert isinstance(make_source("host:9100"), _VarzSource)
    assert isinstance(make_source("http://h:91/varz"), _VarzSource)
    assert make_source("h:9100").url.endswith("/varz")


# ---------------------------------------------------------------------------
# metrics_report: multi-file pod merge
# ---------------------------------------------------------------------------
def test_metrics_report_merges_per_host_files(tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    recs_a = [
        {"ts": 10.0, "kind": "round", "host": "a", "pid": 1,
         "round": 1, "steps": 8,
         "metrics": {"fault.retry": 1}},
        {"ts": 30.0, "kind": "round", "host": "a", "pid": 1,
         "round": 2, "steps": 8,
         "metrics": {"fault.retry": 4}},
        {"ts": 40.0, "kind": "final", "host": "a", "pid": 1,
         "metrics": {"fault.retry": 4}},
    ]
    recs_b = [
        {"ts": 20.0, "kind": "round", "host": "b", "pid": 2,
         "round": 1, "steps": 8,
         "metrics": {"fault.retry": 2}},
        {"ts": 41.0, "kind": "final", "host": "b", "pid": 2,
         "metrics": {"fault.retry": 2}},
    ]
    for path, recs in ((a, recs_a), (b, recs_b)):
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
    agg = aggregate([a, b])
    # merged on ts: a@10, b@20, a@30 - and the per-process counter
    # deltas are not corrupted by the interleave
    assert [(r["proc"], r["round"]) for r in agg["rounds"]] == \
        [("a/1", 1), ("b/2", 1), ("a/1", 2)]
    assert [r["retries"] for r in agg["rounds"]] == [1, 2, 3]
    assert agg["finals"]["a/1"]["fault.retry"] == 4
    assert agg["finals"]["b/2"]["fault.retry"] == 2
    # single-path string form still works (the PR-2 surface)
    assert len(aggregate(a)["rounds"]) == 2


# ---------------------------------------------------------------------------
# off-by-default contract + arm/disarm lifecycle
# ---------------------------------------------------------------------------
def _obs_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("telemetry-")]


def test_arm_observability_all_off_is_a_noop():
    assert telemetry.arm_observability() is None
    assert telemetry.arm_observability(
        metrics_port=None, alert_rules="", alert_cmd="",
        watchdog_secs=0.0) is None
    assert _obs_threads() == []


def test_arm_and_disarm_lifecycle(tmp_path):
    rules = tmp_path / "r.json"
    rules.write_text(json.dumps([
        {"name": "stall", "type": "absence", "beacon": "train.step",
         "for_secs": 30}]))
    srv = telemetry.arm_observability(
        metrics_port=0, alert_rules=str(rules), watchdog_secs=30.0)
    try:
        assert srv is not None and srv.port > 0
        names = _obs_threads()
        assert "telemetry-http" in names
        assert "telemetry-watchdog" in names
        assert "telemetry-alerts" in names
        code, _, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert code == 200
    finally:
        telemetry.disarm_observability()
    deadline = time.monotonic() + 5.0
    while _obs_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert _obs_threads() == []
    with pytest.raises(OSError):
        _get(f"http://127.0.0.1:{srv.port}/healthz", timeout=0.5)


def test_close_tears_down_observability(tmp_path):
    srv = telemetry.arm_observability(metrics_port=0)
    assert srv is not None
    telemetry.close()
    assert _obs_threads() == []


def test_watchdog_only_arming_adds_no_per_step_cost():
    """watchdog_secs (or alert_rules) alone must NOT flip `enabled` -
    that would latch the trainer's per-step device syncs and the
    diagnostic would perturb the thing it diagnoses. Forensics run on
    beacons (unconditional) + the span ring, which fills whenever
    span records are emitted."""
    telemetry.arm_observability(watchdog_secs=60.0)
    try:
        assert not telemetry.enabled()
    finally:
        telemetry.disarm_observability()


def test_span_events_fill_recent_ring():
    tel = Telemetry()
    tel.configure()  # no sink: event() itself is a no-op write...
    # ...but the trainer's direct span-event form must still feed the
    # ring whenever it fires (it is gated on `enabled` at the caller)
    tel.event("span", name="train.step", secs=0.01, round=1)
    tel.event("span", name="train.data", secs=0.002)
    assert [s["name"] for s in tel.recent_spans()] == \
        ["train.step", "train.data"]
    # span() contexts land exactly once (no double-append via event)
    tel2 = Telemetry()
    tel2._http = object()  # stand-in: any armed consumer
    with tel2.span("round"):
        pass
    assert [s["name"] for s in tel2.recent_spans()] == ["round"]


def test_beacons_are_thread_safe():
    n_threads, per_thread = 8, 500

    def mark():
        for _ in range(per_thread):
            telemetry.beacon("serve.batch")

    threads = [threading.Thread(target=mark) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    count, _ = telemetry.beacons()["serve.batch"]
    assert count == n_threads * per_thread


def test_beacon_accumulates_count_and_timestamp():
    t0 = time.monotonic()
    telemetry.beacon("train.step")
    telemetry.beacon("train.step", 4)
    b = telemetry.beacons()
    count, ts = b["train.step"]
    assert count == 5
    assert t0 <= ts <= time.monotonic()


def test_cli_run_with_metrics_port_live_scrape(tmp_path, capfd):
    """End-to-end: a real training run with the plane armed serves
    live scrapes, and the server dies with the run."""
    import socket

    from test_cli import write_conf, write_synth_mnist

    from cxxnet_tpu.main import LearnTask

    tr = write_synth_mnist(tmp_path, n=256, seed=0, prefix="train")
    te = write_synth_mnist(tmp_path, n=64, seed=1, prefix="test")
    conf = write_conf(tmp_path, *tr, *te,
                      extra="num_round = 2\nmax_round = 2\n")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    got = {"metrics": None, "healthz": None, "varz": None}
    stop = threading.Event()

    def poll():
        base = f"http://127.0.0.1:{port}"
        while not stop.wait(0.05):
            try:
                code, ctype, body = _get(base + "/metrics",
                                         timeout=1.0)
                if code == 200:
                    got["metrics"] = (ctype, body.decode())
                code, _, _ = _get(base + "/healthz", timeout=1.0)
                got["healthz"] = code
                _, _, body = _get(base + "/varz", timeout=1.0)
                got["varz"] = json.loads(body)
            except (OSError, ValueError):
                continue

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        rc = LearnTask().run([conf, f"metrics_port={port}",
                              "watchdog_secs=60"])
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert rc == 0
    capfd.readouterr()
    assert got["metrics"] is not None, "no live scrape landed"
    ctype, body = got["metrics"]
    assert ctype == PROM_CONTENT_TYPE
    assert validate_exposition(body) == []
    assert "cxxnet_train_step_s" in body
    assert got["healthz"] == 200
    assert got["varz"]["kind"] == "varz"
    # run over: plane torn down with it
    assert _obs_threads() == []
    with pytest.raises(OSError):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=0.5)


def test_cli_unarmed_run_spawns_no_observability(tmp_path, capfd):
    """Off-by-default contract: no obs keys -> no plane threads, no
    socket, and the CLI output carries no observability text."""
    from test_cli import write_conf, write_synth_mnist

    from cxxnet_tpu.main import LearnTask

    tr = write_synth_mnist(tmp_path, n=128, seed=0, prefix="train")
    te = write_synth_mnist(tmp_path, n=64, seed=1, prefix="test")
    conf = write_conf(tmp_path, *tr, *te,
                      extra="num_round = 1\nmax_round = 1\n")
    rc = LearnTask().run([conf])
    assert rc == 0
    assert _obs_threads() == []
    out, err = capfd.readouterr()
    for needle in ("watchdog", "alert", "metrics", "healthz"):
        assert needle not in out
        assert needle not in err


def test_schema_recognizes_observability_keys():
    from cxxnet_tpu.analysis import schema
    reg = schema.get_registry(refresh=True)
    for key in ("metrics_port", "alert_rules", "alert_cmd",
                "watchdog_secs"):
        assert reg.recognizes(key), key
    assert reg.suggest("metrics_portt") == "metrics_port"
