"""Tests for the numpy wrapper API (wrapper/cxxnet.py parity)."""

import gzip
import struct

import numpy as np
import pytest

from cxxnet_tpu.wrapper import DataIter, Net, train

NET_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1] = tanh
layer[+1] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,6
metric = error
silent = 1
"""


def synth(n=128, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 1, 1, 6).astype(np.float32)
    y = (x.reshape(n, 6).sum(axis=1) > 0).astype(np.float32)
    return x, y


def test_net_update_numpy_batches():
    x, y = synth()
    net = Net(dev="cpu", cfg=NET_CFG)
    net.set_param("batch_size", 32)
    net.set_param("eta", 0.5)
    net.init_model()
    for r in range(10):
        net.start_round(r)
        for i in range(0, 128, 32):
            net.update(x[i:i + 32], y[i:i + 32])
    pred = net.predict(x[:32])
    assert (pred == y[:32]).mean() > 0.9


def test_net_label_validation():
    net = Net(dev="cpu", cfg=NET_CFG)
    net.set_param("batch_size", 4)
    net.set_param("eta", 0.1)
    net.init_model()
    x, y = synth(4)
    with pytest.raises(ValueError):
        net.update(x, None)
    with pytest.raises(ValueError):
        net.update(x, y[:2])
    with pytest.raises(ValueError):
        net.update(x.reshape(4, 6), y)  # not 4-d


def test_get_set_weight_roundtrip():
    net = Net(dev="cpu", cfg=NET_CFG)
    net.set_param("batch_size", 4)
    net.init_model()
    w = net.get_weight("fc1", "wmat")
    assert w.shape == (16, 6)
    net.set_weight(np.ones_like(w), "fc1", "wmat")
    np.testing.assert_allclose(net.get_weight("fc1", "wmat"), 1.0)


def test_train_convenience():
    x, y = synth(256)
    net = train(NET_CFG, x, y, num_round=8,
                param={"eta": 0.5, "momentum": 0.9}, batch_size=32,
                dev="cpu")
    pred = net.predict(x[:32])
    assert (pred == y[:32]).mean() > 0.85


def _write_mnist_gz(tmp_path, images, labels):
    """idx-format .gz fixture shared by the DataIter tests."""
    n, rows, cols = images.shape
    img_path, lbl_path = str(tmp_path / "i.gz"), str(tmp_path / "l.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, rows, cols))
        f.write(images.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path


def test_wrapper_dataiter(tmp_path):
    n, rows, cols = 64, 4, 4
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, size=(n, rows, cols), dtype=np.uint8)
    labels = rng.randint(0, 2, size=n, dtype=np.uint8)
    img_path, lbl_path = _write_mnist_gz(tmp_path, images, labels)

    it = DataIter(f"""
iter = mnist
path_img = "{img_path}"
path_label = "{lbl_path}"
batch_size = 16
silent = 1
""")
    with pytest.raises(RuntimeError):
        it.get_data()  # head state
    assert it.next()
    assert it.get_data().shape == (16, 1, 1, 16)
    assert it.get_label().shape == (16, 1)
    cnt = 1
    while it.next():
        cnt += 1
    assert cnt == 4
    it.before_first()
    assert it.next()


def test_wrapper_sequence_model():
    """The numpy wrapper drives the sequence family end to end."""
    cfg = """
netconfig=start
layer[0->1] = layernorm:ln1
layer[1->2] = attention:att1
  nhead = 2
  causal = 1
layer[2->3] = flatten
layer[3->4] = fullc:head
  nhidden = 4
layer[4->4] = softmax
netconfig=end
input_shape = 1,4,8
batch_size = 8
eta = 0.05
random_type = xavier
silent = 1
"""
    net = Net(dev="cpu", cfg=cfg)
    net.init_model()
    rng = np.random.RandomState(2)
    x = rng.randn(8, 1, 4, 8).astype(np.float32)
    y = rng.randint(0, 4, 8).astype(np.float32)
    for _ in range(3):
        net.update(x, y)
    pred = net.predict(x)
    assert pred.shape == (8,)
    assert np.isfinite(pred).all()


def test_net_drives_dataiter_batches(tmp_path):
    """Net.update/predict/extract with a DataIter argument (the
    reference cxxnet.py accepts an iterator everywhere a numpy array
    is accepted) passes the iterator's current batch (DataIter.value
    is a property); previously untested, so drive every
    DataIter-accepting method."""
    n = 32
    rng = np.random.RandomState(7)
    images = rng.randint(0, 255, size=(n, 4, 4)).astype(np.uint8)
    labels = rng.randint(0, 2, size=n).astype(np.uint8)
    img_path, lbl_path = _write_mnist_gz(tmp_path, images, labels)

    def make_iter():
        return DataIter(f"""
iter = mnist
path_img = "{img_path}"
path_label = "{lbl_path}"
batch_size = 16
input_flat = 1
silent = 1
""")

    cfg = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 16
eta = 0.1
silent = 1
"""
    net = Net(dev="cpu", cfg=cfg)
    net.init_model()
    it = make_iter()
    while it.next():
        net.update(it)
    it.before_first()
    assert it.next()
    pred = net.predict(it)
    assert pred.shape == (16,)
    dist = net.predict_dist(it)
    assert dist.shape == (16, 8)  # fc1 nhidden=8 feeds softmax
    feat = net.extract(it, "top[-2]")  # pre-softmax node
    assert feat.shape[0] == 16


def test_train_staged_equals_streamed(monkeypatch):
    """train()'s device-resident staging (small datasets) must be
    trajectory-identical to the streamed path it replaces."""
    import cxxnet_tpu.wrapper as W
    rng = np.random.RandomState(3)
    w = rng.randn(6)
    x = rng.randn(64, 1, 1, 6).astype(np.float32)
    y = (x.reshape(64, 6) @ w > 0).astype(np.float32)
    param = {"eta": 0.3, "momentum": 0.9}
    # spy: the equivalence check is vacuous unless the staged path
    # actually ran (train() falls back to streaming on stage errors)
    calls = []
    orig = W.NetTrainer.stage_batch

    def spy(self, b):
        calls.append(1)
        return orig(self, b)

    monkeypatch.setattr(W.NetTrainer, "stage_batch", spy)
    net_staged = W.train(NET_CFG, x, y, num_round=3, param=param,
                         batch_size=16, dev="cpu")
    # exactly n_batches calls proves PRE-staging: the streamed path
    # would stage per update (n_batches x num_round = 12 calls)
    assert len(calls) == 4, f"expected 4 pre-staging calls, got {len(calls)}"
    monkeypatch.setattr(W, "_STAGE_BYTES_LIMIT", 0)  # force streaming
    net_stream = W.train(NET_CFG, x, y, num_round=3, param=param,
                         batch_size=16, dev="cpu")
    import jax
    for a, b in zip(
            jax.tree_util.tree_leaves(net_staged._net.state["params"]),
            jax.tree_util.tree_leaves(net_stream._net.state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
