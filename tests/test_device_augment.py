"""Device-side augmentation (ops/augment_jit.py + device_augment=1).

Ground truth is the HOST pipeline (io/augment.py AugmentIterator): the
device path changes where the arithmetic runs, never the math - the
deterministic variants must match the host output exactly, and the
random variant must produce genuine subwindows of the input.
"""

import numpy as np
import pytest

import jax

from cxxnet_tpu.io.augment import AugmentIterator
from cxxnet_tpu.io.data import DataBatch, DataInst
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.ops.augment_jit import make_device_augment
from cxxnet_tpu.utils.config import parse_config_string


class _Base:
    def set_param(self, name, val):
        pass


def _host_augment(raw, *, shape, meanimg=None, mean_value="",
                  scale=1.0, mirror=0):
    """One instance through the real host pipeline (deterministic)."""
    it = AugmentIterator(_Base())
    it.set_param("input_shape", ",".join(str(t) for t in shape))
    if mean_value:
        it.set_param("mean_value", mean_value)
    it.set_param("scale", str(scale))
    it.set_param("mirror", str(mirror))
    if meanimg is not None:
        it.meanimg = meanimg
    it._set_data(DataInst(index=0, data=raw,
                          label=np.zeros(1, np.float32)))
    return it.value().data


@pytest.mark.parametrize("mean_kind", ["none", "crop", "raw", "values"])
@pytest.mark.parametrize("mirror", [0, 1])
def test_deterministic_matches_host(mean_kind, mirror):
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, (3, 12, 10)).astype(np.float32)
    shape = (3, 8, 6)
    kw = {}
    mean_crop = rng.randn(3, 8, 6).astype(np.float32)
    mean_raw = rng.randn(3, 12, 10).astype(np.float32)
    if mean_kind == "crop":
        kw["meanimg"] = mean_crop
    elif mean_kind == "raw":
        kw["meanimg"] = mean_raw
    elif mean_kind == "values":
        kw["mean_value"] = "1.5,2.5,3.5"
    ref = _host_augment(raw, shape=shape, scale=0.25, mirror=mirror,
                        **kw)

    fn = make_device_augment(
        shape,
        mean_loader=((lambda: kw["meanimg"]) if "meanimg" in kw
                     else None),
        mean_values=((1.5, 2.5, 3.5) if mean_kind == "values" else None),
        scale=0.25, mirror=mirror)
    out = fn(raw[None], jax.random.PRNGKey(0), train=False)
    np.testing.assert_allclose(np.asarray(out[0]), ref,
                               rtol=1e-6, atol=1e-5)


def test_contrast_without_mean_is_skipped_like_host():
    """Host-pipeline quirk: contrast/illumination only apply on the
    mean-subtracting branches (augment.py's no-mean branch crops
    without them). The device path must match, not silently 'fix' it."""
    rng = np.random.RandomState(4)
    raw = rng.randn(4, 3, 8, 8).astype(np.float32)
    fn = make_device_augment((3, 6, 6), max_random_contrast=0.5,
                             max_random_illumination=9.0)
    out = np.asarray(fn(raw, jax.random.PRNGKey(1), train=True))
    np.testing.assert_allclose(out, raw[:, :, 1:7, 1:7], rtol=1e-6)
    # an ALL-ZERO mean_value is OFF on the host path too (the branch
    # tests mean_r/g/b > 0), so jitter still must not apply
    fn0 = make_device_augment((3, 6, 6), mean_values=(0.0, 0.0, 0.0),
                              max_random_illumination=9.0)
    out0 = np.asarray(fn0(raw, jax.random.PRNGKey(1), train=True))
    np.testing.assert_allclose(out0, raw[:, :, 1:7, 1:7], rtol=1e-6)
    # with a real mean configured, the jitter DOES apply
    fn2 = make_device_augment((3, 6, 6), mean_values=(1.0, 2.0, 3.0),
                              max_random_illumination=9.0)
    out2 = np.asarray(fn2(raw, jax.random.PRNGKey(1), train=True))
    assert not np.allclose(out2, raw[:, :, 1:7, 1:7])


def test_divideby_and_fixed_crop_reach_device_path():
    """divideby is the reciprocal-scale alias and crop_y/x_start are
    fixed-crop overrides - both must survive into the device spec
    instead of being silently dropped."""
    t = NetTrainer()
    for k, v in parse_config_string(_DAUG_NET):
        t.set_param(k, v)
    t.set_param("device_augment", "1")
    t.set_param("divideby", "256")
    t.set_param("crop_y_start", "0")
    t.set_param("crop_x_start", "2")
    t.set_param("rand_crop", "1")  # fixed offsets beat the random draw
    t.init_model()
    rng = np.random.RandomState(6)
    rb = DataBatch(
        data=rng.randint(0, 256, (8, 1, 9, 9)).astype(np.uint8),
        label=rng.randint(0, 4, size=(8, 1)).astype(np.float32))
    assert float(t._daug_cfg["scale"]) == 1.0 / 256
    fn = t._augment_fn is None  # built at _compile
    t.update(rb)
    out = np.asarray(t._augment_fn(
        rb.data, jax.random.PRNGKey(0), train=True))
    np.testing.assert_allclose(
        out, rb.data[:, :, 0:6, 2:8].astype(np.float32) / 256,
        rtol=1e-6)
    assert not fn or t._augment_fn is not None


def test_cli_eval_block_does_not_clobber_train_augment_spec():
    """main.py feeds conf pairs to the trainer; eval/pred iterator
    blocks are iterator-scoped and must NOT override the train block's
    augment keys (a flat last-writer-wins scan would take the eval
    values - e.g. silently disabling rand_crop for training)."""
    from cxxnet_tpu.main import LearnTask
    conf = """
data = train
iter = mnist
  rand_crop = 1
  scale = 0.5
iter = end
eval = test
iter = mnist
  rand_crop = 0
  scale = 1.0
iter = end
batch_size = 4
"""
    task = LearnTask()
    for k, v in parse_config_string(conf + _DAUG_NET):
        task.set_param(k, v)
    net = task._create_net()
    assert net._daug_cfg["rand_crop"] == "1"
    assert net._daug_cfg["scale"] == "0.5"


def test_random_crops_are_subwindows():
    """Every train-mode output must be an exact subwindow of its input
    (scale 1, no mean, no mirror) and the offsets must vary."""
    rng = np.random.RandomState(1)
    raw = rng.randn(8, 1, 9, 9).astype(np.float32)
    fn = make_device_augment((1, 4, 4), rand_crop=1)
    out = np.asarray(fn(raw, jax.random.PRNGKey(3), train=True))
    found = []
    for i in range(8):
        hit = None
        for yy in range(6):
            for xx in range(6):
                if np.array_equal(raw[i, :, yy:yy + 4, xx:xx + 4],
                                  out[i]):
                    hit = (yy, xx)
        assert hit is not None, f"sample {i} is not a subwindow"
        found.append(hit)
    assert len(set(found)) > 1, "offsets never varied"


def test_eval_mode_is_center_crop_and_deterministic():
    rng = np.random.RandomState(2)
    raw = rng.randn(2, 3, 10, 10).astype(np.float32)
    fn = make_device_augment((3, 4, 4), rand_crop=1, rand_mirror=1,
                             max_random_contrast=0.3)
    a = np.asarray(fn(raw, jax.random.PRNGKey(0), train=False))
    b = np.asarray(fn(raw, jax.random.PRNGKey(9), train=False))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(a, raw[:, :, 3:7, 3:7], rtol=1e-6)


def test_uint8_input_matches_f32():
    rng = np.random.RandomState(3)
    raw8 = rng.randint(0, 256, (2, 3, 8, 8)).astype(np.uint8)
    fn = make_device_augment((3, 6, 6), mean_values=(1.0, 2.0, 3.0),
                             scale=1 / 255.0)
    a = np.asarray(fn(raw8, jax.random.PRNGKey(0), train=False))
    b = np.asarray(fn(raw8.astype(np.float32), jax.random.PRNGKey(0),
                      train=False))
    np.testing.assert_array_equal(a, b)


def test_iterator_passthrough_and_affine_rejection():
    it = AugmentIterator(_Base())
    it.set_param("input_shape", "3,4,4")
    it.set_param("device_augment", "1")
    it.set_param("mean_value", "1,2,3")  # must NOT be applied on host
    raw = np.arange(3 * 6 * 6, dtype=np.uint8).reshape(3, 6, 6)
    it._set_data(DataInst(index=7, data=raw,
                          label=np.zeros(1, np.float32)))
    out = it.value()
    np.testing.assert_array_equal(out.data, raw)
    assert out.data.dtype == np.uint8

    it.set_param("max_rotate_angle", "10")
    with pytest.raises(ValueError, match="affine"):
        it._set_data(DataInst(index=8, data=raw,
                              label=np.zeros(1, np.float32)))


def test_batch_adapter_preserves_uint8():
    from cxxnet_tpu.io.iter_batch import BatchAdaptIterator

    class ListBase:
        def __init__(self, insts):
            self.insts, self.i = insts, -1

        def set_param(self, name, val):
            pass

        def init(self):
            pass

        def before_first(self):
            self.i = -1

        def next(self):
            self.i += 1
            return self.i < len(self.insts)

        def value(self):
            return self.insts[self.i]

    insts = [DataInst(index=i,
                      data=np.full((1, 2, 2), i, np.uint8),
                      label=np.asarray([i], np.float32))
             for i in range(4)]
    it = BatchAdaptIterator(ListBase(insts))
    it.set_param("batch_size", "4")
    it.init()
    it.before_first()
    assert it.next()
    assert it.value().data.dtype == np.uint8


_DAUG_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  nchannel = 4
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 4
layer[4->4] = softmax
netconfig=end
input_shape = 1,6,6
random_type = xavier
eta = 0.05
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
metric = error
"""


def _raw_batches(n=3, b=8, seed=5):
    rng = np.random.RandomState(seed)
    return [DataBatch(
        data=rng.randint(0, 256, (b, 1, 9, 9)).astype(np.uint8),
        label=rng.randint(0, 4, size=(b, 1)).astype(np.float32))
        for _ in range(n)]


def _make(extra=""):
    t = NetTrainer()
    for k, v in parse_config_string(_DAUG_NET + extra):
        t.set_param(k, v)
    t.init_model()
    return t


def test_trainer_device_augment_matches_host_pipeline():
    """Deterministic settings (center crop, no mirror): the device-
    augment trainer must follow the exact trajectory of a standard
    trainer fed host-augmented batches."""
    t_dev = _make("device_augment = 1\nscale = 0.0039\n"
                  "mean_value = 10,20,30\n")
    t_host = _make()
    for rb in _raw_batches():
        t_dev.update(rb)
        host = np.stack([
            _host_augment(im.astype(np.float32), shape=(1, 6, 6),
                          scale=0.0039)
            for im in rb.data])
        # mean_value with c=1 is a no-op on both paths (b,g,r needs 3
        # channels); host pipeline above applies crop+scale only
        t_host.update(DataBatch(data=host, label=rb.label))
    a = np.asarray(t_dev.state["params"]["fc1"]["wmat"])
    b = np.asarray(t_host.state["params"]["fc1"]["wmat"])
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_trainer_device_augment_random_trains_and_evals():
    t = _make("device_augment = 1\nrand_crop = 1\nrand_mirror = 1\n"
              "scale = 0.0039\n")
    bs = _raw_batches()
    for rb in bs:
        t.update(rb)
    leaves = jax.tree.leaves(t.state["params"])
    assert all(bool(np.isfinite(np.asarray(p)).all()) for p in leaves)
    # eval path: deterministic center crop - predictions reproducible
    p1 = t.predict(bs[0])
    p2 = t.predict(bs[0])
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    assert p1.shape == (8,)


def test_mirror_flag_forces_every_sample_under_rand_mirror():
    """Host parity: do_mirror = (rand_mirror and u<0.5) or mirror==1
    (io/augment.py:309-310) - mirror=1 must win over the random draw,
    not be ignored by it."""
    rng = np.random.RandomState(7)
    raw = rng.randn(16, 3, 8, 8).astype(np.float32)
    fn = make_device_augment((3, 8, 8), rand_mirror=1, mirror=1)
    out = np.asarray(fn(raw, jax.random.PRNGKey(3), train=True))
    np.testing.assert_allclose(out, raw[:, :, :, ::-1], rtol=1e-6)


def test_mean_value_beats_mean_image_like_host():
    """Host precedence: the per-channel mean_value branch is checked
    FIRST (io/augment.py:313); a configured mean image must not shadow
    it on the device path."""
    rng = np.random.RandomState(8)
    raw = rng.randint(0, 256, (2, 3, 6, 6)).astype(np.float32)
    meanimg = rng.randn(3, 6, 6).astype(np.float32)
    fn = make_device_augment((3, 6, 6), mean_loader=lambda: meanimg,
                             mean_values=(1.0, 2.0, 3.0))
    out = np.asarray(fn(raw, jax.random.PRNGKey(0), train=False))
    ref = raw - np.asarray([3.0, 2.0, 1.0],
                           np.float32)[None, :, None, None]
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_out_of_range_fixed_crop_fails_loudly():
    """dynamic_slice clamps; a misconfigured crop_y_start must raise
    (the host path errors on the short slice), not train shifted."""
    raw = np.zeros((1, 3, 10, 10), np.float32)
    fn = make_device_augment((3, 8, 8), crop_y_start=5)
    with pytest.raises(ValueError, match="crop_y_start"):
        fn(raw, jax.random.PRNGKey(0), train=True)


def test_cli_rejects_divergent_eval_block_under_device_augment(tmp_path):
    """device_augment bakes ONE normalization spec into the step; an
    eval block with a different image_mean/scale would silently be
    normalized with the train spec - the CLI must reject it."""
    from cxxnet_tpu.main import LearnTask
    conf = tmp_path / "c.conf"
    conf.write_text("""
device_augment = 1
netconfig=start
layer[0->1] = flatten
layer[1->2] = fullc
  nhidden = 4
layer[2->2] = softmax
netconfig=end
input_shape = 1,6,6
batch_size = 4
eta = 0.1
data = train
iter = mnist
  scale = 1.0
iter = end
eval = test
iter = mnist
  scale = 0.5
iter = end
""")
    task = LearnTask()
    task.set_param("silent", "1")
    for k, v in __import__(
            "cxxnet_tpu.utils.config",
            fromlist=["parse_config_file"]).parse_config_file(str(conf)):
        task.set_param(k, v)
    with pytest.raises(ValueError, match="scale"):
        task._create_net()


def _mk_task(conf_text, task="train"):
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.utils.config import parse_config_string
    t = LearnTask()
    t.set_param("silent", "1")
    for k, v in parse_config_string(conf_text):
        t.set_param(k, v)
    t.set_param("task", task)
    return t


_DAUG_CONF_HEAD = """
device_augment = 1
netconfig=start
layer[0->1] = flatten
layer[1->2] = fullc
  nhidden = 4
layer[2->2] = softmax
netconfig=end
input_shape = 1,6,6
batch_size = 4
eta = 0.1
"""


def test_equivalent_block_spec_is_not_rejected():
    """An eval block that restates the compiled defaults (mirror=0) or
    the scale via its divideby alias is IDENTICAL, not divergent - the
    canonicalized comparison must accept it."""
    t = _mk_task(_DAUG_CONF_HEAD + """
scale = 0.00390625
data = train
iter = mnist
iter = end
eval = test
iter = mnist
  mirror = 0
  divideby = 256
iter = end
""")
    t._create_net()  # must not raise


def test_unused_block_divergence_ignored_for_other_task():
    """task=pred never instantiates eval iterators; a divergent eval
    block must not abort a prediction run."""
    t = _mk_task(_DAUG_CONF_HEAD + """
pred = out.txt
iter = mnist
iter = end
eval = test
iter = mnist
  scale = 0.5
iter = end
""", task="pred")
    t._create_net()  # must not raise


def test_block_only_device_augment_fails_loudly():
    """device_augment=1 ONLY inside an eval block: the trainer compiles
    WITHOUT the in-step augment while that iterator stages raw pixels -
    silently garbage eval metrics; must raise instead."""
    t = _mk_task("""
netconfig=start
layer[0->1] = flatten
layer[1->2] = fullc
  nhidden = 4
layer[2->2] = softmax
netconfig=end
input_shape = 1,6,6
batch_size = 4
eta = 0.1
data = train
iter = mnist
iter = end
eval = test
iter = mnist
  device_augment = 1
iter = end
""")
    with pytest.raises(ValueError, match="device_augment mismatch"):
        t._create_net()


def test_pred_block_keys_do_not_clobber_train_net():
    """Iterator-scoped pred-block keys (batch_size) must not reach the
    trainer under task=train - the loss scale is 1/(batch_size *
    update_period), so a clobber silently mis-scales gradients."""
    t = _mk_task(_DAUG_CONF_HEAD + """
data = train
iter = mnist
iter = end
pred = out.txt
iter = mnist
  batch_size = 100
iter = end
""")
    net = t._create_net()
    assert net.batch_size == 4


def test_pred_block_omitting_daug_key_ok_under_train():
    """Under task=train the pred iterator is never instantiated; a
    pred block that merely OMITS a data-block daug key must not abort
    training (the compiled spec is correct)."""
    t = _mk_task(_DAUG_CONF_HEAD + """
data = train
iter = mnist
  divideby = 256
iter = end
pred = out.txt
iter = mnist
iter = end
""")
    t._create_net()  # must not raise
