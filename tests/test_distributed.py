"""Multi-controller distributed training (parallel/distributed.py).

The invariant (the reason the sync-SPMD design can replace the async
parameter server): N worker processes over the same global batch train
to weights identical to a single process - the AllReduce makes gradient
math placement-invariant. Exercised with 2 real OS processes on the CPU
backend via the gloo cross-process collectives (the "local PS stands in
for dist PS" proxy of SURVEY.md par.4.6, upgraded to real processes).
"""

import os
import socket
import subprocess
import sys

import numpy as np

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["CXN_TEST_REPO"])
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

NET = os.environ.get("CXN_TEST_NET") or '''
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,8
random_type = xavier
eta = 0.1
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
param_server = dist
'''
SHAPE = tuple(int(x) for x in
              os.environ.get("CXN_TEST_SHAPE", "1,1,8").split(","))
WKEY = os.environ.get("CXN_TEST_WKEY", "fc1")

t = NetTrainer()
for k, v in parse_config_string(NET):
    t.set_param(k, v)
for k, v in parse_config_string(os.environ.get("CXN_TEST_EXTRA", "")):
    t.set_param(k, v)
t.init_model()

nproc = jax.process_count()
rank = jax.process_index()
assert nproc == int(os.environ["CXN_NUM_WORKER"]), nproc
# rows this process must feed: batch/nproc on a data mesh, the FULL
# batch when the batch dim is replicated across processes (seq mesh)
local_b = t._local_batch
nclass = 4

rng = np.random.RandomState(42)
for step in range(5):
    data = rng.randn(8, *SHAPE).astype(np.float32)    # global batch
    label = rng.randint(0, nclass, size=(8, 1)).astype(np.float32)
    lo = (rank * local_b) % 8
    t.update(DataBatch(data=data[lo:lo + local_b],
                       label=label[lo:lo + local_b]))

bad = t.check_weights()
assert bad == [], bad
w, _ = t.get_weight(WKEY, "wmat")
out = os.environ["CXN_TEST_OUT"]
np.save(f"{out}.{rank}.npy", w)
print("worker", rank, "done", flush=True)
"""

SEQ_NET = """
netconfig=start
layer[0->1] = pos_embed:pe
layer[1->2] = layernorm:ln1
layer[2->3] = attention:att1
  nhead = 2
  causal = 1
layer[3->4] = flatten
layer[4->5] = fullc:head
  nhidden = 4
layer[5->5] = softmax
netconfig=end
input_shape = 1,4,8
random_type = xavier
eta = 0.05
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
param_server = dist
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference(tmp_path, net=None, shape=(1, 1, 8),
                              wkey="fc1", mesh="data:1"):
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = net or WORKER.split("or '''")[1].split("'''")[0]
    cfg = cfg.replace("param_server = dist", "")
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.set_param("mesh", mesh)
    t.init_model()
    rng = np.random.RandomState(42)
    for step in range(5):
        data = rng.randn(8, *shape).astype(np.float32)
        label = rng.randint(0, 4, size=(8, 1)).astype(np.float32)
        t.update(DataBatch(data=data, label=label))
    w, _ = t.get_weight(wkey, "wmat")
    return w


def _spawn_workers(argv, extra_env=None, nproc=2):
    """Launch nproc coordinator-connected worker processes and return
    their outputs; kills survivors if one times out (a dead peer leaves
    the rest blocked inside collectives)."""
    port = _free_port()
    procs = []
    for rank in range(nproc):
        env = {k: v for k, v in os.environ.items() if "axon" not in v}
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        # one CPU device per worker process (a 2-host x 1-chip slice;
        # the pytest parent's 8-virtual-device XLA_FLAGS must not leak)
        env["XLA_FLAGS"] = ""
        env["CXN_COORDINATOR"] = f"127.0.0.1:{port}"
        env["CXN_NUM_WORKER"] = str(nproc)
        env["CXN_WORKER_RANK"] = str(rank)
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=300)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    return outs


def _run_two_process(tmp_path, extra_cfg="", net="", shape="1,1,8",
                     wkey="fc1"):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out_prefix = str(tmp_path / "w")
    _spawn_workers(
        [sys.executable, str(script)],
        {"CXN_TEST_REPO": REPO, "CXN_TEST_OUT": out_prefix,
         "CXN_TEST_EXTRA": extra_cfg, "CXN_TEST_NET": net,
         "CXN_TEST_SHAPE": shape, "CXN_TEST_WKEY": wkey})
    w0 = np.load(f"{out_prefix}.0.npy")
    w1 = np.load(f"{out_prefix}.1.npy")
    return w0, w1


def test_two_process_training_matches_single(tmp_path):
    w0, w1 = _run_two_process(tmp_path)
    np.testing.assert_array_equal(w0, w1)  # cross-process identical
    ref = _single_process_reference(tmp_path)
    np.testing.assert_allclose(w0, ref, rtol=1e-5, atol=1e-6)


def test_two_process_zero1_matches_single(tmp_path):
    """shard_optimizer=1 across 2 real processes: updater state shards
    over devices owned by DIFFERENT processes (put_global_full path +
    GSPMD-partitioned update); training math is unchanged."""
    w0, w1 = _run_two_process(tmp_path,
                              extra_cfg="shard_optimizer = 1\n")
    np.testing.assert_array_equal(w0, w1)
    ref = _single_process_reference(tmp_path)
    np.testing.assert_allclose(w0, ref, rtol=1e-5, atol=1e-6)


def test_two_process_seq_parallel_matches_single(tmp_path):
    """Ring attention with the 'seq' axis spanning 2 REAL processes:
    the batch dim is replicated across hosts (each feeds the full
    batch - trainer._local_batch is mesh-aware) while the sequence dim
    and its ppermute K/V rotation cross the process boundary. Weights
    must match the single-process blockwise run exactly."""
    w0, w1 = _run_two_process(
        tmp_path, extra_cfg="mesh = data:1,seq:2\n", net=SEQ_NET,
        shape="1,4,8", wkey="att1")
    np.testing.assert_array_equal(w0, w1)
    ref = _single_process_reference(tmp_path, net=SEQ_NET,
                                    shape=(1, 4, 8), wkey="att1")
    np.testing.assert_allclose(w0, ref, rtol=1e-5, atol=1e-6)


def test_cli_two_process_seq_parallel(tmp_path):
    """The FULL CLI path (main.py round loop + iterator auto-wiring)
    across 2 real processes on a seq mesh: main must NOT data-shard the
    iterators when the batch dim is replicated across hosts (each
    worker feeds the same full batch), and the per-round
    test_on_server consistency check must pass. Regression for the
    mesh-unaware batch/nproc auto-sharding that silently fed each
    worker different data."""
    import gzip
    import struct
    rng = np.random.RandomState(3)
    n = 64
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    images = rng.randint(0, 255, size=(n, 28, 28)).astype(np.uint8)
    with gzip.open(tmp_path / "img.gz", "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(tmp_path / "lbl.gz", "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())
    conf = tmp_path / "seq.conf"
    conf.write_text(f"""
data = train
iter = mnist
    path_img = "{tmp_path}/img.gz"
    path_label = "{tmp_path}/lbl.gz"
    input_flat = 0
iter = end
netconfig=start
layer[0->1] = layernorm:ln1
layer[1->2] = attention:att1
  nhead = 4
  causal = 1
layer[2->3] = flatten
layer[3->4] = fullc:head
  nhidden = 10
layer[4->4] = softmax
netconfig=end
input_shape = 1,28,28
random_type = xavier
batch_size = 32
eta = 0.05
momentum = 0.9
num_round = 1
max_round = 1
metric = error
save_model = 0
test_on_server = 1
param_server = dist
mesh = data:1,seq:2
silent = 1
""")
    outs = _spawn_workers(
        [sys.executable, "-m", "cxxnet_tpu.main", str(conf)])
    for out in outs:
        assert "diverge" not in out, out
    # both workers saw the same data: identical train-error lines
    lines = [next(l for l in out.splitlines() if "train-error" in l)
             for out in outs]
    assert lines[0] == lines[1], lines


def test_check_replicated_clean():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = WORKER.split("or '''")[1].split("'''")[0]
    cfg = cfg.replace("param_server = dist", "")
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.set_param("mesh", f"data:{min(8, len(jax.devices()))}")
    t.init_model()
    assert t.check_weights() == []
