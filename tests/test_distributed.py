"""Multi-controller distributed training (parallel/distributed.py).

The invariant (the reason the sync-SPMD design can replace the async
parameter server): N worker processes over the same global batch train
to weights identical to a single process - the AllReduce makes gradient
math placement-invariant. Exercised with 2 real OS processes on the CPU
backend via the gloo cross-process collectives (the "local PS stands in
for dist PS" proxy of SURVEY.md par.4.6, upgraded to real processes).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from cxxnet_tpu.parallel import distributed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["CXN_TEST_REPO"])
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

NET = '''
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,8
random_type = xavier
eta = 0.1
momentum = 0.9
batch_size = 8
silent = 1
eval_train = 0
param_server = dist
'''

t = NetTrainer()
for k, v in parse_config_string(NET):
    t.set_param(k, v)
for k, v in parse_config_string(os.environ.get("CXN_TEST_EXTRA", "")):
    t.set_param(k, v)
t.init_model()

nproc = jax.process_count()
rank = jax.process_index()
assert nproc == int(os.environ["CXN_NUM_WORKER"]), nproc
local_b = 8 // nproc

rng = np.random.RandomState(42)
for step in range(5):
    data = rng.randn(8, 1, 1, 8).astype(np.float32)   # global batch
    label = rng.randint(0, 4, size=(8, 1)).astype(np.float32)
    lo = rank * local_b
    t.update(DataBatch(data=data[lo:lo + local_b],
                       label=label[lo:lo + local_b]))

bad = t.check_weights()
assert bad == [], bad
w, _ = t.get_weight("fc1", "wmat")
out = os.environ["CXN_TEST_OUT"]
np.save(f"{out}.{rank}.npy", w)
print("worker", rank, "done", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference(tmp_path):
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = WORKER.split("NET = '''")[1].split("'''")[0]
    cfg = cfg.replace("param_server = dist", "")
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.set_param("mesh", "data:1")
    t.init_model()
    rng = np.random.RandomState(42)
    for step in range(5):
        data = rng.randn(8, 1, 1, 8).astype(np.float32)
        label = rng.randint(0, 4, size=(8, 1)).astype(np.float32)
        t.update(DataBatch(data=data, label=label))
    w, _ = t.get_weight("fc1", "wmat")
    return w


def _run_two_process(tmp_path, extra_cfg=""):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out_prefix = str(tmp_path / "w")
    port = _free_port()
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items() if "axon" not in v}
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        # one CPU device per worker process (a 2-host x 1-chip slice;
        # the pytest parent's 8-virtual-device XLA_FLAGS must not leak)
        env["XLA_FLAGS"] = ""
        env["CXN_COORDINATOR"] = f"127.0.0.1:{port}"
        env["CXN_NUM_WORKER"] = "2"
        env["CXN_WORKER_RANK"] = str(rank)
        env["CXN_TEST_REPO"] = REPO
        env["CXN_TEST_OUT"] = out_prefix
        env["CXN_TEST_EXTRA"] = extra_cfg
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    w0 = np.load(f"{out_prefix}.0.npy")
    w1 = np.load(f"{out_prefix}.1.npy")
    return w0, w1


def test_two_process_training_matches_single(tmp_path):
    w0, w1 = _run_two_process(tmp_path)
    np.testing.assert_array_equal(w0, w1)  # cross-process identical
    ref = _single_process_reference(tmp_path)
    np.testing.assert_allclose(w0, ref, rtol=1e-5, atol=1e-6)


def test_two_process_zero1_matches_single(tmp_path):
    """shard_optimizer=1 across 2 real processes: updater state shards
    over devices owned by DIFFERENT processes (put_global_full path +
    GSPMD-partitioned update); training math is unchanged."""
    w0, w1 = _run_two_process(tmp_path,
                              extra_cfg="shard_optimizer = 1\n")
    np.testing.assert_array_equal(w0, w1)
    ref = _single_process_reference(tmp_path)
    np.testing.assert_allclose(w0, ref, rtol=1e-5, atol=1e-6)


def test_local_batch_size_validation(monkeypatch):
    assert distributed.local_batch_size(8) == 8  # single process here
    monkeypatch.setattr(distributed.jax, "process_count", lambda: 3)
    assert distributed.local_batch_size(9) == 3
    with pytest.raises(ValueError, match="must divide"):
        distributed.local_batch_size(8)


def test_check_replicated_clean():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = WORKER.split("NET = '''")[1].split("'''")[0]
    cfg = cfg.replace("param_server = dist", "")
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.set_param("mesh", f"data:{min(8, len(jax.devices()))}")
    t.init_model()
    assert t.check_weights() == []
