"""Dispatch flight recorder, executable registry, request tracing
(telemetry/flight.py, docs/OBSERVABILITY.md third observability tier).

Covers: ring semantics (wrap, in-flight marking, tail order), the
armed/disarmed contract (unarmed dispatch sites record NOTHING and the
CLI stays byte-identical - pinned by a subprocess A/B), executable
registration at the real trainer/serve jit-cache sites, the
``/executables`` endpoint schema, Prometheus exposition grammar for
every new series (per-executable gauges, the ``serve.request_rows``
bucket histogram, the flight gauge), trace_id propagation through an
oversize split request, the Chrome trace export's complete span
trees, and the watchdog stall dump's flight section under the
one-dump-per-episode rule.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu import telemetry
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.serve import Server
from cxxnet_tpu.telemetry import Telemetry
from cxxnet_tpu.telemetry.flight import (
    ExecutableRegistry, FlightRecorder, fingerprint)
from cxxnet_tpu.telemetry.http import (
    ObservabilityServer, render_prometheus, validate_exposition)
from cxxnet_tpu.telemetry.registry import BucketHistogram
from cxxnet_tpu.telemetry.sink import read_jsonl
from cxxnet_tpu.telemetry.watchdog import Watchdog
from cxxnet_tpu.utils.config import parse_config_string

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
silent = 1
seed = 7
"""


@pytest.fixture(autouse=True)
def _clean_singleton():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def make_trainer():
    t = NetTrainer()
    for k, v in parse_config_string(MLP_CFG):
        t.set_param(k, v)
    t.init_model()
    return t


def _batch(i, b=32):
    rng = np.random.RandomState(100 + i)
    return DataBatch(
        data=rng.rand(b, 1, 1, 36).astype(np.float32),
        label=rng.randint(0, 3, size=(b, 1)).astype(np.float32))


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------
def test_disabled_recorder_records_nothing():
    fr = FlightRecorder(size=8)
    assert fr.start("train", fp="abc") is None
    fr.finish(None)  # finish(None) is the disarmed no-op
    assert fr.snapshot() == []
    assert fr.in_flight() == []
    assert "no dispatches" in fr.format_tail()


def test_record_lifecycle_and_in_flight_marking():
    fr = FlightRecorder(size=8)
    fr.arm()
    fl = fr.start("serve", fp="deadbeef0123", bucket=8, nbytes=1024,
                  trace="t-1", fields={"rows": 5})
    (snap,) = fr.snapshot()
    assert snap["in_flight"] is True
    assert snap["age_s"] >= 0
    assert snap["kind"] == "serve" and snap["fp"] == "deadbeef0123"
    assert snap["bucket"] == 8 and snap["bytes"] == 1024
    assert snap["trace"] == "t-1" and snap["rows"] == 5
    assert fr.in_flight()
    fr.finish(fl)
    (snap,) = fr.snapshot()
    assert snap["in_flight"] is False and snap["secs"] >= 0
    assert fr.in_flight() == []
    assert "IN-FLIGHT" not in fr.format_tail()


def test_ring_wraps_and_keeps_newest():
    fr = FlightRecorder(size=4)
    fr.arm()
    for i in range(10):
        fr.finish(fr.start("train", fp=f"fp{i}"))
    snap = fr.snapshot()
    assert len(snap) == 4
    assert [s["seq"] for s in snap] == [6, 7, 8, 9]
    assert fr.tail(2)[-1]["fp"] == "fp9"
    fr.reset()
    assert fr.snapshot() == [] and not fr.enabled


def test_wedged_in_flight_entry_survives_ring_churn():
    """The partial-hang case: one replica wedges while the others
    keep dispatching. The wedged (in-flight) entry must survive ANY
    amount of ring wrap - it is the one record the recorder exists to
    keep."""
    fr = FlightRecorder(size=4)
    fr.arm()
    wedged = fr.start("serve", fp="wedged99", bucket=8, trace="t-w")
    for i in range(20):  # 5x the ring size of later traffic
        fr.finish(fr.start("serve", fp=f"ok{i}"))
    (inf,) = fr.in_flight()
    assert inf["fp"] == "wedged99" and inf["in_flight"] is True
    # the tail keeps it too (prepended before the bounded window),
    # so /varz, the watchdog dump and bench forensics all name it
    tail = fr.tail(4)
    assert tail[0]["fp"] == "wedged99"
    assert len(tail) == 5
    assert "fp=wedged99" in fr.format_tail(4)
    fr.finish(wedged)
    assert fr.in_flight() == []
    # once finished, the long-evicted entry leaves the tail again
    assert all(t["fp"] != "wedged99" for t in fr.tail(4))


def test_open_table_bounded_when_handles_leak():
    fr = FlightRecorder(size=4)
    fr.arm()
    for i in range(10):
        fr.start("train", fp=f"leak{i}")  # never finished
    assert len(fr.in_flight()) == 4  # backstop: one ring's worth


def test_format_tail_names_in_flight_dispatch():
    fr = FlightRecorder(size=8)
    fr.arm()
    fr.finish(fr.start("train", fp="aaa111"))
    fr.start("serve", fp="bbb222", bucket=16, trace="t-9")
    text = fr.format_tail()
    assert "IN-FLIGHT" in text and "fp=bbb222" in text
    assert "bucket=16" in text and "trace=t-9" in text


def test_fingerprint_stable_and_distinct():
    a = fingerprint("serve.infer", 3, 8, (1, 1, 36), 0)
    assert a == fingerprint("serve.infer", 3, 8, (1, 1, 36), 0)
    assert a != fingerprint("serve.infer", 3, 16, (1, 1, 36), 0)
    assert len(a) == 12


# ---------------------------------------------------------------------------
# executable registry
# ---------------------------------------------------------------------------
def test_registry_register_idempotent_counts_accumulate():
    reg = ExecutableRegistry()
    reg.register("fp1", name="train_step@b32", kind="train",
                 shape="(32, 1, 1, 36)", arg_bytes=4608, donated=1)
    reg.count_dispatch("fp1", secs=0.5)
    reg.count_dispatch("fp1")
    # re-registration must not reset counts; a later compile_s fills in
    reg.register("fp1", name="other", kind="train", compile_s=1.25)
    (e,) = reg.snapshot()
    assert e["name"] == "train_step@b32"  # first registration wins
    assert e["dispatches"] == 2 and e["dispatch_s"] == 0.5
    assert e["compile_s"] == 1.25
    assert e["donated"] == 1 and e["last_used_ts"] is not None
    reg.count_dispatch("unknown-fp")  # no-op, never raises
    assert len(reg) == 1


def test_registry_enrich_cost_analysis():
    import jax
    import jax.numpy as jnp
    reg = ExecutableRegistry()
    fn = jax.jit(lambda x: x @ x)
    x = jnp.ones((8, 8), jnp.float32)
    reg.register("fpX", name="toy", kind="infer")
    reg.enrich("fpX", fn, (x,))
    (e,) = reg.snapshot()
    assert e["flops"] and e["flops"] > 0
    assert e["out_bytes"] == 8 * 8 * 4
    # enriching an unknown fingerprint is a no-op
    reg.enrich("nope", fn, (x,))
    assert len(reg) == 1


# ---------------------------------------------------------------------------
# BucketHistogram + exposition grammar for every new series
# ---------------------------------------------------------------------------
def test_bucket_histogram_cumulative_snapshot():
    h = BucketHistogram(bounds=(1, 2, 4))
    for v in (1, 1, 2, 3, 4, 9):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 6 and snap["sum"] == 20
    assert snap["buckets"] == {"1": 2, "2": 3, "4": 5, "+Inf": 6}
    with pytest.raises(ValueError):
        BucketHistogram(bounds=())


def test_bucket_histogram_kind_mismatch_fails_loudly():
    tel = Telemetry()
    tel.registry.counter("serve.rows")
    with pytest.raises(TypeError):
        tel.registry.bucket_histogram("serve.rows", bounds=(1,))
    h = tel.registry.bucket_histogram("serve.request_rows",
                                      bounds=(1, 2))
    # idempotent: the first creation's bounds win
    assert tel.registry.bucket_histogram("serve.request_rows",
                                         bounds=(8, 16)) is h


def test_exposition_valid_with_every_new_series():
    tel = Telemetry()
    tel.registry.bucket_histogram("serve.request_rows",
                                  bounds=(1, 2, 4)).observe(3)
    tel.executables.register(
        "fp1", name="serve.infer:b8", kind="serve", compile_s=0.5)
    tel.executables.register("fp2", name="train_step@b32",
                             kind="train")
    tel.executables.count_dispatch("fp1")
    tel.flight.arm()
    tel.flight.start("serve", fp="fp1", bucket=8)  # stays in flight
    text = render_prometheus(tel)
    assert validate_exposition(text) == []
    assert 'cxxnet_serve_request_rows_bucket{le="+Inf"} 1' in text
    assert ('cxxnet_executable_dispatches_total{fingerprint="fp1"'
            in text)
    assert "cxxnet_executable_compile_seconds" in text
    assert "cxxnet_flight_inflight 1" in text


def test_executables_endpoint_schema_and_varz_flight_tail():
    tel = Telemetry()
    tel.flight.arm()
    tel.executables.register("fpZ", name="serve.infer:b4",
                             kind="serve", shape="(4, 1, 1, 36)",
                             arg_bytes=576, donated=0, compile_s=0.1)
    tel.executables.count_dispatch("fpZ")
    tel.flight.finish(tel.flight.start("serve", fp="fpZ", bucket=4))
    tel.flight.start("serve", fp="fpZ", bucket=4)  # in flight
    srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        rec = json.loads(_get(base + "/executables"))
        assert rec["kind"] == "executables"
        for tag in ("ts", "host", "pid"):
            assert tag in rec
        (e,) = rec["executables"]
        for field in ("fingerprint", "name", "kind", "shape",
                      "arg_bytes", "device", "donated", "compile_s",
                      "flops", "cost_bytes", "out_bytes",
                      "dispatches", "dispatch_s", "last_used_ts"):
            assert field in e, field
        assert e["dispatches"] == 1
        (inf,) = rec["in_flight"]
        assert inf["fp"] == "fpZ" and inf["in_flight"] is True
        varz = json.loads(_get(base + "/varz"))
        assert varz["kind"] == "varz"
        assert [f["fp"] for f in varz["flight"]] == ["fpZ", "fpZ"]
    finally:
        srv.close()


def test_varz_omits_flight_when_disarmed():
    tel = Telemetry()
    srv = ObservabilityServer(tel, 0, host="127.0.0.1").start()
    try:
        # the endpoint itself does not arm the recorder - only
        # Telemetry.arm_observability does (this server is detached)
        varz = json.loads(
            _get(f"http://127.0.0.1:{srv.port}/varz"))
        assert "flight" not in varz
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# arming contract
# ---------------------------------------------------------------------------
def test_flight_arms_with_sinks_and_plane(tmp_path):
    tel = Telemetry()
    assert tel.flight.enabled is False
    tel.configure(log_file=str(tmp_path / "ev.jsonl"))
    assert tel.flight.enabled is True
    tel.configure()  # disarm sinks -> recorder follows
    assert tel.flight.enabled is False
    tel.arm_observability(watchdog_secs=60.0)
    assert tel.flight.enabled is True  # the watchdog is a consumer
    tel.disarm_observability()
    assert tel.flight.enabled is False
    tel.flight.arm()  # explicit (flight_recorder=1) survives refresh
    tel.configure()
    assert tel.flight.enabled is True
    tel.close()


# ---------------------------------------------------------------------------
# trainer + serve dispatch sites
# ---------------------------------------------------------------------------
def test_trainer_sites_register_and_record():
    tr = make_trainer()
    tr.update(_batch(0))
    tr.update_chunk([_batch(1), _batch(2)])
    tr.predict(_batch(3))
    by_name = {e["name"]: e
               for e in telemetry.executables().snapshot()}
    assert by_name["train_step@b32"]["dispatches"] == 1
    assert by_name["train_step@b32"]["donated"] == 1
    assert by_name["train_chunk@K2b32"]["dispatches"] == 1
    infer = [e for e in by_name.values() if e["kind"] == "infer"]
    assert infer and infer[0]["dispatches"] == 1
    assert infer[0]["donated"] == 0
    # unarmed: the registry filled but the ring stayed EMPTY
    assert telemetry.flight().snapshot() == []
    telemetry.flight().arm()
    tr.update(_batch(4))
    tr.predict(_batch(5))
    kinds = [f["kind"] for f in telemetry.flight().snapshot()]
    assert kinds == ["train", "infer"]
    fps = {f["fp"] for f in telemetry.flight().snapshot()}
    assert fps <= {e["fingerprint"]
                   for e in telemetry.executables().snapshot()}


def test_evaluate_registers_eval_executable():
    tr = make_trainer()

    class _OneBatch:
        def __init__(self):
            self._served = False

        def before_first(self):
            self._served = False

        def next(self):
            if self._served:
                return False
            self._served = True
            return True

        def value(self):
            return _batch(9)

    tr.evaluate(_OneBatch(), "eval")
    kinds = {e["kind"] for e in telemetry.executables().snapshot()}
    assert "eval" in kinds


def test_trace_id_propagates_through_oversize_split(tmp_path):
    """One oversize submit (10 rows, max_batch=4 -> 3 parts) must
    resolve as ONE trace id with a complete part set, each part
    carrying the queue-vs-device breakdown and ordered stamps."""
    events = str(tmp_path / "ev.jsonl")
    telemetry.configure(log_file=events)
    tr = make_trainer()
    srv = Server(tr, max_batch=4, max_wait_ms=2.0, replicas=2)
    srv.warmup()
    srv.start()
    fut = srv.submit(np.random.RandomState(0)
                     .rand(10, 1, 1, 36).astype(np.float32))
    out = fut.result(timeout=60)
    assert out.shape[0] == 10
    stats = srv.stop()
    telemetry.close()
    traces = [r for r in read_jsonl(events) if r.get("kind") == "trace"]
    assert len(traces) == 3
    assert len({r["trace"] for r in traces}) == 1
    assert sorted(r["part"] for r in traces) == [0, 1, 2]
    assert all(r["parts"] == 3 for r in traces)
    assert sum(r["rows"] for r in traces) == 10
    for r in traces:
        assert (r["t_submit"] <= r["t_collect"] <= r["t_dispatch"]
                <= r["t_done"])
        assert r["queue_ms"] >= 0 and r["device_ms"] >= 0
        # the queue/device cut is the dispatch stamp: the coalesce
        # fill wait is queue time, never device time
        assert r["queue_ms"] == pytest.approx(
            (r["t_dispatch"] - r["t_submit"]) * 1e3, abs=0.01)
        assert r["device_ms"] == pytest.approx(
            (r["t_done"] - r["t_dispatch"]) * 1e3, abs=0.01)
        assert r["fp"], "dispatch must name its executable"
    # the ring recorded the dispatches with the same fingerprints
    serve_flights = [f for f in telemetry.flight().snapshot()
                     if f["kind"] == "serve"]
    assert serve_flights
    reg_fps = {e["fingerprint"]
               for e in telemetry.executables().snapshot()
               if e["kind"] == "serve"}
    assert {f["fp"] for f in serve_flights} <= reg_fps
    # stats() exposes the breakdown next to the headline latency
    assert stats["queue_p50_ms"] is not None
    assert stats["device_p99_ms"] is not None


def test_failed_dispatch_closes_flight_entry_with_error():
    """A dispatch that RAISES must not read as a hung one: the entry
    closes carrying the error; only a dispatch that never returns
    stays in-flight (the hang signature)."""
    telemetry.flight().arm()
    tr = make_trainer()
    srv = Server(tr, max_batch=4, max_wait_ms=1.0, replicas=1)
    srv.warmup()
    real = tr.stage_infer_rows
    state = {"fail": True}

    def flaky(data, extras=()):
        if state.pop("fail", False):
            raise RuntimeError("injected staging failure")
        return real(data, extras)

    tr.stage_infer_rows = flaky
    srv.start()
    bad = srv.submit(np.zeros((2, 1, 1, 36), np.float32))
    with pytest.raises(RuntimeError):
        bad.result(timeout=60)
    good = srv.submit(np.zeros((2, 1, 1, 36), np.float32))
    good.result(timeout=60)
    srv.stop()
    serve_flights = [f for f in telemetry.flight().snapshot()
                     if f["kind"] == "serve"]
    assert len(serve_flights) == 2
    failed, ok = serve_flights
    assert failed["in_flight"] is False
    assert "injected staging failure" in failed["error"]
    assert ok["in_flight"] is False and "error" not in ok
    assert telemetry.flight().in_flight() == []


def test_programmatic_metrics_server_arms_flight():
    """Server(trainer, metrics_port=...) - the programmatic twin of
    the CLI key - must arm the recorder too: the endpoint it attaches
    serves /varz and /executables, and warmup's cost enrichment runs
    before start(). stop() re-derives (nothing else armed -> off)."""
    tr = make_trainer()
    srv = Server(tr, max_batch=4, max_wait_ms=1.0, replicas=1,
                 metrics_port=0, metrics_host="127.0.0.1")
    assert telemetry.flight().enabled  # armed at construction
    srv.warmup()
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.metrics_server.port}"
        srv.submit(np.zeros((3, 1, 1, 36), np.float32)
                   ).result(timeout=60)
        varz = json.loads(_get(base + "/varz"))
        assert any(f["kind"] == "serve" for f in varz["flight"])
        execs = json.loads(_get(base + "/executables"))
        serve_entries = [e for e in execs["executables"]
                         if e["kind"] == "serve"]
        assert serve_entries
        # armed-at-warmup: the cost enrichment ran
        assert all(e["flops"] is not None for e in serve_entries)
    finally:
        srv.stop()
    assert telemetry.flight().enabled is False


def test_request_rows_histogram_reaches_metrics(tmp_path):
    telemetry.configure(log_file=str(tmp_path / "ev.jsonl"))
    tr = make_trainer()
    srv = Server(tr, max_batch=8, max_wait_ms=1.0, replicas=1)
    srv.warmup()
    srv.start()
    for n in (1, 3, 8, 8):
        srv.submit(np.random.RandomState(n)
                   .rand(n, 1, 1, 36).astype(np.float32)
                   ).result(timeout=60)
    srv.stop()
    text = render_prometheus(telemetry.get())
    assert validate_exposition(text) == []
    assert 'cxxnet_serve_request_rows_bucket{le="8"} 4' in text
    assert "cxxnet_serve_request_rows_count 4" in text
    telemetry.close()


# ---------------------------------------------------------------------------
# watchdog stall dump carries the flight tail (one dump per episode)
# ---------------------------------------------------------------------------
def test_watchdog_dump_names_in_flight_executable(tmp_path, capfd):
    tel = Telemetry()
    log = str(tmp_path / "ev.jsonl")
    tel.configure(log_file=log)
    tel.flight.finish(tel.flight.start("train", fp="aaa111",
                                       bucket=32))
    tel.flight.start("serve", fp="bbb222", bucket=8, trace="t-42")
    now = time.monotonic()
    wd = Watchdog(tel, 5.0)
    wd._armed_at = now
    tel.beacon("train.step")
    base = time.monotonic()
    assert wd.check_now(base + 6) is True    # stalled: one dump
    assert wd.check_now(base + 7) is True    # same episode: no second
    tel.close()
    err = capfd.readouterr().err
    assert "flight recorder" in err
    assert "IN-FLIGHT" in err and "fp=bbb222" in err
    assert "trace=t-42" in err
    assert err.count("flight recorder") == 1  # one dump per episode
    dumps = [e for e in read_jsonl(log)
             if e.get("kind") == "watchdog"
             and e.get("op") == "stall_dump"]
    assert len(dumps) == 1
    flights = dumps[0]["flights"]
    assert [f["fp"] for f in flights] == ["aaa111", "bbb222"]
    assert flights[-1]["in_flight"] is True


# ---------------------------------------------------------------------------
# trace export: Chrome trace-event JSON span trees
# ---------------------------------------------------------------------------
# synthetic records use a fixed wall-monotonic offset of 990 s (the
# record-level `ts` is wall time stamped at emission ~= t_done)
_WALL_OFF = 990.0


def _trace_rec(trace, part, parts, t0, tc, t1, bucket=8, rows=4,
               pid=7):
    return {"kind": "trace", "pid": pid, "trace": trace, "part": part,
            "parts": parts, "rows": rows, "bucket": bucket,
            "fp": "fp1", "t_submit": t0, "t_collect": tc,
            "t_done": t1, "ts": t1 + _WALL_OFF,
            "queue_ms": (tc - t0) * 1e3,
            "device_ms": (t1 - tc) * 1e3}


def test_trace_export_complete_span_trees(tmp_path):
    from cxxnet_tpu.tools import trace_export
    events = tmp_path / "ev.jsonl"
    recs = [
        _trace_rec("r-1", 0, 1, 10.0, 10.01, 10.02),
        _trace_rec("r-2", 0, 2, 10.005, 10.02, 10.03),
        _trace_rec("r-2", 1, 2, 10.005, 10.03, 10.04),
        # incomplete request: part 1 of 2 never resolved
        _trace_rec("r-3", 0, 2, 10.05, 10.06, 10.07),
        # stall dump at wall 10.035+990: must land BETWEEN r-2's
        # resolution (mono 10.04) and r-3 (mono 10.05) on the SHARED
        # timeline, not shifted by the wall/monotonic epoch gap
        {"kind": "watchdog", "op": "stall_dump", "pid": 7,
         "ts": 10.035 + _WALL_OFF, "stalled_secs": 9.0},
    ]
    with open(events, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = tmp_path / "trace.json"
    summary = trace_export.export(str(events), str(out),
                                  str(tmp_path / "summary.json"))
    assert summary["parts"] == 4
    assert summary["requests"] == 3
    assert summary["complete_requests"] == 2  # r-3 is incomplete
    assert summary["queue_p99_ms"] is not None
    assert summary["dispatches_by_bucket"] == {"8": 4}
    trace = json.loads(out.read_text())
    ev = trace["traceEvents"]
    spans = [e for e in ev if e["ph"] == "X"]
    # request + queue + device per part
    assert len(spans) == 3 * 4
    names = {e["name"] for e in spans}
    assert "queue" in names and "device" in names
    assert any(n.startswith("request r-2 [2/2]") for n in names)
    # spans carry microsecond ts/dur and the split args
    req = [e for e in spans if e["name"].startswith("request r-1")][0]
    assert req["dur"] == pytest.approx(0.02 * 1e6, rel=1e-3)
    assert req["args"]["trace"] == "r-1"
    # concurrent r-1/r-2 got distinct lanes; the marker rendered ON
    # the request timeline (wall ts re-anchored via the per-record
    # wall/monotonic pair): mono 10.035 - base 10.0 = 35 ms
    assert len({e["tid"] for e in spans}) >= 2
    (marker,) = [e for e in ev if e["ph"] == "i"
                 and "stall_dump" in e["name"]]
    assert marker["ts"] == pytest.approx(0.035 * 1e6, rel=1e-3)
    assert (tmp_path / "summary.json").exists()


def test_trace_export_cli_empty_stream(tmp_path):
    from cxxnet_tpu.tools import trace_export
    events = tmp_path / "empty.jsonl"
    events.write_text("")
    rc = trace_export.main([str(events), "-o",
                            str(tmp_path / "t.json")])
    assert rc == 1  # nothing to export is a loud condition


# ---------------------------------------------------------------------------
# config schema + the unarmed byte-parity contract
# ---------------------------------------------------------------------------
def test_schema_recognizes_flight_recorder_key():
    from cxxnet_tpu.analysis.schema import validate_pairs
    from cxxnet_tpu.utils.config import ConfigError
    validate_pairs([("flight_recorder", "1")], source="x.conf")
    with pytest.raises(ConfigError) as ei:
        validate_pairs([("flight_recorderr", "1")], source="x.conf")
    assert "flight_recorder" in str(ei.value)


CLI_CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 0
num_round = 1
max_round = 1
eta = 0.3
metric = error
silent = 0
"""


def test_cli_byte_parity_with_flight_armed(tmp_path):
    """tracing off = zero behavior change, and an ARMED ring with no
    sink writes nothing either: stdout+stderr of a plain run and a
    flight_recorder=1 run must be byte-identical (the in-memory ring
    is invisible at the product surface)."""
    from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist
    d = str(tmp_path)
    write_synth_mnist(d, 64, 0, "train")
    conf = os.path.join(d, "parity.conf")
    with open(conf, "w") as f:
        f.write(CLI_CONF.format(d=d))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu.main", conf,
             f"model_dir={d}/models"] + list(extra),
            capture_output=True, timeout=300, env=env, cwd=REPO)

    plain = run()
    armed = run("flight_recorder=1")
    assert plain.returncode == armed.returncode == 0
    assert plain.stdout == armed.stdout
    assert plain.stderr == armed.stderr
