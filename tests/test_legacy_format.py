"""Reference-binary checkpoint format (nnet/legacy_format.py): byte
layout spot checks + round trips through the trainer (save cxxnet ->
load auto-sniffed) on a net covering every weighted layer type."""

import io
import struct

import numpy as np

import jax

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 8
  ngroup = 2
layer[1->2] = relu
layer[2->3] = batch_norm:bn1
layer[3->4] = prelu:pr1
layer[4->5] = max_pooling
  kernel_size = 2
  stride = 2
layer[5->6] = flatten
layer[6->7] = fullc:fc1
  nhidden = 12
layer[7->7] = bias:bs1
layer[7->8] = fullc:fc2
  nhidden = 4
  no_bias = 1
layer[8->8] = softmax
netconfig=end
input_shape = 4,8,8
random_type = gaussian
eta = 0.1
batch_size = 4
silent = 1
eval_train = 0
"""


def _trainer(extra=()):
    t = NetTrainer()
    for k, v in parse_config_string(NET):
        t.set_param(k, v)
    for k, v in extra:
        t.set_param(k, v)
    t.init_model()
    return t


def test_byte_layout():
    t = _trainer([("model_format", "cxxnet")])
    buf = io.BytesIO()
    t.save_model(buf)
    raw = buf.getvalue()
    # int32 net_type = 0
    assert struct.unpack_from("<i", raw, 0)[0] == 0
    # NetParam: num_nodes, num_layers, input_shape (c,y,x)
    nn, nl = struct.unpack_from("<ii", raw, 4)
    assert nn == t.net_cfg.num_nodes and nl == t.net_cfg.num_layers
    assert struct.unpack_from("<3I", raw, 12) == (4, 8, 8)
    # NetParam is 152 bytes; first node name follows ("in")
    (slen,) = struct.unpack_from("<Q", raw, 4 + 152)
    name = raw[4 + 160: 4 + 160 + slen].decode()
    assert name == "in"
    # layer records: first layer is conv (enum 10)
    off = 4 + 152
    for _ in range(nn):
        (n,) = struct.unpack_from("<Q", raw, off)
        off += 8 + n
    assert struct.unpack_from("<i", raw, off)[0] == 10


def test_roundtrip_all_weighted_layers():
    t = _trainer([("model_format", "cxxnet")])
    rng = np.random.RandomState(0)
    for _ in range(3):
        t.update(DataBatch(
            data=rng.randn(4, 4, 8, 8).astype(np.float32),
            label=rng.randint(0, 4, size=(4, 1)).astype(np.float32)))
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    t2 = NetTrainer()
    for k, v in parse_config_string(NET):
        t2.set_param(k, v)
    t2.load_model(buf)  # auto-sniffed as legacy
    a = jax.tree.map(np.asarray, t.state["params"])
    b = jax.tree.map(np.asarray, t2.state["params"])
    assert sorted(a) == sorted(b)
    for lk in a:
        for pn in a[lk]:
            np.testing.assert_array_equal(a[lk][pn], b[lk][pn]), (lk, pn)
    assert t2.epoch == t.epoch
    # predictions identical
    batch = DataBatch(
        data=rng.randn(4, 4, 8, 8).astype(np.float32),
        label=np.zeros((4, 1), np.float32))
    np.testing.assert_array_equal(t.predict(batch), t2.predict(batch))


def test_structure_mismatch_rejected():
    t = _trainer([("model_format", "cxxnet")])
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    other = NetTrainer()
    for k, v in parse_config_string(
            NET.replace("nhidden = 12", "nhidden = 16")):
        other.set_param(k, v)
    try:
        other.load_model(buf)
    except ValueError as e:
        assert "shape" in str(e) or "mismatch" in str(e)
    else:
        raise AssertionError("mismatched structure must be rejected")


def test_finetune_from_legacy_model():
    t = _trainer([("model_format", "cxxnet")])
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    # a different net that shares cv1/fc1 by name
    other_cfg = NET.replace("nhidden = 4", "nhidden = 7")
    t2 = NetTrainer()
    for k, v in parse_config_string(other_cfg):
        t2.set_param(k, v)
    t2.init_model()
    t2.copy_model_from(buf)
    a = jax.tree.map(np.asarray, t.state["params"])
    b = jax.tree.map(np.asarray, t2.state["params"])
    np.testing.assert_array_equal(a["cv1"]["wmat"], b["cv1"]["wmat"])
    np.testing.assert_array_equal(a["fc1"]["wmat"], b["fc1"]["wmat"])
    np.testing.assert_array_equal(a["bn1"]["slope"], b["bn1"]["slope"])
    assert b["fc2"]["wmat"].shape[0] == 7  # not copied (shape change)


def test_torch_layer_rejected_in_legacy_format():
    import pytest
    # the torch plugin type has no reference encoding: exporting a net
    # containing it must fail loudly, never silently drop its weights
    cfg = NET.replace(
        "layer[1->2] = relu",
        'layer[1->2] = torch:tc1\n  torch_module = "nn.Conv2d(8,8,1)"')
    t = NetTrainer()
    for k, v in parse_config_string(cfg):
        t.set_param(k, v)
    t.set_param("model_format", "cxxnet")
    t.init_model()
    with pytest.raises(ValueError, match="no reference encoding"):
        t.save_model(io.BytesIO())


def test_native_format_still_roundtrips():
    t = _trainer()
    buf = io.BytesIO()
    t.save_model(buf)
    buf.seek(0)
    t2 = NetTrainer()
    for k, v in parse_config_string(NET):
        t2.set_param(k, v)
    t2.load_model(buf)
    a = jax.tree.map(np.asarray, t.state["params"])
    b = jax.tree.map(np.asarray, t2.state["params"])
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(x, y)
